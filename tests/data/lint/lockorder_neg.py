"""C5 negative fixture: sanctioned patterns that must stay clean.

Declared nesting order, RLock re-entrancy, collect-then-call callback
delivery, read-modify-write under the second hold (atomicity exempt),
constant reset writes, and awaiting while holding only an asyncio lock.
"""

import asyncio
import threading
import time


class Pipeline:
    _GUARDED_FIELDS = {"_queue": "_state"}
    # lock-order: _flush -> _state

    def __init__(self):
        self._flush = threading.Lock()
        self._state = threading.RLock()
        self._queue = []

    def flush(self):
        # declared order: the serializer wraps the state commit
        with self._flush:
            staged = self.compute()
            with self._state:
                self._queue.extend(staged)

    def compute(self):
        return [1]

    def reentrant_ok(self):
        with self._state:
            with self._state:  # RLock: legal re-entry
                return len(self._queue)

    def drain(self, reason):
        # collect-then-call: callbacks run after the lock is released
        with self._state:
            drained = list(self._queue)
            self._queue = []  # constant-free reset is a fresh list, but
            # the value never depends on the stale read above
        for req in drained:
            req.finish(reason)

    def merge(self, extra):
        with self._state:
            leftover = list(self._queue)
        combined = leftover + extra
        with self._state:
            # RMW under the second hold re-validates: exempt
            self._queue = combined + self._queue

    def sleep_unlocked(self):
        time.sleep(0.001)
        with self._state:
            return len(self._queue)


class AioLedger:
    def __init__(self):
        self._alock = asyncio.Lock()

    async def commit(self):
        async with self._alock:
            # holding an asyncio lock across await is the normal idiom
            await asyncio.sleep(0)
