"""C6 — jit signature budgets: the compile-cache ladder as a static proof.

PR 2/PR 5 perf rests on decode/prefill staying on a *finite, enumerable*
ladder of XLA signatures: every static argument of a jitted hot-path
callable must come from the pow2 bucket ladders
(``round_up_to_bucket``, ``plan_decode_tiers``) or engine-lifetime
config, never from raw lengths or ad-hoc arithmetic.  The soak tests pin
this at runtime; this checker proves it at lint time and quantifies it:

- ``off-ladder-static``: a call site of a registered jitted callable
  (assignments shaped ``self._x_fn = jax.jit(f, static_argnums=...)`` or
  ``x_fn = jax.jit(...)`` in a ``# areal-lint: hot-path`` file) passes a
  static argument the abstract evaluator cannot prove on-ladder.  The
  value lattice: ``0``/``None``/bools are sentinels; ``round_up_to_bucket(...)``
  is ladder by construction; ``self.<attr>`` (engine-lifetime config) is
  a fixed point; ``min``/``max``/``int``/ternaries/``or`` of safe values
  stay safe; local names resolve through every reaching assignment;
  parameters resolve one level into resolved callers.  Arithmetic
  (``span + n``), ``len(...)`` and bare non-zero literals are OFF-ladder
  — each would mint an unbounded signature family.
- ``signature-budget-stale``: ``analysis/signature_budget.json`` (the
  checked-in per-function budget, cross-checked by the soak tests via
  observed-compiled-programs ≤ budget) no longer matches what the ladder
  math derives from its own reference configs.  Regenerate with
  ``python scripts/lint.py --write-budget``.

The budget arithmetic below deliberately re-derives the ladder in pure
Python (no jax/numpy import: the lint CLI and CI hook run in bare
venvs) and mirrors ``areal_tpu/utils/datapack.py round_up_to_bucket`` /
``gen/engine.py plan_decode_tiers`` exactly; test_lint.py pins the two
against each other.
"""

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from areal_tpu.analysis.callgraph import CallGraph, FuncInfo, dotted_name
from areal_tpu.analysis.core import Finding, SourceFile, apply_suppression

RULE_OFF_LADDER = "off-ladder-static"
RULE_STALE = "signature-budget-stale"

BUDGET_PATH = os.path.join("areal_tpu", "analysis", "signature_budget.json")

_LADDER_CALLS = {"round_up_to_bucket"}
_SAFE_WRAPPERS = {"min", "max", "int", "abs"}


# --------------------------- ladder arithmetic -------------------------
# Pure-python mirrors of the runtime bucket math (datapack.py /
# engine.plan_decode_tiers).  Keep in lockstep — test_lint.py compares
# them against the real implementations.


def ladder_values(quantum: int, max_len: int) -> List[int]:
    """Every value `round_up_to_bucket(n, quantum, max_len)` can return."""
    vals: List[int] = []
    b = quantum
    while b < max_len:
        vals.append(b)
        b *= 2
    vals.append(max_len)
    return vals


def pow2_row_counts(n_slots: int) -> int:
    """Distinct `1 << (k - 1).bit_length()` paddings for k in 1..n_slots."""
    return (n_slots - 1).bit_length() + 1 if n_slots > 0 else 0


def plan_tier_count(n_slots: int, n_tiers: int) -> int:
    if n_tiers <= 1:
        return 1
    if n_slots >> (n_tiers - 1) < 1:
        raise ValueError(f"decode_tiers={n_tiers} needs more slots")
    return n_tiers


def compute_budgets(config: Dict[str, int]) -> Dict[str, int]:
    """Static signature budget per jitted hot-path function for one
    engine config.  These are upper bounds on distinct compiled programs:
    static-arg combinations x shape buckets x the x2 sharding family
    (cold device_put vs decode-output resident arrays).  Soak tests
    assert observed `_cache_size()` <= these."""
    if "train_shapes" in config:
        # train-step cache (engine/jax_train.py _train_step_cache): one
        # program per (loss_fn, n_mbs, row_len, padded_len) signature.
        # The reference soak drives exactly `train_shapes` distinct
        # signatures; the two-level layer scan (layer_group_size), remat
        # rung, and scan unroll are engine-lifetime config baked into the
        # traced program — they must add NO signature axis.
        return {"train_step": config["train_shapes"]}
    q = config["prompt_bucket"]
    m = config["max_seq_len"]
    slots = config["n_slots"]
    tiers = plan_tier_count(slots, config.get("decode_tiers", 1))
    ladder = len(ladder_values(q, m))
    rows = pow2_row_counts(slots)
    return {
        # per non-empty tier: key_window rides ladder(q, m)
        "decode": tiers * ladder,
        # pow2 row padding x prompt bucket x sharding family
        "prefill": rows * ladder * 2,
        # + static (copy_block in ladder+{0}, key_window in ladder)
        "suffix_prefill": rows * ladder * (ladder + 1) * 2,
        # host spill: one program per block bucket (row is traced)
        "host_gather": ladder,
        # host swap-in: shape-keyed on the same bucketed block
        "host_scatter": ladder,
        # speculative verify (ISSUE 12): per tier x key bucket x nonzero
        # draft-length rung (D=0 reuses the decode program, so only the
        # nonzero rungs of the spec ladder mint verify signatures);
        # spec_rungs=0 (spec decode off) budgets zero verify programs
        "verify": tiers * ladder * config.get("spec_rungs", 0),
        # ragged paged-decode attention (ISSUE 19): the collapsed
        # grid-wide dispatch drops the tier factor entirely — one decode
        # program per K bucket plus one verify program per (K bucket,
        # nonzero D rung).  Page-count buckets add NO axis: the kernel's
        # page size rides the prompt-bucket quantum, so each K bucket IS
        # its page-count bucket (K/q pages, 1:1).  ragged=0 (flag off)
        # budgets zero ragged programs.
        "ragged_decode": ladder
        * (1 + config.get("spec_rungs", 0))
        * config.get("ragged", 0),
    }


def budget_drift(doc: Dict) -> List[str]:
    """Mismatches between the checked-in budgets and what the ladder math
    derives from the document's own reference configs (empty = fresh)."""
    problems: List[str] = []
    refs = doc.get("reference_configs")
    if not isinstance(refs, dict) or not refs:
        return ["no reference_configs section"]
    for name, entry in refs.items():
        cfg = entry.get("config", {})
        try:
            fresh = compute_budgets(cfg)
        except (KeyError, ValueError) as e:
            problems.append(f"{name}: unusable config ({e})")
            continue
        stored = entry.get("budgets", {})
        if stored != fresh:
            problems.append(
                f"{name}: stored budgets {stored} != derived {fresh}"
            )
    return problems


def render_budget_doc(reference_configs: Dict[str, Dict[str, int]]) -> Dict:
    """The signature_budget.json payload for a set of reference configs
    (what `scripts/lint.py --write-budget` emits)."""
    return {
        "comment": (
            "Static jit-signature budgets (areal-lint C6).  For each "
            "reference engine config: the maximum number of distinct "
            "compiled programs each hot-path jitted callable may mint, "
            "derived from the pow2 bucket ladders.  The jit-cache soak "
            "tests assert observed _cache_size() <= budget; lint "
            "(`signature-budget-stale`) asserts these numbers match the "
            "ladder math.  Regenerate: python scripts/lint.py "
            "--write-budget.  This file is the authoritative ladder "
            "spec (docs/perf.md)."
        ),
        "formulas": {
            "ladder(q, M)": "|{q*2^k : q*2^k < M}| + 1  (round_up_to_bucket image)",
            "rows(S)": "(S-1).bit_length() + 1  (pow2 row paddings)",
            "decode": "decode_tiers * ladder(prompt_bucket, max_seq_len)",
            "prefill": "rows(n_slots) * ladder * 2",
            "suffix_prefill": "rows(n_slots) * ladder * (ladder + 1) * 2",
            "host_gather": "ladder  (traced row; one program per block bucket)",
            "host_scatter": "ladder  (shape-keyed on the bucketed block)",
            "verify": (
                "decode_tiers * ladder * spec_rungs  (nonzero draft-length"
                " rungs of the spec ladder; 0 when spec decode is off)"
            ),
            "ragged_decode": (
                "ladder * (1 + spec_rungs) * ragged  (collapsed grid-wide"
                " dispatch: the tier factor is gone, and page-count"
                " buckets map 1:1 onto K buckets because the kernel page"
                " size IS the prompt-bucket quantum; 0 when the ragged"
                " flag is off)"
            ),
            "train_step": (
                "train_shapes  (distinct (loss_fn, n_mbs, row_len,"
                " padded_len) signatures the soak drives;"
                " layer_group_size / remat rung / scan unroll are"
                " engine-lifetime config and add NO axis)"
            ),
        },
        "reference_configs": {
            name: {"config": cfg, "budgets": compute_budgets(cfg)}
            for name, cfg in reference_configs.items()
        },
    }


# --------------------------- jit def collection ------------------------


@dataclass
class JitDef:
    name: str  # handle attribute/name, e.g. "_decode_fn"
    line: int
    static_positions: List[int]
    params: List[str] = field(default_factory=list)  # wrapped fn params


def _static_positions(call: ast.Call) -> List[int]:
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return [v.value]
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                        el.value, int
                    ):
                        out.append(el.value)
                    else:
                        return []
                return out
    return []


def collect_jit_defs(sf: SourceFile) -> List[JitDef]:
    defs: List[JitDef] = []
    if sf.tree is None:
        return defs
    fn_params: Dict[str, List[str]] = {}
    for n in ast.walk(sf.tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn_params[n.name] = [
                a.arg for a in list(n.args.posonlyargs) + list(n.args.args)
            ]
    for n in ast.walk(sf.tree):
        if not isinstance(n, ast.Assign) or not isinstance(
            n.value, ast.Call
        ):
            continue
        if dotted_name(n.value.func) != "jax.jit":
            continue
        static = _static_positions(n.value)
        if not static:
            continue
        wrapped = n.value.args[0] if n.value.args else None
        params: List[str] = []
        if isinstance(wrapped, ast.Name):
            params = fn_params.get(wrapped.id, [])
        for tgt in n.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                defs.append(JitDef(tgt.attr, n.lineno, static, params))
            elif isinstance(tgt, ast.Name):
                defs.append(JitDef(tgt.id, n.lineno, static, params))
    return defs


# ------------------------- abstract evaluation -------------------------


class _Safety:
    def __init__(self, graph: CallGraph, depth: int = 2):
        self.graph = graph
        self.depth = depth

    def safe(
        self, expr: ast.AST, fi: FuncInfo, depth: Optional[int] = None
    ) -> Tuple[bool, str]:
        depth = self.depth if depth is None else depth
        if isinstance(expr, ast.Constant):
            v = expr.value
            if v is None or isinstance(v, (bool, str)):
                return True, ""
            if v == 0:
                return True, ""
            return (
                False,
                f"literal {v!r} is not provably on the bucket ladder",
            )
        if isinstance(expr, ast.Attribute):
            return True, ""  # engine-lifetime config / module constant
        if isinstance(expr, ast.Subscript):
            return self.safe(expr.value, fi, depth)
        if isinstance(expr, ast.Call):
            d = dotted_name(expr.func) or ""
            base = d.split(".")[-1]
            if base in _LADDER_CALLS:
                return True, ""
            if base in _SAFE_WRAPPERS:
                for a in expr.args:
                    ok, why = self.safe(a, fi, depth)
                    if not ok:
                        return False, why
                return True, ""
            if base == "len":
                return False, "len(...) is a raw (unbucketed) length"
            return False, f"call {d or '<expr>'}(...) not on the ladder"
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                ok, why = self.safe(v, fi, depth)
                if not ok:
                    return False, why
            return True, ""
        if isinstance(expr, ast.IfExp):
            for v in (expr.body, expr.orelse):
                ok, why = self.safe(v, fi, depth)
                if not ok:
                    return False, why
            return True, ""
        if isinstance(expr, ast.Name):
            return self._safe_name(expr.id, fi, depth)
        if isinstance(expr, (ast.BinOp, ast.UnaryOp)):
            return (
                False,
                "arithmetic on lengths can leave the ladder — wrap it in "
                "round_up_to_bucket(...)",
            )
        return False, "expression shape not recognized as ladder-safe"

    def _safe_name(
        self, name: str, fi: FuncInfo, depth: int
    ) -> Tuple[bool, str]:
        assigns: List[ast.AST] = []
        augmented = False
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign):
                for tgt in n.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name:
                        assigns.append(n.value)
            elif isinstance(n, ast.AnnAssign) and isinstance(
                n.target, ast.Name
            ):
                if n.target.id == name and n.value is not None:
                    assigns.append(n.value)
            elif isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Name
            ):
                if n.target.id == name:
                    augmented = True
        if augmented:
            return False, f"`{name}` is arithmetically updated (+=)"
        if assigns:
            for v in assigns:
                ok, why = self.safe(v, fi, depth)
                if not ok:
                    return False, f"`{name}` <- {why}"
            return True, ""
        # a parameter: every resolved caller must pass something safe
        params = _param_names(fi.node)
        if name in params:
            if depth <= 0:
                return True, ""  # depth-bounded benefit of the doubt
            pos = params.index(name)
            for caller_key, calls in self.graph.calls.items():
                for call, callee in calls:
                    if callee != fi.key:
                        continue
                    arg = _arg_for_param(call, fi, pos, name)
                    if arg is None:
                        continue  # default applies
                    ok, why = self.safe(
                        arg, self.graph.functions[caller_key], depth - 1
                    )
                    if not ok:
                        return False, f"caller passes `{name}` = {why}"
            default = _default_for_param(fi.node, pos)
            if default is not None:
                ok, why = self.safe(default, fi, depth)
                if not ok:
                    return False, f"default for `{name}`: {why}"
            return True, ""
        return (
            False,
            f"`{name}` has no reaching definition the checker can prove "
            f"on-ladder",
        )


def _param_names(fn: ast.AST) -> List[str]:
    return [a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)]


def _arg_for_param(
    call: ast.Call, fi: FuncInfo, pos: int, name: str
) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    # positional: methods are invoked without the explicit self
    eff = pos - 1 if fi.cls_key is not None else pos
    if 0 <= eff < len(call.args):
        return call.args[eff]
    return None


def _default_for_param(fn: ast.AST, pos: int) -> Optional[ast.AST]:
    args = list(fn.args.posonlyargs) + list(fn.args.args)
    defaults = list(fn.args.defaults)
    offset = len(args) - len(defaults)
    if pos >= offset:
        return defaults[pos - offset]
    return None


# ------------------------------ the checker ----------------------------


def check_jit_signatures(
    files: Dict[str, SourceFile], root: Optional[str] = None
) -> List[Finding]:
    graph = CallGraph(files)
    safety = _Safety(graph)
    findings: List[Finding] = []

    for rel, sf in files.items():
        if sf.tree is None or not sf.hot:
            continue
        defs = {d.name: d for d in collect_jit_defs(sf)}
        if not defs:
            continue
        for key, fi in graph.functions.items():
            if fi.rel != rel:
                continue
            for call in ast.walk(fi.node):
                if not isinstance(call, ast.Call):
                    continue
                jd = _match_handle(call, defs)
                if jd is None:
                    continue
                for p in jd.static_positions:
                    expr = _static_arg_expr(call, jd, p)
                    if expr is None:
                        continue
                    ok, why = safety.safe(expr, fi)
                    if not ok:
                        pname = (
                            jd.params[p]
                            if p < len(jd.params)
                            else f"arg{p}"
                        )
                        findings.append(
                            apply_suppression(
                                sf,
                                Finding(
                                    RULE_OFF_LADDER,
                                    sf.rel,
                                    expr.lineno,
                                    f"static arg `{pname}` of "
                                    f"{jd.name} can mint an off-ladder "
                                    f"signature: {why} — every value "
                                    f"must come from "
                                    f"round_up_to_bucket/engine config "
                                    f"(see signature_budget.json)",
                                ),
                            )
                        )

    if root is not None:
        findings.extend(_budget_findings(root))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _match_handle(call: ast.Call, defs: Dict[str, JitDef]) -> Optional[JitDef]:
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id == "self"
    ):
        return defs.get(f.attr)
    if isinstance(f, ast.Name):
        return defs.get(f.id)
    return None


def _static_arg_expr(
    call: ast.Call, jd: JitDef, pos: int
) -> Optional[ast.AST]:
    if pos < len(call.args):
        return call.args[pos]
    if pos < len(jd.params):
        pname = jd.params[pos]
        for kw in call.keywords:
            if kw.arg == pname:
                return kw.value
    return None


def _budget_findings(root: str) -> List[Finding]:
    path = os.path.join(root, BUDGET_PATH)
    if not os.path.exists(path):
        return [
            Finding(
                RULE_STALE,
                BUDGET_PATH,
                1,
                "signature budget file missing — generate it with "
                "`python scripts/lint.py --write-budget`",
            )
        ]
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [
            Finding(RULE_STALE, BUDGET_PATH, 1, f"unreadable budget: {e}")
        ]
    return [
        Finding(RULE_STALE, BUDGET_PATH, 1, p) for p in budget_drift(doc)
    ]
