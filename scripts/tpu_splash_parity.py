"""Real-TPU check: splash vs naive attention parity through the full model
forward + gradients, and a microbench of both paths.

Run on a machine with a TPU attached (tests/ run on CPU and always take the
naive path; this script is the on-hardware counterpart).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models import forward, init_params
from areal_tpu.models.model_config import TransformerConfig


def main():
    assert jax.default_backend() != "cpu", "needs a TPU"
    cfg = TransformerConfig(
        vocab_size=2048,
        hidden_size=512,
        intermediate_size=1024,
        num_layers=4,
        num_heads=8,
        num_kv_heads=2,
        head_dim=128,
        qkv_bias=True,
        remat=True,
        dtype="bfloat16",
        param_dtype="float32",
    )
    params = init_params(cfg, jax.random.PRNGKey(0))

    B, T = 2, 1024
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (B, T)).astype(np.int32)
    # packed rows: two segments per row + trailing padding
    seg = np.zeros((B, T), np.int32)
    seg[:, 400:900] = 1
    seg[:, 900:] = -1
    pos = np.where(seg == 1, np.arange(T) - 400, np.arange(T)).astype(np.int32)
    pos = np.where(seg < 0, 0, pos)

    def run(impl):
        c = cfg.replace(attn_impl=impl)

        @jax.jit
        def f(p):
            logits = forward(p, c, ids, pos, seg)
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            tgt = jnp.roll(jnp.asarray(ids), -1, axis=-1)
            tok_lp = jnp.take_along_axis(lp, tgt[..., None], axis=-1)[..., 0]
            loss = -(tok_lp * (jnp.asarray(seg) >= 0)).sum()
            return loss

        loss, grads = jax.jit(jax.value_and_grad(f))(params)
        jax.block_until_ready(grads)
        return loss, grads

    t0 = time.perf_counter()
    loss_s, g_s = run("splash")
    t1 = time.perf_counter()
    loss_n, g_n = run("naive")
    print(f"loss splash={float(loss_s):.4f} naive={float(loss_n):.4f}")
    rel = abs(float(loss_s) - float(loss_n)) / abs(float(loss_n))
    print(f"loss rel err {rel:.2e}")
    errs = jax.tree_util.tree_map(
        lambda a, b: float(
            jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(b)) + 1e-9)
        ),
        g_s,
        g_n,
    )
    worst = max(jax.tree_util.tree_leaves(errs))
    print(f"worst grad rel err {worst:.2e}")
    assert rel < 2e-2 and worst < 5e-2, "parity failure"

    # microbench both impls, bigger shape
    T2 = 4096
    ids2 = rng.integers(0, cfg.vocab_size, (B, T2)).astype(np.int32)
    seg2 = np.zeros((B, T2), np.int32)
    pos2 = np.broadcast_to(np.arange(T2, dtype=np.int32), (B, T2))
    for impl in ("splash", "naive"):
        c = cfg.replace(attn_impl=impl)

        @jax.jit
        def f(p):
            logits = forward(p, c, ids2, pos2, seg2)
            return (logits.astype(jnp.float32) ** 2).mean()

        vg = jax.jit(jax.grad(f))
        g = vg(params)
        jax.block_until_ready(g)
        t0 = time.perf_counter()
        for _ in range(5):
            g = vg(params)
        jax.block_until_ready(g)
        print(f"{impl}: fwd+bwd T={T2} {(time.perf_counter() - t0) / 5 * 1e3:.1f} ms")
    print("OK")


if __name__ == "__main__":
    main()
