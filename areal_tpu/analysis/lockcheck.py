"""Runtime validation of the C1 lock-discipline annotations.

The static checker (lock_discipline.py) proves every *lexical* access to a
guarded field sits under its lock; this module proves the annotation set
matches *actual* lock usage by asserting ownership at runtime.  Opt-in via
``AREAL_DEBUG_LOCKS=1`` (checked at instance construction): the existing
gen-engine concurrency/abort-storm tests run with it enabled, so a field
annotated as guarded that is in fact touched lock-free on some dynamic
path raises `LockDisciplineError` instead of racing silently.

Usage::

    @lock_guarded
    class GenEngine:
        _GUARDED_FIELDS = {"_holdback": "_lock", "_abort_gen": "_lock"}

With the env flag OFF (production, and every test that does not opt in)
the decorator's only cost is one env lookup per construction — instances
keep their original class and plain attribute access.

With the flag ON, the instance is re-classed to a cached subclass where
each guarded field is a data descriptor asserting the declared lock is
held by the current thread on every read and write; `threading.Lock`
attributes named by the registry are wrapped with an owner-tracking proxy
(plain locks do not expose ownership).  `asyncio.Lock` degrades to a
``locked()`` check — single-loop code cannot identify the holding task
cheaply, so only the held-by-nobody violation is caught there.
"""

import os
import threading
from typing import Dict

__all__ = [
    "LockDisciplineError",
    "debug_locks_enabled",
    "lock_guarded",
]


class LockDisciplineError(AssertionError):
    """A guarded field was touched without holding its declared lock."""


def debug_locks_enabled() -> bool:
    return os.environ.get("AREAL_DEBUG_LOCKS", "") == "1"


class _OwnerTrackingLock:
    """threading.Lock with owner identity, for held-by-me assertions."""

    def __init__(self):
        self._lock = threading.Lock()
        self._owner = None

    def acquire(self, *args, **kwargs):
        got = self._lock.acquire(*args, **kwargs)
        if got:
            self._owner = threading.get_ident()
        return got

    def release(self):
        self._owner = None
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident()


def _normalize(registry) -> Dict[str, str]:
    if isinstance(registry, dict):
        return dict(registry)
    return {name: "_lock" for name in registry}


def _assert_held(instance, field: str, lock_name: str, mode: str) -> None:
    lock = instance.__dict__.get(lock_name)
    if lock is None:
        lock = getattr(type(instance), lock_name, None)
    if lock is None:
        raise LockDisciplineError(
            f"{type(instance).__name__}.{field}: declared lock "
            f"`{lock_name}` does not exist on the instance"
        )
    probe = getattr(lock, "held_by_current_thread", None)
    if probe is not None:
        held = probe()
    else:
        # asyncio.Lock (or an unwrapped lock): best effort — catch the
        # nobody-holds-it case, miss the someone-else-holds-it case
        held = bool(getattr(lock, "locked", lambda: True)())
    if not held:
        raise LockDisciplineError(
            f"{type(instance).__name__}.{field} {mode} without holding "
            f"{lock_name} (AREAL_DEBUG_LOCKS=1)"
        )


def _guard_property(field: str, lock_name: str) -> property:
    def fget(self):
        _assert_held(self, field, lock_name, "read")
        try:
            return self.__dict__[field]
        except KeyError:
            raise AttributeError(field) from None

    def fset(self, value):
        _assert_held(self, field, lock_name, "write")
        self.__dict__[field] = value

    def fdel(self):
        _assert_held(self, field, lock_name, "delete")
        del self.__dict__[field]

    return property(fget, fset, fdel)


_CHECKED: Dict[type, type] = {}


def _checked_class(cls: type) -> type:
    checked = _CHECKED.get(cls)
    if checked is None:
        guarded = _normalize(cls._GUARDED_FIELDS)
        ns = {
            field: _guard_property(field, lock_name)
            for field, lock_name in guarded.items()
        }
        checked = type(cls.__name__ + "+LockChecked", (cls,), ns)
        _CHECKED[cls] = checked
    return checked


def lock_guarded(cls: type) -> type:
    """Class decorator arming runtime guards for `_GUARDED_FIELDS` when
    AREAL_DEBUG_LOCKS=1 (see module docstring)."""
    if not hasattr(cls, "_GUARDED_FIELDS"):
        raise TypeError(
            f"@lock_guarded on {cls.__name__} requires a _GUARDED_FIELDS "
            "registry"
        )
    orig_init = cls.__init__

    def __init__(self, *args, **kwargs):
        orig_init(self, *args, **kwargs)
        # exact-class check: a subclass runs this via super().__init__
        # mid-construction, when re-classing would be premature (and its
        # own guarded set may differ)
        if type(self) is cls and debug_locks_enabled():
            for lock_name in set(_normalize(cls._GUARDED_FIELDS).values()):
                lock = self.__dict__.get(lock_name)
                if isinstance(lock, type(threading.Lock())):
                    wrapped = _OwnerTrackingLock()
                    # the plain lock was just constructed in __init__ and
                    # cannot be held yet; swap in place
                    self.__dict__[lock_name] = wrapped
            self.__class__ = _checked_class(cls)

    __init__.__wrapped__ = orig_init
    __init__.__doc__ = orig_init.__doc__
    cls.__init__ = __init__
    return cls
