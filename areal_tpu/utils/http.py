"""Async HTTP with retry (reference: areal/utils/http.py arequest_with_retry)."""

import asyncio
from typing import Any, Dict, Optional

import aiohttp

from areal_tpu.utils import logging

logger = logging.getLogger("http")

def get_default_connector() -> aiohttp.TCPConnector:
    # A fresh connector per session: sessions are created per-request-context
    # on the runner's event loop, and connectors cannot be shared across loops.
    return aiohttp.TCPConnector(limit=0, ttl_dns_cache=300)


class HttpRequestError(RuntimeError):
    pass


async def arequest_with_retry(
    addr: str,
    endpoint: str,
    payload: Optional[Dict[str, Any]] = None,
    method: str = "POST",
    max_retries: int = 3,
    timeout: float = 3600,
    retry_delay: float = 0.5,
    session: Optional[aiohttp.ClientSession] = None,
) -> Dict[str, Any]:
    url = f"http://{addr}{endpoint}"
    last_exc: Optional[BaseException] = None
    owns_session = session is None
    if owns_session:
        session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=timeout, sock_connect=min(30, timeout)),
            connector=get_default_connector(),
        )
    try:
        for attempt in range(max_retries):
            try:
                async with session.request(
                    method, url, json=payload if method != "GET" else None
                ) as resp:
                    if resp.status == 200:
                        ctype = resp.headers.get("Content-Type", "")
                        if "application/json" in ctype:
                            return await resp.json()
                        return {"text": await resp.text()}
                    body = await resp.text()
                    last_exc = HttpRequestError(
                        f"{method} {url} -> HTTP {resp.status}: {body[:200]}"
                    )
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                last_exc = e
            if attempt < max_retries - 1:
                await asyncio.sleep(retry_delay * (2**attempt))
        raise HttpRequestError(
            f"request to {url} failed after {max_retries} attempts"
        ) from last_exc
    finally:
        if owns_session:
            await session.close()


def request_with_retry_sync(
    addr: str,
    endpoint: str,
    payload: Optional[Dict[str, Any]] = None,
    method: str = "POST",
    max_retries: int = 3,
    timeout: float = 3600,
) -> Dict[str, Any]:
    """Blocking variant for non-async contexts (launchers, tools)."""
    import requests

    url = f"http://{addr}{endpoint}"
    last_exc: Optional[BaseException] = None
    for attempt in range(max_retries):
        try:
            resp = requests.request(
                method,
                url,
                json=payload if method != "GET" else None,
                timeout=timeout,
            )
            if resp.status_code == 200:
                try:
                    return resp.json()
                except ValueError:
                    return {"text": resp.text}
            last_exc = HttpRequestError(
                f"{method} {url} -> HTTP {resp.status_code}: {resp.text[:200]}"
            )
        except OSError as e:
            last_exc = e
        if attempt < max_retries - 1:
            import time

            time.sleep(0.5 * (2**attempt))
    raise HttpRequestError(
        f"request to {url} failed after {max_retries} attempts"
    ) from last_exc
