"""GSM8K Dr.GRPO — GRPO done right: no per-group std division.

Counterpart of the reference's `examples/experimental/dr.grpo/
gsm8k_drgrpo.py`. Dr.GRPO's fix is configuration, not code: dividing each
group's advantage by the group's reward std up-weights near-deterministic
groups (all-right/all-wrong) and biases the objective; the recipe keeps the
group-mean baseline but drops the std division (`reward_norm.std_level:
null`, reference yaml: examples/experimental/dr.grpo/gsm8k_drgrpo.yaml),
widens the clip (`eps_clip: 0.4`), and normalizes advantages at batch
level. The training loop is `examples/math/gsm8k_grpo.py`.

Launch:
    python examples/experimental/dr_grpo/gsm8k_drgrpo.py \
        --config examples/experimental/dr_grpo/gsm8k_drgrpo.yaml
"""

import importlib.util
import os
import sys

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def _load_grpo_main():
    spec = importlib.util.spec_from_file_location(
        "gsm8k_grpo_shared",
        os.path.join(_REPO, "examples", "math", "gsm8k_grpo.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


if __name__ == "__main__":
    sys.path.insert(0, _REPO)
    _load_grpo_main()(sys.argv[1:])
