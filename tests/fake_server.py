"""Fake generation server speaking the areal_tpu wire protocol over real HTTP.

The reference tests system logic against FastAPI fake SGLang servers
(realhf/tests/system/test_gserver_manager.py:38); this is the same trick:
an aiohttp server that "generates" deterministic tokens chunk-by-chunk, so
client code (RemoteInfEngine, workflows, executor) is exercised against real
sockets, including the abort/interruption path.

Fault injection (ISSUE 11): pass a `FaultPlan` and every handler consults
it by (endpoint, call-index) before doing real work — HTTP 500s, latency
spikes, hangs, and mid-request disconnects replay deterministically from a
seed.  Pass a fixed `port` to rehearse process death + restart: `stop()`
then a fresh `FakeGenServer(port=same)` is a backend rejoining the fleet.
"""

import asyncio
import threading
from typing import List, Optional

from aiohttp import web

from areal_tpu.utils.faults import FaultPlan, apply_fault


class FakeGenServer:
    """Emits `chunk_size` tokens per /generate call, then stop_reason:

    - "stop" once the scripted completion is exhausted,
    - "length" when the request budget runs out,
    - "abort" whenever `abort_next` is armed (simulating a weight-update
      interruption mid-generation).
    """

    def __init__(
        self,
        completion: Optional[List[int]] = None,
        chunk_size: int = 1024,
        eos_token: Optional[int] = None,
        port: int = 0,
        fault_plan: Optional[FaultPlan] = None,
        role: str = "both",
        shutdown_grace: float = 0.5,
    ):
        self.completion = completion if completion is not None else list(range(100, 108))
        self.chunk_size = chunk_size
        self.eos_token = eos_token
        self.fault_plan = fault_plan
        self.version = 0
        self.paused = False
        self.abort_once = False
        self.delay_s = 0.0  # holds /generate in flight (load-balancing tests)
        # how long stop() lets in-flight handlers finish; chaos tests set
        # it below delay_s so a kill provably aborts the active request
        self.shutdown_grace = shutdown_grace
        # disaggregated serving (ISSUE 17): role advertised on /health,
        # /kv_export + /kv_import record the handoff protocol, and the
        # /metrics tier fields feed the router's decode-occupancy poller
        self.role = role
        self.kv_exports: List[dict] = []
        self.kv_imports: List[dict] = []
        self.tier_occupancy: List[int] = [0]
        self.tier_slots: List[int] = [8]
        self.requests: List[dict] = []
        self.weight_updates: List[dict] = []
        # interleaved ("generate"|"update_weights", body) history — recovery
        # tests assert the pinned weight reload lands BEFORE any re-admitted
        # generate, which the two per-endpoint lists above cannot order
        self.log: List[tuple] = []
        self.port: Optional[int] = port or None
        self._requested_port = port
        self._runner = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()

    async def _maybe_fault(self, request: web.Request, endpoint: str):
        """Returns a faulted Response to serve instead of the real one, or
        None to proceed (a `slow` fault has already delayed by now)."""
        if self.fault_plan is None:
            return None
        return await apply_fault(self.fault_plan.decide(endpoint), request)

    # --- handlers ---
    async def _generate(self, request: web.Request):
        faulted = await self._maybe_fault(request, "/generate")
        if faulted is not None:
            return faulted
        body = await request.json()
        self.requests.append(body)
        self.log.append(("generate", body))
        if self.delay_s:
            await asyncio.sleep(self.delay_s)
        prompt = body["input_ids"]
        params = body["sampling_params"]
        budget = params["max_new_tokens"]
        # how much of the scripted completion has already been consumed is
        # inferred from the prompt tail (interruption resends accumulated ids)
        done = 0
        for k in range(min(len(self.completion), len(prompt)), 0, -1):
            if prompt[-k:] == self.completion[:k]:
                done = k
                break
        remaining = self.completion[done:]
        n = min(len(remaining), budget)
        if self.abort_once:
            n = min(n, max(1, len(remaining) // 2))  # interrupt mid-sequence
        else:
            n = min(n, self.chunk_size)
        out = remaining[:n]
        gen_version = self.version  # tokens carry the version that produced them
        if self.abort_once:
            stop = "abort"
            self.abort_once = False
            self.version += 1  # weight update happened during the interruption
        elif n == len(remaining):
            stop = "stop"
        elif n >= budget:
            stop = "length"
        else:
            stop = "abort"  # chunk cap reached: behave like chunked generation
        return web.json_response(
            {
                "output_tokens": out,
                "output_logprobs": [-0.5] * len(out),
                # the real engine stamps every token with the weight version
                # active when it was sampled (staleness accounting reads it)
                "output_versions": [gen_version] * len(out),
                "stop_reason": stop,
                "version": gen_version,
                # the real engine echoes the client-pinned sampler stream
                # (or the one it allocated) so a handoff leg 2 / failover
                # resubmit continues the identical counter-keyed stream
                "stream_id": int(body.get("stream_id", 0) or 0),
                # the real engine reports how many prompt tokens hit the
                # radix/paged prefix cache; the fake's analogue is the
                # already-consumed completion carried back in the prompt
                # (nonzero exactly on interruption/failover resubmits)
                "cache_hit_tokens": done,
            }
        )

    async def _kv_export(self, request: web.Request):
        faulted = await self._maybe_fault(request, "/kv_export")
        if faulted is not None:
            return faulted
        body = await request.json()
        self.kv_exports.append(body)
        # the recorder keeps the raw body; the wire read below tolerates an
        # empty probe request from transport-level tests
        # areal-lint: disable=payload-silent-default fake export of an empty prefix is a valid degenerate entry
        ids = list(body.get("input_ids", []))
        # full kv_pool.wire_encode_entry shape — router leg-2 import decodes
        # version/block/kv, so a fake omitting them would mask real drift
        return web.json_response(
            {
                "tokens": ids,
                "valid_len": len(ids),
                "version": self.version,
                "block": 0,
                "nbytes": 64 * len(ids),
                "kv": {},
            }
        )

    async def _kv_import(self, request: web.Request):
        faulted = await self._maybe_fault(request, "/kv_import")
        if faulted is not None:
            return faulted
        body = await request.json()
        self.kv_imports.append(body)
        return web.json_response(
            {"ok": True, "valid_len": int(body.get("valid_len", 0) or 0)}
        )

    async def _metrics(self, request: web.Request):
        return web.json_response(
            {
                "role": self.role,
                "tier_occupancy": list(self.tier_occupancy),
                "tier_slots": list(self.tier_slots),
            }
        )

    async def _pause(self, request):
        faulted = await self._maybe_fault(request, "/pause_generation")
        if faulted is not None:
            return faulted
        self.paused = True
        return web.json_response({"ok": True})

    async def _resume(self, request):
        faulted = await self._maybe_fault(request, "/continue_generation")
        if faulted is not None:
            return faulted
        self.paused = False
        return web.json_response({"ok": True})

    async def _update_weights_from_disk(self, request):
        faulted = await self._maybe_fault(request, "/update_weights_from_disk")
        if faulted is not None:
            return faulted
        body = await request.json()
        self.weight_updates.append(body)
        self.log.append(("update_weights", body))
        # a publish that names its version is authoritative (the router's
        # rejoin force-reload stamps the fleet version); legacy publishes
        # without one just advance
        if body.get("version") is not None:
            self.version = int(body["version"])
        else:
            self.version += 1
        return web.json_response({"ok": True, "version": self.version})

    async def _health(self, request):
        faulted = await self._maybe_fault(request, "/health")
        if faulted is not None:
            return faulted
        return web.json_response(
            {"status": "ok", "version": self.version, "role": self.role}
        )

    # --- lifecycle ---
    def _make_app(self) -> web.Application:
        app = web.Application()
        app.router.add_post("/generate", self._generate)
        app.router.add_post("/pause_generation", self._pause)
        app.router.add_post("/continue_generation", self._resume)
        app.router.add_post("/update_weights_from_disk", self._update_weights_from_disk)
        app.router.add_post("/kv_export", self._kv_export)
        app.router.add_post("/kv_import", self._kv_import)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/health", self._health)
        return app

    def start(self) -> str:
        def _run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _serve():
                # short shutdown grace: a chaos-killed fleet member must die
                # abruptly (keep-alive connections from router/client
                # sessions would otherwise hold cleanup for 60 s)
                runner = web.AppRunner(
                    self._make_app(), shutdown_timeout=self.shutdown_grace
                )
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", self._requested_port)
                await site.start()
                self.port = runner.addresses[0][1]
                self._runner = runner
                self._started.set()

            self._loop.run_until_complete(_serve())
            self._loop.run_forever()

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("fake server failed to start")
        return f"127.0.0.1:{self.port}"

    def stop(self):
        if self._loop is not None:
            async def _cleanup():
                if self._runner is not None:
                    await self._runner.cleanup()

            asyncio.run_coroutine_threadsafe(_cleanup(), self._loop).result(timeout=5)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)
