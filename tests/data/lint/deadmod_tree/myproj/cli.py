"""Alive: an executable entry point (python -m myproj.cli)."""


def main():
    print("ok")


if __name__ == "__main__":
    main()
