"""Image wire helpers (reference: areal/utils/image.py image2base64)."""

import base64
import io
from typing import Any, List, Union


def image2base64(images: Union[Any, List[Any]]) -> List[str]:
    """PIL images (or numpy arrays) -> base64-encoded PNG strings, the wire
    format ModelRequest.image_data carries to inference servers."""
    if not isinstance(images, (list, tuple)):
        images = [images]
    out = []
    for img in images:
        if isinstance(img, (bytes, bytearray)):
            out.append(base64.b64encode(bytes(img)).decode())
            continue
        if hasattr(img, "save"):  # PIL
            buf = io.BytesIO()
            img.save(buf, format="PNG")
            out.append(base64.b64encode(buf.getvalue()).decode())
            continue
        import numpy as np

        arr = np.asarray(img)
        try:
            from PIL import Image

            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="PNG")
            out.append(base64.b64encode(buf.getvalue()).decode())
        except ImportError:  # raw bytes fallback
            out.append(base64.b64encode(arr.tobytes()).decode())
    return out


def load_images(images: Union[Any, List[Any]]) -> List[Any]:
    """Resolve dataset image entries — file paths, PIL images, arrays — to
    in-memory images (paths are what the CLEVR manifest carries)."""
    if not isinstance(images, (list, tuple)):
        images = [images]
    out = []
    for img in images:
        if isinstance(img, str):
            from PIL import Image

            with Image.open(img) as f:
                out.append(f.convert("RGB").copy())
        else:
            out.append(img)
    return out
