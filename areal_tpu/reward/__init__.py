from areal_tpu.reward.math_parser import (
    extract_answer,
    gsm8k_reward_fn,
    math_equal,
    math_verify_reward,
)

__all__ = [
    "extract_answer",
    "math_equal",
    "gsm8k_reward_fn",
    "math_verify_reward",
]
