"""Non-test root: whatever this imports (transitively) is alive."""

from myproj.used import run

print(run())
