"""areal-lint interprocedural core: module-level call graph + fixpoint.

The C1–C4 checkers are per-file and lexical.  The v2 checkers (C5
lock-order, C6 jit signature budgets, C7 slot typestate) need facts that
cross function boundaries — "does this callee acquire a lock my caller
already holds", "which fields does this helper write", "what values can
this parameter carry" — so this module builds the shared substrate once
per lint run:

- an index of every class and function in the scanned tree, keyed
  ``"<rel>::<Class>.<meth>"`` / ``"<rel>::<func>"``;
- a per-class **lock registry** read from ``__init__`` assignments
  (``self._lock = threading.Lock()`` → kind ``threading``;
  ``asyncio.Lock()`` → ``asyncio``; ``RLock`` marks reentrancy);
- **attribute type inference** good enough for this codebase's idiom:
  ``def __init__(self, engine: GenEngine)`` + ``self.engine = engine``
  and ``self.x = ClassName(...)`` give ``self.engine.step()`` a target;
- call resolution for ``self.m()``, ``self.attr.m()`` and same-module
  bare calls (anything else resolves to ``None`` — the checkers treat
  unresolved calls conservatively per-rule);
- a generic ``fixpoint`` worklist so each checker can propagate its own
  summary lattice (lock sets, write sets, blocking witnesses) to
  convergence without re-implementing the iteration.

Deliberately NOT a points-to analysis: the repo's concurrency surface is
a handful of long-lived singletons wired by constructor injection, which
is exactly what this resolves.  Precision failures are soundly degraded:
an unresolvable call contributes no facts, so checkers stay
false-positive-free at the cost of missing exotic call shapes.
"""

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from areal_tpu.analysis.core import SourceFile

_LOCK_FACTORIES = {
    "threading.Lock": ("threading", False),
    "threading.RLock": ("threading", True),
    "asyncio.Lock": ("asyncio", False),
    "asyncio.Condition": ("asyncio", False),
    "asyncio.Semaphore": ("asyncio", False),
    "threading.Condition": ("threading", False),
    "threading.Semaphore": ("threading", False),
}


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c"; None for anything not a pure dotted chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class LockInfo:
    name: str  # attribute name, e.g. "_lock"
    kind: str  # "threading" | "asyncio" | "unknown"
    reentrant: bool = False


@dataclass
class ClassInfo:
    key: str  # "<rel>::<name>"
    name: str
    rel: str
    node: ast.ClassDef
    sf: SourceFile
    locks: Dict[str, LockInfo] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> bare class name
    methods: Dict[str, str] = field(default_factory=dict)  # name -> func key


@dataclass
class FuncInfo:
    key: str
    name: str
    rel: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    sf: SourceFile
    cls_key: Optional[str] = None  # owning ClassInfo key, if a method


class CallGraph:
    """Class/function index + call resolution over one scanned tree."""

    def __init__(self, files: Dict[str, SourceFile]):
        self.files = files
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.classes_by_name: Dict[str, List[str]] = {}
        self._module_funcs: Dict[Tuple[str, str], str] = {}
        for rel, sf in files.items():
            if sf.tree is None:
                continue
            self._index_module(rel, sf)
        for ci in self.classes.values():
            self._infer_class_facts(ci)
        # callee edges, resolved once: key -> [(ast.Call, callee key|None)]
        self.calls: Dict[str, List[Tuple[ast.Call, Optional[str]]]] = {}
        for fi in self.functions.values():
            self.calls[fi.key] = [
                (call, self.resolve_call(fi, call))
                for call in self._own_calls(fi.node)
            ]

    # ------------------------------ indexing ---------------------------

    def _index_module(self, rel: str, sf: SourceFile) -> None:
        for stmt in sf.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{rel}::{stmt.name}"
                self.functions[key] = FuncInfo(key, stmt.name, rel, stmt, sf)
                self._module_funcs[(rel, stmt.name)] = key
            elif isinstance(stmt, ast.ClassDef):
                ckey = f"{rel}::{stmt.name}"
                ci = ClassInfo(ckey, stmt.name, rel, stmt, sf)
                self.classes[ckey] = ci
                self.classes_by_name.setdefault(stmt.name, []).append(ckey)
                for meth in stmt.body:
                    if isinstance(
                        meth, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        mkey = f"{rel}::{stmt.name}.{meth.name}"
                        self.functions[mkey] = FuncInfo(
                            mkey, meth.name, rel, meth, sf, cls_key=ckey
                        )
                        ci.methods[meth.name] = mkey

    def _infer_class_facts(self, ci: ClassInfo) -> None:
        init_key = ci.methods.get("__init__")
        if init_key is None:
            return
        init = self.functions[init_key].node
        param_types: Dict[str, str] = {}
        args = init.args
        for a in list(args.posonlyargs) + list(args.args) + list(
            args.kwonlyargs
        ):
            ann = a.annotation
            if isinstance(ann, ast.Name):
                param_types[a.arg] = ann.id
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                param_types[a.arg] = ann.value.split(".")[-1].strip("'\" ")
            elif (
                isinstance(ann, ast.Subscript)
                and dotted_name(ann.value) in ("Optional", "typing.Optional")
                and isinstance(ann.slice, ast.Name)
            ):
                param_types[a.arg] = ann.slice.id
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                val = node.value
                if isinstance(val, ast.Call):
                    d = dotted_name(val.func)
                    if d in _LOCK_FACTORIES:
                        kind, re = _LOCK_FACTORIES[d]
                        ci.locks[tgt.attr] = LockInfo(tgt.attr, kind, re)
                    elif d in ("Lock", "RLock"):
                        ci.locks[tgt.attr] = LockInfo(
                            tgt.attr, "unknown", d == "RLock"
                        )
                    elif d in self.classes_by_name:
                        ci.attr_types[tgt.attr] = d
                    elif "lock" in tgt.attr.lower():
                        ci.locks.setdefault(
                            tgt.attr, LockInfo(tgt.attr, "unknown", False)
                        )
                elif isinstance(val, ast.Name) and val.id in param_types:
                    ci.attr_types[tgt.attr] = param_types[val.id]

    # ----------------------------- resolution --------------------------

    def _class_by_bare_name(self, name: str) -> Optional[ClassInfo]:
        keys = self.classes_by_name.get(name, [])
        if len(keys) == 1:  # ambiguous bare names resolve to nothing
            return self.classes[keys[0]]
        return None

    def resolve_call(
        self, caller: FuncInfo, call: ast.Call
    ) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name):  # bare same-module call
            return self._module_funcs.get((caller.rel, f.id))
        if not isinstance(f, ast.Attribute):
            return None
        recv = f.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            if caller.cls_key is not None:
                return self.classes[caller.cls_key].methods.get(f.attr)
            return None
        if (
            isinstance(recv, ast.Attribute)
            and isinstance(recv.value, ast.Name)
            and recv.value.id == "self"
            and caller.cls_key is not None
        ):
            # self.<attr>.<meth>() through the inferred attribute type
            owner = self.classes[caller.cls_key]
            tname = owner.attr_types.get(recv.attr)
            if tname:
                target = self._class_by_bare_name(tname)
                if target is not None:
                    return target.methods.get(f.attr)
        return None

    @staticmethod
    def _own_calls(fn: ast.AST) -> List[ast.Call]:
        """Call nodes in `fn`'s own body, not descending into nested
        defs/lambdas (those run at an unknown later time — each nested def
        is its own analysis context, or no context at all)."""
        out: List[ast.Call] = []
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                out.append(node)
            stack.extend(ast.iter_child_nodes(node))
        return out

    def lock_of(
        self, caller: FuncInfo, attr: str
    ) -> Optional[Tuple[str, LockInfo]]:
        """`with self.<attr>:` in `caller` -> (owning class key, LockInfo)
        when <attr> is a registered lock of the caller's class."""
        if caller.cls_key is None:
            return None
        li = self.classes[caller.cls_key].locks.get(attr)
        if li is None:
            return None
        return caller.cls_key, li


def fixpoint(
    init: Dict[str, Set],
    edges: Dict[str, Iterable[str]],
) -> Dict[str, Set]:
    """Transitive set union over the call graph: out[f] = init[f] ∪
    ⋃ out[callee].  `edges[f]` lists f's callees; unknown keys contribute
    nothing.  Terminates because summaries only grow within finite sets."""
    out: Dict[str, Set] = {k: set(v) for k, v in init.items()}
    callers: Dict[str, List[str]] = {}
    for f, cs in edges.items():
        for c in cs:
            callers.setdefault(c, []).append(f)
    work = list(init)
    while work:
        f = work.pop()
        merged = set(out.get(f, ()))
        for c in edges.get(f, ()):
            merged |= out.get(c, set())
        if merged != out.get(f, set()):
            out[f] = merged
            work.extend(callers.get(f, ()))
    return out
