"""Deterministic fault injection (ISSUE 11): seeded plan generation,
replay, and faults flowing through the fake server into the client's
failover path.  The chaos-harness contract is that one integer (the seed)
reproduces the exact injected-failure sequence."""

import asyncio

import pytest

from areal_tpu.api.config import GenerationHyperparameters, InferenceEngineConfig
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.engine.jax_remote import RemoteJaxEngine
from areal_tpu.utils.faults import Fault, FaultPlan

from tests.fake_server import FakeGenServer


# ---------------------------------------------------------------------------
# plan determinism
# ---------------------------------------------------------------------------


def test_plan_generation_is_seed_deterministic():
    a = FaultPlan.generate(seed=7)
    b = FaultPlan.generate(seed=7)
    assert a.to_dict() == b.to_dict()
    assert a.plan, "default rate over 64 calls must plan at least one fault"
    assert FaultPlan.generate(seed=8).to_dict() != a.to_dict()


def test_decide_replay_matches_injected_log():
    plan = FaultPlan.generate(seed=3, n_calls=32, rate=0.4)
    seq = ["/generate"] * 32 + ["/health"] * 4
    first = [plan.decide(ep) for ep in seq]
    log1 = plan.injected_log()
    assert log1, "rate=0.4 over 32 calls must inject"
    plan.reset_counters()
    assert [plan.decide(ep) for ep in seq] == first
    assert plan.injected_log() == log1


def test_plan_dict_roundtrip():
    plan = FaultPlan.generate(
        seed=5, n_calls=32, rate=0.5, kinds=("slow", "hang"), slow_s=0.2
    )
    clone = FaultPlan.from_dict(plan.to_dict())
    assert clone.plan == plan.plan
    assert FaultPlan.from_dict({}).plan == {}


def test_unknown_fault_kind_rejected():
    with pytest.raises(ValueError):
        Fault("segfault")


# ---------------------------------------------------------------------------
# injection through the fake server -> client failover
# ---------------------------------------------------------------------------


def _engine(addrs, **kw):
    cfg = InferenceEngineConfig(
        experiment_name="e", trial_name="t", consumer_batch_size=2,
        max_concurrent_rollouts=16, request_timeout=10, request_retries=2,
        **kw,
    )
    eng = RemoteJaxEngine(cfg)
    eng.initialize(addr=addrs)
    return eng


@pytest.mark.parametrize("kind", ["http_500", "disconnect"])
def test_injected_fault_drives_failover(kind):
    """An injected backend fault on the first /generate call must push the
    trajectory through the client's failover path and still complete on
    the healthy replica."""
    plan = FaultPlan({("/generate", 0): Fault(kind)})
    faulty = FakeGenServer(completion=list(range(100, 106)), fault_plan=plan)
    healthy = FakeGenServer(completion=list(range(100, 106)))
    addrs = [faulty.start(), healthy.start()]
    eng = _engine(addrs)  # round_robin: first rid places on the faulty server
    try:
        resp = asyncio.run(eng.agenerate(ModelRequest(
            rid="r0", input_ids=[1, 2],
            gconfig=GenerationHyperparameters(max_new_tokens=16),
        )))
        assert resp.output_tokens == list(range(100, 106))
        assert resp.stop_reason == "stop"
        assert plan.injected_log() == [("/generate", 0, kind)]
        assert healthy.requests, "failover must reach the healthy replica"
    finally:
        eng.destroy()
        faulty.stop()
        healthy.stop()


def test_slow_fault_passes_through():
    plan = FaultPlan({("/generate", 0): Fault("slow", delay_s=0.05)})
    server = FakeGenServer(completion=[100, 101], fault_plan=plan)
    addr = server.start()
    eng = _engine([addr])
    try:
        resp = asyncio.run(eng.agenerate(ModelRequest(
            rid="r0", input_ids=[1],
            gconfig=GenerationHyperparameters(max_new_tokens=8),
        )))
        assert resp.output_tokens == [100, 101]
        assert plan.injected_log() == [("/generate", 0, "slow")]
    finally:
        eng.destroy()
        server.stop()


def test_seeded_chaos_run_replays_identically():
    """End-to-end determinism (acceptance criterion): two fresh runs with
    the same seed and the same single-threaded call sequence produce the
    SAME injected-failure log — what makes a CI chaos failure reproducible
    locally from one integer."""

    def run_once():
        plan = FaultPlan.generate(
            seed=11, n_calls=16, rate=0.5, kinds=("http_500",)
        )
        faulty = FakeGenServer(
            completion=list(range(100, 104)), chunk_size=2, fault_plan=plan
        )
        healthy = FakeGenServer(completion=list(range(100, 104)), chunk_size=2)
        eng = _engine([faulty.start(), healthy.start()], failover_retries=8)
        try:
            for i in range(4):
                resp = asyncio.run(eng.agenerate(ModelRequest(
                    rid=f"r{i}", input_ids=[1],
                    gconfig=GenerationHyperparameters(max_new_tokens=8),
                )))
                assert resp.output_tokens == list(range(100, 104))
            return plan.injected_log()
        finally:
            eng.destroy()
            faulty.stop()
            healthy.stop()

    first = run_once()
    assert first, "seed 11 at rate=0.5 must inject on the exercised calls"
    assert run_once() == first


def test_kill_process_sigkills_and_reaps():
    """kill_process is the one fault the in-process injector cannot
    express: SIGKILL with no flush, exactly like an OOM-killed fleet
    member.  It must reap the child (no zombie) and report the signal."""
    import subprocess
    import sys

    from areal_tpu.utils.faults import kill_process

    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(60)"])
    rc = kill_process(proc, timeout=10)
    assert rc == -9
    assert proc.poll() == -9  # reaped, not a zombie


# ---------------------------------------------------------------------------
# fault points (ISSUE 15: trainer-kill chaos hooks)
# ---------------------------------------------------------------------------


def test_fault_point_raise_action_counts_hits():
    from areal_tpu.utils.faults import (
        InjectedFault,
        arm_fault_point,
        fault_point,
        reset_fault_points,
    )

    try:
        arm_fault_point("train_step", action="raise", at_hit=3)
        fault_point("train_step")  # hit 1
        fault_point("train_step")  # hit 2
        with pytest.raises(InjectedFault):
            fault_point("train_step")  # hit 3 fires
        # a fired point is spent: later hits pass through
        fault_point("train_step")
        # unarmed names are free
        fault_point("never_armed")
    finally:
        reset_fault_points()


def test_kill_trainer_at_step_maps_to_relative_hit():
    from areal_tpu.utils.faults import (
        _FAULT_POINTS,
        kill_trainer_at_step,
        reset_fault_points,
    )

    try:
        # resumed run: start_step=2, kill at global step 4 -> the 3rd
        # per-step hit of this process
        kill_trainer_at_step(4, start_step=2)
        assert _FAULT_POINTS["train_step"]["at_hit"] == 3
        assert _FAULT_POINTS["train_step"]["action"] == "kill"
    finally:
        reset_fault_points()


def test_fault_points_parse_env(monkeypatch):
    from areal_tpu.utils.faults import (
        _FAULT_POINTS,
        InjectedFault,
        fault_point,
        reset_fault_points,
    )

    try:
        reset_fault_points()
        monkeypatch.setenv(
            "AREAL_FAULT_POINTS",
            "recover_mid_dump@2:raise, train_step:raise",
        )
        fault_point("recover_mid_dump")  # hit 1 of 2: passes
        assert _FAULT_POINTS["recover_mid_dump"]["at_hit"] == 2
        assert _FAULT_POINTS["train_step"]["at_hit"] == 1
        with pytest.raises(InjectedFault):
            fault_point("recover_mid_dump")
        with pytest.raises(InjectedFault):
            fault_point("train_step")
    finally:
        reset_fault_points()


def test_arm_fault_point_validates():
    from areal_tpu.utils.faults import arm_fault_point, reset_fault_points

    try:
        with pytest.raises(ValueError):
            arm_fault_point("x", action="explode")
        with pytest.raises(ValueError):
            arm_fault_point("x", at_hit=0)
    finally:
        reset_fault_points()
