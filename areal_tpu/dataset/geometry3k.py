"""Geometry3K visual math-RL dataset (reference:
areal/dataset/geometry3k.py get_geometry3k_rl_dataset).

Rows: {"images", "messages", "answer", "query_id"} feeding
VisionRLVRWorkflow, same shape as the CLEVR loader (dataset/clevr.py).
Images are padded to square RGB before reaching the processor — geometry
diagrams are extreme-aspect-ratio and vision towers expect near-square
crops (reference pad_to_square, geometry3k.py:10).  Offline-friendly: a
jsonl manifest with image paths, or an HF dataset dir.
"""

import json
import os
from typing import Optional

from areal_tpu.dataset import register_dataset


def pad_to_square(img, fill=(0, 0, 0)):
    from PIL import Image

    w, h = img.size
    if w == h:
        return img
    side = max(w, h)
    out = Image.new("RGB" if img.mode != "RGB" else img.mode, (side, side), fill)
    out.paste(img, ((side - w) // 2, (side - h) // 2))
    return out


@register_dataset("geometry3k")
def get_geometry3k_rl_dataset(
    path: str,
    split: str = "train",
    tokenizer=None,
    processor=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    """jsonl manifest rows: {"images": [path...] | "image": path,
    "messages": str | chat list, "answer": str} (keys mirror the
    reference's image/problem/answer columns)."""
    manifest = path
    if os.path.isdir(path):
        manifest = os.path.join(path, f"{split}.jsonl")
    samples = []
    base = os.path.dirname(os.path.abspath(manifest))
    with open(manifest) as f:
        for i, line in enumerate(f):
            if not line.strip():
                continue
            row = json.loads(line)
            images = row.get("images") or [row["image"]]
            images = [
                img if not isinstance(img, str) or os.path.isabs(img)
                else os.path.join(base, img)
                for img in images
            ]
            messages = row.get("messages", row.get("problem"))
            sample = {
                "images": images,
                "messages": messages,
                "answer": str(row["answer"]),
                "query_id": str(row.get("query_id", i)),
                "image_transform": "pad_to_square",
            }
            if "input_ids" in row:
                sample["input_ids"] = row["input_ids"]
                if max_length and len(sample["input_ids"]) > max_length:
                    continue
            samples.append(sample)
    return samples
