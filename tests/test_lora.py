"""LoRA adapter tests (round-1 review: LoRAConfig was dead config).

Coverage: adapter math vs merged weights, base-weight freezing (bit-exact),
optimizer masking, merged export for inference, and the recover round trip
with adapters persisted next to the optimizer state.
(Reference: areal/engine/fsdp_engine.py:270-296 PEFT integration.)
"""

import jax
import numpy as np

from areal_tpu.api.config import (
    LoRAConfig,
    MeshConfig,
    MicroBatchSpec,
    OptimizerConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta
from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.models import forward, init_params
from areal_tpu.models.lora import add_lora_params, merge_lora
from areal_tpu.models.model_config import tiny_config
from areal_tpu.ops import sft_loss_fn

TARGETS = ["q_proj", "v_proj", "o_proj", "up_proj"]


def _mcfg(**kw):
    return tiny_config(
        vocab_size=97, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=2, num_kv_heads=2, **kw,
    )


def _engine(tmp=None, lr=5e-2):
    cfg = TrainEngineConfig(
        experiment_name="lora", trial_name="t", init_from_scratch=True,
        dtype="float32", param_dtype="float32", gradient_checkpointing=False,
        mesh=MeshConfig(fsdp_parallel_size=2, tensor_parallel_size=2),
        mb_spec=MicroBatchSpec(n_mbs=1),
        optimizer=OptimizerConfig(lr=lr, warmup_steps_proportion=0.0),
        pack_length_quantum=32, max_pack_length=64,
        lora=LoRAConfig(enabled=True, rank=4, alpha=8.0, target_modules=TARGETS),
    )
    eng = JaxTrainEngine(cfg, model_config=_mcfg())
    eng.initialize(ft_spec=FinetuneSpec(1, 32, 4))
    return eng


def _batch(rng, B=4, L=24):
    return {
        "input_ids": rng.integers(0, 97, (B, L)).astype(np.int32),
        "attention_mask": np.ones((B, L), bool),
        "loss_mask": np.ones((B, L), np.float32),
    }


def _weight(b):
    return float(np.sum(b["loss_mask"]))


def test_lora_trains_adapters_only():
    eng = _engine()
    rng = np.random.default_rng(0)
    base_before = {
        k: np.asarray(v).copy()
        for k, v in eng.params["layers"]["attn"].items()
        if "_lora_" not in k
    }
    emb_before = np.asarray(eng.params["embedding"]).copy()
    b_before = np.asarray(eng.params["layers"]["attn"]["wq_lora_b"]).copy()
    losses = [eng.train_batch(_batch(rng), sft_loss_fn, _weight)["loss"]
              for _ in range(3)]
    # base weights bit-identical; adapters moved; loss finite and changing
    for k, v in base_before.items():
        np.testing.assert_array_equal(
            np.asarray(eng.params["layers"]["attn"][k]), v
        )
    np.testing.assert_array_equal(np.asarray(eng.params["embedding"]), emb_before)
    assert not np.array_equal(
        np.asarray(eng.params["layers"]["attn"]["wq_lora_b"]), b_before
    )
    assert np.isfinite(losses).all()


def test_merge_matches_adapter_forward():
    """forward(base + adapters) == forward(merged base) exactly."""
    mcfg = _mcfg(lora_rank=4, lora_alpha=8.0,
                 lora_targets=("q_proj", "v_proj", "o_proj", "up_proj"))
    params = init_params(mcfg, jax.random.PRNGKey(0))
    params = add_lora_params(params, mcfg, jax.random.PRNGKey(1))
    # give B nonzero values so the delta actually matters
    rng = np.random.default_rng(2)
    for sub in params["layers"].values():
        if isinstance(sub, dict):
            for k in list(sub):
                if k.endswith("_lora_b"):
                    sub[k] = np.asarray(
                        rng.normal(0, 0.02, sub[k].shape), np.float32
                    )
    ids = rng.integers(0, 97, (1, 16)).astype(np.int32)
    pos = np.arange(16, dtype=np.int32)[None]
    seg = np.zeros((1, 16), np.int32)
    with_adapters = np.asarray(forward(params, mcfg, ids, pos, seg))

    merged = merge_lora(
        jax.tree_util.tree_map(np.asarray, params), mcfg
    )
    plain_cfg = mcfg.replace(lora_rank=0, lora_targets=())
    merged_out = np.asarray(forward(merged, plain_cfg, ids, pos, seg))
    np.testing.assert_allclose(with_adapters, merged_out, rtol=2e-4, atol=2e-5)
    # the delta is real: plain base differs from adapter forward
    base_out = np.asarray(forward(params, plain_cfg, ids, pos, seg))
    assert np.abs(base_out - with_adapters).max() > 1e-4


def test_lora_recover_round_trip(tmp_path):
    eng = _engine()
    rng = np.random.default_rng(3)
    eng.train_batch(_batch(rng), sft_loss_fn, _weight)
    before = eng.eval_batch(_batch(np.random.default_rng(9)), sft_loss_fn, _weight)
    eng.save(SaveLoadMeta(path=str(tmp_path / "ckpt"), with_optim=True))

    eng2 = _engine()
    eng2.load(SaveLoadMeta(path=str(tmp_path / "ckpt"), with_optim=True))
    after = eng2.eval_batch(_batch(np.random.default_rng(9)), sft_loss_fn, _weight)
    np.testing.assert_allclose(before["loss"], after["loss"], rtol=1e-5)
    assert eng2.step_count == eng.step_count


def test_lora_export_is_merged(tmp_path):
    """save(with_optim=False) folds adapters in: reloading the exported dir
    as a plain model reproduces the adapter model's outputs."""
    from areal_tpu.models.hf import load_hf_params

    eng = _engine()
    rng = np.random.default_rng(4)
    eng.train_batch(_batch(rng), sft_loss_fn, _weight)
    out_dir = tmp_path / "export"
    eng.save(SaveLoadMeta(path=str(out_dir), with_optim=False))

    ids = rng.integers(0, 97, (1, 16)).astype(np.int32)
    pos = np.arange(16, dtype=np.int32)[None]
    seg = np.zeros((1, 16), np.int32)
    live = np.asarray(
        forward(
            jax.tree_util.tree_map(np.asarray, eng.params),
            eng.model_config, ids, pos, seg,
        )
    )
    plain_cfg = eng.model_config.replace(lora_rank=0, lora_targets=())
    loaded, _ = load_hf_params(str(out_dir), plain_cfg, dtype="float32")
    exported = np.asarray(forward(loaded, plain_cfg, ids, pos, seg))
    # export is bf16 (serving format): compare within bf16 rounding, and
    # check the merge actually happened — the exported model must be far
    # closer to the adapter model than the unmerged base is
    np.testing.assert_allclose(live, exported, rtol=0.05, atol=0.05)
    base = np.asarray(
        forward(
            jax.tree_util.tree_map(np.asarray, eng.params), plain_cfg,
            ids, pos, seg,
        )
    )
    err_export = np.abs(exported - live).mean()
    err_base = np.abs(base - live).mean()
    assert err_export < err_base * 0.5, (err_export, err_base)
