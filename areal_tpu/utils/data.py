"""Padded / packed batch representations and transformations.

Capability counterpart of the reference's `areal/utils/data.py` (1364 LoC:
pad/pack/unpack, concat_padded_tensors, microbatch splitting, Normalization,
KLEstimator).  TPU-first re-design:

- Batches are plain `dict[str, np.ndarray]` (host side) — no TensorDict/torch.
- Padded layout: every per-token key is [B, L] plus boolean "attention_mask".
- Packed layout: per-token keys are flat [T] plus "cu_seqlens" [B+1] and
  int32 "segment_ids" [T]; attention masking on TPU is segment-id based
  (replaces flash-attn varlen), and packed buffers are *bucketed* to
  power-of-two lengths so jit sees few distinct shapes.
"""

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from areal_tpu.utils.datapack import allocate_balanced_mbs, round_up_to_bucket

MbList = List[Dict[str, np.ndarray]]

_NON_SEQ_KEYS = ("cu_seqlens", "max_seqlen", "segment_ids", "total_lens")


def _is_per_token(key: str, arr: np.ndarray, batch: int, seqlen: int) -> bool:
    return arr.ndim >= 2 and arr.shape[0] == batch and arr.shape[1] == seqlen


# vision batch keys indexed by PATCH (not row) plus the per-row span
# metadata that lets row-wise splitters carve them — the ONE list the
# controller, the batch container, and the VLM engine all share
VISION_PATCH_KEYS = ("pixel_values", "patch_img_ids", "patch_pos_hw")
VISION_BATCH_KEYS = VISION_PATCH_KEYS + ("patches_per_row",)


# ---------------------------------------------------------------------------
# Padded representation
# ---------------------------------------------------------------------------


def pad_sequences_to_tensors(
    seqs: List[Dict[str, Any]], pad_value: float = 0.0
) -> Dict[str, np.ndarray]:
    """Stack a list of per-trajectory dicts (1-D arrays of varying length per
    per-token key; scalars allowed) into a padded batch with attention_mask."""
    if not seqs:
        return {}
    keys = list(seqs[0].keys())
    token_keys = [
        k
        for k in keys
        if np.asarray(seqs[0][k]).ndim >= 1 and k != "attention_mask"
    ]
    if not token_keys:
        raise ValueError("trajectory dicts contain no per-token (1-D+) keys")
    lens = []
    for s in seqs:
        klens = {k: len(np.asarray(s[k])) for k in token_keys}
        if len(set(klens.values())) != 1:
            raise ValueError(f"per-token keys disagree on length: {klens}")
        lens.append(next(iter(klens.values())))
    max_len = max(lens)
    out: Dict[str, np.ndarray] = {}
    for k in keys:
        vals = [np.asarray(s[k]) for s in seqs]
        if vals[0].ndim == 0:
            out[k] = np.stack(vals)
            continue
        padded = []
        for v in vals:
            pad_width = [(0, max_len - v.shape[0])] + [(0, 0)] * (v.ndim - 1)
            padded.append(np.pad(v, pad_width, constant_values=pad_value))
        out[k] = np.stack(padded)
    out["attention_mask"] = (
        np.arange(max_len)[None, :] < np.asarray(lens)[:, None]
    )
    return out


def concat_padded_tensors(
    dicts: List[Dict[str, np.ndarray]], pad_value: float = 0.0
) -> Dict[str, np.ndarray]:
    """Concatenate padded batches along batch dim, re-padding to the common
    max length (reference: data.py:152)."""
    dicts = [d for d in dicts if d]
    if not dicts:
        return {}
    assert all("attention_mask" in d for d in dicts)
    max_len = max(d["attention_mask"].shape[1] for d in dicts)
    keys = set(dicts[0].keys())
    for d in dicts[1:]:
        if set(d.keys()) != keys:
            raise ValueError(f"inconsistent keys: {keys} vs {set(d.keys())}")
    out: Dict[str, np.ndarray] = {}
    for k in keys:
        parts = []
        img_offset = 0
        for d in dicts:
            arr = d[k]
            B, L = d["attention_mask"].shape
            if _is_per_token(k, arr, B, L) and L < max_len:
                pad_width = [(0, 0), (0, max_len - L)] + [(0, 0)] * (arr.ndim - 2)
                fill = False if arr.dtype == np.bool_ else pad_value
                arr = np.pad(arr, pad_width, constant_values=fill)
            if k == "patch_img_ids":
                # image ids must stay unique across episodes: patch order
                # defines the embedding<->placeholder mapping and shared ids
                # would merge attention across different images.  -1 is the
                # pad sentinel and never advances the offset.
                arr = np.where(arr >= 0, arr + img_offset, arr)
                if arr.size and int(arr.max()) >= 0:
                    img_offset = max(img_offset, int(arr.max()) + 1)
            parts.append(arr)
        out[k] = np.concatenate(parts, axis=0)
    return out


def seq_lens(batch: Dict[str, np.ndarray]) -> np.ndarray:
    if "attention_mask" in batch:
        return batch["attention_mask"].astype(np.int64).sum(-1)
    if "cu_seqlens" in batch:
        cu = batch["cu_seqlens"]
        return (cu[1:] - cu[:-1]).astype(np.int64)
    raise ValueError("batch has neither attention_mask nor cu_seqlens")


def select_rows(batch: Dict[str, np.ndarray], idx: Sequence[int]) -> Dict[str, np.ndarray]:
    idx = np.asarray(idx, dtype=np.int64)
    return {k: v[idx] if isinstance(v, np.ndarray) and v.ndim >= 1 else v
            for k, v in batch.items()}


def select_rows_vision(
    batch: Dict[str, np.ndarray], idx: Sequence[int]
) -> Dict[str, np.ndarray]:
    """`select_rows` for batches carrying vision keys.

    Patch arrays (`pixel_values`, `patch_img_ids`) are indexed by PATCH, not
    row: naive row slicing would tear pixels away from their sequences (the
    reason the v1 VLM actor forbade dynamic sampling / minibatching).  Using
    the per-row spans (`patches_per_row`, emitted by VisionRLVRWorkflow) the
    selected rows' patch ranges are gathered in the new row order and the
    per-patch image indices renumbered by first appearance, preserving the
    scan-order invariant `forward_vlm_lm` matches embeddings by.
    """
    idx = np.asarray(idx, dtype=np.int64)
    token = {k: v for k, v in batch.items() if k not in VISION_BATCH_KEYS}
    out = select_rows(token, idx)
    if "pixel_values" not in batch:
        return out
    if "patches_per_row" not in batch:
        raise ValueError(
            "row selection on a vision batch needs 'patches_per_row'"
        )
    spans = np.asarray(batch["patches_per_row"], np.int64)
    bounds = np.concatenate([[0], np.cumsum(spans)])
    patch_idx = (
        np.concatenate(
            [np.arange(bounds[i], bounds[i + 1]) for i in idx]
        ).astype(np.int64)
        if len(idx)
        else np.zeros(0, np.int64)
    )
    ids = np.asarray(batch["patch_img_ids"])[patch_idx]
    # renumber image indices by first appearance = new scan order
    new_ids = np.full(ids.shape, -1, np.int32)
    real = ids >= 0
    if real.any():
        _, first_pos, inverse = np.unique(
            ids[real], return_index=True, return_inverse=True
        )
        order = np.empty(first_pos.shape[0], np.int64)
        order[np.argsort(first_pos)] = np.arange(first_pos.shape[0])
        new_ids[real] = order[inverse].astype(np.int32)
    for k in VISION_PATCH_KEYS:
        if k in batch:
            out[k] = np.asarray(batch[k])[patch_idx]
    out["patch_img_ids"] = new_ids
    out["patches_per_row"] = spans[idx]
    return out


def batch_size(batch: Dict[str, np.ndarray]) -> int:
    if "attention_mask" in batch:
        return batch["attention_mask"].shape[0]
    if "cu_seqlens" in batch:
        return len(batch["cu_seqlens"]) - 1
    raise ValueError("cannot infer batch size")


# ---------------------------------------------------------------------------
# Packed representation
# ---------------------------------------------------------------------------


def pack_tensor_dict(
    batch: Dict[str, np.ndarray],
    pad_to: Optional[int] = None,
    quantum: int = 0,
    max_len: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """Padded [B, L] -> packed flat [T] with cu_seqlens & segment_ids
    (reference: data.py:266).

    If `pad_to` or `quantum` is given, the flat buffer is right-padded to a
    bucketed length with segment_id = -1 filler tokens, keeping XLA shapes
    static across steps.
    """
    mask = batch["attention_mask"].astype(bool)
    B, L = mask.shape
    lens = mask.sum(-1).astype(np.int32)
    total = int(lens.sum())
    cu = np.zeros(B + 1, dtype=np.int32)
    np.cumsum(lens, out=cu[1:])
    target = total
    if pad_to is not None:
        target = max(pad_to, total)
    elif quantum:
        target = round_up_to_bucket(total, quantum, max_len)
        if target < total:
            raise ValueError(f"packed length {total} exceeds max bucket {target}")
    flat_idx = np.nonzero(mask.reshape(-1))[0]
    out: Dict[str, np.ndarray] = {}
    token_keys = []
    for k, arr in batch.items():
        if k == "attention_mask":
            continue
        if _is_per_token(k, arr, B, L):
            flat = arr.reshape(B * L, *arr.shape[2:])[flat_idx]
            if target > total:
                pad_width = [(0, target - total)] + [(0, 0)] * (flat.ndim - 1)
                flat = np.pad(flat, pad_width)
            out[k] = flat
            token_keys.append(k)
        else:
            out[k] = arr
    seg = np.repeat(np.arange(B, dtype=np.int32), lens)
    if target > total:
        seg = np.pad(seg, (0, target - total), constant_values=-1)
    # per-token position within each sequence (for RoPE on packed data)
    pos = np.concatenate([np.arange(n, dtype=np.int32) for n in lens]) if B else \
        np.zeros(0, np.int32)
    if target > total:
        pos = np.pad(pos, (0, target - total))
    out["segment_ids"] = seg
    out["positions"] = pos
    out["cu_seqlens"] = cu
    out["max_seqlen"] = np.asarray(int(lens.max()) if B else 0, dtype=np.int32)
    out["total_lens"] = np.asarray(total, dtype=np.int32)
    # explicit per-token key registry — unpacking must never guess from shapes
    out["__token_keys__"] = np.array(sorted(token_keys))
    return out


def unpack_sequence(packed: Dict[str, np.ndarray]) -> List[Dict[str, np.ndarray]]:
    """Packed -> list of per-sequence dicts (inverse of pack for per-token keys)."""
    cu = packed["cu_seqlens"]
    B = len(cu) - 1
    out: List[Dict[str, np.ndarray]] = []
    if "__token_keys__" in packed:
        token_keys = set(str(k) for k in packed["__token_keys__"])
    else:  # packed dict from an external source: fall back to shape heuristic
        total = int(packed["total_lens"]) if "total_lens" in packed else int(cu[-1])
        token_keys = {
            k
            for k, arr in packed.items()
            if k not in _NON_SEQ_KEYS
            and k not in ("positions", "__token_keys__")
            and isinstance(arr, np.ndarray)
            and arr.ndim >= 1
            and arr.shape[0] >= max(total, B + 1)
        }
    for i in range(B):
        d: Dict[str, np.ndarray] = {}
        s, e = int(cu[i]), int(cu[i + 1])
        for k, arr in packed.items():
            if k in _NON_SEQ_KEYS or k in ("positions", "__token_keys__"):
                continue
            if k in token_keys:
                d[k] = arr[s:e]
            elif isinstance(arr, np.ndarray) and arr.ndim >= 1 and arr.shape[0] == B:
                d[k] = arr[i]
        out.append(d)
    return out


def pad_packed_tensor_dict(
    packed: Dict[str, np.ndarray], target: int
) -> Dict[str, np.ndarray]:
    """Right-pad an existing packed dict's flat buffers to `target` tokens."""
    total = int(packed["total_lens"])
    if target < total:
        raise ValueError(f"target {target} < total {total}")
    if target == int(packed["segment_ids"].shape[0]):
        return packed
    out = dict(packed)
    cur = int(packed["segment_ids"].shape[0])
    extra = target - cur
    if "__token_keys__" in packed:
        token_keys = set(str(k) for k in packed["__token_keys__"])
    else:  # external packed dict: every flat buffer of the current length
        token_keys = {
            k
            for k, arr in packed.items()
            if k not in _NON_SEQ_KEYS
            and k != "__token_keys__"
            and isinstance(arr, np.ndarray)
            and arr.ndim >= 1
            and arr.shape[0] == cur
        }
    token_keys |= {"segment_ids", "positions"}
    for k in token_keys:
        arr = packed[k]
        if extra < 0:  # shrink only ever removes filler (target >= total checked)
            out[k] = arr[:target]
        elif extra > 0:
            pad_width = [(0, extra)] + [(0, 0)] * (arr.ndim - 1)
            fill = -1 if k == "segment_ids" else 0
            out[k] = np.pad(arr, pad_width, constant_values=fill)
    return out


# ---------------------------------------------------------------------------
# Micro-batch splitting
# ---------------------------------------------------------------------------


@dataclass
class MicroBatchList:
    mbs: MbList
    groups: List[List[int]]  # original row indices per micro-batch
    forward_indices: List[int]  # flattened order rows were dispatched in

    def merge_outputs(self, outputs: List[np.ndarray]) -> np.ndarray:
        """Re-assemble per-row outputs produced per-microbatch back into
        original batch order."""
        flat = np.concatenate(outputs, axis=0)
        inv = np.empty(len(self.forward_indices), dtype=np.int64)
        inv[np.asarray(self.forward_indices)] = np.arange(len(self.forward_indices))
        return flat[inv]


def split_padded_tensor_dict_into_mb_list(
    batch: Dict[str, np.ndarray],
    n_mbs: int = 1,
    max_tokens_per_mb: Optional[int] = None,
) -> MicroBatchList:
    """Balanced micro-batch split of a padded batch (reference: data.py:404)."""
    lens = seq_lens(batch)
    groups = allocate_balanced_mbs(lens, max_tokens_per_mb, n_mbs)
    groups = [sorted(g) for g in groups if g]
    mbs = [select_rows(batch, g) for g in groups]
    fwd = [i for g in groups for i in g]
    return MicroBatchList(mbs=mbs, groups=groups, forward_indices=fwd)


# ---------------------------------------------------------------------------
# Normalization / KL estimators
# ---------------------------------------------------------------------------


class Normalization:
    """Masked mean/std normalization at batch or group level (reference:
    data.py:1073 `Normalization` used for advantage normalization)."""

    def __init__(
        self,
        mean_level: Optional[str] = "batch",
        std_level: Optional[str] = "batch",
        group_size: int = 1,
        eps: float = 1e-5,
    ):
        for lvl in (mean_level, std_level):
            if lvl not in (None, "none", "batch", "group"):
                raise ValueError(f"bad normalization level {lvl!r}")
        self.mean_level = None if mean_level in (None, "none") else mean_level
        self.std_level = None if std_level in (None, "none") else std_level
        self.group_size = group_size
        self.eps = eps

    @staticmethod
    def _masked_moments(x: np.ndarray, mask: np.ndarray, axis=None):
        cnt = np.maximum(mask.sum(axis=axis, keepdims=True), 1)
        mean = (x * mask).sum(axis=axis, keepdims=True) / cnt
        var = (((x - mean) ** 2) * mask).sum(axis=axis, keepdims=True) / cnt
        return mean, np.sqrt(var)

    def __call__(self, x: np.ndarray, mask: Optional[np.ndarray] = None) -> np.ndarray:
        x = np.asarray(x, dtype=np.float32)
        if mask is None:
            mask = np.ones_like(x, dtype=np.float32)
        mask = mask.astype(np.float32)
        B = x.shape[0]

        def group_view(a):
            assert B % self.group_size == 0, (B, self.group_size)
            return a.reshape(B // self.group_size, self.group_size, *x.shape[1:])

        if self.mean_level == "batch":
            mean, _ = self._masked_moments(x, mask)
        elif self.mean_level == "group":
            gm, _ = self._masked_moments(
                group_view(x), group_view(mask), axis=tuple(range(1, x.ndim + 1))
            )
            # gm is [G, 1, ..., 1]; expand back to per-row then broadcast
            mean = np.broadcast_to(
                np.repeat(gm.reshape(-1), self.group_size)
                .reshape(B, *([1] * (x.ndim - 1))),
                x.shape,
            )
        else:
            mean = np.zeros_like(x)
        centered = x - mean
        if self.std_level == "batch":
            _, std = self._masked_moments(x, mask)
        elif self.std_level == "group":
            _, gs = self._masked_moments(
                group_view(x), group_view(mask), axis=tuple(range(1, x.ndim + 1))
            )
            std = np.broadcast_to(
                np.repeat(gs.reshape(-1), self.group_size)
                .reshape(B, *([1] * (x.ndim - 1))),
                x.shape,
            )
        else:
            std = None
        denom = 1.0 if std is None else std + self.eps
        return np.where(mask > 0, centered / denom, x * 0.0)


class KLEstimator:
    """k1/k2/k3 KL estimators (http://joschu.net/blog/kl-approx.html;
    reference: data.py:1306)."""

    def __init__(self, kind: str = "k1", clip: float = 20.0):
        if kind not in ("k1", "k2", "k3"):
            raise ValueError(kind)
        self.kind = kind
        self.clip = clip

    def __call__(self, logp: np.ndarray, ref_logp: np.ndarray) -> np.ndarray:
        log_ratio = np.clip(logp - ref_logp, -self.clip, self.clip)
        if self.kind == "k1":
            return log_ratio
        if self.kind == "k2":
            return 0.5 * log_ratio**2
        return np.expm1(-log_ratio) + log_ratio  # k3


# ---------------------------------------------------------------------------
# Misc host-side helpers
# ---------------------------------------------------------------------------


def to_jax(batch: Dict[str, np.ndarray], device=None):
    import jax

    return {
        k: (
            jax.device_put(v, device)
            if isinstance(v, np.ndarray) and v.dtype.kind not in "USO"
            else v
        )
        for k, v in batch.items()
    }


def tree_bytes(batch: Dict[str, np.ndarray]) -> int:
    return sum(v.nbytes for v in batch.values() if isinstance(v, np.ndarray))


# ---------------------------------------------------------------------------
# Row-packed representation (TPU training layout)
# ---------------------------------------------------------------------------


@dataclass
class RowPackedBatch:
    """Sequences FFD-packed into fixed-length rows `[R, row_len]`.

    The TPU-first evolution of the reference's flat packed layout
    (areal/utils/data.py:266 pack_tensor_dict): rows are simultaneously
    *packed* (no FLOPs wasted on per-sequence padding beyond row remainder)
    and *shardable* over the (dp, fsdp) mesh axes, with static shapes for jit.
    `segment_ids` isolate sequences within a row for attention; `positions`
    restart at 0 per sequence for RoPE.

    `placements[r]` lists `(orig_index, length)` in order for row r, enabling
    exact inverse mapping of per-token outputs.
    """

    data: Dict[str, np.ndarray]
    placements: List[List[tuple]]
    row_len: int

    @property
    def n_rows(self) -> int:
        return len(self.placements)


def pack_into_rows(
    batch: Dict[str, np.ndarray],
    row_len: int,
    rows_multiple: int = 1,
    rows_bucket_pow2: bool = False,
) -> RowPackedBatch:
    """Padded [B, L] batch -> RowPackedBatch.

    First-fit-decreasing over rows of capacity `row_len` (the balancing role
    of the reference's ffd_allocate, datapack.py); the row count is padded up
    to a multiple of `rows_multiple` (dp-shard divisibility) with empty rows.
    With `rows_bucket_pow2` the count is additionally rounded to the next
    power-of-two multiple, so the (row_len, rows) shape signature — and
    therefore the number of compiled step programs — stays logarithmic in
    batch-size variation.
    """
    mask = batch["attention_mask"].astype(bool)
    B, L = mask.shape
    lens = mask.sum(-1).astype(np.int64)
    if lens.max(initial=0) > row_len:
        raise ValueError(
            f"sequence of length {int(lens.max())} exceeds row_len {row_len}"
        )
    order = np.argsort(-lens, kind="stable")
    rows: List[List[tuple]] = []
    space: List[int] = []
    for i in order:
        n = int(lens[i])
        if n == 0:
            continue
        placed = False
        for r in range(len(rows)):
            if space[r] >= n:
                rows[r].append((int(i), n))
                space[r] -= n
                placed = True
                break
        if not placed:
            rows.append([(int(i), n)])
            space.append(row_len - n)
    R = max(1, len(rows))
    if rows_multiple > 1:
        R = ((R + rows_multiple - 1) // rows_multiple) * rows_multiple
    if rows_bucket_pow2:
        mult = max(rows_multiple, 1)
        k = 1 << max(0, (R // mult) - 1).bit_length()  # next pow2 of R/mult
        R = k * mult
    while len(rows) < R:
        rows.append([])

    token_keys = [
        k
        for k, arr in batch.items()
        if k != "attention_mask" and _is_per_token(k, arr, B, L)
    ]
    out: Dict[str, np.ndarray] = {}
    for k in token_keys:
        arr = batch[k]
        buf = np.zeros((R, row_len, *arr.shape[2:]), dtype=arr.dtype)
        for r, row in enumerate(rows):
            ofs = 0
            for i, n in row:
                buf[r, ofs : ofs + n] = arr[i, :n]
                ofs += n
        out[k] = buf
    seg = np.full((R, row_len), -1, dtype=np.int32)
    pos = np.zeros((R, row_len), dtype=np.int32)
    for r, row in enumerate(rows):
        ofs = 0
        for s, (i, n) in enumerate(row):
            seg[r, ofs : ofs + n] = s
            pos[r, ofs : ofs + n] = np.arange(n, dtype=np.int32)
            ofs += n
    out["segment_ids"] = seg
    out["positions"] = pos
    return RowPackedBatch(data=out, placements=rows, row_len=row_len)


def unpack_rows(
    rp: RowPackedBatch, row_outputs: np.ndarray, batch_size: int, max_len: int
) -> np.ndarray:
    """Per-token row outputs [R, row_len, ...] -> padded [B, max_len, ...]."""
    out = np.zeros((batch_size, max_len, *row_outputs.shape[2:]), row_outputs.dtype)
    for r, row in enumerate(rp.placements):
        ofs = 0
        for i, n in row:
            out[i, :n] = row_outputs[r, ofs : ofs + n]
            ofs += n
    return out
