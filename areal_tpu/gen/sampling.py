"""Batched token sampling, shape-static for the decode jit.

Counterpart of the sampling the reference delegates to SGLang/vLLM servers
(temperature / top-k / top-p / greedy, areal/api/cli_args.py
GenerationHyperparameters).  Per-slot parameters are arrays so one compiled
step serves heterogeneous requests.  Unrestricted slots (top_k<=0 and
top_p>=1) sample from the full-vocab categorical so the behavior
distribution exactly matches the reported full-vocab log-softmax logprobs
(the PPO importance ratios depend on this agreement); restricted slots run
top-k/top-p inside a static `TOPK_WINDOW`-wide candidate window
(lax.top_k), exact whenever the nucleus fits the window.
"""

from typing import Dict

import jax
import jax.numpy as jnp

TOPK_WINDOW = 64
NEG_INF = -1e30


def _masked_window(
    logits: jax.Array,  # [S, V] fp32
    temperature: jax.Array,  # [S]; 0 = greedy
    top_k: jax.Array,  # [S] int32; 0 = disabled
    top_p: jax.Array,  # [S]; 1.0 = disabled
):
    """Shared masking front half: temperature-scale, take the static
    candidate window, apply top-k/top-p.  Returns
    (scaled [S, V], masked window logits [S, W], window idx [S, W], greedy
    [S])."""
    S, V = logits.shape
    logits = logits.astype(jnp.float32)
    greedy = temperature <= 0.0
    safe_temp = jnp.where(greedy, 1.0, temperature)
    scaled = logits / safe_temp[:, None]

    # candidate window (static shape; clamped for tiny vocabularies —
    # lax.top_k rejects k > V)
    window = min(TOPK_WINDOW, V)
    win_logits, win_idx = jax.lax.top_k(scaled, window)  # [S, W]
    ranks = jnp.arange(window)[None, :]
    # top-k mask (0 = off)
    k = jnp.where(top_k <= 0, window, jnp.minimum(top_k, window))
    keep = ranks < k[:, None]
    # top-p mask over the window distribution
    win_probs = jax.nn.softmax(win_logits, axis=-1)
    cum = jnp.cumsum(win_probs, axis=-1)
    keep &= (cum - win_probs) < top_p[:, None]  # keep first token exceeding p
    keep |= ranks == 0  # top_p=0 must mean near-greedy, never mask everything
    masked = jnp.where(keep, win_logits, NEG_INF)
    return scaled, masked, win_idx, greedy


def _token_logprob(scaled: jax.Array, tokens: jax.Array) -> jax.Array:
    logz = jax.nn.logsumexp(scaled, axis=-1)
    tok_logit = jnp.take_along_axis(scaled, tokens[:, None], axis=-1)[:, 0]
    return tok_logit - logz


def sample_tokens(
    logits: jax.Array,  # [S, V] fp32
    rng: jax.Array,
    temperature: jax.Array,  # [S]; 0 = greedy
    top_k: jax.Array,  # [S] int32; 0 = disabled
    top_p: jax.Array,  # [S]; 1.0 = disabled
):
    """Returns (tokens [S], logprobs [S]) — logprob of the sampled token
    under the *unmodified* (temperature-scaled) distribution, matching what
    inference servers report and what decoupled PPO consumes."""
    scaled, masked, win_idx, greedy = _masked_window(
        logits, temperature, top_k, top_p
    )
    rng_win, rng_full = jax.random.split(rng)
    choice = jax.random.categorical(rng_win, masked, axis=-1)  # [S] window index
    sampled = jnp.take_along_axis(win_idx, choice[:, None], axis=-1)[:, 0]
    # unrestricted slots: full-vocab categorical (behavior == reported
    # logprobs); skipped entirely when every slot is restricted
    unrestricted = (top_k <= 0) & (top_p >= 1.0)
    full_sampled = jax.lax.cond(
        jnp.any(unrestricted),
        lambda: jax.random.categorical(rng_full, scaled, axis=-1),
        lambda: sampled,
    )
    sampled = jnp.where(unrestricted, full_sampled, sampled)
    tokens = jnp.where(greedy, win_idx[:, 0], sampled)
    return tokens, _token_logprob(scaled, tokens)


def sample_tokens_keyed(
    logits: jax.Array,  # [S, V] fp32
    keys: jax.Array,  # [S] per-slot PRNG keys (vmapped leading axis)
    temperature: jax.Array,  # [S]; 0 = greedy
    top_k: jax.Array,  # [S] int32; 0 = disabled
    top_p: jax.Array,  # [S]; 1.0 = disabled
):
    """`sample_tokens` with one independent PRNG key PER ROW.

    The batch-keyed sampler draws its noise as one [S, ...] tensor, so a
    row's draw depends on the batch SHAPE — splitting the slot grid into
    length-cohort tiers (ISSUE 5) would change every slot's stream.  Keyed
    per row (the engine derives key = fold(decode_key, stream_id, position)
    — a counter-based scheme), a slot's tokens are a function of its own
    (key, logits) only, so any partitioning of slots into decode dispatches
    yields identical streams: the tiered-vs-untiered parity contract."""
    scaled, masked, win_idx, greedy = _masked_window(
        logits, temperature, top_k, top_p
    )
    split2 = jax.vmap(lambda k: jax.random.split(k, 2))(keys)  # [S, 2, ...]
    rng_win, rng_full = split2[:, 0], split2[:, 1]
    choice = jax.vmap(jax.random.categorical)(rng_win, masked)  # [S]
    sampled = jnp.take_along_axis(win_idx, choice[:, None], axis=-1)[:, 0]
    unrestricted = (top_k <= 0) & (top_p >= 1.0)
    full_sampled = jax.lax.cond(
        jnp.any(unrestricted),
        lambda: jax.vmap(jax.random.categorical)(rng_full, scaled),
        lambda: sampled,
    )
    sampled = jnp.where(unrestricted, full_sampled, sampled)
    tokens = jnp.where(greedy, win_idx[:, 0], sampled)
    return tokens, _token_logprob(scaled, tokens)
