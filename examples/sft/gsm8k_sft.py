"""GSM8K SFT — supervised finetuning entry point.

Behavioral counterpart of the reference's SFT example family
(examples/ -> areal/engine/sft/lm_engine.py path): tokenize
(prompt, solution) pairs with the chat template, train the LM loss on the
solution span only, evaluate perplexity on the valid split each epoch.

Launch:  python examples/sft/gsm8k_sft.py --config examples/sft/gsm8k_sft.yaml
"""

import sys

import numpy as np

from areal_tpu.api.config import SFTConfig, load_expr_config
from areal_tpu.api.io_struct import FinetuneSpec, SaveLoadMeta, StepInfo
from areal_tpu.dataset import get_custom_dataset
from areal_tpu.engine.sft import JaxLMEngine
from areal_tpu.utils import logging, seeding, stats
from areal_tpu.utils.data import pad_sequences_to_tensors
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.stats_logger import StatsLogger

logger = logging.getLogger("gsm8k_sft")


def tokenize_sample(sample, tokenizer, max_length):
    """(messages, answer) -> input_ids + loss_mask over the answer span."""
    prompt_ids = tokenizer.apply_chat_template(
        sample["messages"], add_generation_prompt=True, tokenize=True
    )
    answer_ids = tokenizer.encode(
        str(sample["answer"]), add_special_tokens=False
    )
    if tokenizer.eos_token_id is not None:
        answer_ids = answer_ids + [tokenizer.eos_token_id]
    ids = (prompt_ids + answer_ids)[:max_length]
    n_prompt = min(len(prompt_ids), len(ids))
    loss_mask = [0.0] * n_prompt + [1.0] * (len(ids) - n_prompt)
    return {
        "input_ids": np.asarray(ids, np.int32),
        "loss_mask": np.asarray(loss_mask, np.float32),
    }


def collate(samples, tokenizer, max_length):
    rows = [tokenize_sample(s, tokenizer, max_length) for s in samples]
    return pad_sequences_to_tensors(rows)


def main(argv):
    config, _ = load_expr_config(argv, SFTConfig)
    seeding.set_random_seed(config.seed, "sft")

    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(
        config.tokenizer_path or config.model.path
    )

    train_dataset = get_custom_dataset(
        path=config.train_dataset.path,
        type=config.train_dataset.type,
        split="train",
        tokenizer=tokenizer,
        max_length=config.train_dataset.max_length,
    )
    dataloader = StatefulDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        drop_last=config.train_dataset.drop_last,
        seed=config.seed,
    )
    steps_per_epoch = len(dataloader)
    total_steps = config.total_train_steps or (
        config.total_train_epochs * steps_per_epoch
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=len(train_dataset),
        train_batch_size=config.train_dataset.batch_size,
    )

    engine = JaxLMEngine(config.model)
    engine.initialize(ft_spec=ft_spec)
    saver = Saver(config.saver, ft_spec)
    stats_logger = StatsLogger(config.stats_logger)
    max_len = config.train_dataset.max_length or 1024

    global_step = 0
    step_info = StepInfo(
        global_step=0, epoch=0, epoch_step=0, steps_per_epoch=steps_per_epoch
    )
    for epoch in range(config.total_train_epochs):
        for epoch_step, samples in enumerate(dataloader):
            if global_step >= total_steps:
                break
            batch = collate(samples, tokenizer, max_len)
            with stats.DEFAULT_TRACKER.scope("sft"):
                st = engine.train_lm(batch)
                stats.DEFAULT_TRACKER.scalar(
                    **{k: v for k, v in st.items() if np.isscalar(v)}
                )
            engine.step_lr_scheduler()
            step_info = StepInfo(
                global_step=global_step,
                epoch=epoch,
                epoch_step=epoch_step,
                steps_per_epoch=steps_per_epoch,
            )
            saver.save(engine, epoch, epoch_step, global_step, tokenizer=tokenizer)
            stats_logger.commit(
                epoch, epoch_step, global_step,
                [stats.DEFAULT_TRACKER.export()],
            )
            logger.info(
                f"Epoch {epoch + 1}/{config.total_train_epochs} "
                f"Step {epoch_step + 1}/{steps_per_epoch} done. "
                f"loss={st['loss']:.4f} ppl={st['ppl']:.2f}"
            )
            global_step += 1

    engine.save(
        SaveLoadMeta(path=saver.save_path(step_info, "final"), tokenizer=tokenizer)
    )
    stats_logger.close()
    engine.destroy()


if __name__ == "__main__":
    main(sys.argv[1:])
