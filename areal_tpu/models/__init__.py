from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.models.transformer import (
    LMOutput,
    forward,
    forward_lm,
    init_params,
    param_partition_specs,
)

__all__ = [
    "TransformerConfig",
    "LMOutput",
    "forward",
    "forward_lm",
    "init_params",
    "param_partition_specs",
]
