"""Numerics for GAE and loss functions vs independent references
(ports the reference's kernel-vs-reference tests:
realhf/tests/cpp_extensions/test_cugae.py, tests/data/test_dual_clip.py)."""

import numpy as np
import pytest

from areal_tpu.ops import (
    gae_padded,
    gae_segments,
    gather_logprobs,
    gather_logprobs_entropy,
    grpo_loss_fn,
    kl_estimate,
    masked_normalize,
    pairwise_reward_loss_fn,
    ppo_actor_loss_fn,
    ppo_critic_loss_fn,
    sft_loss_fn,
)
from areal_tpu.ops.gae import gae_numpy


def _rand_batch(rng, B=3, L=11):
    lens = rng.integers(2, L + 1, B)
    mask = np.arange(L)[None, :] < lens[:, None]
    rewards = rng.normal(size=(B, L)).astype(np.float32) * mask
    values = rng.normal(size=(B, L)).astype(np.float32) * mask
    return rewards, values, lens, mask


def test_gae_padded_matches_numpy():
    rng = np.random.default_rng(0)
    rewards, values, lens, mask = _rand_batch(rng)
    adv, ret = gae_padded(rewards, values, mask, gamma=0.99, lam=0.95)
    ref_adv, ref_ret = gae_numpy(rewards, values, lens, 0.99, 0.95)
    np.testing.assert_allclose(np.asarray(adv), ref_adv, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret), ref_ret, rtol=1e-5, atol=1e-5)


def test_gae_segments_matches_padded():
    rng = np.random.default_rng(1)
    rewards, values, lens, mask = _rand_batch(rng, B=4, L=9)
    adv_p, _ = gae_padded(rewards, values, mask, gamma=1.0, lam=0.9)
    # pack
    flat_r = np.concatenate([rewards[b, : lens[b]] for b in range(4)])
    flat_v = np.concatenate([values[b, : lens[b]] for b in range(4)])
    seg = np.concatenate([np.full(lens[b], b, np.int32) for b in range(4)])
    # add filler
    flat_r = np.pad(flat_r, (0, 5))
    flat_v = np.pad(flat_v, (0, 5))
    seg = np.pad(seg, (0, 5), constant_values=-1)
    adv_s, _ = gae_segments(flat_r, flat_v, seg, gamma=1.0, lam=0.9)
    adv_s = np.asarray(adv_s)
    ofs = 0
    for b in range(4):
        n = int(lens[b])
        np.testing.assert_allclose(
            adv_s[ofs : ofs + n], np.asarray(adv_p)[b, :n], rtol=1e-5, atol=1e-5
        )
        ofs += n
    assert np.all(adv_s[ofs:] == 0)


def test_gather_logprobs_vs_torch():
    import torch

    rng = np.random.default_rng(2)
    logits = rng.normal(size=(7, 13)).astype(np.float32)
    labels = rng.integers(0, 13, 7)
    ref = (
        torch.log_softmax(torch.from_numpy(logits), dim=-1)
        .gather(-1, torch.from_numpy(labels)[:, None])[:, 0]
        .numpy()
    )
    got = np.asarray(gather_logprobs(logits, labels))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    got2, ent = gather_logprobs_entropy(logits, labels)
    np.testing.assert_allclose(np.asarray(got2), ref, rtol=1e-4, atol=1e-4)
    p = torch.softmax(torch.from_numpy(logits), -1)
    ref_ent = -(p * p.log()).sum(-1).numpy()
    np.testing.assert_allclose(np.asarray(ent), ref_ent, rtol=1e-4, atol=1e-4)


def _np_ppo_loss(logp, old, adv, eps, mask, prox=None, cap=None, c_clip=None):
    """Independent numpy re-derivation of the decoupled loss
    (reference math: areal/utils/functional.py:171-235)."""
    denorm = prox if prox is not None else old
    ratio = np.exp(logp - denorm)
    clipped = np.clip(ratio, 1 - eps, 1 + eps)
    l1, l2 = -adv * ratio, -adv * clipped
    loss = np.maximum(l1, l2)
    if c_clip is not None:
        l3 = np.sign(adv) * c_clip * adv
        loss = np.where(adv < 0, np.minimum(loss, l3), loss)
    if prox is not None:
        w = np.exp(prox - old)
        wmask = (w <= cap) if cap is not None else np.ones_like(w, bool)
        wmask &= mask > 0
        loss = loss * np.where(wmask, w, 0.0)
    return np.sum(loss * mask)


@pytest.mark.parametrize("decoupled", [False, True])
@pytest.mark.parametrize("c_clip", [None, 3.0])
def test_ppo_actor_loss(decoupled, c_clip):
    rng = np.random.default_rng(3)
    T = 50
    logp = rng.normal(scale=0.5, size=T).astype(np.float32)
    old = logp + rng.normal(scale=0.2, size=T).astype(np.float32)
    prox = (logp + rng.normal(scale=0.1, size=T).astype(np.float32)) if decoupled else None
    adv = rng.normal(size=T).astype(np.float32)
    mask = (rng.random(T) > 0.3).astype(np.float32)
    loss, stats = ppo_actor_loss_fn(
        logp, old, adv, 0.2, mask,
        c_clip=c_clip, proximal_logprobs=prox, behav_imp_weight_cap=5.0,
    )
    ref = _np_ppo_loss(logp, old, adv, 0.2, mask, prox, 5.0, c_clip)
    np.testing.assert_allclose(float(loss), ref, rtol=1e-4)
    assert float(stats["n_valid_tokens"]) == mask.sum()


def test_grpo_loss_runs_and_masks():
    rng = np.random.default_rng(4)
    T, V = 12, 29
    logits = rng.normal(size=(T, V)).astype(np.float32)
    batch = {
        "input_ids": rng.integers(0, V, T),
        "loss_mask": (rng.random(T) > 0.4).astype(np.float32),
        "logprobs": rng.normal(scale=0.1, size=T).astype(np.float32),
        "prox_logp": rng.normal(scale=0.1, size=T).astype(np.float32),
        "advantages": rng.normal(size=T).astype(np.float32),
    }
    loss, stats = grpo_loss_fn(logits, batch, eps_clip=0.2)
    assert np.isfinite(float(loss))
    # zero mask => zero loss
    batch["loss_mask"] = np.zeros(T, np.float32)
    loss0, _ = grpo_loss_fn(logits, batch, eps_clip=0.2)
    assert float(loss0) == 0.0


def test_critic_and_sft_and_rw_losses():
    rng = np.random.default_rng(5)
    T, V = 10, 17
    v = rng.normal(size=T).astype(np.float32)
    ov = v + rng.normal(scale=0.05, size=T).astype(np.float32)
    ret = rng.normal(size=T).astype(np.float32)
    mask = np.ones(T, np.float32)
    loss, _ = ppo_critic_loss_fn(v, ov, ret, mask, eps_clip_value=0.2)
    ref_unclipped = 0.5 * np.sum(np.square(v - ret))
    assert float(loss) >= ref_unclipped - 1e-5  # clipping takes the max

    logits = rng.normal(size=(T, V)).astype(np.float32)
    batch = {"input_ids": rng.integers(0, V, T), "loss_mask": mask}
    sloss, sstats = sft_loss_fn(logits, batch)
    assert float(sloss) > 0 and float(sstats["n_valid_tokens"]) == T

    ch, rj = rng.normal(size=4).astype(np.float32), rng.normal(size=4).astype(np.float32)
    rloss, rstats = pairwise_reward_loss_fn(ch, rj)
    ref = -np.sum(np.log(1 / (1 + np.exp(-(ch - rj)))))
    np.testing.assert_allclose(float(rloss), ref, rtol=1e-4)


def test_kl_and_norm_utils():
    rng = np.random.default_rng(6)
    a, b = rng.normal(size=20).astype(np.float32), rng.normal(size=20).astype(np.float32)
    np.testing.assert_allclose(np.asarray(kl_estimate(a, b, "k1")), a - b, rtol=1e-6)
    k3 = np.asarray(kl_estimate(a, b, "k3"))
    assert np.all(k3 >= -1e-6)  # k3 is non-negative
    x = rng.normal(loc=3.0, scale=2.0, size=100).astype(np.float32)
    mask = np.ones_like(x)
    y = np.asarray(masked_normalize(x, mask))
    assert abs(y.mean()) < 1e-3 and abs(y.std() - 1.0) < 1e-2


def _gae_holes_loop(rewards, values, mask, gamma, lam):
    """Independent loop with the reference's frozen-carry hole semantics
    (areal/engine/ppo/actor.py:146-151)."""
    B, L = rewards.shape
    adv = np.zeros((B, L), np.float64)
    for b in range(B):
        lastgaelam, nextvalues = 0.0, 0.0
        for t in reversed(range(L)):
            delta = rewards[b, t] + gamma * nextvalues - values[b, t]
            newgaelam = delta + gamma * lam * lastgaelam
            if mask[b, t]:
                lastgaelam = newgaelam
                nextvalues = values[b, t]
                adv[b, t] = lastgaelam
    return adv


def test_gae_padded_freezes_carry_across_mask_holes():
    """Multi-turn loss masks have interior holes (user tokens); the carry and
    bootstrap must skip them, not decay through them."""
    rng = np.random.default_rng(3)
    B, L = 4, 16
    mask = (rng.random((B, L)) > 0.4).astype(np.float32)
    mask[:, -1] = 0.0
    mask[:, 2] = 1.0  # ensure some loss tokens
    rewards = rng.normal(size=(B, L)).astype(np.float32) * mask
    values = rng.normal(size=(B, L)).astype(np.float32) * mask
    adv, ret = gae_padded(rewards, values, mask, gamma=0.9, lam=0.8)
    ref = _gae_holes_loop(rewards, values, mask, 0.9, 0.8)
    np.testing.assert_allclose(np.asarray(adv), ref, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ret), ref + values * mask, rtol=1e-5, atol=1e-5)


def test_gae_segments_holes_match_padded():
    rng = np.random.default_rng(4)
    L = 12
    mask = (rng.random((2, L)) > 0.3).astype(np.float32)
    rewards = rng.normal(size=(2, L)).astype(np.float32) * mask
    values = rng.normal(size=(2, L)).astype(np.float32) * mask
    adv_p, _ = gae_padded(rewards, values, mask, gamma=0.95, lam=0.9)
    seg = np.concatenate([np.zeros(L, np.int32), np.ones(L, np.int32)])
    adv_s, _ = gae_segments(
        rewards.reshape(-1), values.reshape(-1), seg, 0.95, 0.9,
        loss_mask=mask.reshape(-1),
    )
    np.testing.assert_allclose(
        np.asarray(adv_s).reshape(2, L), np.asarray(adv_p), rtol=1e-5, atol=1e-5
    )


def test_dual_clip_mask_counts_activations():
    import jax.numpy as jnp

    # advantage very negative + ratio huge => dual clip engages
    logp = jnp.array([2.0, 0.0])
    old = jnp.array([0.0, 0.0])
    adv = jnp.array([-1.0, 1.0])
    lm = jnp.ones(2)
    _, st = ppo_actor_loss_fn(logp, old, adv, eps_clip=0.2, loss_mask=lm, c_clip=3.0)
    # position 0 (adv<0, ratio=e^2): dual clip binds; position 1 does not
    assert float(st["dual_clip_ratio"]) == 1.0


def test_sampling_unrestricted_full_vocab():
    """top_k=0, top_p=1 must be able to emit tokens beyond the top-64
    window (ADVICE r1: behavior policy must match reported logprobs)."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.gen.sampling import sample_tokens

    S, V = 64, 256
    # near-uniform logits: window-truncated sampling could only ever emit
    # 64 distinct tokens; full-vocab sampling covers far more
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 0.01, (S, V)).astype(np.float32))
    seen = set()
    for i in range(8):
        toks, lps = sample_tokens(
            logits,
            jax.random.PRNGKey(i),
            temperature=jnp.ones(S),
            top_k=jnp.zeros(S, jnp.int32),
            top_p=jnp.ones(S),
        )
        seen.update(np.asarray(toks).tolist())
        assert np.all(np.isfinite(np.asarray(lps)))
    assert len(seen) > 64, f"only {len(seen)} distinct tokens: still truncated"
    # restricted slots still honour top_k
    toks, _ = sample_tokens(
        logits,
        jax.random.PRNGKey(99),
        temperature=jnp.ones(S),
        top_k=jnp.full(S, 2, jnp.int32),
        top_p=jnp.ones(S),
    )
    top2 = np.argsort(np.asarray(logits), axis=-1)[:, -2:]
    assert all(t in top2[i] for i, t in enumerate(np.asarray(toks).tolist()))


def test_chunked_lm_head_matches_dense():
    """LMOutput chunked scan == dense logits path, values and gradients."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.models.transformer import LMOutput
    from areal_tpu.ops.functional import lm_logprobs_entropy

    rng = np.random.default_rng(5)
    B, T, D, V = 2, 12, 16, 37
    hidden = jnp.asarray(rng.normal(size=(B, T, D)), jnp.float32)
    head = jnp.asarray(rng.normal(size=(D, V)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (B, T)))

    logits = hidden @ head
    lp_d, ent_d, corr_d = lm_logprobs_entropy(logits, labels, temperature=0.7)
    lp_c, ent_c, corr_c = lm_logprobs_entropy(
        LMOutput(hidden, head), labels, temperature=0.7, chunk=8
    )
    np.testing.assert_allclose(np.asarray(lp_c), np.asarray(lp_d), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ent_c), np.asarray(ent_d), rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(corr_c), np.asarray(corr_d))

    def loss_dense(hidden, head):
        lp, ent, _ = lm_logprobs_entropy(hidden @ head, labels)
        return (lp + 0.1 * ent).sum()

    def loss_chunk(hidden, head):
        lp, ent, _ = lm_logprobs_entropy(LMOutput(hidden, head), labels, chunk=8)
        return (lp + 0.1 * ent).sum()

    gd = jax.grad(loss_dense, argnums=(0, 1))(hidden, head)
    gc = jax.grad(loss_chunk, argnums=(0, 1))(hidden, head)
    for a, b in zip(gc, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_grpo_loss_accepts_lm_output():
    import jax.numpy as jnp

    from areal_tpu.models.transformer import LMOutput
    from areal_tpu.ops.functional import grpo_loss_fn

    rng = np.random.default_rng(6)
    T, D, V = 16, 8, 23
    hidden = jnp.asarray(rng.normal(size=(1, T, D)), jnp.float32)
    head = jnp.asarray(rng.normal(size=(D, V)), jnp.float32)
    batch = {
        "input_ids": jnp.asarray(rng.integers(0, V, (1, T))),
        "loss_mask": jnp.asarray((rng.random((1, T)) > 0.3).astype(np.float32)),
        "logprobs": jnp.asarray(rng.normal(-1.0, 0.2, (1, T)).astype(np.float32)),
        "advantages": jnp.asarray(rng.normal(size=(1, T)).astype(np.float32)),
    }
    batch["prox_logp"] = batch["logprobs"]
    loss_d, stats_d = grpo_loss_fn(hidden @ head, batch, eps_clip=0.2)
    loss_c, stats_c = grpo_loss_fn(LMOutput(hidden, head), batch, eps_clip=0.2)
    np.testing.assert_allclose(float(loss_c), float(loss_d), rtol=1e-5)
    np.testing.assert_allclose(
        float(stats_c["entropy"]), float(stats_d["entropy"]), rtol=1e-5
    )


def test_sampling_vocab_smaller_than_window():
    """lax.top_k rejects k > V: a vocabulary smaller than TOPK_WINDOW (64)
    must clamp the candidate window instead of crashing (regression found
    driving the gen server with a 61-token tiny model)."""
    import jax
    import jax.numpy as jnp

    from areal_tpu.gen.sampling import sample_tokens

    S, V = 4, 61
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(0, 1.0, (S, V)).astype(np.float32))
    toks, lps = sample_tokens(
        logits,
        jax.random.PRNGKey(0),
        temperature=jnp.array([0.0, 1.0, 1.0, 1.0]),
        top_k=jnp.array([0, 0, 5, 0], jnp.int32),
        top_p=jnp.array([1.0, 1.0, 1.0, 0.9]),
    )
    toks = np.asarray(toks)
    assert toks.shape == (S,) and (0 <= toks).all() and (toks < V).all()
    assert toks[0] == int(np.argmax(np.asarray(logits)[0]))  # greedy slot
    assert np.all(np.isfinite(np.asarray(lps)))
