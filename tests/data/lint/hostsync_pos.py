"""C2 positive fixture (marked hot): host syncs + recompile hazards.

Expected findings: host-sync (np.asarray/float on a jitted result),
host-item (.item()), unbucketed-shape (len()-derived int into a jitted
call), host-upload (jnp.asarray(self.<attr>) re-uploaded per dispatch).
"""
# areal-lint: hot-path

import jax
import jax.numpy as jnp
import numpy as np


def decode_loop(self, prompts):
    toks, cache = self._decode_fn(
        self.params,
        self.cache,
        jnp.asarray(self.lengths),  # VIOLATION host-upload: standing state
    )
    host = np.asarray(toks)  # VIOLATION host-sync: fence per loop pass
    first = float(toks)  # VIOLATION host-sync: scalar fence
    flag = cache.sum().item()  # VIOLATION host-item
    n = len(prompts)  # un-bucketed shape int
    out = self._prefill_fn(self.params, n)  # VIOLATION unbucketed-shape
    out2 = self._prefill_fn(self.params, len(prompts))  # VIOLATION inline
    return host, first, flag, out, out2


def direct_jit(params, xs):
    y = jax.jit(lambda p: p)(params)
    return int(y)  # VIOLATION host-sync on a jax.jit(...)(...) result
