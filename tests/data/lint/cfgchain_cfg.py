"""C10 fixture: the config side of a clean field -> flag -> engine-kwarg
chain (CFG_DOC in test_lint.py)."""

from dataclasses import dataclass


@dataclass
class TinyServerConfig:
    depth: int = 1
    width: int = 2

    @staticmethod
    def build_cmd(config, port):
        args = [
            "prog",
            f"--depth={config.depth}",
            f"--width={config.width}",
        ]
        return " ".join(args)
