"""Anthropic HH-RLHF preference-pair dataset for reward-model training
(reference: areal/dataset/hhrlhf.py get_hhrlhf_rw_dataset).

Rows become {"chosen_ids", "rejected_ids"} token lists — the pairwise
format the Bradley-Terry RW engine consumes (engine/rw/rw_engine.py
interleaves them chosen/rejected).  Offline-friendly: accepts a jsonl file
with {"chosen": str, "rejected": str} rows as well as an HF dataset id.
"""

from typing import Optional

from areal_tpu.dataset import register_dataset


@register_dataset("hhrlhf")
def get_hhrlhf_rw_dataset(
    path: str,
    split: str = "train",
    tokenizer=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    if tokenizer is None:
        raise ValueError("hhrlhf needs a tokenizer to build preference pairs")
    import datasets as hf_datasets

    if path.endswith(".jsonl") or path.endswith(".json"):
        ds = hf_datasets.load_dataset("json", data_files=path, split="train")
    else:
        ds = hf_datasets.load_dataset(path, split=split)

    eos = tokenizer.eos_token or ""

    def process(sample):
        return {
            "chosen_ids": tokenizer.encode(sample["chosen"] + eos),
            "rejected_ids": tokenizer.encode(sample["rejected"] + eos),
        }

    ds = ds.map(process, remove_columns=[
        c for c in ds.column_names if c in ("chosen", "rejected")
    ])
    if max_length is not None:
        ds = ds.filter(
            lambda x: len(x["chosen_ids"]) <= max_length
            and len(x["rejected_ids"]) <= max_length
        )
    return ds
