"""Backend protocol for the areal_tpu JAX generation server.

Counterpart of the reference's `SGLangBackend`/`RemoteSGLangEngine`
(areal/engine/sglang_remote.py:22,173), speaking this framework's own server
wire format (areal_tpu/gen/server.py):

    POST /generate                     {rid, input_ids, sampling_params}
    POST /pause_generation             {}
    POST /continue_generation          {}
    POST /update_weights_from_disk     {path, version}
    POST /update_weights_chunk         {name, dtype, shape, data_b64, ...}
    GET  /health, /metrics

Responses carry `output_tokens`, `output_logprobs`, `stop_reason`
("stop" | "length" | "abort") and the server's current weight `version` so
the client can tag per-token versions without a race.
"""

from typing import Any, Dict

from areal_tpu.api.config import InferenceEngineConfig
from areal_tpu.api.io_struct import (
    HttpGenerationResult,
    HttpRequest,
    ModelRequest,
    WeightUpdateMeta,
    WeightUpdateRequests,
)
from areal_tpu.core.remote import RemoteInfEngine


class JaxBackend:
    def build_generation_request(self, req: ModelRequest) -> HttpRequest:
        g = req.gconfig
        payload = {
            "rid": req.rid,
            # group affinity + fan-out clustering hints (gen/engine.py)
            "group_id": req.group_id,
            "group_n": req.group_n,
            # trajectory-lifecycle trace id (utils/telemetry.py)
            "trace_id": req.trace_id or req.rid,
            "input_ids": list(req.input_ids),
            "sampling_params": {
                "max_new_tokens": g.max_new_tokens,
                "min_new_tokens": g.min_new_tokens,
                "temperature": 0.0 if g.greedy else g.temperature,
                "top_p": g.top_p,
                "top_k": g.top_k,
                "stop_token_ids": list(g.stop_token_ids),
                # frequency_penalty is NOT forwarded: the JAX sampler has no
                # penalty support, and shipping the key would silently imply
                # it does (C8 payload-contract drift class).
            },
        }
        if req.pixel_values is not None:
            import base64

            import numpy as np

            pv = np.ascontiguousarray(req.pixel_values, dtype=np.float32)
            payload["pixel_values_b64"] = base64.b64encode(pv.tobytes()).decode()
            payload["pixel_values_shape"] = list(pv.shape)
            payload["image_grid_thw"] = (
                np.asarray(req.image_grid_thw).reshape(-1, 3).tolist()
            )
        return HttpRequest(endpoint="/generate", payload=payload)

    def parse_generation_response(self, resp: Dict[str, Any]) -> HttpGenerationResult:
        return HttpGenerationResult(
            output_tokens=list(resp["output_tokens"]),
            output_logprobs=list(resp["output_logprobs"]),
            stop_reason=resp["stop_reason"],
            version=int(resp.get("version", -1)),
            cache_hit_tokens=int(resp.get("cache_hit_tokens", 0)),
        )

    def build_pause_request(self) -> HttpRequest:
        return HttpRequest(endpoint="/pause_generation", payload={})

    def build_resume_request(self) -> HttpRequest:
        return HttpRequest(endpoint="/continue_generation", payload={})

    def build_weight_update_requests(
        self, meta: WeightUpdateMeta
    ) -> WeightUpdateRequests:
        if meta.type == "disk":
            payload: Dict[str, Any] = {"path": meta.path}
            # recovery replays pin the version (see WeightUpdateMeta.version);
            # the server loads exactly path/v{version} instead of the newest
            # snapshot, which may postdate the recovered checkpoint
            if meta.version is not None:
                payload["version"] = int(meta.version)
            return WeightUpdateRequests(
                requests=[
                    HttpRequest(
                        endpoint="/update_weights_from_disk",
                        payload=payload,
                    )
                ]
            )
        if meta.type == "transfer":
            # the trainer already streamed + committed the weights over
            # /update_weights_chunk (JaxTrainEngine._update_weights_transfer);
            # nothing for the client to send
            return WeightUpdateRequests(requests=[])
        raise NotImplementedError(f"weight update type {meta.type!r}")


class RemoteJaxEngine(RemoteInfEngine):
    """Inference-engine client for areal_tpu generation servers."""

    def __init__(self, config: InferenceEngineConfig):
        super().__init__(config, backend=JaxBackend())
