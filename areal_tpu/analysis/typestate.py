"""C7 — slot / cache-row lifecycle typestate.

The engine's slot machine is a typestate automaton the ROADMAP-item-1
radix/paged-KV refactor must preserve:

    free -> reserved -> prefilled -> decoding -> retained/free

with cache-row ownership riding along (``kv_version`` stamps rows against
stale reuse after a weight publish; a migration's source row frees as a
*retained prefix*).  The automaton is declared on the class:

    class GenEngine:
        _SLOT_TYPESTATE = {
            "owner": "slot_req",          # slot s is owned iff owner[s] is not None
            "acquire_writes": [...],      # per-slot arrays an acquire MUST settle
            "release_writes": [...],      # the only arrays writable after release
            "version_field": "kv_version",
            "retained_field": "retained_len",
        }

Rules (anchored on every ``self.<owner>[idx] = ...`` transition):

- ``slot-double-free``: the same block frees ``owner[idx]`` twice with no
  intervening re-acquire — the second free clobbers a slot that may have
  been re-admitted concurrently.
- ``slot-lifecycle``: an *acquire* (``owner[idx] = <req>``) that does not
  settle every ``acquire_writes`` array for the same index in the same
  block (a reservation/bookkeeping leak: the slot decodes with a stale
  temperature, kv_version, or an un-cleared ``_reserved_until``); a
  *release* (``owner[idx] = None``) that does not settle
  ``retained_field``; or a write to a non-release array for an index the
  block already freed (use-after-free of the row's bookkeeping).
- ``retained-unversioned``: a method that acquires slots AND reads
  ``retained_field`` (i.e. makes reuse decisions over retained rows) must
  also read ``version_field`` — reusing a retained prefix without
  consulting its version resurrects pre-publish K/V.

Co-writes may be delegated: a helper called in the same block satisfies a
required write when its **transitive** field-write summary (fixpoint over
the call graph) covers the field — the interprocedural part, so the
checker keeps up when the refactor extracts ``_activate_slot`` helpers.

The ``for arr in (self.a, self.b, ...): arr[dst] = arr[s]`` idiom
(migration state copy) counts as writing every tuple element.
"""

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from areal_tpu.analysis.callgraph import CallGraph, fixpoint
from areal_tpu.analysis.core import Finding, SourceFile, apply_suppression

RULE_DOUBLE_FREE = "slot-double-free"
RULE_LIFECYCLE = "slot-lifecycle"
RULE_UNVERSIONED = "retained-unversioned"


@dataclass
class TypestateSpec:
    owner: str
    acquire_writes: List[str]
    release_writes: List[str]
    version_field: str
    retained_field: str


def _literal(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def _parse_spec(
    sf: SourceFile, cls: ast.ClassDef, findings: List[Finding]
) -> Optional[TypestateSpec]:
    for stmt in cls.body:
        if not isinstance(stmt, ast.Assign):
            continue
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name) and tgt.id == "_SLOT_TYPESTATE":
                val = _literal(stmt.value)
                if (
                    not isinstance(val, dict)
                    or not isinstance(val.get("owner"), str)
                    or not isinstance(val.get("acquire_writes"), list)
                ):
                    findings.append(
                        apply_suppression(
                            sf,
                            Finding(
                                "guard-syntax",
                                sf.rel,
                                stmt.lineno,
                                "_SLOT_TYPESTATE must be a literal dict "
                                "with 'owner' and 'acquire_writes' (see "
                                "docs/lint.md)",
                            ),
                        )
                    )
                    return None
                return TypestateSpec(
                    owner=val["owner"],
                    acquire_writes=list(val["acquire_writes"]),
                    release_writes=list(val.get("release_writes", [])),
                    version_field=val.get("version_field", "kv_version"),
                    retained_field=val.get("retained_field", "retained_len"),
                )
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _subscript_write(stmt: ast.Assign) -> List[Tuple[str, str, ast.AST]]:
    """[(field, index source text, value)] for `self.<field>[idx] = v`."""
    out = []
    for tgt in stmt.targets:
        if isinstance(tgt, ast.Subscript):
            fld = _self_attr(tgt.value)
            if fld is not None:
                out.append((fld, ast.unparse(tgt.slice), stmt.value))
    return out


def _function_write_sets(graph: CallGraph) -> Dict[str, Set[str]]:
    """key -> self-attribute names the function (transitively) writes."""
    local: Dict[str, Set[str]] = {}
    for key, fi in graph.functions.items():
        writes: Set[str] = set()
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Assign):
                for tgt in n.targets:
                    base = tgt
                    if isinstance(base, ast.Subscript):
                        base = base.value
                    fld = _self_attr(base)
                    if fld is not None:
                        writes.add(fld)
            elif isinstance(n, ast.AugAssign):
                base = n.target
                if isinstance(base, ast.Subscript):
                    base = base.value
                fld = _self_attr(base)
                if fld is not None:
                    writes.add(fld)
            elif isinstance(n, ast.For) and isinstance(
                n.iter, (ast.Tuple, ast.List)
            ):
                # for arr in (self.a, self.b): arr[i] = ... writes a and b
                if any(
                    isinstance(b, ast.Assign)
                    and any(
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        for t in b.targets
                    )
                    for b in ast.walk(n)
                ):
                    for el in n.iter.elts:
                        fld = _self_attr(el)
                        if fld is not None:
                            writes.add(fld)
        local[key] = writes
    edges = {
        key: [c for _, c in graph.calls.get(key, ()) if c is not None]
        for key in graph.functions
    }
    return fixpoint(local, edges)


@dataclass
class _BlockWrite:
    line: int
    field: str
    index: str
    is_none: bool  # owner write of None (release)


def _innermost_transitions(
    meth: ast.AST, spec: TypestateSpec
) -> Dict[int, Tuple[List[ast.stmt], List[_BlockWrite]]]:
    """Owner transitions grouped by their INNERMOST enclosing statement
    list (keyed by id(block)).  Each transition is analyzed exactly once,
    against the tightest scope that contains it — the block where its
    required co-writes live in every in-tree transition site."""
    out: Dict[int, Tuple[List[ast.stmt], List[_BlockWrite]]] = {}

    def visit(stmt: ast.stmt, block: List[ast.stmt]) -> None:
        if isinstance(stmt, ast.Assign):
            for fld, idx, val in _subscript_write(stmt):
                if fld == spec.owner:
                    tw = _BlockWrite(
                        stmt.lineno,
                        fld,
                        idx,
                        isinstance(val, ast.Constant) and val.value is None,
                    )
                    out.setdefault(id(block), (block, []))[1].append(tw)
        for fname in ("body", "orelse", "finalbody"):
            child = getattr(stmt, fname, None)
            if isinstance(child, list):
                for s in child:
                    visit(s, child)
        for h in getattr(stmt, "handlers", []) or []:
            for s in h.body:
                visit(s, h.body)

    for s in meth.body:
        visit(s, meth.body)
    return out


def _block_facts(
    block: List[ast.stmt], spec: TypestateSpec
) -> Tuple[Set[Tuple[str, str]], List[Tuple[int, str]]]:
    """((field, idx) writes available as co-writes, helper calls
    (line, attr name)) — searched recursively through the whole block, so
    co-writes inside the same `for`/`if` count."""
    cowrites: Set[Tuple[str, str]] = set()
    helper_calls: List[Tuple[int, str]] = []
    for stmt in block:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Assign):
                for fld, idx, val in _subscript_write(n):
                    if fld != spec.owner:
                        cowrites.add((fld, idx))
                # for arr in (self.a, ...): arr[idx] = ... expansion
            elif isinstance(n, ast.For) and isinstance(
                n.iter, (ast.Tuple, ast.List)
            ):
                loop_var = (
                    n.target.id if isinstance(n.target, ast.Name) else None
                )
                if loop_var is None:
                    continue
                idxs = [
                    ast.unparse(t.slice)
                    for b in ast.walk(n)
                    if isinstance(b, ast.Assign)
                    for t in b.targets
                    if isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == loop_var
                ]
                for el in n.iter.elts:
                    fld = _self_attr(el)
                    if fld is not None:
                        for idx in idxs:
                            cowrites.add((fld, idx))
            elif isinstance(n, ast.Call):
                fn = n.func
                if (
                    isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "self"
                ):
                    helper_calls.append((n.lineno, fn.attr))
    return cowrites, helper_calls


def check_typestate(files: Dict[str, SourceFile]) -> List[Finding]:
    graph = CallGraph(files)
    write_sets = _function_write_sets(graph)
    findings: List[Finding] = []

    for ci in graph.classes.values():
        spec = _parse_spec(ci.sf, ci.node, findings)
        if spec is None:
            continue
        sf = ci.sf
        release_ok = set(spec.release_writes) | {spec.retained_field}
        for meth in ci.node.body:
            if (
                not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef))
                or meth.name == "__init__"
            ):
                continue
            acquired_any = False
            for block, transitions in _innermost_transitions(
                meth, spec
            ).values():
                cowrites, helper_calls = _block_facts(block, spec)

                def helper_writes(fld: str) -> bool:
                    for _, attr in helper_calls:
                        mkey = ci.methods.get(attr)
                        if mkey and fld in write_sets.get(mkey, ()):
                            return True
                    return False

                # double-free: two releases of one index, no re-acquire
                # between them
                by_idx: Dict[str, List[_BlockWrite]] = {}
                for t in transitions:
                    by_idx.setdefault(t.index, []).append(t)
                for idx, ts in by_idx.items():
                    ts.sort(key=lambda t: t.line)
                    for prev, cur in zip(ts, ts[1:]):
                        if prev.is_none and cur.is_none:
                            findings.append(
                                apply_suppression(
                                    sf,
                                    Finding(
                                        RULE_DOUBLE_FREE,
                                        sf.rel,
                                        cur.line,
                                        f"{ci.name}.{spec.owner}[{idx}] "
                                        f"freed twice (also at line "
                                        f"{prev.line}) with no re-acquire "
                                        f"between — the second free can "
                                        f"clobber a re-admitted slot",
                                    ),
                                )
                            )

                for t in transitions:
                    # a later re-acquire of the same index re-opens the
                    # slot: writes past it are the new owner's, not
                    # use-after-free
                    reacquire = min(
                        (
                            x.line
                            for x in by_idx.get(t.index, ())
                            if x.line > t.line and not x.is_none
                        ),
                        default=None,
                    )
                    if t.is_none:
                        # release must settle the retained prefix length
                        if (
                            (spec.retained_field, t.index) not in cowrites
                            and not helper_writes(spec.retained_field)
                        ):
                            findings.append(
                                apply_suppression(
                                    sf,
                                    Finding(
                                        RULE_LIFECYCLE,
                                        sf.rel,
                                        t.line,
                                        f"{ci.name}.{spec.owner}"
                                        f"[{t.index}] freed without "
                                        f"settling "
                                        f"{spec.retained_field}[{t.index}]"
                                        f" — the next reuse pass reads a "
                                        f"stale retained prefix length",
                                    ),
                                )
                            )
                        # use-after-free: non-release bookkeeping written
                        # for this index after the free
                        for fld, idx in sorted(cowrites):
                            if (
                                idx == t.index
                                and fld in spec.acquire_writes
                                and fld not in release_ok
                            ):
                                line = _first_write_after(
                                    block, fld, idx, t.line
                                )
                                if line is not None and (
                                    reacquire is None or line < reacquire
                                ):
                                    findings.append(
                                        apply_suppression(
                                            sf,
                                            Finding(
                                                RULE_LIFECYCLE,
                                                sf.rel,
                                                line,
                                                f"{ci.name}.{fld}"
                                                f"[{idx}] written after "
                                                f"{spec.owner}[{idx}] was "
                                                f"freed at line {t.line} "
                                                f"— bookkeeping for a "
                                                f"slot this path no "
                                                f"longer owns",
                                            ),
                                        )
                                    )
                    else:
                        acquired_any = True
                        missing = [
                            fld
                            for fld in spec.acquire_writes
                            if (fld, t.index) not in cowrites
                            and not helper_writes(fld)
                        ]
                        if missing:
                            findings.append(
                                apply_suppression(
                                    sf,
                                    Finding(
                                        RULE_LIFECYCLE,
                                        sf.rel,
                                        t.line,
                                        f"{ci.name}.{spec.owner}"
                                        f"[{t.index}] acquired without "
                                        f"settling {missing} for the "
                                        f"same index — the slot decodes "
                                        f"with stale per-slot state "
                                        f"(reservation/bookkeeping "
                                        f"leak)",
                                    ),
                                )
                            )
            if acquired_any and _true_loads(meth, spec.retained_field):
                if not _true_loads(meth, spec.version_field):
                    findings.append(
                        apply_suppression(
                            sf,
                            Finding(
                                RULE_UNVERSIONED,
                                sf.rel,
                                meth.lineno,
                                f"{ci.name}.{meth.name} acquires slots "
                                f"and reads {spec.retained_field} but "
                                f"never consults {spec.version_field} — "
                                f"a retained row can be reused across a "
                                f"weight publish without a version "
                                f"check",
                            ),
                        )
                    )

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _first_write_after(
    block: List[ast.stmt], fld: str, idx: str, after_line: int
) -> Optional[int]:
    best: Optional[int] = None
    for stmt in block:
        for n in ast.walk(stmt):
            if not isinstance(n, ast.Assign) or n.lineno <= after_line:
                continue
            for f2, i2, _ in _subscript_write(n):
                if f2 == fld and i2 == idx:
                    if best is None or n.lineno < best:
                        best = n.lineno
    return best


def _true_loads(meth: ast.AST, fld: str) -> bool:
    """A genuine read of self.<fld>: an Attribute Load that is not merely
    the base of a subscript STORE (``self.x[i] = v`` loads ``self.x`` per
    the AST but writes semantically)."""
    store_bases = set()
    for n in ast.walk(meth):
        if isinstance(n, ast.Subscript) and isinstance(
            n.ctx, (ast.Store, ast.Del)
        ):
            store_bases.add(id(n.value))
    for n in ast.walk(meth):
        if (
            isinstance(n, ast.Attribute)
            and n.attr == fld
            and isinstance(n.value, ast.Name)
            and n.value.id == "self"
            and isinstance(n.ctx, ast.Load)
            and id(n) not in store_bases
        ):
            return True
    return False
