"""Vision tower parity vs the REAL transformers Qwen2.5-VL implementation
(ADVICE r2: the visual.* maps must match real checkpoints, and the tower
needs 2D rotary + biases to compute the same features).

A tiny Qwen2_5_VLForConditionalGeneration is saved with save_pretrained and
loaded through this repo's converter; the towers must then produce the same
merged embeddings, and the name map must round-trip."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")


def _tiny_hf_model(tmp_path):
    from transformers import Qwen2_5_VLConfig, Qwen2_5_VLForConditionalGeneration

    cfg = Qwen2_5_VLConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        tie_word_embeddings=False,
        image_token_id=120,
        video_token_id=121,
        vision_start_token_id=118,
        vision_end_token_id=119,
        rope_scaling={"type": "mrope", "mrope_section": [1, 1, 2]},
        vision_config=dict(
            depth=2,
            hidden_size=32,
            intermediate_size=64,
            num_heads=2,  # head_dim 16 -> 2D rope quarter = 4
            in_channels=3,
            patch_size=2,
            temporal_patch_size=2,
            spatial_merge_size=2,
            out_hidden_size=32,
            window_size=10_000,  # windows larger than any test image
            fullatt_block_indexes=[0, 1],  # full attention everywhere
            tokens_per_second=2,
        ),
    )
    torch.manual_seed(0)
    model = Qwen2_5_VLForConditionalGeneration(cfg)
    model = model.eval().to(torch.float32)
    d = tmp_path / "hf"
    model.save_pretrained(str(d))
    return model, str(d)


def test_vision_tower_matches_transformers(tmp_path):
    from areal_tpu.models.hf import load_hf_params
    from areal_tpu.models.vision import vision_forward, vision_rot_pos_ids

    model, path = _tiny_hf_model(tmp_path)
    params, cfg = load_hf_params(path, dtype="float32")
    assert "vision" in params, "visual.* tree failed to map"
    assert cfg.vision is not None and cfg.image_token_id == 120

    # one 4x4-patch image (t=1): N=16 patches, 4 merged embeddings
    rng = np.random.default_rng(0)
    grid = np.array([[1, 4, 4]], np.int64)
    pv = rng.normal(size=(16, cfg.vision.patch_dim)).astype(np.float32)

    with torch.no_grad():
        ref = model.visual(
            torch.from_numpy(pv), grid_thw=torch.from_numpy(grid)
        ).numpy()

    ours = np.asarray(vision_forward(
        params["vision"],
        cfg.vision,
        pv,
        np.zeros(16, np.int32),
        patch_pos_hw=vision_rot_pos_ids(grid, cfg.vision.spatial_merge_size),
    ))
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-5)


def test_vision_tower_windowed_attention_matches_transformers(tmp_path):
    """ADVICE r3: real Qwen2.5-VL checkpoints use WINDOWED attention in most
    blocks (full attention only at fullatt_block_indexes).  An 8x8-patch
    image with window_size=8px (=> 4x4-patch windows) spans 4 windows, so
    this fails if the tower runs full attention everywhere."""
    from transformers import Qwen2_5_VLConfig, Qwen2_5_VLForConditionalGeneration

    cfg_hf = Qwen2_5_VLConfig(
        vocab_size=128,
        hidden_size=32,
        intermediate_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        tie_word_embeddings=False,
        image_token_id=120,
        video_token_id=121,
        vision_start_token_id=118,
        vision_end_token_id=119,
        rope_scaling={"type": "mrope", "mrope_section": [1, 1, 2]},
        vision_config=dict(
            depth=2,
            hidden_size=32,
            intermediate_size=64,
            num_heads=2,
            in_channels=3,
            patch_size=2,
            temporal_patch_size=2,
            spatial_merge_size=2,
            out_hidden_size=32,
            window_size=8,  # 8px / 2px patches / merge 2 -> 4-patch windows
            fullatt_block_indexes=[0],  # block 1 is windowed
            tokens_per_second=2,
        ),
    )
    torch.manual_seed(1)
    model = Qwen2_5_VLForConditionalGeneration(cfg_hf).eval().to(torch.float32)
    d = tmp_path / "hf_win"
    model.save_pretrained(str(d))

    from areal_tpu.models.hf import load_hf_params
    from areal_tpu.models.vision import vision_forward, vision_rot_pos_ids

    params, cfg = load_hf_params(str(d), dtype="float32")
    assert cfg.vision.window_size == 8
    assert cfg.vision.fullatt_block_indexes == (0,)

    rng = np.random.default_rng(2)
    grid = np.array([[1, 8, 8]], np.int64)  # 64 patches, 4 windows
    pv = rng.normal(size=(64, cfg.vision.patch_dim)).astype(np.float32)

    with torch.no_grad():
        ref = model.visual(
            torch.from_numpy(pv), grid_thw=torch.from_numpy(grid)
        ).numpy()

    ours = np.asarray(vision_forward(
        params["vision"],
        cfg.vision,
        pv,
        np.zeros(64, np.int32),
        patch_pos_hw=vision_rot_pos_ids(grid, cfg.vision.spatial_merge_size),
    ))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-5)

    # sanity: full attention everywhere would NOT match (window mask matters)
    full = np.asarray(vision_forward(
        params["vision"],
        cfg.vision.replace(window_size=0),
        pv,
        np.zeros(64, np.int32),
        patch_pos_hw=vision_rot_pos_ids(grid, cfg.vision.spatial_merge_size),
    ))
    assert np.abs(full - ref).max() > 1e-3


def test_vision_checkpoint_roundtrip(tmp_path):
    """our params -> HF names (real Qwen2.5-VL layout) -> our params."""
    from areal_tpu.models.hf import load_hf_params, save_hf_checkpoint

    _, path = _tiny_hf_model(tmp_path)
    params, cfg = load_hf_params(path, dtype="float32")
    out = tmp_path / "roundtrip"
    save_hf_checkpoint(params, cfg, str(out), save_dtype="float32")
    params2, cfg2 = load_hf_params(str(out), dtype="float32")
    assert "vision" in params2
    import jax

    leaves1 = jax.tree_util.tree_leaves_with_path(params["vision"])
    flat2 = dict(jax.tree_util.tree_leaves_with_path(params2["vision"]))
    assert len(leaves1) == len(flat2)
    for key, v1 in leaves1:
        np.testing.assert_allclose(
            np.asarray(v1), np.asarray(flat2[key]), rtol=1e-6,
            err_msg=str(key),
        )


def test_unmappable_vision_degrades_to_text_only(tmp_path, caplog):
    """A Qwen2-VL-style (LayerNorm/fc1-fc2) tower cannot map onto the
    gated-RMSNorm tree: the loader must warn and keep the text weights
    instead of raising (ADVICE r2)."""
    from safetensors.numpy import save_file

    from areal_tpu.models.hf import state_to_params
    from areal_tpu.models.model_config import VisionConfig, tiny_config

    cfg = tiny_config(
        vocab_size=64, qkv_bias=True, hf_architecture="Qwen2VLForConditionalGeneration",
    ).replace(
        vision=VisionConfig(
            patch_size=2, temporal_patch_size=1, in_channels=3,
            hidden_size=16, intermediate_size=32, num_layers=1, num_heads=2,
            spatial_merge_size=2, out_hidden_size=64,
        ),
        image_token_id=60,
    )
    from areal_tpu.models import init_params
    import jax

    host = init_params(cfg, jax.random.PRNGKey(0))
    from areal_tpu.models.hf import params_to_hf_state

    state = {k: np.ascontiguousarray(v) for k, v in params_to_hf_state(host, cfg)}
    # fabricate an old-style Qwen2-VL tower: unmappable mlp.fc1/fc2 + LN bias
    state["visual.patch_embed.proj.weight"] = np.zeros((16, 3, 1, 2, 2), np.float32)
    state["visual.blocks.0.norm1.weight"] = np.ones(16, np.float32)
    state["visual.blocks.0.norm1.bias"] = np.zeros(16, np.float32)
    state["visual.blocks.0.mlp.fc1.weight"] = np.zeros((32, 16), np.float32)
    state["visual.blocks.0.mlp.fc2.weight"] = np.zeros((16, 32), np.float32)

    params = state_to_params(iter(state.items()), cfg, dtype="float32")
    assert "vision" not in params  # degraded, not raised
    assert "embedding" in params and "layers" in params
