"""Sandboxed code-reward tests (reference: functioncall/code local verify +
realhf math/code reward interfaces)."""

import time

import pytest

from areal_tpu.reward.code_verifier import (
    CaseResult,
    code_reward_fn,
    extract_code,
    verify_code,
)


def test_extract_code_prefers_last_fence():
    text = (
        "First try:\n```python\nprint(1)\n```\n"
        "Actually:\n```python\nprint(2)\n```\n"
    )
    assert extract_code(text) == "print(2)"
    assert extract_code("print(3)") == "print(3)"


def test_stdio_pass_and_fail():
    gen = "```python\nx = int(input())\nprint(x * 2)\n```"
    problem = {"inputs": ["3\n", "10\n"], "outputs": ["6\n", "20\n"]}
    results = verify_code(gen, problem)
    assert all(r.passed for r in results)

    bad = "```python\nx = int(input())\nprint(x + 1)\n```"
    results = verify_code(bad, problem)
    assert not any(r.passed for r in results)
    assert "wrong answer" in results[0].reason


def test_numeric_tolerance():
    gen = "```python\nprint(1/3)\n```"
    problem = {"inputs": [""], "outputs": ["0.3333333333\n"]}
    # 0.3333333333333333 vs 0.3333333333 within 1e-6 relative
    assert verify_code(gen, problem)[0].passed


def test_assert_style():
    gen = "```python\ndef f(x):\n    return x * x\n```"
    ok = verify_code(gen, {"asserts": ["assert f(3) == 9"]})
    assert ok[0].passed
    bad = verify_code(gen, {"asserts": ["assert f(3) == 10"]})
    assert not bad[0].passed


def test_crash_and_timeout_and_memory():
    crash = verify_code("raise RuntimeError('boom')", {"inputs": [""], "outputs": [""]})
    assert not crash[0].passed and "exit" in crash[0].reason

    t0 = time.monotonic()
    loop = verify_code(
        "while True:\n    pass", {"inputs": [""], "outputs": [""]}, timeout=1.5
    )
    assert not loop[0].passed and loop[0].reason == "timeout"
    assert time.monotonic() - t0 < 10

    bomb = verify_code(
        "x = bytearray(10**10)\nprint('survived')",
        {"inputs": [""], "outputs": ["survived\n"]},
        timeout=5.0,
        memory_mb=128,
    )
    assert not bomb[0].passed  # allocation refused by RLIMIT_AS


def test_sandbox_env_is_bare():
    # generated code cannot see the parent's environment variables
    import os

    os.environ["AREAL_SECRET_PROBE"] = "leak"
    try:
        res = verify_code(
            "import os\nprint(os.environ.get('AREAL_SECRET_PROBE', 'clean'))",
            {"inputs": [""], "outputs": ["clean\n"]},
        )
        assert res[0].passed
    finally:
        del os.environ["AREAL_SECRET_PROBE"]


def test_reward_fn_surface():
    problem = {"inputs": ["2\n"], "outputs": ["4\n"]}
    good = code_reward_fn(
        "p", "```python\nprint(int(input())**2)\n```", [], [], problem=problem
    )
    bad = code_reward_fn("p", "```python\nprint(5)\n```", [], [], problem=problem)
    assert (good, bad) == (1.0, 0.0)

    import json

    as_str = code_reward_fn(
        "p", "```python\nprint(int(input())**2)\n```", [], [],
        problem=json.dumps(problem),
    )
    assert as_str == 1.0

    with pytest.raises(ValueError):
        code_reward_fn("p", "x", [], [])


# ---------------------------------------------------------------------------
# Service mode (VERDICT r3 missing #5 — the reference's functioncall/ FaaS)
# ---------------------------------------------------------------------------


class _ServiceHarness:
    """Run the verifier service on a background loop (fake-server pattern)."""

    def __init__(self):
        import asyncio
        import threading

        from aiohttp import web

        from areal_tpu.reward.code_verifier_service import CodeVerifierService

        self.service = CodeVerifierService(max_workers=2)
        self.port = None
        started = threading.Event()

        def _run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _serve():
                runner = web.AppRunner(self.service.app())
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                self.port = runner.addresses[0][1]
                self._runner = runner
                started.set()

            self._loop.run_until_complete(_serve())
            self._loop.run_forever()

        import threading as _t

        self._thread = _t.Thread(target=_run, daemon=True)
        self._thread.start()
        assert started.wait(10)
        self.addr = f"127.0.0.1:{self.port}"

    def stop(self):
        import asyncio

        async def _cleanup():
            await self._runner.cleanup()

        asyncio.run_coroutine_threadsafe(_cleanup(), self._loop).result(5)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


def test_service_mode_verifies_remotely():
    h = _ServiceHarness()
    try:
        import requests

        r = requests.post(
            f"http://{h.addr}/verify",
            json={
                "generation": "```python\nprint(int(input())**2)\n```",
                "problem": {"inputs": ["3\n"], "outputs": ["9\n"]},
            },
            timeout=30,
        )
        assert r.status_code == 200
        body = r.json()
        assert body["reward"] == 1.0 and body["results"][0]["passed"]

        r = requests.post(
            f"http://{h.addr}/verify",
            json={"generation": "print(1)",
                  "problem": {"inputs": ["\n"], "outputs": ["2\n"]}},
            timeout=30,
        )
        assert r.json()["reward"] == 0.0

        # malformed problems are a 400, not a worker crash
        r = requests.post(
            f"http://{h.addr}/verify",
            json={"generation": "x", "problem": {"bogus": 1}},
            timeout=30,
        )
        assert r.status_code == 400
        assert requests.get(f"http://{h.addr}/health", timeout=10).json()[
            "served"
        ] == 2
    finally:
        h.stop()


def test_reward_fn_targets_service_env(monkeypatch):
    """code_reward_fn uses AREAL_CODE_VERIFIER_ADDR when set, and falls back
    to the local sandbox when the service is unreachable."""
    h = _ServiceHarness()
    try:
        monkeypatch.setenv("AREAL_CODE_VERIFIER_ADDR", h.addr)
        problem = {"inputs": ["2\n"], "outputs": ["4\n"]}
        good = code_reward_fn(
            "p", "```python\nprint(int(input())**2)\n```", [], [],
            problem=problem,
        )
        assert good == 1.0
        assert h.service.n_served == 1  # it really went through the service
    finally:
        h.stop()

    # dead address: local fallback still produces the right reward
    monkeypatch.setenv("AREAL_CODE_VERIFIER_ADDR", "127.0.0.1:1")
    assert code_reward_fn(
        "p", "```python\nprint(int(input())**2)\n```", [], [],
        problem={"inputs": ["2\n"], "outputs": ["4\n"]},
    ) == 1.0
