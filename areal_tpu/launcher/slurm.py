"""Slurm launcher: sbatch-script generation + submit/babysit/cancel.

Behavioral counterpart of the reference's `SlurmLauncher`
(areal/launcher/slurm.py:46; sbatch generation :93-267): the experiment is
submitted as two Slurm jobs — a generation-server job array and one
multi-task trainer job — wired together through the shared-filesystem
name_resolve store.  TPU-first differences: tasks request
`--gres=tpu:N`-style generic resources instead of GPUs, and the trainer
tasks join one jax.distributed runtime via the AREAL_COORDINATOR /
AREAL_NUM_PROCESSES / AREAL_PROCESS_ID contract (parallel/distributed.py)
with SLURM_PROCID providing the process id.

All slurm binaries are injectable (`sbatch_bin`, ...) so the launcher is
testable on machines without Slurm (the reference tests its sbatch
rendering the same way).
"""

import argparse
import os
import shlex
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from areal_tpu.api.config import GRPOConfig, load_expr_config
from areal_tpu.utils import logging

logger = logging.getLogger("launcher.slurm")

TERMINAL_STATES = {
    "COMPLETED", "FAILED", "CANCELLED", "TIMEOUT", "NODE_FAIL",
    "PREEMPTED", "OUT_OF_MEMORY",
}
COORDINATOR_PORT = 20025


@dataclass
class SlurmJobSpec:
    job_name: str
    cmd: str
    n_tasks: int = 1
    tasks_per_node: int = 1
    cpus_per_task: int = 8
    mem_per_task_mb: int = 32768
    gres: str = ""  # e.g. "tpu:1"
    partition: str = ""
    account: str = ""
    time_limit: str = ""
    container: str = ""  # apptainer/singularity image (reference srun wraps)
    env: Dict[str, str] = field(default_factory=dict)  # static, quoted
    # exported inside each srun task UNQUOTED so $VARS and $(cmds) expand
    # per-task at runtime (e.g. the coordinator-host lookup)
    runtime_env: Dict[str, str] = field(default_factory=dict)
    log_path: str = "slurm-%j.out"


def render_sbatch(spec: SlurmJobSpec) -> str:
    """One sbatch script per job; srun fans the command across tasks with
    SLURM_PROCID exported as the process id (reference slurm.py:93-267)."""
    lines = [
        "#!/bin/bash",
        f"#SBATCH --job-name={spec.job_name}",
        f"#SBATCH --ntasks={spec.n_tasks}",
        f"#SBATCH --ntasks-per-node={spec.tasks_per_node}",
        f"#SBATCH --cpus-per-task={spec.cpus_per_task}",
        f"#SBATCH --mem-per-cpu="
        f"{max(1, spec.mem_per_task_mb // max(1, spec.cpus_per_task))}M",
        f"#SBATCH --output={spec.log_path}",
        "#SBATCH --open-mode=append",
    ]
    if spec.gres:
        lines.append(f"#SBATCH --gres={spec.gres}")
    if spec.partition:
        lines.append(f"#SBATCH --partition={spec.partition}")
    if spec.account:
        lines.append(f"#SBATCH --account={spec.account}")
    if spec.time_limit:
        lines.append(f"#SBATCH --time={spec.time_limit}")
    lines.append("")
    for k, v in spec.env.items():
        lines.append(f"export {k}={shlex.quote(v)}")
    lines.append("")
    # per-task setup must run INSIDE the srun'd shell: at batch-script level
    # SLURM_PROCID is 0 and command substitutions would be expanded once for
    # all tasks; inside `bash -c '...'` each task expands them itself
    per_task = ["export AREAL_PROCESS_ID=$SLURM_PROCID"]
    for k, v in spec.runtime_env.items():
        per_task.append(f"export {k}={v}")  # deliberately unquoted: expands
    inner = "; ".join(per_task + [spec.cmd])
    if spec.container:
        inner = (
            f"apptainer exec --bind {shlex.quote(os.getcwd())} "
            f"{shlex.quote(spec.container)} bash -c {shlex.quote(inner)}"
        )
    lines.append(f"srun --kill-on-bad-exit=1 bash -c {shlex.quote(inner)}")
    lines.append("")
    return "\n".join(lines)


class SlurmLauncher:
    def __init__(
        self,
        entry: str,
        config_args: List[str],
        n_gen_servers: int,
        n_train_procs: int,
        sbatch_bin: str = "sbatch",
        squeue_bin: str = "squeue",
        scancel_bin: str = "scancel",
        sacct_bin: str = "sacct",
        workdir: Optional[str] = None,
    ):
        self.entry = entry
        self.config_args = config_args
        self.config, _ = load_expr_config(config_args, GRPOConfig, ignore_unknown_top=True)
        self.n_gen_servers = n_gen_servers
        self.n_train_procs = n_train_procs
        self.sbatch_bin = sbatch_bin
        self.squeue_bin = squeue_bin
        self.scancel_bin = scancel_bin
        self.sacct_bin = sacct_bin
        self.workdir = workdir or os.getcwd()
        self.job_ids: List[str] = []
        nr = self.config.cluster.name_resolve
        if nr.type == "nfs":
            self._common_env = {
                "AREAL_NAME_RESOLVE": f"nfs:{nr.nfs_record_root}",
            }
        elif nr.type == "http":
            # TTL'd KV service (utils/kv_store.py) reachable from every
            # node — the etcd-style fleet rendezvous.  slurm nodes are
            # always remote, so a loopback address can never be right.
            host = nr.http_addr.rsplit(":", 1)[0]
            if host in ("localhost", "127.0.0.1", "::1", "0.0.0.0"):
                raise ValueError(
                    f"name_resolve.http_addr={nr.http_addr!r} is loopback; "
                    f"slurm nodes need an address they can reach"
                )
            self._common_env = {
                "AREAL_NAME_RESOLVE": f"http:{nr.http_addr}",
            }
        else:
            raise ValueError(
                "slurm runs need cluster.name_resolve.type=nfs (shared "
                "path) or http (kv_store service) visible from every node"
            )
        self._script_dir = os.path.join(
            self.config.cluster.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "slurm",
        )

    # ----------------------------- job specs ----------------------------

    def gen_server_spec(self) -> SlurmJobSpec:
        g = self.config.gen_server
        from areal_tpu.api.config import GenServerConfig

        cmd = (
            GenServerConfig.build_cmd(g, host="$(hostname -i)", port=0)
            + f" --experiment-name {shlex.quote(self.config.experiment_name)}"
            + f" --trial-name {shlex.quote(self.config.trial_name)}"
            + " --server-idx $SLURM_PROCID"
        )
        return SlurmJobSpec(
            job_name=f"{self.config.experiment_name}-gen",
            cmd=cmd,
            n_tasks=self.n_gen_servers,
            gres="tpu:1",
            env=dict(self._common_env),
            log_path=os.path.join(self._script_dir, "gen_%j_%t.log"),
        )

    def trainer_spec(self, run_id: int = 0) -> SlurmJobSpec:
        cmd = (
            f"{shlex.quote(sys.executable)} {shlex.quote(self.entry)} "
            + " ".join(shlex.quote(a) for a in self.config_args)
        )
        env = dict(self._common_env)
        env.update(
            AREAL_RUN_ID=str(run_id),
            AREAL_NUM_PROCESSES=str(self.n_train_procs),
        )
        return SlurmJobSpec(
            job_name=f"{self.config.experiment_name}-train",
            cmd=cmd,
            n_tasks=self.n_train_procs,
            gres="tpu:4",
            env=env,
            runtime_env={
                # trainer task 0's node hosts the jax.distributed
                # coordinator; resolved per task inside srun so the
                # substitution actually runs
                "AREAL_COORDINATOR": (
                    "$(scontrol show hostnames $SLURM_JOB_NODELIST | head -n1):"
                    f"{COORDINATOR_PORT + run_id}"
                ),
            },
            log_path=os.path.join(self._script_dir, "train_%j_%t.log"),
        )

    # ----------------------------- lifecycle ----------------------------

    def submit(self, spec: SlurmJobSpec) -> str:
        os.makedirs(self._script_dir, exist_ok=True)
        path = os.path.join(self._script_dir, f"{spec.job_name}.sbatch")
        with open(path, "w") as f:
            f.write(render_sbatch(spec))
        out = subprocess.run(
            [self.sbatch_bin, "--parsable", path],
            capture_output=True,
            text=True,
            cwd=self.workdir,
            check=True,
        )
        job_id = out.stdout.strip().split(";")[0]
        self.job_ids.append(job_id)
        logger.info(f"submitted {spec.job_name} as job {job_id}")
        return job_id

    def job_state(self, job_id: str) -> str:
        out = subprocess.run(
            [self.squeue_bin, "-j", job_id, "-h", "-o", "%T"],
            capture_output=True,
            text=True,
        )
        state = out.stdout.strip().splitlines()
        if state:
            return state[0].strip()
        if out.returncode != 0:
            # squeue itself failed (slurmctld blip): unknown, NOT completed
            return "UNKNOWN"
        # gone from the queue: ask the accounting db how it ended; a job
        # that FAILED between polls must not be reported as COMPLETED
        try:
            acct = subprocess.run(
                [self.sacct_bin, "-j", job_id, "-n", "-X", "-o", "State"],
                capture_output=True,
                text=True,
            )
        except FileNotFoundError:  # no accounting on this cluster at all
            return "COMPLETED"
        lines = acct.stdout.strip().splitlines()
        if acct.returncode == 0 and lines:
            return lines[0].strip().split()[0].rstrip("+")
        err = (acct.stderr or "").lower()
        if "disabled" in err or "no association" in err:
            # sacct exists but accounting is off: the job left the queue and
            # its outcome is unknowable — report that distinctly instead of
            # claiming success for a possibly-crashed trainer
            return "VANISHED"
        # accounting blip or record not landed yet: keep polling — never
        # guess COMPLETED for a job we cannot observe
        return "UNKNOWN"

    def cancel_all(self):
        for job_id in self.job_ids:
            subprocess.run([self.scancel_bin, job_id], capture_output=True)
        self.job_ids.clear()

    def run(self, poll_interval: float = 10.0) -> int:
        """Submit both jobs and babysit: trainer completion ends the run;
        either job failing cancels the other (the reference's all-or-nothing
        worker semantics)."""
        try:
            gen_id = self.submit(self.gen_server_spec()) if self.n_gen_servers else None
            train_id = self.submit(self.trainer_spec())
            unknown_streak = 0
            while True:
                t_state = self.job_state(train_id)
                if t_state == "VANISHED":
                    logger.warning(
                        f"trainer job {train_id} left the queue but the "
                        "cluster has no accounting; outcome unknown (rc 2) "
                        "— enable slurm accounting for reliable exit codes"
                    )
                    return 2
                if t_state in TERMINAL_STATES:
                    return 0 if t_state == "COMPLETED" else 1
                # a long streak of UNKNOWN means the control plane cannot
                # observe the job at all — fail loudly instead of forever
                unknown_streak = unknown_streak + 1 if t_state == "UNKNOWN" else 0
                if unknown_streak >= 60:
                    logger.error(
                        f"trainer job {train_id} unobservable for "
                        f"{unknown_streak} polls; giving up"
                    )
                    return 1
                if gen_id is not None:
                    g_state = self.job_state(gen_id)
                    if g_state in TERMINAL_STATES and g_state != "COMPLETED":
                        logger.error(f"gen-server job {gen_id}: {g_state}")
                        return 1
                time.sleep(poll_interval)
        finally:
            self.cancel_all()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("entry")
    parser.add_argument("--n-gen-servers", type=int, default=1)
    parser.add_argument("--n-train-procs", type=int, default=1)
    args, config_args = parser.parse_known_args()
    launcher = SlurmLauncher(
        args.entry, config_args, args.n_gen_servers, args.n_train_procs
    )
    sys.exit(launcher.run())


if __name__ == "__main__":
    main()
