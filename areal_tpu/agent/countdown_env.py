"""Countdown arithmetic-game environment + reward.

Capability counterpart of the reference's countdown example family
(examples/countdown): the model receives a list of numbers and a target and
must produce an arithmetic expression — inside \\boxed{} or an
<answer>...</answer> tag — that evaluates to the target using each given
number at most once (+ - * / and parentheses only).  Verification is a
safe AST walk, not eval().
"""

import ast
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from areal_tpu.api.env import Environment

_ANSWER_RES = [
    re.compile(r"\\boxed\{([^{}]+)\}"),
    re.compile(r"<answer>(.*?)</answer>", re.DOTALL),
]

_ALLOWED_BINOPS = (ast.Add, ast.Sub, ast.Mult, ast.Div)


def extract_expression(text: str) -> Optional[str]:
    for rx in _ANSWER_RES:
        found = rx.findall(text)
        if found:
            return found[-1].strip()
    return None


def _safe_eval(node: ast.AST, used: List[int]) -> float:
    """Evaluate the expression tree, recording number literals; raises on
    anything but numbers, + - * /, parens, and unary minus."""
    if isinstance(node, ast.Expression):
        return _safe_eval(node.body, used)
    if isinstance(node, ast.Constant):
        if not isinstance(node.value, (int, float)):
            raise ValueError(f"non-numeric constant {node.value!r}")
        used.append(int(node.value) if float(node.value).is_integer() else node.value)
        return float(node.value)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return -_safe_eval(node.operand, used)
    if isinstance(node, ast.BinOp) and isinstance(node.op, _ALLOWED_BINOPS):
        left = _safe_eval(node.left, used)
        right = _safe_eval(node.right, used)
        if isinstance(node.op, ast.Add):
            return left + right
        if isinstance(node.op, ast.Sub):
            return left - right
        if isinstance(node.op, ast.Mult):
            return left * right
        if right == 0:
            raise ZeroDivisionError("division by zero")
        return left / right
    raise ValueError(f"disallowed syntax: {ast.dump(node)[:60]}")


def verify_countdown(
    completion: str, numbers: Sequence[int], target: float
) -> float:
    """1.0 iff the extracted expression evaluates to the target (1e-6
    tolerance) using each provided number at most once."""
    expr = extract_expression(completion)
    if expr is None:
        return 0.0
    try:
        tree = ast.parse(expr, mode="eval")
        used: List[int] = []
        value = _safe_eval(tree, used)
    except (
        SyntaxError,
        ValueError,
        ZeroDivisionError,
        RecursionError,
        OverflowError,  # e.g. a 400-digit literal: float() overflows
        MemoryError,
    ):
        return 0.0
    pool = list(numbers)
    for n in used:
        if n in pool:
            pool.remove(n)
        else:
            return 0.0  # number not provided (or used twice)
    return 1.0 if abs(value - float(target)) < 1e-6 else 0.0


def countdown_reward_fn(
    prompt, completions, prompt_ids, completion_ids, **data
) -> float:
    """Reward-API entry (same family as gsm8k_reward_fn)."""
    return verify_countdown(
        completions, data["numbers"], float(data["target"])
    )


class CountdownEnv(Environment):
    """verify_answer tool over one episode's (numbers, target)."""

    def __init__(self, numbers: Sequence[int], target: float):
        self.numbers = list(numbers)
        self.target = float(target)

    def list_tools(self) -> List[Dict[str, Any]]:
        return [
            {
                "name": "verify_answer",
                "description": "Check a countdown expression against the target.",
                "parameters": {
                    "type": "object",
                    "properties": {"completion": {"type": "string"}},
                    "required": ["completion"],
                },
            }
        ]

    async def aexecute_tool(
        self, tool_name: str, arguments: Dict[str, Any]
    ) -> Tuple[Any, float, bool]:
        if tool_name != "verify_answer":
            raise ValueError(f"unknown tool {tool_name!r}")
        reward = verify_countdown(
            arguments["completion"], self.numbers, self.target
        )
        # done only on success, so multi-turn agents can retry with
        # feedback (MathVerifyEnv convention: done = reward > 0)
        return None, reward, reward > 0


def make_countdown_dataset(
    n: int, seed: int = 0, n_numbers: int = 4, max_number: int = 25
) -> List[Dict[str, Any]]:
    """Solvable-by-construction problems: compose a random expression from
    the drawn numbers, use its value as the target."""
    import random

    rng = random.Random(seed)
    out = []
    for i in range(n):
        while True:
            numbers = [rng.randint(1, max_number) for _ in range(n_numbers)]
            value = numbers[0]
            for x in numbers[1:]:
                op = rng.choice("+-*")
                value = value + x if op == "+" else value - x if op == "-" else value * x
            if 0 < value <= 10_000:
                break
        out.append(
            {
                "messages": [
                    {
                        "role": "user",
                        "content": (
                            f"Using the numbers {numbers}, each at most once, "
                            f"with + - * / and parentheses, write an expression "
                            f"equal to {value}. Put it in \\boxed{{}}."
                        ),
                    }
                ],
                "numbers": numbers,
                "target": value,
                "query_id": str(i),
            }
        )
    return out
