"""GSM8K LitePPO — the minimalist recipe: group-mean reward centering +
batch-level std + wide clip, nothing else.

Counterpart of the reference's `examples/experimental/lite_ppo/
gsm8k_liteppo.py`. LitePPO's claim is that two components carry RL4LLM:
advantages = (reward - group mean) / batch std (`reward_norm.mean_level:
group`, `std_level: batch`, reference yaml: examples/experimental/
lite_ppo/gsm8k_liteppo.yaml) and token-level loss with a wide clip
(`eps_clip: 0.4`) — no KL, no dynamic sampling, no length penalty. The
training loop is `examples/math/gsm8k_grpo.py`.

Launch:
    python examples/experimental/lite_ppo/gsm8k_liteppo.py \
        --config examples/experimental/lite_ppo/gsm8k_liteppo.yaml
"""

import importlib.util
import os
import sys

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def _load_grpo_main():
    spec = importlib.util.spec_from_file_location(
        "gsm8k_grpo_shared",
        os.path.join(_REPO, "examples", "math", "gsm8k_grpo.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


if __name__ == "__main__":
    sys.path.insert(0, _REPO)
    _load_grpo_main()(sys.argv[1:])
