"""Arrival processes for trace-driven load replay.

`scripts/bench_replay.py` needs a list of *when* requests arrive and
*what shape* they are.  Two sources:

- :func:`arrivals_from_trace` — replay a recorded run: every
  ``rollout_submit`` in a lifecycle JSONL becomes an arrival at its
  original relative wall time, with the prompt length from the submit
  record and the decode budget from the matching ``gen_done`` (so the
  replayed load reproduces the recorded compute mix, not just the
  arrival clock).
- :func:`synthetic_mixed` — a seeded synthetic mix of the three traffic
  shapes the ROADMAP names for fleet claims: *chat bursts* (a Poisson
  process of bursts, each a quick volley of short prompts), *GRPO
  groups* (``group_n`` siblings sharing one prompt, arriving together —
  exercises shared prefill), and *long-context stragglers* (rare, big
  prompt, big budget — exercises tier migration and admission holds).

:func:`scale` compresses the arrival clock by a rate multiplier; shapes
are untouched, so a 16× replay is "the same work, sixteen times as
fast", which is exactly what a latency-vs-throughput curve wants.

Determinism: all randomness comes from one `random.Random(seed)`; the
same (seed, duration, base_rps) always yields the same workload, so
replay curves are comparable across commits.  Stdlib-only, offline —
nothing here touches the engine.
"""

import dataclasses
import random
from typing import Any, Dict, Iterable, List

from areal_tpu.obs.trace import EventSource, iter_events


@dataclasses.dataclass
class Arrival:
    """One request in an arrival process (times relative to run start)."""

    t: float                 # arrival time, seconds from start
    prompt_len: int
    max_new_tokens: int
    kind: str = "chat"       # chat | group | straggler | trace
    group_id: str = ""       # nonempty: GRPO siblings share a prompt
    group_n: int = 1
    trace_id: str = ""       # original trace id when replaying a trace


def arrivals_from_trace(source: EventSource, *,
                        default_budget: int = 64) -> List[Arrival]:
    """Extract the arrival process of a recorded run from its lifecycle
    JSONL: submit times + prompt lengths from ``rollout_submit``, decode
    budgets from each trace's ``gen_done.output_len`` (falling back to
    ``default_budget`` for trajectories still open at dump time)."""
    events = iter_events(source)
    out_len: Dict[str, int] = {}
    for e in events:
        if e.get("event") == "gen_done" and e.get("trace_id"):
            n = e.get("output_len")
            if n:
                out_len[e["trace_id"]] = int(n)
    submits = [e for e in events if e.get("event") == "rollout_submit"]
    if not submits:
        return []
    t0 = min(float(e["ts"]) for e in submits)
    arrivals = [
        Arrival(
            t=float(e["ts"]) - t0,
            prompt_len=max(1, int(e.get("input_len", 1) or 1)),
            max_new_tokens=out_len.get(e.get("trace_id", ""), default_budget),
            kind="trace",
            group_id=str(e.get("group_id", "") or ""),
            trace_id=str(e.get("trace_id", "") or ""),
        )
        for e in submits
    ]
    arrivals.sort(key=lambda a: a.t)
    return arrivals


def synthetic_mixed(*, seed: int, duration_s: float, base_rps: float,
                    max_prompt_len: int = 128,
                    max_new_tokens: int = 64) -> List[Arrival]:
    """Seeded synthetic mixed workload over ``duration_s`` seconds.

    Component rates are fractions of ``base_rps`` (expected *request*
    rate, all components combined, is roughly ``base_rps``): chat bursts
    carry most of the volume, GRPO groups arrive less often but bring
    ``group_n`` siblings each, stragglers are rare and heavy.
    """
    rng = random.Random(seed)
    arrivals: List[Arrival] = []

    def poisson_times(rate: float) -> Iterable[float]:
        t = 0.0
        while True:
            if rate <= 0:
                return
            t += rng.expovariate(rate)
            if t >= duration_s:
                return
            yield t

    # Chat bursts: ~60% of volume; burst of 2-6 requests 10-50ms apart.
    mean_burst = 4.0
    for tb in list(poisson_times(0.60 * base_rps / mean_burst)):
        for i in range(2 + rng.randrange(5)):
            arrivals.append(Arrival(
                t=tb + i * rng.uniform(0.01, 0.05),
                prompt_len=rng.randrange(8, max(9, max_prompt_len // 2)),
                max_new_tokens=rng.randrange(4, max(5, max_new_tokens // 2)),
                kind="chat",
            ))

    # GRPO groups: ~35% of volume in groups of 4 sharing a prompt.
    group_n = 4
    for gi, tg in enumerate(list(poisson_times(0.35 * base_rps / group_n))):
        plen = rng.randrange(8, max(9, (3 * max_prompt_len) // 4))
        budget = rng.randrange(8, max(9, max_new_tokens))
        for _ in range(group_n):
            arrivals.append(Arrival(
                t=tg, prompt_len=plen, max_new_tokens=budget,
                kind="group", group_id=f"g{seed}-{gi}", group_n=group_n,
            ))

    # Long-context stragglers: ~5% of volume, near-max prompt + budget.
    for ts in list(poisson_times(0.05 * base_rps)):
        arrivals.append(Arrival(
            t=ts,
            prompt_len=max(8, (3 * max_prompt_len) // 4
                           + rng.randrange(max(1, max_prompt_len // 4))),
            max_new_tokens=max_new_tokens,
            kind="straggler",
        ))

    arrivals.sort(key=lambda a: a.t)
    return arrivals


def scale(arrivals: List[Arrival], rate: float) -> List[Arrival]:
    """Compress the arrival clock by ``rate`` (2.0 = twice as fast);
    request shapes are unchanged."""
    if rate <= 0:
        raise ValueError(f"rate must be positive, got {rate}")
    return [dataclasses.replace(a, t=a.t / rate) for a in arrivals]


def summarize(arrivals: List[Arrival]) -> Dict[str, Any]:
    """Small JSON-able description of a workload for report headers."""
    by_kind: Dict[str, int] = {}
    for a in arrivals:
        by_kind[a.kind] = by_kind.get(a.kind, 0) + 1
    dur = arrivals[-1].t if arrivals else 0.0
    return {
        "n": len(arrivals),
        "by_kind": by_kind,
        "span_s": dur,
        "offered_rps": (len(arrivals) / dur) if dur > 0 else None,
        "prompt_tokens": sum(a.prompt_len for a in arrivals),
        "budget_tokens": sum(a.max_new_tokens for a in arrivals),
        "groups": len({a.group_id for a in arrivals if a.group_id}),
    }


def prompt_ids(a: Arrival, *, vocab: int, seed: int) -> List[int]:
    """Deterministic token ids for an arrival's prompt.  Group siblings
    (same ``group_id``) get identical prompts — that is the whole point
    of the group component (shared prefill); everything else is keyed by
    its position-independent identity."""
    key = a.group_id if a.group_id else f"{a.kind}-{a.t:.6f}-{a.prompt_len}"
    rng = random.Random(f"{seed}:{key}")
    lo, hi = 3, max(4, vocab - 1)
    return [rng.randrange(lo, hi) for _ in range(a.prompt_len)]
