"""Suppressed: an intentional library surface with a stated reason."""
# areal-lint: disable=dead-module experimental user-facing surface kept for downstream scripts


def api():
    return "stable"
