"""RLVR (RL with verifiable rewards) rollout workflow.

Behavioral counterpart of the reference's `RLVRWorkflow`
(areal/workflow/rlvr.py:37): generate `n_samples` completions per prompt
concurrently, score each with a (sync) reward function run off-loop, and emit
one padded trajectory batch.  Per-token `versions` from the inference engine
ride along for decoupled-PPO staleness correction.
"""

import asyncio
import os
import uuid
from typing import Any, Callable, Dict, Optional

import numpy as np

from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.api.reward import AsyncRewardWrapper
from areal_tpu.api.workflow import RolloutWorkflow
from areal_tpu.utils import logging, telemetry
from areal_tpu.utils.data import pad_sequences_to_tensors

logger = logging.getLogger("rlvr")


class RLVRWorkflow(RolloutWorkflow):
    def __init__(
        self,
        reward_fn: Callable[..., float],
        gconfig: GenerationHyperparameters,
        tokenizer=None,
        enable_thinking: bool = False,
        rollout_stat_scope: str = "rollout",
        dump_dir: Optional[str] = None,
    ):
        self.reward_fn = AsyncRewardWrapper(reward_fn)
        self.gconfig = gconfig
        self.tokenizer = tokenizer
        self.enable_thinking = enable_thinking
        self.dump_dir = dump_dir
        if dump_dir:
            os.makedirs(dump_dir, exist_ok=True)

    def _tokenize_prompt(self, data: Dict[str, Any]):
        if "input_ids" in data:
            return list(data["input_ids"])
        if self.tokenizer is None:
            raise ValueError("need tokenizer or pre-tokenized input_ids")
        if "messages" in data:
            return self.tokenizer.apply_chat_template(
                data["messages"],
                add_generation_prompt=True,
                tokenize=True,
                enable_thinking=self.enable_thinking,
            )
        return self.tokenizer.encode(data["prompt"])

    def _build_request(self, data: Dict[str, Any]) -> ModelRequest:
        """Hook: subclasses (vision) add modality payloads to the request.

        A dataset item may carry its own `max_new_tokens` to cap this
        prompt's generation budget below the workflow default (e.g.
        per-difficulty budgets, or benchmark workloads with realistic
        length variance)."""
        overrides = {"n_samples": 1}
        if "max_new_tokens" in data:
            overrides["max_new_tokens"] = min(
                int(data["max_new_tokens"]), self.gconfig.max_new_tokens
            )
        return ModelRequest(
            rid=str(uuid.uuid4()),
            input_ids=self._tokenize_prompt(data),
            gconfig=self.gconfig.new(**overrides),
            tokenizer=self.tokenizer,
        )

    def _reward_kwargs(self, data: Dict[str, Any]) -> Dict[str, Any]:
        """Hook: subclasses filter non-picklable/heavy fields (images)."""
        return data

    async def arun_episode(self, engine, data: Dict[str, Any]):
        n = self.gconfig.n_samples
        req = self._build_request(data)
        reqs = [req.copy() for _ in range(n)]
        if n > 1:
            # GRPO group: declare the siblings so routing keeps them on one
            # replica and the engine admits them as one prefix-sharing
            # cluster (one prefill + KV fan-out instead of n prefills)
            for k, r in enumerate(reqs):
                r.rid = f"{req.rid}-{k}"
                r.group_id = req.rid
                r.group_n = n
        # pin the lifecycle trace id here (not in agenerate) so reward and
        # trainer-consumption events can be joined to generation-side spans
        for r in reqs:
            r.trace_id = r.rid
        resps = await asyncio.gather(
            *[engine.agenerate(r) for r in reqs]
        )
        results = []
        for r, resp in zip(reqs, resps):
            completion_str = (
                self.tokenizer.decode(resp.output_tokens)
                if self.tokenizer is not None
                else ""
            )
            prompt_str = (
                self.tokenizer.decode(resp.input_tokens)
                if self.tokenizer is not None
                else ""
            )
            reward = await self.reward_fn(
                prompt_str,
                completion_str,
                resp.input_tokens,
                resp.output_tokens,
                **self._reward_kwargs(data),
            )
            seq = resp.input_tokens + resp.output_tokens
            logprobs = [0.0] * resp.input_len + resp.output_logprobs
            loss_mask = [0] * resp.input_len + [1] * resp.output_len
            versions = [-1] * resp.input_len + resp.output_versions
            result = dict(
                input_ids=np.array(seq, dtype=np.int32),
                logprobs=np.array(logprobs, dtype=np.float32),
                loss_mask=np.array(loss_mask, dtype=np.int32),
                versions=np.array(versions, dtype=np.int32),
                rewards=np.float32(reward),
            )
            if telemetry.is_enabled():
                out_v = [v for v in resp.output_versions if v >= 0]
                telemetry.emit(
                    "reward",
                    trace_id=r.trace_id,
                    reward=float(reward),
                    output_len=resp.output_len,
                    stop_reason=resp.stop_reason,
                    version_min=min(out_v) if out_v else -1,
                    version_max=max(out_v) if out_v else -1,
                )
                # 0-d scalar: pad_sequences_to_tensors stacks it to [B], and
                # the trainer strips it before device transfer (no new XLA
                # signature); keyed only when enabled so concat across a run
                # sees a consistent key set
                result["trace_keys"] = np.int64(telemetry.trace_key(r.trace_id))
            results.append(self._augment_result(result, data, resp))
            if self.dump_dir:
                self._dump(data, prompt_str, completion_str, reward, resp)
        batch = pad_sequences_to_tensors(results)
        return self._augment_batch(batch, data, len(results))

    def _augment_result(self, result, data, resp):
        """Hook: subclasses add per-sample keys (vision: mrope positions)."""
        return result

    def _augment_batch(self, batch, data, n_samples: int):
        """Hook: subclasses add batch-level payloads (vision: pixels)."""
        return batch

    def _dump(self, data, prompt_str, completion_str, reward, resp):
        qid = str(data.get("query_id", data.get("qid", "unknown")))
        path = os.path.join(self.dump_dir, f"{qid}.txt")
        with open(path, "a") as f:
            f.write(
                f"prompt: {prompt_str}\ncompletion: {completion_str}\n"
                f"reward: {reward} stop: {resp.stop_reason} "
                f"len: {resp.output_len}\n{'-' * 40}\n"
            )
