"""HH-RLHF reward-model training entry point.

Behavioral counterpart of the reference's RW path
(areal/engine/rw/rw_engine.py + hhrlhf dataset): Bradley-Terry pairwise
loss over interleaved (chosen, rejected) rows.

Launch:  python examples/rw/hhrlhf_rw.py --config examples/rw/hhrlhf_rw.yaml
"""

import sys

import numpy as np

from areal_tpu.api.config import RWConfig, load_expr_config
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.dataset import get_custom_dataset
from areal_tpu.engine.rw import JaxRewardModelEngine
from areal_tpu.utils import logging, seeding, stats
from areal_tpu.utils.data import pad_sequences_to_tensors
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.stats_logger import StatsLogger

logger = logging.getLogger("hhrlhf_rw")


def collate(samples):
    """Interleave pairs: rows [2i] = chosen, [2i+1] = rejected (the layout
    engine/rw/rw_engine.py scores)."""
    rows = []
    for s in samples:
        rows.append({"input_ids": np.asarray(s["chosen_ids"], np.int32)})
        rows.append({"input_ids": np.asarray(s["rejected_ids"], np.int32)})
    return pad_sequences_to_tensors(rows)


def main(argv):
    config, _ = load_expr_config(argv, RWConfig)
    seeding.set_random_seed(config.seed, "rw")

    from transformers import AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(
        config.tokenizer_path or config.model.path
    )
    train_dataset = get_custom_dataset(
        path=config.train_dataset.path,
        type=config.train_dataset.type,
        split="train",
        tokenizer=tokenizer,
        max_length=config.train_dataset.max_length,
    )
    dataloader = StatefulDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        drop_last=config.train_dataset.drop_last,
        seed=config.seed,
    )
    steps_per_epoch = len(dataloader)
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=len(train_dataset),
        train_batch_size=config.train_dataset.batch_size,
    )
    engine = JaxRewardModelEngine(config.model)
    engine.initialize(ft_spec=ft_spec)
    saver = Saver(config.saver, ft_spec)
    stats_logger = StatsLogger(config.stats_logger)

    total_steps = config.total_train_steps or (
        config.total_train_epochs * steps_per_epoch
    )
    global_step = 0
    for epoch in range(config.total_train_epochs):
        for epoch_step, samples in enumerate(dataloader):
            if global_step >= total_steps:
                break
            batch = collate(samples)
            with stats.DEFAULT_TRACKER.scope("rw"):
                st = engine.train_rw(batch)
                stats.DEFAULT_TRACKER.scalar(
                    **{k: v for k, v in st.items() if np.isscalar(v)}
                )
            engine.step_lr_scheduler()
            saver.save(engine, epoch, epoch_step, global_step, tokenizer=tokenizer)
            stats_logger.commit(
                epoch, epoch_step, global_step,
                [stats.DEFAULT_TRACKER.export()],
            )
            logger.info(
                f"Epoch {epoch + 1}/{config.total_train_epochs} "
                f"Step {epoch_step + 1}/{steps_per_epoch} done. "
                f"loss={st['loss']:.4f} acc={st.get('pair_acc', float('nan')):.3f}"
            )
            global_step += 1
    stats_logger.close()
    engine.destroy()


if __name__ == "__main__":
    main(sys.argv[1:])
