"""Generation-side router/controller service.

Behavioral counterpart of the reference's `GserverManager`
(realhf/system/gserver_manager.py:32): a standalone HTTP service sitting in
front of N generation servers that

- **routes** `/generate` to a backend under a configurable policy —
  round_robin | least_requests | least_tokens — with rid->server affinity so
  interruption re-submissions land on the server that already holds the KV
  prefix (gserver_manager.py:175-191 routing policies; :351 routing service);
- **gates rollout admission globally**: `/allocate_request` applies the
  staleness capacity formula across ALL clients of the fleet, not per-client
  like the in-process StalenessManager (is_staled, gserver_manager.py:334);
- **watches for new checkpoints** published by the trainer in name_resolve
  and flushes + updates every backend: pause all -> update_weights_from_disk
  -> resume all, bumping the served version (check_new_params
  gserver_manager.py:131, flush_requests_and_update_weights :158).

The staleness gate's capacity formula depends on the trainer's weight
version, so the router needs a version source in EVERY deployment mode
(ADVICE r3): disk fleets advance via the checkpoint watcher; transfer-mode
fleets (trainer pushes chunks straight to servers) advance via POST
/set_version from the train loop, with a background poll of the backends'
/health version as a safety net — the transfer commit bumps each server's
served version, so the fleet's max is adopted even if the trainer never
calls /set_version.

Clients need no new protocol: the router speaks the same wire format as a
generation server (areal_tpu/gen/server.py), so RemoteInfEngine can point at
the router exactly as it would at one big server.
"""

import argparse
import asyncio
import time
import uuid
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import aiohttp
from aiohttp import web

from areal_tpu.analysis.lockcheck import lock_guarded
from areal_tpu.gen.health import STATE_CODES, BackendHealthChecker
from areal_tpu.utils import logging, name_resolve, names, network, telemetry

logger = logging.getLogger("gen.router")

RID_CACHE_SIZE = 8192


@dataclass
class RouterConfig:
    experiment_name: str = ""
    trial_name: str = ""
    schedule_policy: str = "least_requests"  # round_robin | least_requests | least_tokens
    # global staleness gate (capacity formula shared with core/staleness.py)
    train_batch_size: int = 0  # 0 => gate disabled
    max_head_offpolicyness: int = 0
    # checkpoint watcher
    weights_path: str = ""  # trainer's WeightUpdateMeta.path; ckpts at v{N}/
    poll_interval: float = 1.0
    # transfer-mode version safety net: poll backend /health and adopt the
    # fleet's max served version (0 disables; only runs when the staleness
    # gate is enabled and no disk watcher owns the version)
    version_poll_interval: float = 2.0
    request_timeout: float = 3600.0
    # allocations older than this are reclaimed, so a client that crashed
    # mid-episode cannot permanently wedge fleet admission (0 => request_timeout)
    alloc_ttl: float = 0.0
    # active health checking / circuit breaker (gen/health.py): probe every
    # interval seconds (0 disables the background loop; state still updates
    # from proxied-request outcomes), trip a backend open after this many
    # consecutive failures
    health_check_interval: float = 5.0
    health_failure_threshold: int = 3
    health_probe_timeout: float = 2.0
    # disaggregated prefill/decode serving (ISSUE 17): when enabled and
    # both role pools are non-empty, /generate runs as two legs — leg 1
    # (admission + prefill + the first sampled token) on a prefill-role
    # server, then a /kv_export -> /kv_import page-set transfer, then
    # leg 2 (the decode tail) on a decode-role server.  The counter-keyed
    # sampler makes the merged stream bit-identical to colocated serving.
    disagg: bool = False
    # backpressure: at most this many export/import transfers in flight
    # fleet-wide; excess requests fall back to colocated placement
    handoff_max_inflight: int = 4
    # tokens generated on the prefill server before the handoff (>= 1:
    # prefill itself samples the first token)
    handoff_leg1_tokens: int = 1
    # decode-pool placement signal: poll decode servers' /metrics for
    # tier occupancy at this cadence (0 falls back to in-flight counts)
    occupancy_poll_interval: float = 1.0


@lock_guarded
class Router:
    # the fleet staleness gate's admission ledger: every handler that
    # reads or mutates it does so under the asyncio _lock so capacity
    # checks are atomic with lease grants (areal-lint C1; the asyncio
    # flavor of the runtime check degrades to a locked() probe)
    _GUARDED_FIELDS = {
        "_running": "_lock",
        "_accepted": "_lock",
        "_failovers": "_lock",
        "_publish_partial_failures": "_lock",
        "_last_publish": "_lock",
        "_handoffs": "_lock",
        "_handoff_fallbacks": "_lock",
    }
    # declared acquisition order (areal-lint C5): _flush_and_update holds
    # the flush serializer across the backend fan-out, then takes the
    # ledger lock to commit — never nest them the other way around
    # lock-order: _flush_lock -> _lock

    def __init__(self, config: RouterConfig, addresses: Optional[List[str]] = None):
        self.config = config
        self.addresses: List[str] = list(addresses or [])
        self.version = 0
        self._rr = 0
        self._inflight: Dict[str, int] = {}
        self._routed: Dict[str, int] = {}  # cumulative requests per backend
        self._tokens: Dict[str, int] = {}  # live in-flight tokens per backend
        self._rid_to_addr: "OrderedDict[str, str]" = OrderedDict()
        # global rollout accounting for the staleness gate; allocations carry
        # a lease timestamp so orphans (crashed clients) age out
        self._running: Dict[str, float] = {}
        self._accepted = 0
        self._lock = asyncio.Lock()
        self._flush_lock = asyncio.Lock()
        self._session: Optional[aiohttp.ClientSession] = None
        self._watcher: Optional[asyncio.Task] = None
        self._version_poller: Optional[asyncio.Task] = None
        self.n_flushes = 0
        # failure-handling state (ISSUE 11): circuit breaker + failover
        # bookkeeping.  _last_publish remembers (path, version) of the last
        # successful disk publish so a rejoining backend can be force-fed
        # current weights before it takes placements again.
        self._health: Optional[BackendHealthChecker] = None
        self._failovers = 0
        self._publish_partial_failures = 0
        self._last_publish: Optional[tuple] = None
        # disaggregated serving (ISSUE 17): role advertised by each
        # backend's /health ("prefill" | "decode" | "both"), the decode
        # pool's polled tier occupancy, the transfer-backpressure
        # semaphore, and the handoff ledger.  Roles/occupancy are only
        # touched on the event loop, so they ride without the ledger lock.
        self._roles: Dict[str, str] = {}
        self._decode_occ: Dict[str, float] = {}
        self._handoff_sem: Optional[asyncio.Semaphore] = None
        self._occ_poller: Optional[asyncio.Task] = None
        self._handoffs = 0
        self._handoff_fallbacks = 0

    # ---------------------------- scheduling ----------------------------

    def _placeable(self) -> List[str]:
        """Backends eligible for NEW placements: the breaker's closed set,
        in canonical address order (so round-robin indices are stable when
        everyone is healthy).  Falls back to the full list when the whole
        fleet is tripped — routing into a dead fleet fails fast per-request
        and keeps probing, instead of crashing the scheduler."""
        if self._health is None:
            return self.addresses
        ok = set(self._health.placeable_cache)
        pool = [a for a in self.addresses if a in ok]
        return pool or self.addresses

    def _choose(self) -> str:
        pool = self._placeable()
        policy = self.config.schedule_policy
        if policy == "least_requests":
            return min(pool, key=lambda a: self._inflight.get(a, 0))
        if policy == "least_tokens":
            return min(pool, key=lambda a: self._tokens.get(a, 0))
        addr = pool[self._rr % len(pool)]
        self._rr += 1
        return addr

    def _server_for_rid(self, rid: str) -> str:
        if rid and rid in self._rid_to_addr:
            addr = self._rid_to_addr[rid]
            if addr in self._placeable():
                self._rid_to_addr.move_to_end(rid)
                return addr
            # affinity points at a dead/draining backend: the KV prefix is
            # gone anyway, so re-place (whole groups share one key, so GRPO
            # siblings reroute together and fan-out prefix sharing survives)
            del self._rid_to_addr[rid]
        addr = self._choose()
        if rid:
            if len(self._rid_to_addr) >= RID_CACHE_SIZE:
                self._rid_to_addr.popitem(last=False)
            self._rid_to_addr[rid] = addr
        return addr

    # ---------------- disaggregated placement (ISSUE 17) ----------------

    def _role_pool(self, role: str) -> List[str]:
        """Placeable backends advertising exactly `role`.  Servers running
        `both` stay out of the role pools — they are the colocated
        fallback capacity, not handoff endpoints."""
        return [
            a for a in self._placeable()
            if self._roles.get(a, "both") == role
        ]

    def _prefill_for_rid(self, rid: str, pool: List[str]) -> str:
        """Prefill-pool placement: group/rid affinity first (GRPO fan-out
        must share cluster prefixes inside ONE prefill engine), else the
        shallowest queue."""
        if rid and self._rid_to_addr.get(rid) in pool:
            addr = self._rid_to_addr[rid]
            self._rid_to_addr.move_to_end(rid)
            return addr
        addr = min(pool, key=lambda a: self._inflight.get(a, 0))
        if rid:
            if len(self._rid_to_addr) >= RID_CACHE_SIZE:
                self._rid_to_addr.popitem(last=False)
            self._rid_to_addr[rid] = addr
        return addr

    def _decode_pick(self, pool: List[str]) -> str:
        """Decode-pool placement: lowest polled tier occupancy (the
        /metrics signal), in-flight count as the tiebreak/fallback."""
        return min(
            pool,
            key=lambda a: (
                self._decode_occ.get(a, 0.0),
                self._inflight.get(a, 0),
            ),
        )

    def _evict_backend_locked(self, addr: str) -> int:  # holds: _lock
        """Drop every rid affinity pinned to `addr`; returns the count.
        Called on death so resubmissions re-place instead of chasing the
        corpse, and each evicted key is one failover."""
        evicted = [r for r, a in self._rid_to_addr.items() if a == addr]
        for r in evicted:
            del self._rid_to_addr[r]
        self._failovers += len(evicted)
        return len(evicted)

    async def _on_backend_death(self, addr: str):
        """Breaker callback (closed/half_open -> open)."""
        async with self._lock:
            n = self._evict_backend_locked(addr)
        logger.warning(
            f"backend {addr} dead: rerouted {n} rid/group affinities"
        )

    # ------------------------- staleness gate ---------------------------

    def _prune_allocations(self) -> None:  # holds: _lock
        """Reclaim leases whose client never called /finish_request."""
        ttl = self.config.alloc_ttl or self.config.request_timeout
        cutoff = time.monotonic() - ttl
        stale = [aid for aid, t in self._running.items() if t < cutoff]
        for aid in stale:
            del self._running[aid]
        if stale:
            logger.warning(f"reclaimed {len(stale)} expired rollout allocations")

    def _capacity(self) -> Optional[int]:  # holds: _lock
        """Remaining global admissions, or None when the gate is disabled.

        Same formula as StalenessManager.get_capacity (reference
        staleness_manager.py:96) evaluated fleet-wide: samples admitted so
        far may not exceed (staleness + version + 1) * train_batch_size."""
        bs = self.config.train_batch_size
        if bs <= 0:
            return None
        self._prune_allocations()
        allowed = (self.config.max_head_offpolicyness + self.version + 1) * bs
        return allowed - (len(self._running) + self._accepted)

    # ---------------------------- handlers ------------------------------

    async def generate(self, request: web.Request) -> web.Response:
        body = await request.json()
        # group members must land on ONE replica: the KV prefix is only
        # shareable within a single engine's cache, so the affinity key is
        # the group when one is declared, the rid otherwise (interruption
        # resubmits keep riding the same key either way)
        rid = body.get("group_id") or body.get("rid", "")
        if self.config.disagg:
            resp = await self._generate_disagg(body, rid)
            if resp is not None:
                return resp
            # fall through: colocated placement (empty role pool, breaker
            # open, transfer backpressure, or a failed prefill leg)
        # _tokens tracks tokens currently resident on each backend (a proxy
        # for live KV usage, the reference's least_token_usage signal) — NOT
        # a cumulative history, so finished requests free their share
        n_prompt = len(body.get("input_ids", ()))
        async with self._lock:
            addr = self._server_for_rid(rid)
            self._inflight[addr] = self._inflight.get(addr, 0) + 1
            self._routed[addr] = self._routed.get(addr, 0) + 1
            self._tokens[addr] = self._tokens.get(addr, 0) + n_prompt
        try:
            try:
                async with self._session.post(
                    f"http://{addr}/generate", json=body
                ) as resp:
                    payload = await resp.json()
                    status = resp.status
            finally:
                async with self._lock:
                    self._inflight[addr] = max(
                        0, self._inflight.get(addr, 1) - 1
                    )
                    self._tokens[addr] = max(
                        0, self._tokens.get(addr, 0) - n_prompt
                    )
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            return await self._proxy_failed(addr, rid, e)
        if self._health is not None and status == 200:
            await self._health.report_success(addr)
        return web.json_response(payload, status=status)

    async def generate_batch(self, request: web.Request) -> web.Response:
        """Route a whole group to ONE backend in one POST (the batch-submit
        path that guarantees co-resident admission for the engine's group
        fan-out).  Affinity key: the first member's group_id/rid."""
        body = await request.json()
        reqs = body.get("requests", [])
        if not reqs:
            return web.json_response({"error": "empty batch"}, status=400)
        first = reqs[0]
        key = first.get("group_id") or first.get("rid", "")
        n_prompt = sum(len(r.get("input_ids", ())) for r in reqs)
        async with self._lock:
            addr = self._server_for_rid(key)
            self._inflight[addr] = self._inflight.get(addr, 0) + len(reqs)
            self._routed[addr] = self._routed.get(addr, 0) + len(reqs)
            self._tokens[addr] = self._tokens.get(addr, 0) + n_prompt
        try:
            try:
                async with self._session.post(
                    f"http://{addr}/generate_batch", json=body
                ) as resp:
                    payload = await resp.json()
                    status = resp.status
            finally:
                async with self._lock:
                    self._inflight[addr] = max(
                        0, self._inflight.get(addr, len(reqs)) - len(reqs)
                    )
                    self._tokens[addr] = max(
                        0, self._tokens.get(addr, 0) - n_prompt
                    )
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            return await self._proxy_failed(addr, key, e)
        if self._health is not None and status == 200:
            await self._health.report_success(addr)
        return web.json_response(payload, status=status)

    # ---------------- disaggregated handoff (ISSUE 17) ------------------

    async def _leg_post(self, addr: str, path: str, body: dict,
                        n_tokens: int):
        """One backend POST with the same in-flight/token bookkeeping the
        colocated path keeps; transport errors propagate to the caller."""
        async with self._lock:
            self._inflight[addr] = self._inflight.get(addr, 0) + 1
            self._routed[addr] = self._routed.get(addr, 0) + 1
            self._tokens[addr] = self._tokens.get(addr, 0) + n_tokens
        try:
            async with self._session.post(
                f"http://{addr}{path}", json=body
            ) as resp:
                return resp.status, await resp.json()
        finally:
            async with self._lock:
                self._inflight[addr] = max(0, self._inflight.get(addr, 1) - 1)
                self._tokens[addr] = max(0, self._tokens.get(addr, 0) - n_tokens)

    async def _generate_disagg(self, body: dict, rid: str):
        """Two-leg disaggregated /generate: leg 1 (admission + prefill +
        the first sampled token) on a prefill-role server, the page-set
        transfer, then leg 2 (the decode tail) on a decode-role server.
        Returns None to make the caller fall back to colocated placement
        — which is always exact, because the counter-keyed sampler makes
        the stream a pure function of (stream_id, position)."""
        cfg = self.config
        if body.get("pixel_values_b64"):
            # VLM prefill still samples from the engine's rng stream, so
            # a cross-server continuation is not reproducible — colocate
            return None
        sp = dict(body.get("sampling_params", {}) or {})
        orig_max = int(sp.get("max_new_tokens", 256))
        orig_min = int(sp.get("min_new_tokens", 0) or 0)
        leg1_n = max(1, cfg.handoff_leg1_tokens)
        async with self._lock:
            prefill_pool = self._role_pool("prefill")
            decode_pool = self._role_pool("decode")
            if (
                not prefill_pool
                or not decode_pool
                or orig_max <= leg1_n
                or self._handoff_sem is None
                or self._handoff_sem.locked()  # backpressure: at capacity
            ):
                return None
            prefill_addr = self._prefill_for_rid(rid, prefill_pool)
            decode_addr = self._decode_pick(decode_pool)

        # --- leg 1 -----------------------------------------------------
        leg1_body = dict(body)
        leg1_sp = dict(sp)
        leg1_sp["max_new_tokens"] = leg1_n
        leg1_sp["min_new_tokens"] = min(orig_min, leg1_n)
        leg1_body["sampling_params"] = leg1_sp
        n_prompt = len(body.get("input_ids", ()))
        try:
            status, leg1 = await self._leg_post(
                prefill_addr, "/generate", leg1_body, n_prompt
            )
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            # the prefill leg died before any token was delivered, so
            # nothing is lost: strike the breaker, drop the affinity, and
            # let the caller place the whole request colocated
            if self._health is not None:
                await self._health.report_failure(prefill_addr, repr(e))
            async with self._lock:
                if self._rid_to_addr.get(rid) == prefill_addr:
                    del self._rid_to_addr[rid]
                    self._failovers += 1
                self._handoff_fallbacks += 1
            return None
        if status != 200:
            async with self._lock:
                self._handoff_fallbacks += 1
            return None
        if self._health is not None:
            await self._health.report_success(prefill_addr)
        toks = [int(t) for t in leg1.get("output_tokens", [])]
        if leg1.get("stop_reason") != "length" or not toks:
            # finished inside leg 1 (eos / stop token): nothing to hand off
            return web.json_response(leg1, status=200)

        # --- page-set transfer -----------------------------------------
        full_ids = [int(t) for t in body["input_ids"]] + toks
        trace_id = str(body.get("trace_id", "") or "")
        t0 = time.perf_counter()
        moved = False
        nbytes = 0
        async with self._handoff_sem:
            try:
                async with self._session.post(
                    f"http://{prefill_addr}/kv_export",
                    json={"input_ids": full_ids},
                ) as resp:
                    doc = await resp.json() if resp.status == 200 else None
                if doc is not None:
                    nbytes = int(doc.get("nbytes", 0) or 0)
                    async with self._session.post(
                        f"http://{decode_addr}/kv_import", json=doc
                    ) as iresp:
                        moved = iresp.status == 200
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                moved = False
        if moved:
            telemetry.emit(
                "handoff",
                trace_id=trace_id or None,
                latency_s=time.perf_counter() - t0,
                bytes=nbytes,
                src=prefill_addr,
                dst=decode_addr,
            )
            async with self._lock:
                self._handoffs += 1
        else:
            # transfer failed (cache miss, dead decode server, no host
            # tier): continue the tail on the prefill server itself — a
            # colocated continuation, exact under the counter-keyed stream
            decode_addr = prefill_addr
            async with self._lock:
                self._handoff_fallbacks += 1

        # --- leg 2 -----------------------------------------------------
        leg2_body = dict(body)
        leg2_sp = dict(sp)
        leg2_sp["max_new_tokens"] = orig_max - len(toks)
        leg2_sp["min_new_tokens"] = max(0, orig_min - len(toks))
        leg2_body["sampling_params"] = leg2_sp
        leg2_body["input_ids"] = full_ids
        leg2_body["stream_id"] = int(leg1.get("stream_id", 0) or 0)
        try:
            status2, leg2 = await self._leg_post(
                decode_addr, "/generate", leg2_body, len(full_ids)
            )
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
            if self._health is not None:
                await self._health.report_failure(decode_addr, repr(e))
            if decode_addr == prefill_addr:
                return await self._proxy_failed(prefill_addr, rid, e)
            # the decode server died mid-tail; the prefill server still
            # retains the pages, so retry the tail there
            try:
                status2, leg2 = await self._leg_post(
                    prefill_addr, "/generate", leg2_body, len(full_ids)
                )
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e2:
                return await self._proxy_failed(prefill_addr, rid, e2)
        if status2 != 200:
            return web.json_response(leg2, status=status2)
        if self._health is not None:
            await self._health.report_success(decode_addr)
        merged = dict(leg2)
        merged["output_tokens"] = toks + [
            int(t) for t in leg2.get("output_tokens", [])
        ]
        merged["output_logprobs"] = list(
            leg1.get("output_logprobs", [])
        ) + list(leg2.get("output_logprobs", []))
        merged["output_versions"] = list(
            leg1.get("output_versions", [])
        ) + list(leg2.get("output_versions", []))
        # the admission that mattered for warm-start accounting is leg 1's
        merged["cache_hit_tokens"] = leg1.get("cache_hit_tokens", 0)
        merged["handoff"] = moved
        return web.json_response(merged, status=200)

    async def _proxy_failed(
        self, addr: str, rid: str, exc: BaseException
    ) -> web.Response:
        """A proxied request died at the transport layer: count the strike
        against the backend's breaker, break this rid's affinity so its
        resubmission re-places, and surface 502 — the client's failover
        loop (RemoteInfEngine) owns the resubmit, because only it knows the
        tokens generated so far."""
        async with self._lock:
            if self._rid_to_addr.get(rid) == addr:
                del self._rid_to_addr[rid]
                self._failovers += 1
        if self._health is not None:
            await self._health.report_failure(addr, repr(exc))
        return web.json_response(
            {"error": f"backend {addr} unreachable: {exc!r}"}, status=502
        )

    async def allocate_request(self, request: web.Request) -> web.Response:
        """Admission control for a new rollout sample.  Returns an allocation
        lease + the server the client should use, or 409 when the fleet is
        staleness-bound (reference is_staled, gserver_manager.py:334)."""
        await request.json()  # body reserved for future fields (qid, ...)
        async with self._lock:
            cap = self._capacity()
            if cap is None:
                # gate disabled (train_batch_size=0): admit freely WITHOUT
                # a lease — leases would never be pruned (no capacity
                # checks) and crashed clients would leak them forever
                return web.json_response(
                    {"version": self.version, "staled": False,
                     "alloc_id": None}
                )
            if cap <= 0:
                return web.json_response(
                    {"staled": True, "version": self.version}, status=409
                )
            alloc_id = uuid.uuid4().hex
            self._running[alloc_id] = time.monotonic()
        # note: no _server_for_rid here — the client routes its own
        # /generate traffic, and inserting one-shot qids would evict live
        # rid affinities from the LRU
        return web.json_response(
            {"version": self.version, "staled": False, "alloc_id": alloc_id}
        )

    async def finish_request(self, request: web.Request) -> web.Response:
        body = await request.json()
        alloc_id = body.get("alloc_id", "")
        async with self._lock:
            if alloc_id in self._running:
                del self._running[alloc_id]
            elif alloc_id:
                # the lease was TTL-reclaimed (client stalled past alloc_ttl
                # and its slot is already re-placeable): reject the late
                # completion outright — counting it would double-book the
                # admission budget against whoever now holds the slot
                return web.json_response({"ok": False, "expired": True})
            elif self._running:
                # legacy caller without a lease id: free the oldest.  A
                # KNOWN-but-absent id (TTL-pruned lease) must NOT pop some
                # other client's live lease — that would double-free
                # admissions and let the fleet overshoot the budget.
                self._running.pop(next(iter(self._running)))
            if body.get("accepted", True):
                self._accepted += 1
        return web.json_response({"ok": True})

    async def update_weights(self, request: web.Request) -> web.Response:
        body = await request.json()
        version = await self._flush_and_update(
            body["path"], body.get("version")
        )
        return web.json_response({"ok": True, "version": version})

    async def set_version(self, request: web.Request) -> web.Response:
        """Trainer-pushed version signal for transfer-mode fleets, where no
        disk checkpoint exists for the watcher to see (ADVICE r3): without
        it the staleness gate's budget (offpolicyness + version + 1) * bs
        never grows and admission wedges at 409 forever."""
        body = await request.json()
        version = int(body["version"])
        async with self._lock:
            self.version = max(self.version, version)
        return web.json_response({"ok": True, "version": self.version})

    async def pause(self, request: web.Request) -> web.Response:
        await self._fanout("/pause_generation", {})
        return web.json_response({"ok": True})

    async def resume(self, request: web.Request) -> web.Response:
        await self._fanout("/continue_generation", {})
        return web.json_response({"ok": True})

    async def health(self, request: web.Request) -> web.Response:
        """Serve the health-checker's CACHED view (satellite: the old code
        re-probed all backends inline per scrape — 5 s worst case per hit).
        One probe sweep is only forced when a backend has never been
        probed at all (startup race, or probe loop disabled in tests)."""
        if self._health is None:
            return web.json_response(
                {"status": "starting", "version": self.version, "servers": {}},
                status=503,
            )
        states = await self._health.snapshot()
        if any(s["age_s"] is None for s in states.values()):
            await self._health.probe_now()
            states = await self._health.snapshot()
        ok = all(
            s["state"] in ("closed", "draining") for s in states.values()
        )
        freshness = max(
            (s["age_s"] for s in states.values() if s["age_s"] is not None),
            default=None,
        )
        return web.json_response(
            {"status": "ok" if ok else "degraded", "version": self.version,
             "servers": states, "freshness_s": freshness},
            status=200 if ok else 503,
        )

    async def drain(self, request: web.Request) -> web.Response:
        """Operator-requested graceful removal: no new placements, but the
        backend keeps receiving fanouts so in-flight work completes."""
        body = await request.json()
        addr = body.get("addr", "")
        ok = self._health is not None and await self._health.drain(addr)
        return web.json_response({"ok": ok}, status=200 if ok else 404)

    async def undrain(self, request: web.Request) -> web.Response:
        body = await request.json()
        addr = body.get("addr", "")
        ok = self._health is not None and await self._health.undrain(addr)
        return web.json_response({"ok": ok}, status=200 if ok else 404)

    async def metrics(self, request: web.Request) -> web.Response:
        # ledger fields are lock-guarded (C1), so the Prometheus path must
        # snapshot inside the handler under _lock — NOT via a sync scrape-time
        # collector that would read _running/_accepted without the lock
        async with self._lock:
            cap = self._capacity()
            snap = {
                "version": self.version,
                "inflight": dict(self._inflight),
                "requests_routed": dict(self._routed),
                "tokens_inflight": dict(self._tokens),
                "running": len(self._running),
                "accepted": self._accepted,
                "capacity": cap,
                "n_flushes": self.n_flushes,
                "failovers": self._failovers,
                "publish_partial_failures": self._publish_partial_failures,
                "handoffs": self._handoffs,
                "handoff_fallbacks": self._handoff_fallbacks,
                "roles": dict(self._roles),
            }
        snap["backend_states"] = (
            await self._health.snapshot() if self._health is not None else {}
        )
        if telemetry.wants_prometheus(
            request.query.get("format"), request.headers.get("Accept", "")
        ):
            reg = telemetry.ROUTER
            reg.gauge("weight_version", "fleet weight version").set(snap["version"])
            reg.gauge("rollout_running", "leased rollout allocations").set(
                snap["running"]
            )
            reg.counter("rollout_accepted_total", "accepted rollouts").set_total(
                snap["accepted"]
            )
            reg.gauge(
                "admission_capacity", "remaining staleness-gate admissions"
            ).set(-1 if cap is None else cap)
            reg.counter("flushes_total", "fleet weight flushes").set_total(
                snap["n_flushes"]
            )
            for addr, v in snap["requests_routed"].items():
                reg.counter("requests_routed_total", "requests per backend").set_total(
                    v, server=addr
                )
            for addr, v in snap["inflight"].items():
                reg.gauge("requests_inflight", "in-flight per backend").set(
                    v, server=addr
                )
            for addr, v in snap["tokens_inflight"].items():
                reg.gauge("tokens_inflight", "in-flight tokens per backend").set(
                    v, server=addr
                )
            reg.counter(
                "failovers_total",
                "rid/group affinities rerouted off failed backends",
            ).set_total(snap["failovers"])
            reg.counter(
                "areal_publish_partial_failures_total",
                "fleet members missed by weight publishes",
            ).set_total(snap["publish_partial_failures"])
            reg.counter(
                "handoffs_total",
                "completed prefill->decode KV handoffs",
            ).set_total(snap["handoffs"])
            reg.counter(
                "handoff_fallbacks_total",
                "disaggregated requests that fell back to colocated "
                "placement (empty pool, backpressure, or transfer failure)",
            ).set_total(snap["handoff_fallbacks"])
            state_gauge = reg.gauge(
                "backend_state",
                "circuit state per backend "
                "(0=closed 1=half_open 2=open 3=draining)",
            )
            for addr, st in snap["backend_states"].items():
                state_gauge.set(STATE_CODES[st["state"]], server=addr)
            return web.Response(
                text=reg.render_prometheus(), content_type="text/plain"
            )
        return web.json_response(snap)

    # ------------------------ flush + update ----------------------------

    async def _one_post(self, addr: str, endpoint: str, payload: dict,
                        timeout: float = 300.0):
        async with self._session.post(
            f"http://{addr}{endpoint}",
            json=payload,
            timeout=aiohttp.ClientTimeout(total=timeout),
        ) as resp:
            resp.raise_for_status()
            return await resp.json()

    def _alive_targets(self) -> List[str]:
        """Fanout recipients: everyone the breaker considers reachable
        (closed + draining).  Tripped-open backends are skipped — they get
        current weights through the rejoin path instead."""
        if self._health is None:
            return self.addresses
        alive = set(self._health.alive_cache)
        return [a for a in self.addresses if a in alive]

    async def _fanout(
        self,
        endpoint: str,
        payload: dict,
        timeout: float = 300.0,
        targets: Optional[List[str]] = None,
    ) -> Dict[str, object]:
        """POST to each target, returning per-server outcomes (an Exception
        value marks that server's failure) — one dead fleet member must
        never wedge a whole fanout behind its timeout."""
        if targets is None:
            targets = self._alive_targets()
        results = await asyncio.gather(
            *[self._one_post(a, endpoint, payload, timeout) for a in targets],
            return_exceptions=True,
        )
        return dict(zip(targets, results))

    async def _flush_and_update(self, path: str, version: Optional[int]) -> int:
        """Pause every backend (in-flight requests abort and resume client-
        side with fresh weights — interruptible generation), swap weights,
        resume (reference flush_requests_and_update_weights,
        gserver_manager.py:158).

        Degraded mode: the publish proceeds over whatever subset of the
        fleet is reachable; per-server failures are counted (and strike the
        breaker) rather than failing the publish, as long as at least one
        backend took the weights."""
        async with self._flush_lock:
            targets = self._alive_targets()
            try:
                await self._fanout("/pause_generation", {}, targets=targets)
                outcomes = await self._fanout(
                    "/update_weights_from_disk",
                    {"path": path, "version": version},
                    targets=targets,
                )
            finally:
                # always resume — a failed pause/update on one backend must
                # not leave the healthy rest of the fleet parked
                await asyncio.gather(
                    *[
                        self._one_post(a, "/continue_generation", {})
                        for a in targets
                    ],
                    return_exceptions=True,
                )
            successes = {
                a: r
                for a, r in outcomes.items()
                if not isinstance(r, BaseException)
            }
            for a, r in outcomes.items():
                if isinstance(r, BaseException):
                    logger.warning(
                        f"weight publish to {a} failed: {r!r}"
                    )
                    if self._health is not None:
                        await self._health.report_failure(a, repr(r))
            if not successes:
                raise RuntimeError(
                    f"weight publish {path} v{version} reached no backend "
                    f"(targets={targets})"
                )
            missed = len(self.addresses) - len(successes)
            async with self._lock:
                self.version = (
                    version
                    if version is not None
                    else max(r.get("version", 0) for r in successes.values())
                )
                self.n_flushes += 1
                self._last_publish = (path, self.version)
                self._publish_partial_failures += missed
            if missed:
                logger.warning(
                    f"degraded publish: v{self.version} on "
                    f"{len(successes)}/{len(self.addresses)} servers "
                    f"({missed} missed; rejoin will reload them)"
                )
            else:
                logger.info(f"weights updated to v{self.version} on "
                            f"{len(successes)} servers")
            return self.version

    async def _probe_backend(self, addr: str) -> dict:
        """Health-checker probe: GET /health with a short timeout (a probe
        must answer fast or count as a failure; the default request timeout
        is an hour)."""
        async with self._session.get(
            f"http://{addr}/health",
            timeout=aiohttp.ClientTimeout(
                total=self.config.health_probe_timeout
            ),
        ) as resp:
            resp.raise_for_status()
            health = await resp.json()
        # role advertisement rides the health probe, so a restarted
        # backend that changed roles is re-pooled within one probe sweep
        if health.get("role"):
            self._roles[addr] = str(health["role"])
        return health

    async def _poll_decode_occupancy(self):
        """Decode-pool placement signal: poll decode-role backends'
        /metrics for tier occupancy (occupied slots / total slots)."""
        while True:
            await asyncio.sleep(self.config.occupancy_poll_interval)
            try:
                targets = [
                    a for a in self.addresses
                    if self._roles.get(a) == "decode"
                ]

                async def probe(a: str) -> Optional[float]:
                    try:
                        async with self._session.get(
                            f"http://{a}/metrics",
                            timeout=aiohttp.ClientTimeout(total=2),
                        ) as resp:
                            m = await resp.json()
                        occ = m.get("tier_occupancy") or []
                        slots = m.get("tier_slots") or []
                        total = sum(slots)
                        if not total:
                            return None
                        return float(sum(occ)) / float(total)
                    except Exception:  # noqa: BLE001 — unreachable = no info
                        return None

                vals = await asyncio.gather(*[probe(a) for a in targets])
                for a, v in zip(targets, vals):
                    if v is not None:
                        self._decode_occ[a] = v
            except Exception:  # noqa: BLE001 — poller must survive blips
                logger.exception("decode occupancy poll failed")

    async def _verify_rejoin(self, addr: str, health: dict) -> bool:
        """Gate for half_open -> closed: a backend that answered after being
        declared dead (restart, network heal) may be serving stale weights.
        Check its served version against the fleet's; force-reload from the
        last published checkpoint when behind.  Returning False keeps it
        tripped open until the next probe retries."""
        served = int(health.get("version", -1))
        async with self._lock:
            fleet = self.version
            last = self._last_publish
        if served >= fleet:
            return True
        if last is None:
            # no disk publish on record (transfer-mode fleet, or no publish
            # yet): nothing to reload from — admit and let the trainer's
            # next transfer publish catch it up
            logger.warning(
                f"rejoining {addr} serves v{served} < fleet v{fleet} but no "
                "publish path is recorded; admitting as-is"
            )
            return True
        path, _ = last
        try:
            result = await self._one_post(
                addr,
                "/update_weights_from_disk",
                {"path": path, "version": fleet},
            )
        except Exception as e:  # noqa: BLE001 — any failure blocks rejoin
            logger.warning(f"rejoin reload of {addr} failed: {e!r}")
            return False
        reloaded = int(result.get("version", -1))
        logger.info(
            f"rejoining {addr}: force-reloaded v{served} -> v{reloaded} "
            f"(fleet v{fleet})"
        )
        return reloaded >= fleet

    async def _poll_backend_versions(self):
        """Transfer-mode safety net: the binary-chunk commit bumps each gen
        server's served version (gen/server.py /health reports it), so
        adopting the fleet's max keeps the staleness gate's budget moving
        even when the trainer never POSTs /set_version."""
        while True:
            await asyncio.sleep(self.config.version_poll_interval)
            try:
                async def probe(a: str) -> int:
                    try:
                        async with self._session.get(
                            f"http://{a}/health",
                            timeout=aiohttp.ClientTimeout(total=5),
                        ) as resp:
                            return int((await resp.json()).get("version", 0))
                    except Exception:  # noqa: BLE001 — unreachable = no info
                        return 0

                versions = await asyncio.gather(
                    *[probe(a) for a in self.addresses]
                )
                newest = max(versions, default=0)
                async with self._lock:
                    if newest > self.version:
                        logger.info(
                            f"adopting fleet version v{newest} from backend "
                            "health (transfer-mode publish)"
                        )
                        self.version = newest
            except Exception:  # noqa: BLE001 — poller must survive blips
                logger.exception("backend version poll failed")

    async def _watch_checkpoints(self):
        """Poll name_resolve for trainer-published weight versions newer than
        what the fleet serves (reference check_new_params,
        gserver_manager.py:131)."""
        root = names.update_weights_from_disk(
            self.config.experiment_name, self.config.trial_name, ""
        ).rstrip("/")
        while True:
            try:
                keys = name_resolve.find_subtree(root)
                new = [
                    int(v)
                    for k in keys
                    if (v := k.rsplit("/", 1)[-1]).isdigit()
                    and int(v) > self.version
                ]
                if new:
                    version = max(new)
                    path = f"{self.config.weights_path}/v{version}"
                    await self._flush_and_update(path, version)
            except Exception:  # noqa: BLE001 — watcher must survive blips
                logger.exception("checkpoint watcher iteration failed")
            await asyncio.sleep(self.config.poll_interval)

    # ----------------------------- wiring -------------------------------

    async def on_startup(self, app):
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.config.request_timeout),
            connector=aiohttp.TCPConnector(limit=0),
        )
        if not self.addresses:
            self.addresses = await self._discover()
        self._inflight = {a: 0 for a in self.addresses}
        self._routed = {a: 0 for a in self.addresses}
        self._tokens = {a: 0 for a in self.addresses}
        self._health = BackendHealthChecker(
            self.addresses,
            self._probe_backend,
            failure_threshold=self.config.health_failure_threshold,
            interval=self.config.health_check_interval,
            on_death=self._on_backend_death,
            verify_rejoin=self._verify_rejoin,
        )
        self._health.start()
        if self.config.disagg:
            self._handoff_sem = asyncio.Semaphore(
                max(1, self.config.handoff_max_inflight)
            )
            # prime the role map so the first /generate can already place
            # disaggregated (the health probes keep it fresh afterwards)
            for a in self.addresses:
                try:
                    await self._probe_backend(a)
                except Exception:  # noqa: BLE001 — backend not up yet
                    continue
            if self.config.occupancy_poll_interval > 0:
                self._occ_poller = asyncio.create_task(
                    self._poll_decode_occupancy()
                )
            logger.info(
                "disaggregated serving on: roles="
                + str({a: self._roles.get(a, '?') for a in self.addresses})
            )
        if self.config.weights_path and self.config.experiment_name:
            self._watcher = asyncio.create_task(self._watch_checkpoints())
        elif (
            self.config.train_batch_size > 0
            and self.config.version_poll_interval > 0
        ):
            # gate enabled with no disk watcher: transfer-mode deployment —
            # the gate needs SOME version source or admission wedges
            self._version_poller = asyncio.create_task(
                self._poll_backend_versions()
            )
        logger.info(f"router over {len(self.addresses)} servers: {self.addresses}")

    async def _discover(self, timeout: float = 300.0) -> List[str]:
        key = names.gen_servers(
            self.config.experiment_name, self.config.trial_name
        )
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            found = name_resolve.get_subtree(key)
            if found:
                return sorted(found)
            await asyncio.sleep(0.5)
        raise TimeoutError(f"no generation servers under {key}")

    async def on_cleanup(self, app):
        if self._watcher is not None:
            self._watcher.cancel()
        if self._version_poller is not None:
            self._version_poller.cancel()
        if self._occ_poller is not None:
            self._occ_poller.cancel()
        if self._health is not None:
            await self._health.stop()
        if self._session is not None:
            await self._session.close()

    def app(self) -> web.Application:
        app = web.Application(client_max_size=1024**3)
        app.router.add_post("/generate", self.generate)
        app.router.add_post("/generate_batch", self.generate_batch)
        app.router.add_post("/allocate_request", self.allocate_request)
        app.router.add_post("/finish_request", self.finish_request)
        app.router.add_post("/update_weights", self.update_weights)
        app.router.add_post("/set_version", self.set_version)
        app.router.add_post("/pause_generation", self.pause)
        app.router.add_post("/continue_generation", self.resume)
        app.router.add_post("/drain", self.drain)
        app.router.add_post("/undrain", self.undrain)
        app.router.add_get("/health", self.health)
        app.router.add_get("/metrics", self.metrics)
        app.on_startup.append(self.on_startup)
        app.on_cleanup.append(self.on_cleanup)
        return app


def main():
    name_resolve.reconfigure_from_env()
    p = argparse.ArgumentParser()
    p.add_argument("--experiment-name", default="")
    p.add_argument("--trial-name", default="")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--addrs", default="", help="comma-separated backend addrs "
                   "(default: discover via name_resolve)")
    p.add_argument("--schedule-policy", default="least_requests")
    p.add_argument("--train-batch-size", type=int, default=0)
    p.add_argument("--max-head-offpolicyness", type=int, default=0)
    p.add_argument("--weights-path", default="")
    p.add_argument("--disagg", action="store_true",
                   help="disaggregated prefill/decode serving: run "
                        "/generate as a prefill leg + KV handoff + decode "
                        "leg when both role pools are populated")
    p.add_argument("--handoff-max-inflight", type=int, default=4)
    args = p.parse_args()
    cfg = RouterConfig(
        experiment_name=args.experiment_name,
        trial_name=args.trial_name,
        schedule_policy=args.schedule_policy,
        train_batch_size=args.train_batch_size,
        max_head_offpolicyness=args.max_head_offpolicyness,
        weights_path=args.weights_path,
        disagg=args.disagg,
        handoff_max_inflight=args.handoff_max_inflight,
    )
    router = Router(cfg, addresses=args.addrs.split(",") if args.addrs else None)
    port = args.port or network.find_free_port()
    if args.experiment_name:
        name_resolve.add(
            names.gen_router(args.experiment_name, args.trial_name),
            f"{network.gethostip()}:{port}",
            replace=True,
        )
    logger.info(f"router on :{port}")
    web.run_app(router.app(), port=port, print=None)


if __name__ == "__main__":
    main()
