"""Offline eval harness: run the real CLI against a tiny checkpoint and a
tiny gsm8k jsonl (reference: evaluation/ offline benchmark eval)."""

import json
import os
import subprocess
import sys

import pytest

from tests.fixtures import make_gsm8k_jsonl, make_tiny_ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_eval_cli_end_to_end(tmp_path):
    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data = make_gsm8k_jsonl(str(tmp_path / "test.jsonl"), n=6)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [
            sys.executable, "-m", "areal_tpu.evaluation.run_eval",
            "--ckpt", str(ckpt),
            "--dataset", data,
            "--k", "2",
            "--max-new-tokens", "16",
            "--max-seq-len", "256",
            "--limit", "4",
            "--type", "gsm8k",
        ],
        capture_output=True,
        text=True,
        timeout=600,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    metrics = json.loads(proc.stdout.strip().splitlines()[-1])
    assert metrics["n_problems"] == 4 and metrics["k"] == 2
    for key in ("pass@1", "pass@2", "majority"):
        assert 0.0 <= metrics[key] <= 1.0
    assert metrics["gen_tokens"] > 0


def test_evaluate_checkpoint_api(tmp_path):
    from areal_tpu.evaluation import evaluate_checkpoint

    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data = make_gsm8k_jsonl(str(tmp_path / "t.jsonl"), n=3)
    result = evaluate_checkpoint(
        ckpt=str(ckpt),
        dataset=data,
        dataset_type="gsm8k",
        k=1,
        max_new_tokens=8,
        max_seq_len=128,
        n_slots=4,
        limit=2,
    )
    assert result["n_problems"] == 2
    assert "pass@1" in result


def _make_bench_root(tmp_path, names=("aime24", "amc23", "math_500")):
    """Tiny benchmark files in the reference's data layout."""
    root = tmp_path / "benchdata"
    for name in names:
        d = root / name
        d.mkdir(parents=True)
        with open(d / "test.jsonl", "w") as f:
            for i in range(3):
                f.write(json.dumps({
                    "id": i,
                    "problem": f"What is {i} + {i + 1}?",
                    "answer": str(2 * i + 1),
                }) + "\n")
    return str(root)


def test_benchmark_registry_and_loader(tmp_path):
    from areal_tpu.evaluation.benchmarks import BENCHMARKS, load_benchmark

    # the reference's suite is covered: AIME 24/25, AMC, MATH-500, GPQA
    assert {"aime24", "aime25", "amc23", "math_500", "gpqa_diamond"} <= set(
        BENCHMARKS
    )
    root = _make_bench_root(tmp_path)
    probs = load_benchmark("aime24", data_root=root)
    assert len(probs) == 3
    assert "boxed" in probs[0]["messages"][0]["content"]
    assert probs[1]["answer"] == "3"
    with pytest.raises(KeyError):
        load_benchmark("nope", data_root=root)
    with pytest.raises(FileNotFoundError, match="fetch_eval_data"):
        load_benchmark("aime25", data_root=root)


def test_benchmark_suite_one_command(tmp_path):
    """VERDICT r3 missing #4: one command evaluates a saved ckpt on >= 3
    benchmarks with majority@k."""
    from areal_tpu.evaluation.run_eval import evaluate_benchmark_suite

    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    root = _make_bench_root(tmp_path)
    result = evaluate_benchmark_suite(
        ckpt=str(ckpt),
        benchmarks=["aime24", "amc23", "math_500"],
        data_root=root,
        k=2,
        max_new_tokens=8,
        max_seq_len=128,
        n_slots=4,
    )
    assert set(result["benchmarks"]) == {"aime24", "amc23", "math_500"}
    for m in result["benchmarks"].values():
        assert m["n_problems"] == 3 and "majority" in m and "pass@2" in m
    assert 0.0 <= result["avg_pass@1"] <= 1.0
    assert 0.0 <= result["avg_majority"] <= 1.0


FIXTURE_EVAL_ROOT = os.path.join(REPO, "tests", "data", "eval")


def test_gpqa_fixture_options_appear_once():
    """The dataset's 'question' field already embeds the lettered options;
    the loader must build from ori_question + labeled_options so each
    option renders exactly once."""
    from areal_tpu.evaluation.benchmarks import load_benchmark

    probs = load_benchmark("gpqa_diamond", data_root=FIXTURE_EVAL_ROOT)
    assert len(probs) == 5
    for prob in probs:
        content = prob["messages"][0]["content"]
        assert content.count("A. ") == 1, content
        assert prob["answer"] in "ABCD"
        assert "chosen option" in content  # the multiple-choice instruction


def test_benchmark_suite_all_five_offline(tmp_path):
    """VERDICT r4 missing #5: the checked-in 5-problem fixtures let the
    whole benchmark suite (incl. gpqa's multiple-choice path) run without
    network."""
    from areal_tpu.evaluation.benchmarks import BENCHMARKS
    from areal_tpu.evaluation.run_eval import evaluate_benchmark_suite

    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    result = evaluate_benchmark_suite(
        ckpt=str(ckpt),
        benchmarks=sorted(BENCHMARKS),
        data_root=FIXTURE_EVAL_ROOT,
        k=1,
        max_new_tokens=8,
        max_seq_len=192,
        n_slots=4,
        limit=2,
    )
    assert set(result["benchmarks"]) == set(BENCHMARKS)
    for m in result["benchmarks"].values():
        assert m["n_problems"] == 2 and m["gen_tokens"] > 0
    assert 0.0 <= result["avg_pass@1"] <= 1.0


@pytest.mark.slow
def test_auto_eval_drives_run_eval_offline(tmp_path):
    """The AutomaticEvaluator sidecar spawns the real run_eval CLI against
    the checked-in benchmark fixtures — the full offline eval loop with no
    network."""
    from areal_tpu.utils.auto_eval import AutoEvalConfig, AutomaticEvaluator

    root = tmp_path / "ckpts"
    ckpt = root / "globalstep5"
    make_tiny_ckpt(str(ckpt))
    ev = AutomaticEvaluator(
        AutoEvalConfig(
            ckpt_root=str(root),
            eval_cmd=(
                f"{sys.executable} -m areal_tpu.evaluation.run_eval "
                "--ckpt {ckpt} --benchmark aime24,gpqa_diamond "
                f"--data-root {FIXTURE_EVAL_ROOT} "
                "--k 1 --max-new-tokens 8 --max-seq-len 192 "
                "--n-slots 4 --limit 2"
            ),
            env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
            timeout=590,
        )
    )
    results = ev.step()
    assert [r["name"] for r in results] == ["globalstep5"]
    assert results[0]["rc"] == 0, results[0]
    metrics = results[0]["metrics"]
    assert set(metrics["benchmarks"]) == {"aime24", "gpqa_diamond"}
    assert ev.step() == []  # recorded: never re-evaluated
