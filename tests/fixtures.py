"""Offline test fixtures: tiny tokenizer + tiny HF checkpoint dir.

The reference builds fixtures the same way (realhf/tests/fixtures.py trains
a fresh WordPiece tokenizer and saves a cpu-sized model) because CI has no
network access.
"""

import json
import os


CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "{{ message['role'] }}: {{ message['content'] }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}assistant: {% endif %}"
)


def make_tiny_tokenizer(out_dir: str, vocab_size: int = 256):
    """Train a tiny byte-level BPE tokenizer on synthetic text and save it as
    a transformers PreTrainedTokenizerFast with a simple chat template."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<|endoftext|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    corpus = [
        "What is 1 + 1? The answer is 2.",
        "Compute 3 * 4. #### 12",
        "Please reason step by step, and put your final answer within \\boxed{}.",
        "user assistant system: numbers 0 1 2 3 4 5 6 7 8 9 10 11 12 13",
    ] * 50
    tok.train_from_iterator(corpus, trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok,
        eos_token="<|endoftext|>",
        pad_token="<|endoftext|>",
    )
    fast.chat_template = CHAT_TEMPLATE
    os.makedirs(out_dir, exist_ok=True)
    fast.save_pretrained(out_dir)
    return fast


def make_tiny_ckpt(out_dir: str, vocab_size: int = 384, seed: int = 0):
    """Tiny Qwen2-style checkpoint dir (weights + config + tokenizer) that
    both the train engine and the generation server can load."""
    import jax

    from areal_tpu.models import init_params
    from areal_tpu.models.hf import save_hf_checkpoint
    from areal_tpu.models.model_config import tiny_config

    tokenizer = make_tiny_tokenizer(out_dir, vocab_size=256)
    cfg = tiny_config(
        vocab_size=vocab_size,
        qkv_bias=True,
        hf_architecture="Qwen2ForCausalLM",
        eos_token_id=tokenizer.eos_token_id,
    )
    params = init_params(cfg, jax.random.PRNGKey(seed))
    save_hf_checkpoint(params, cfg, out_dir, save_dtype="float32")
    return cfg


def make_gsm8k_jsonl(path: str, n: int = 32):
    rows = [
        {
            "question": f"What is {i} + {i + 1}?",
            "answer": f"Adding gives {2 * i + 1}.\n#### {2 * i + 1}",
        }
        for i in range(n)
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return path


def make_tiny_vlm_ckpt(out_dir: str, vocab_size: int = 384, seed: int = 0):
    """Tiny Qwen2-VL-style checkpoint (text + vision tower + tokenizer)
    loadable by TransformerConfig.from_hf + the train/serving engines."""
    import jax

    from areal_tpu.models import init_params
    from areal_tpu.models.hf import save_hf_checkpoint
    from areal_tpu.models.model_config import VisionConfig, tiny_config
    from areal_tpu.models.vision import init_vision_params

    tokenizer = make_tiny_tokenizer(out_dir, vocab_size=256)
    image_token_id = 251  # inside the tokenizer vocab, unused by text
    vcfg = VisionConfig(
        patch_size=2,
        temporal_patch_size=1,
        in_channels=3,
        hidden_size=16,
        intermediate_size=32,
        num_layers=1,
        num_heads=2,
        spatial_merge_size=2,
        out_hidden_size=64,
    )
    cfg = tiny_config(
        vocab_size=vocab_size,
        qkv_bias=True,
        hf_architecture="Qwen2VLForConditionalGeneration",
        eos_token_id=tokenizer.eos_token_id,
    ).replace(vision=vcfg, image_token_id=image_token_id,
              mrope_section=(2, 3, 3))
    params = init_params(cfg, jax.random.PRNGKey(seed))
    params["vision"] = init_vision_params(vcfg, jax.random.PRNGKey(seed + 1))
    save_hf_checkpoint(params, cfg, out_dir, save_dtype="float32")
    return cfg


def make_clevr_jsonl(path: str, cfg, n: int = 8, rng_seed: int = 0):
    """Pre-patchified CLEVR-count manifest rows: input_ids with placeholder
    runs, inline pixel patches, and the integer answer."""
    import json

    import numpy as np

    vcfg = cfg.vision
    rng = np.random.default_rng(rng_seed)
    n_placeholder = 4  # 4x4 patches / merge 2x2
    rows = []
    for i in range(n):
        ids = [5, 6 + (i % 7)] + [cfg.image_token_id] * n_placeholder + [20, 21]
        rows.append({
            "input_ids": ids,
            "messages": f"How many objects? (scene {i})",
            "answer": i % 5,
            "pixel_values": rng.normal(
                size=(16, vcfg.patch_dim)
            ).astype(np.float32).round(3).tolist(),
            "image_grid_thw": [[1, 4, 4]],
        })
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    return path
