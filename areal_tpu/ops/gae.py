"""Generalised Advantage Estimation over padded and packed sequences.

TPU-native counterpart of the reference's CUDA `cugae` kernel
(csrc/cugae/gae.cu:10-60 `gae_1d_nolp_misalign`) and lite's python GAE loop
(areal/engine/ppo/actor.py:136-151).  Instead of a hand-written backward CUDA
kernel, a single reverse `jax.lax.scan` runs the recurrence

    adv[t] = delta[t] + gamma * lam * (not boundary[t]) * adv[t+1]
    delta[t] = r[t] + gamma * V[t+1] * (not boundary[t]) - V[t]

across the whole (packed) buffer at once; sequence boundaries reset the
carry, which is exactly the cu_seqlens-misalignment handling of the CUDA
kernel, but shape-static and fusable by XLA.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def gae_padded(
    rewards: jax.Array,  # [B, L]
    values: jax.Array,  # [B, L]
    mask: jax.Array,  # [B, L] 1 where token is valid
    gamma: float,
    lam: float,
) -> Tuple[jax.Array, jax.Array]:
    """GAE over right-padded batches; bootstrap value after the last valid
    token is 0 (terminal).  Returns (advantages, returns) masked to 0 on pads.
    """
    mask = mask.astype(jnp.float32)
    rewards = rewards.astype(jnp.float32) * mask
    values = values.astype(jnp.float32) * mask
    # next value: V[t+1] if t+1 valid else 0
    nxt = jnp.concatenate([values[:, 1:], jnp.zeros_like(values[:, :1])], axis=1)
    nxt_valid = jnp.concatenate([mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1)
    delta = rewards + gamma * nxt * nxt_valid - values

    def step(carry, xs):
        d, valid_next = xs
        adv = d + gamma * lam * valid_next * carry
        return adv, adv

    # reverse scan over time, batched over B via vmap-free transpose
    _, adv_rev = jax.lax.scan(
        step,
        jnp.zeros(rewards.shape[0], jnp.float32),
        (delta.T[::-1], nxt_valid.T[::-1]),
    )
    adv = adv_rev[::-1].T * mask
    returns = adv + values
    return adv, returns * mask


def gae_segments(
    rewards: jax.Array,  # [T] packed
    values: jax.Array,  # [T]
    segment_ids: jax.Array,  # [T], -1 on filler
    gamma: float,
    lam: float,
) -> Tuple[jax.Array, jax.Array]:
    """GAE over a packed flat buffer; boundaries where segment id changes.

    Equivalent to cugae's `gae_1d_nolp_misalign` with per-sequence terminal
    bootstrap 0 (RLVR episodes end at the final token).
    """
    valid = segment_ids >= 0
    rewards = jnp.where(valid, rewards.astype(jnp.float32), 0.0)
    values = jnp.where(valid, values.astype(jnp.float32), 0.0)
    nxt_same = jnp.concatenate(
        [(segment_ids[1:] == segment_ids[:-1]) & valid[1:], jnp.zeros((1,), bool)]
    )
    nxt = jnp.concatenate([values[1:], jnp.zeros((1,), jnp.float32)])
    delta = rewards + gamma * nxt * nxt_same - values

    def step(carry, xs):
        d, same = xs
        adv = d + gamma * lam * same * carry
        return adv, adv

    _, adv_rev = jax.lax.scan(
        step, jnp.zeros((), jnp.float32), (delta[::-1], nxt_same[::-1])
    )
    adv = jnp.where(valid, adv_rev[::-1], 0.0)
    returns = adv + values
    return adv, jnp.where(valid, returns, 0.0)


# ---------------------------------------------------------------------------
# Host-side numpy reference (used by tests and by host-side advantage calc)
# ---------------------------------------------------------------------------


def gae_numpy(
    rewards: np.ndarray, values: np.ndarray, lens: np.ndarray, gamma: float, lam: float
):
    """Straightforward per-sequence loop over a padded [B, L] batch."""
    B, L = rewards.shape
    adv = np.zeros_like(rewards, dtype=np.float64)
    for b in range(B):
        n = int(lens[b])
        carry = 0.0
        for t in reversed(range(n)):
            nxt = values[b, t + 1] if t + 1 < n else 0.0
            delta = rewards[b, t] + gamma * nxt - values[b, t]
            carry = delta + gamma * lam * carry
            adv[b, t] = carry
    ret = adv + np.where(
        np.arange(L)[None, :] < lens[:, None], values.astype(np.float64), 0.0
    )
    return adv, ret
