"""Bradley-Terry pairwise reward-model engine.

Behavioral counterpart of the reference's `RWEngine`
(areal/engine/rw/rw_engine.py): batches interleave (chosen, rejected) rows;
the score of a sequence is the value head's output at its final token, and
the loss is -log sigmoid(score_chosen - score_rejected).

Unlike the per-token engines, sequence identity matters for pairing, so this
engine keeps the padded one-sequence-per-row layout instead of row packing
(score extraction and pairing stay trivially correct; RW training is not a
throughput-critical path).
"""

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.engine.ppo.critic import JaxPPOCritic
from areal_tpu.ops.functional import pairwise_reward_loss_fn


def _rw_loss(values, mb):
    """values [R, L] where rows alternate chosen/rejected and each row holds
    exactly one sequence (segment 0 tokens)."""
    valid = mb["segment_ids"] >= 0
    lens = jnp.sum(valid, axis=-1)
    idx = jnp.maximum(lens - 1, 0)
    scores = jnp.take_along_axis(
        values.astype(jnp.float32), idx[:, None], axis=-1
    )[:, 0]
    real = lens > 0  # filler rows from dp padding score nothing
    chosen, rejected = scores[0::2], scores[1::2]
    pair_real = real[0::2] & real[1::2]
    return pairwise_reward_loss_fn(chosen, rejected, pair_mask=pair_real)


class JaxRewardModelEngine(JaxPPOCritic):
    def _prepare_rows(self, batch, n_mbs):
        """One sequence per row (no FFD packing) so row index == sequence
        index and chosen/rejected interleaving survives."""
        from areal_tpu.utils.data import RowPackedBatch

        mask = batch["attention_mask"].astype(bool)
        B, L = mask.shape
        row_len = self._row_len(batch)
        dp = (self.mesh.shape["dp"] * self.mesh.shape["fsdp"]
              * self.mesh.shape.get("ep", 1))
        mult = n_mbs * dp * 2  # pairs must not straddle shard boundaries
        R = ((B + mult - 1) // mult) * mult
        lens = mask.sum(-1).astype(np.int32)
        if lens.max(initial=0) > row_len:
            raise ValueError(
                f"sequence of length {int(lens.max())} exceeds max_pack_length "
                f"bucket {row_len}"
            )
        data = {}
        for k, arr in batch.items():
            if k == "attention_mask" or not (
                arr.ndim >= 2 and arr.shape[:2] == (B, L)
            ):
                continue
            buf = np.zeros((R, row_len, *arr.shape[2:]), arr.dtype)
            for i in range(B):  # per-sequence copy: L may exceed row_len
                buf[i, : lens[i]] = arr[i, : lens[i]]
            data[k] = buf
        seg = np.full((R, row_len), -1, np.int32)
        pos = np.zeros((R, row_len), np.int32)
        for i in range(B):
            seg[i, : lens[i]] = 0
            pos[i, : lens[i]] = np.arange(lens[i])
        data["segment_ids"] = seg
        data["positions"] = pos
        data["input_ids"] = data["input_ids"].astype(np.int32)
        placements = [[(i, int(lens[i]))] if i < B else [] for i in range(R)]
        return (
            RowPackedBatch(data=data, placements=placements, row_len=row_len),
            data,
            row_len,
        )

    def train_rw(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if batch["attention_mask"].shape[0] % 2 != 0:
            raise ValueError("reward batches must interleave chosen/rejected pairs")
        stats = self.train_batch(
            batch,
            _rw_loss,
            loss_weight_fn=lambda b: float(
                np.sum(np.any(b["segment_ids"] >= 0, axis=-1)) // 2 or 1
            ),
        )
        n = max(stats.get("n_pairs", 1.0), 1.0)
        stats["acc"] = stats.get("acc", 0.0) / n
        return stats

    def evaluate_rw(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        stats = self.eval_batch(
            batch,
            _rw_loss,
            loss_weight_fn=lambda b: float(
                np.sum(np.any(b["segment_ids"] >= 0, axis=-1)) // 2 or 1
            ),
        )
        n = max(stats.get("n_pairs", 1.0), 1.0)
        stats["acc"] = stats.get("acc", 0.0) / n
        return stats
