"""OpenAI-compatible client tests (reference: experimental/openai/client.py
ArealOpenAI — chat surface, reward backfill, prefix-tree export)."""

import asyncio

import numpy as np
import pytest

from areal_tpu.experimental.openai_client import ArealOpenAI


class _Tok:
    """Minimal chat-template tokenizer: one token per character."""

    def apply_chat_template(self, messages, add_generation_prompt=True,
                            tokenize=True, **kw):
        text = "".join(f"<{m['role']}>{m['content']}" for m in messages)
        if add_generation_prompt:
            text += "<assistant>"
        return [ord(c) % 256 for c in text]

    def decode(self, tokens):
        return "".join(chr(t) for t in tokens)


class _FakeEngine:
    def __init__(self, reply="ok!"):
        self.reply = reply

    async def agenerate(self, req):
        out = [ord(c) for c in self.reply]

        class R:
            input_tokens = list(req.input_ids)
            output_tokens = out
            output_logprobs = [-0.1] * len(out)
            output_versions = [5] * len(out)
            input_len = len(req.input_ids)
            output_len = len(out)
            stop_reason = "stop"

        return R()


def _chat(client, messages):
    return asyncio.run(
        client.chat.completions.create(messages=messages, max_completion_tokens=8)
    )


def test_chat_surface_and_cache():
    client = ArealOpenAI(_FakeEngine("hi"), tokenizer=_Tok())
    resp = _chat(client, [{"role": "user", "content": "hello"}])
    assert resp.choices[0].message.content == "hi"
    assert resp.choices[0].finish_reason == "stop"
    comp = client.get_completions(resp.id)
    assert comp is not None and comp.text == "hi"
    assert comp.output_versions == [5, 5]
    assert resp.usage.completion_tokens == 2


def test_reward_discount_backfill():
    client = ArealOpenAI(_FakeEngine("a"), tokenizer=_Tok())
    messages = [{"role": "user", "content": "t0"}]
    ids = []
    for i in range(3):
        resp = _chat(client, messages)
        ids.append(resp.id)
        messages = messages + [
            {"role": "assistant", "content": "a"},
            {"role": "user", "content": f"t{i + 1}"},
        ]
    client.set_reward(ids[-1], 1.0)
    client.apply_reward_discount(turn_discount=0.5)
    rewards = [client.get_completions(c).reward for c in ids]
    # reward flows backward along the turn chain: 0.25, 0.5, 1.0
    np.testing.assert_allclose(rewards, [0.25, 0.5, 1.0])


def test_reward_discount_does_not_leak_across_conversations():
    """Interleaved independent conversations keep their rewards separate
    (the prefix-tree, not creation order, defines the chains)."""
    client = ArealOpenAI(_FakeEngine("a"), tokenizer=_Tok())
    conv_a = [{"role": "user", "content": "A"}]
    ra1 = _chat(client, conv_a)
    rb1 = _chat(client, [{"role": "user", "content": "B"}])  # unrelated
    conv_a2 = conv_a + [
        {"role": "assistant", "content": "a"},
        {"role": "user", "content": "A2"},
    ]
    ra2 = _chat(client, conv_a2)
    client.set_reward(ra2.id, 1.0)
    client.apply_reward_discount(turn_discount=0.5)
    assert client.get_completions(ra2.id).reward == 1.0
    assert client.get_completions(ra1.id).reward == 0.5  # parent of ra2
    assert client.get_completions(rb1.id).reward == 0.0  # isolated


def test_concat_export_returns_leaves_only():
    client = ArealOpenAI(_FakeEngine("yes"), tokenizer=_Tok())
    turn1 = [{"role": "user", "content": "q1"}]
    r1 = _chat(client, turn1)
    # second turn extends the first conversation (r1's reply included)
    turn2 = turn1 + [
        {"role": "assistant", "content": "yes"},
        {"role": "user", "content": "q2"},
    ]
    r2 = _chat(client, turn2)
    # an unrelated conversation
    r3 = _chat(client, [{"role": "user", "content": "other"}])

    leaves = client.export_completions(style="concat")
    assert set(leaves) == {r2.id, r3.id}
    assert set(client.export_completions(style="individual")) == {
        r1.id, r2.id, r3.id,
    }


def test_export_batch_shapes():
    client = ArealOpenAI(_FakeEngine("done"), tokenizer=_Tok())
    r = _chat(client, [{"role": "user", "content": "go"}])
    client.set_reward(r.id, 1.0)
    batch = client.export_batch(style="individual")
    B, L = batch["input_ids"].shape
    assert B == 1
    comp = client.get_completions(r.id)
    assert L == len(comp.input_tokens) + len(comp.output_tokens)
    assert batch["loss_mask"][0].sum() == len(comp.output_tokens)
    assert batch["rewards"][0] == 1.0
    with pytest.raises(ValueError):
        ArealOpenAI(_FakeEngine(), tokenizer=_Tok()).export_batch()


def test_concat_export_trains_ancestor_turns():
    """Concat rows must train every turn of the conversation, with each
    ancestor reply's stored logprobs/versions restored at its span."""
    client = ArealOpenAI(_FakeEngine("yes"), tokenizer=_Tok())
    turn1 = [{"role": "user", "content": "q1"}]
    r1 = _chat(client, turn1)
    c1 = client.get_completions(r1.id)
    turn2 = turn1 + [
        {"role": "assistant", "content": "yes"},
        {"role": "user", "content": "q2"},
    ]
    r2 = _chat(client, turn2)
    client.set_reward(r2.id, 1.0)
    client.apply_reward_discount(0.5)

    # token-concat prefix property holds for this template iff r1's
    # input+output is a prefix of r2's input
    full1 = c1.input_tokens + c1.output_tokens
    c2 = client.get_completions(r2.id)
    if c2.input_tokens[: len(full1)] == full1:
        batch = client.export_batch(style="concat")
        assert batch["input_ids"].shape[0] == 1
        start, end = len(c1.input_tokens), len(full1)
        row_mask = batch["loss_mask"][0]
        assert row_mask[start:end].sum() == len(c1.output_tokens)
        np.testing.assert_allclose(
            batch["logprobs"][0][start:end], c1.output_logprobs
        )
