"""Router-side active health checking with a per-backend circuit breaker.

Replaces the scrape-time-only `/health` fanout: a background probe loop
feeds per-backend state machines, and placement reads an immutable
snapshot of who is placeable — so a dead backend is evicted from routing
within ~`failure_threshold * interval`, not discovered per-request.

State machine per backend::

    closed ──(failure_threshold consecutive failures)──► open
    open ──(probe succeeds)──► half_open
    half_open ──(rejoin verify ok)──► closed
    half_open ──(probe/verify fails)──► open
    any ──drain()──► draining ──undrain()──► closed

`closed` is the healthy steady state (breaker terminology: requests flow
through the closed circuit).  `open` backends receive no placements and
no fanouts.  `half_open` means a probe answered after death — the backend
is kept out of placement until ``verify_rejoin`` (router-supplied: check
the served weight version against the fleet's, force-reload if stale)
passes, so a restarted server can never serve stale weights into a batch.
`draining` is operator-requested graceful removal: no NEW placements, but
the backend still counts as alive for fanouts so in-flight work and the
final weight sync complete.

Locking: `_states` is guarded by a dedicated asyncio `_lock`, a *leaf*
lock — no router lock is ever awaited while it is held, and the router
never holds its own `_lock` across a call into this class, so no order
edge exists between the two.  Placement reads `placeable_cache` /
`alive_cache`, immutable tuples rebuilt on every state change and
swapped atomically — the hot path takes no lock at all.
"""

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, Optional, Sequence, Tuple

from areal_tpu.analysis.lockcheck import lock_guarded

logger = logging.getLogger("AReaLtpu.health")

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"
DRAINING = "draining"

# Gauge encoding for areal_router_backend_state (pinned in the schema).
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2, DRAINING: 3}


@dataclass
class BackendState:
    state: str = CLOSED
    consecutive_failures: int = 0
    last_probe: float = 0.0  # time.monotonic(); 0.0 = never probed
    last_ok: float = 0.0
    version: int = -1  # weight version the backend last reported
    error: str = ""


@lock_guarded
class BackendHealthChecker:
    """Probe loop + breaker bookkeeping for a fixed set of addresses.

    ``probe(addr)`` is router-supplied (GET /health with a short timeout)
    and returns the backend's health payload or raises.  ``on_death`` is
    fired (outside the lock) exactly once per closed/half_open → open
    transition so the router can evict rid affinity.  ``verify_rejoin``
    gates half_open → closed.
    """

    _GUARDED_FIELDS = {"_states": "_lock"}

    def __init__(
        self,
        addresses: Sequence[str],
        probe: Callable[[str], Awaitable[dict]],
        *,
        failure_threshold: int = 3,
        interval: float = 5.0,
        on_death: Optional[Callable[[str], None]] = None,
        verify_rejoin: Optional[Callable[[str, dict], Awaitable[bool]]] = None,
    ):
        self._lock = asyncio.Lock()
        self._states: Dict[str, BackendState] = {
            addr: BackendState() for addr in addresses
        }
        self._probe = probe
        self._failure_threshold = max(1, failure_threshold)
        self._interval = interval
        self._on_death = on_death
        self._verify_rejoin = verify_rejoin
        self._task: Optional[asyncio.Task] = None
        # Immutable placement views, swapped atomically on state change;
        # readers take no lock (tuple reference read is atomic in CPython).
        self.placeable_cache: Tuple[str, ...] = tuple(addresses)
        self.alive_cache: Tuple[str, ...] = tuple(addresses)

    # --- lifecycle ---

    def start(self):
        if self._task is None and self._interval > 0:
            self._task = asyncio.get_running_loop().create_task(
                self._probe_loop()
            )

    async def stop(self):
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _probe_loop(self):
        while True:
            try:
                await self.probe_now()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("health probe sweep failed")
            await asyncio.sleep(self._interval)

    async def probe_now(self):
        """One probe sweep over every backend, concurrently."""
        async with self._lock:
            addrs = list(self._states)
        await asyncio.gather(
            *(self._probe_one(a) for a in addrs), return_exceptions=True
        )

    # --- probe + breaker transitions ---

    async def _probe_one(self, addr: str):
        try:
            health = await self._probe(addr)
        except Exception as e:  # noqa: BLE001 — any probe failure counts
            async with self._lock:
                st = self._states.get(addr)
                if st is not None:
                    st.last_probe = time.monotonic()
            await self.report_failure(addr, repr(e))
            return

        rejoining = False
        async with self._lock:
            st = self._states.get(addr)
            if st is None:
                return
            now = time.monotonic()
            st.last_probe = now
            st.last_ok = now
            st.error = ""
            st.version = int(health.get("version", st.version))
            if st.state == CLOSED:
                st.consecutive_failures = 0
            elif st.state == OPEN:
                # answered after death: candidate, but gate re-admission
                # on the rejoin check (stale weights must not place)
                st.state = HALF_OPEN
                rejoining = True
                self._rebuild_cache_locked()
            elif st.state == HALF_OPEN:
                rejoining = True
            # DRAINING: record the probe, never auto-transition

        if rejoining:
            await self._complete_rejoin(addr, health)

    async def _complete_rejoin(self, addr: str, health: dict):
        ok = True
        if self._verify_rejoin is not None:
            try:
                ok = await self._verify_rejoin(addr, health)
            except Exception as e:  # noqa: BLE001
                logger.warning("rejoin verify for %s raised: %r", addr, e)
                ok = False
        async with self._lock:
            st = self._states.get(addr)
            if st is None or st.state != HALF_OPEN:
                return
            if ok:
                st.state = CLOSED
                st.consecutive_failures = 0
                logger.info("backend %s rejoined the fleet", addr)
            else:
                st.state = OPEN
                st.consecutive_failures = self._failure_threshold
                st.error = "rejoin verification failed"
            self._rebuild_cache_locked()

    async def report_failure(self, addr: str, error: str = ""):
        """Count a probe/request failure against `addr`; trips the breaker
        at `failure_threshold` consecutive failures (immediately if the
        backend was half-open)."""
        died = False
        async with self._lock:
            st = self._states.get(addr)
            if st is None or st.state == DRAINING:
                return
            st.consecutive_failures += 1
            st.error = error
            if st.state == HALF_OPEN or (
                st.state == CLOSED
                and st.consecutive_failures >= self._failure_threshold
            ):
                st.state = OPEN
                died = True
                self._rebuild_cache_locked()
        if died:
            logger.warning("backend %s declared dead: %s", addr, error)
            if self._on_death is not None:
                res = self._on_death(addr)
                if asyncio.iscoroutine(res):
                    await res

    async def report_success(self, addr: str):
        """A proxied request succeeded.  Only resets the failure streak of
        a CLOSED backend — recovery from OPEN must go through the probe +
        rejoin-verify path so stale weights never slip back in."""
        async with self._lock:
            st = self._states.get(addr)
            if st is not None and st.state == CLOSED:
                st.consecutive_failures = 0
                st.last_ok = time.monotonic()
                st.error = ""

    # --- operator drain ---

    async def drain(self, addr: str) -> bool:
        async with self._lock:
            st = self._states.get(addr)
            if st is None:
                return False
            st.state = DRAINING
            self._rebuild_cache_locked()
            return True

    async def undrain(self, addr: str) -> bool:
        async with self._lock:
            st = self._states.get(addr)
            if st is None or st.state != DRAINING:
                return False
            st.state = CLOSED
            st.consecutive_failures = 0
            self._rebuild_cache_locked()
            return True

    # --- views ---

    def _rebuild_cache_locked(self):  # holds: _lock
        self.placeable_cache = tuple(
            a for a, s in self._states.items() if s.state == CLOSED
        )
        # alive = will answer HTTP: everything not tripped open.  Draining
        # backends stay in fanouts (they must receive the final publishes)
        # but not in placement; half-open ones are excluded from both
        # placement and publish until rejoin-verified.
        self.alive_cache = tuple(
            a
            for a, s in self._states.items()
            if s.state in (CLOSED, DRAINING)
        )

    async def snapshot(self) -> Dict[str, dict]:
        """Cached state for the /health handler — no probes issued."""
        now = time.monotonic()
        async with self._lock:
            return {
                addr: {
                    "state": st.state,
                    "consecutive_failures": st.consecutive_failures,
                    "version": st.version,
                    "age_s": (
                        round(now - st.last_probe, 3)
                        if st.last_probe > 0
                        else None
                    ),
                    "error": st.error,
                }
                for addr, st in self._states.items()
            }
