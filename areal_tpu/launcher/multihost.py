"""Multi-host launcher: gen servers + multi-process trainers across hosts.

Behavioral counterpart of the reference's `RayLauncher` / `SlurmLauncher`
(areal/launcher/ray.py:68, slurm.py:46): place generation servers on the
inference hosts and one trainer process per training host, wire the
rendezvous, babysit everything, and relaunch the whole run on failure with
AREAL_RUN_ID incremented (the reference's recover loop).

TPU-first differences:
- No placement-group scheduler dependency: remote processes are started
  over a pluggable `remote_shell` (ssh by default — TPU pods ship with
  password-less ssh between workers; tests inject a local shell), which is
  the role slurm's sbatch/srun plays for the reference.
- Rendezvous is file-based: AREAL_NAME_RESOLVE=nfs:<root> points every
  process at the shared-filesystem name_resolve store (gen servers register
  their addresses; trainer clients discover them) and the trainer processes
  join one jax.distributed runtime via the AREAL_COORDINATOR/NUM_PROCESSES/
  PROCESS_ID contract (parallel/distributed.py) — collectives then ride
  ICI/DCN with no launcher involvement.

Usage:
    python -m areal_tpu.launcher.multihost entry.py --config cfg.yaml \
        [--gen-hosts h1,h2] [--train-hosts h3,h4] [k=v ...]
"""

import argparse
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from areal_tpu.api.alloc import AllocationMode
from areal_tpu.api.config import GRPOConfig, load_expr_config
from areal_tpu.utils import logging, network
from areal_tpu.utils.shutdown import RESUME_EXIT_CODE

logger = logging.getLogger("launcher.multihost")

RECOVER_TIME_INTERVAL = 10.0
# immediate-relaunch pause after an orderly preemption exit (ssh
# propagates the remote trainer's RESUME_EXIT_CODE as its own status)
RESUME_RELAUNCH_DELAY = 1.0
COORDINATOR_PORT_BASE = 20000


_LOOPBACK = ("localhost", "127.0.0.1", "::1", "0.0.0.0")


def _check_kv_addr_reachable_from_remote(addr: str, hosts: List[str]) -> None:
    """A loopback kv_store address handed to REMOTE hosts points each worker
    at itself — catch the misconfiguration at launch, not as a fleet-wide
    rendezvous hang."""
    host = addr.rsplit(":", 1)[0]
    remote = [h for h in hosts if h not in _LOOPBACK]
    if host in _LOOPBACK and remote:
        raise ValueError(
            f"name_resolve.http_addr={addr!r} is a loopback address but the "
            f"fleet spans remote hosts {remote}; use an address every host "
            f"can reach (e.g. the launcher host's IP)"
        )


def ssh_shell(host: str, cmd: str, env: Dict[str, str], workdir: str) -> List[str]:
    """Wrap a command for remote execution over ssh.

    -tt forces a remote pty, so killing the local ssh client (stop_all)
    delivers SIGHUP to the remote process tree — without it remote
    trainers/servers would be orphaned and the recover relaunch would
    collide with them over devices and name_resolve registrations."""
    exports = " ".join(f"export {k}={shlex.quote(v)};" for k, v in env.items())
    remote = f"{exports} cd {shlex.quote(workdir)} && {cmd}"
    return ["ssh", "-tt", "-o", "StrictHostKeyChecking=no", host, remote]


def local_shell(host: str, cmd: str, env: Dict[str, str], workdir: str) -> List[str]:
    """Run 'remote' commands locally — the 2-'host' test fabric (the
    reference's testing.py trick of fabricating a cluster without one)."""
    exports = " ".join(f"export {k}={shlex.quote(v)};" for k, v in env.items())
    return ["bash", "-c", f"{exports} cd {shlex.quote(workdir)} && {cmd}"]


class MultiHostLauncher:
    def __init__(
        self,
        entry: str,
        config_args: List[str],
        gen_hosts: List[str],
        train_hosts: List[str],
        remote_shell: Callable = ssh_shell,
        workdir: Optional[str] = None,
        coordinator_host: Optional[str] = None,
    ):
        self.entry = entry
        self.config_args = config_args
        self.config, _ = load_expr_config(config_args, GRPOConfig, ignore_unknown_top=True)
        self.gen_hosts = gen_hosts
        self.train_hosts = train_hosts
        self.remote_shell = remote_shell
        self.workdir = workdir or os.getcwd()
        # jax.distributed coordinator: process 0's host (tests fabricating
        # hosts locally pass 127.0.0.1)
        self.coordinator_host = coordinator_host or train_hosts[0]
        self.procs: List[subprocess.Popen] = []
        nr = self.config.cluster.name_resolve
        if nr.type == "nfs":
            self._nr_env = f"nfs:{nr.nfs_record_root}"
        elif nr.type == "http":
            # TTL'd KV service (utils/kv_store.py): fleets without a shared
            # filesystem rendezvous through it (etcd-lease semantics)
            _check_kv_addr_reachable_from_remote(nr.http_addr, train_hosts)
            self._nr_env = f"http:{nr.http_addr}"
        else:
            raise ValueError(
                "multi-host runs need a shared name_resolve store: "
                "cluster.name_resolve.type=nfs (shared path) or http "
                "(kv_store service) reachable from every host"
            )

    # ------------------------------------------------------------------

    def _spawn(self, host: str, cmd: str, env: Dict[str, str], tag: str):
        log_dir = os.path.join(
            self.config.cluster.fileroot,
            self.config.experiment_name,
            self.config.trial_name,
            "logs",
        )
        os.makedirs(log_dir, exist_ok=True)
        log_f = open(os.path.join(log_dir, f"{tag}.log"), "a")
        env = {"AREAL_NAME_RESOLVE": self._nr_env, **env}
        argv = self.remote_shell(host, cmd, env, self.workdir)
        logger.info(f"spawn [{tag}] on {host}: {cmd}")
        p = subprocess.Popen(
            argv, stdout=log_f, stderr=subprocess.STDOUT, start_new_session=True
        )
        self.procs.append(p)
        return p

    def start_gen_servers(self) -> None:
        """One server per gen host; each registers its address in the shared
        name_resolve store (clients + the trainer's transfer path discover
        them there)."""
        from areal_tpu.api.config import GenServerConfig

        g = self.config.gen_server
        for idx, host in enumerate(self.gen_hosts):
            cmd = (
                GenServerConfig.build_cmd(g, host=host, port=0)
                + f" --experiment-name {shlex.quote(self.config.experiment_name)}"
                + f" --trial-name {shlex.quote(self.config.trial_name)}"
                + f" --server-idx {idx}"
            )
            self._spawn(host, cmd, {}, tag=f"gen_server_{idx}")

    def start_trainers(self, run_id: int) -> List[subprocess.Popen]:
        """One trainer process per train host, all joining one
        jax.distributed runtime; process 0 (on the first host) is the
        coordinator and the DP head."""
        n = len(self.train_hosts)
        coordinator = f"{self.coordinator_host}:{COORDINATOR_PORT_BASE + run_id}"
        cmd = f"{shlex.quote(sys.executable)} {shlex.quote(self.entry)} " + " ".join(
            shlex.quote(a) for a in self.config_args
        )
        trainers = []
        for pid, host in enumerate(self.train_hosts):
            env = {
                "AREAL_RUN_ID": str(run_id),
                "AREAL_COORDINATOR": coordinator,
                "AREAL_NUM_PROCESSES": str(n),
                "AREAL_PROCESS_ID": str(pid),
            }
            trainers.append(
                self._spawn(host, cmd, env, tag=f"trainer_p{pid}_run{run_id}")
            )
        return trainers

    def stop_all(self):
        for p in self.procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except ProcessLookupError:
                    pass
        self.procs.clear()

    # ------------------------------------------------------------------

    def run(self) -> int:
        retries = max(1, self.config.recover.retries)
        run_id = int(os.environ.get("AREAL_RUN_ID", 0))
        failures = 0  # crash relaunches consumed; preemptions don't count
        rc = 1
        try:
            while True:
                self.start_gen_servers()
                trainers = self.start_trainers(run_id)
                rc = self._babysit(trainers)
                self.stop_all()
                if rc == 0:
                    logger.info("all trainer processes finished successfully")
                    return 0
                if self.config.recover.mode == "disabled":
                    return rc
                run_id += 1
                if rc == RESUME_EXIT_CODE:
                    # orderly preemption (utils/shutdown.py): known-good
                    # dump on the shared filesystem — relaunch now without
                    # burning a crash retry
                    logger.warning(
                        f"trainer preempted (rc={rc}); relaunching "
                        f"immediately (run {run_id})"
                    )
                    time.sleep(RESUME_RELAUNCH_DELAY)
                    continue
                failures += 1
                if failures < retries and self.config.recover.mode in (
                        "auto", "fault"):
                    logger.warning(
                        f"run failed rc={rc}; relaunching (run {run_id}) in "
                        f"{RECOVER_TIME_INTERVAL}s [crash {failures}/{retries}]"
                    )
                    time.sleep(RECOVER_TIME_INTERVAL)
                else:
                    break
            return rc
        finally:
            self.stop_all()

    def _babysit(self, trainers: List[subprocess.Popen]) -> int:
        """Wait for every trainer; any trainer failure or gen-server death
        fails the whole run (multi-process jax cannot lose a member)."""
        while True:
            codes = [t.poll() for t in trainers]
            if any(c not in (None, 0) for c in codes):
                return next(c for c in codes if c not in (None, 0))
            if all(c == 0 for c in codes):
                return 0
            for p in self.procs:
                if p not in trainers and p.poll() is not None:
                    logger.error("a generation server died; restarting run")
                    return 1
            time.sleep(1.0)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("entry")
    parser.add_argument("--gen-hosts", default="",
                        help="comma-separated hosts for generation servers")
    parser.add_argument("--train-hosts", default="",
                        help="comma-separated hosts for trainer processes")
    args, config_args = parser.parse_known_args()
    gen_hosts = [h for h in args.gen_hosts.split(",") if h]
    train_hosts = [h for h in args.train_hosts.split(",") if h]
    if not train_hosts:
        parser.error("--train-hosts is required")
    if not gen_hosts:
        cfg, _ = load_expr_config(config_args, GRPOConfig, ignore_unknown_top=True)
        alloc = (
            AllocationMode.from_str(cfg.allocation_mode)
            if cfg.allocation_mode
            else None
        )
        n = max(1, alloc.gen.dp_size) if alloc and alloc.gen else 1
        gen_hosts = train_hosts[:n]  # colocate by default
    launcher = MultiHostLauncher(
        args.entry, config_args, gen_hosts, train_hosts
    )
    sys.exit(launcher.run())


if __name__ == "__main__":
    main()
