"""Staleness-aware rollout capacity control — THE async-RL throttle.

Behavioral counterpart of the reference's `StalenessManager`
(areal/core/staleness_manager.py:12); the capacity formula at
staleness_manager.py:96 is preserved exactly:

    capacity = min(max_concurrent - running,
                   (max_staleness + version + 1) * batch_size
                       - (accepted + running))

so that by the time a sample is consumed, its off-policyness cannot exceed
`max_staleness` versions.
"""

import threading
from dataclasses import asdict

from areal_tpu.api.io_struct import RolloutStat


class StalenessManager:
    def __init__(
        self,
        max_concurrent_rollouts: int,
        consumer_batch_size: int,
        max_staleness: int,
    ):
        self.max_concurrent_rollouts = max_concurrent_rollouts
        self.consumer_batch_size = consumer_batch_size
        self.max_staleness = max_staleness
        self._lock = threading.Lock()
        self._stat = RolloutStat()

    def get_capacity(self, current_version: int) -> int:
        """Slots available for new rollouts; may be negative when over
        capacity (submission must then stall)."""
        with self._lock:
            concurrency_cap = max(1, self.max_concurrent_rollouts) - self._stat.running
            sample_cnt = self._stat.accepted + self._stat.running
            staleness_cap = (
                (self.max_staleness + current_version + 1)
                * max(1, self.consumer_batch_size)
                - sample_cnt
            )
            return min(concurrency_cap, staleness_cap)

    def _check_locked(self) -> None:  # holds: _lock
        """Ledger invariant: every submitted rollout is exactly one of
        accepted / rejected / still running.  A violation means a death
        path settled a rollout twice or not at all — the capacity-leak bug
        class this class exists to prevent — so fail loudly at the
        transition that broke it, not thousands of steps later as a wedged
        admission gate."""
        s = self._stat
        if s.submitted != s.accepted + s.rejected + s.running or s.running < 0:
            raise RuntimeError(
                f"staleness ledger violated: submitted={s.submitted} != "
                f"accepted={s.accepted} + rejected={s.rejected} + "
                f"running={s.running}"
            )

    def on_rollout_submitted(self) -> None:
        with self._lock:
            self._stat.submitted += 1
            self._stat.running += 1
            self._check_locked()

    def on_rollout_accepted(self) -> None:
        with self._lock:
            self._stat.accepted += 1
            self._stat.running -= 1
            self._check_locked()

    def on_rollout_rejected(self) -> None:
        with self._lock:
            self._stat.rejected += 1
            self._stat.running -= 1
            self._check_locked()

    def get_stats(self) -> RolloutStat:
        with self._lock:
            return RolloutStat(**asdict(self._stat))

    def restore(self, stat: RolloutStat) -> int:
        """Adopt a ledger snapshot from a recover manifest (ISSUE 15).

        Trajectories that were in flight when the trainer died can never
        settle through their futures — those are gone with the process —
        so they are folded into `rejected` here, which keeps the invariant
        checkable from the first post-restore transition.  Returns how
        many were settled that way (the caller counts them as lost)."""
        settled = max(0, stat.running)
        with self._lock:
            self._stat = RolloutStat(
                submitted=stat.submitted,
                accepted=stat.accepted,
                rejected=stat.rejected + settled,
                running=0,
            )
            self._check_locked()
        return settled

    def register_metrics(self, reg=None) -> None:
        """Expose submitted/accepted/running as scrape-time gauges.

        Collectors run only at scrape, so the lock in get_stats is never
        taken on the rollout hot path.  Defaults to the canonical GEN
        registry so the gauges ride the generation-side /metrics surface.
        """
        from areal_tpu.utils import telemetry

        telemetry.register_staleness(reg or telemetry.GEN, self)
