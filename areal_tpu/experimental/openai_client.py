"""OpenAI-compatible chat client over the inference engine.

Capability counterpart of the reference's `ArealOpenAI`
(areal/experimental/openai/client.py:216): agentic code written against the
OpenAI chat.completions surface runs unchanged on the in-repo inference
engine, while every completion's tokens/logprobs/versions are cached so the
conversation can be exported as RL training data — per-completion rewards,
backward discounted credit assignment across turns
(`apply_reward_discount`, reference :262), and prefix-tree leaf export
(`export_completions(style="concat")`, reference :311).

The `openai` SDK is not available in this environment, so the facade is
self-contained: `client.chat.completions.create(...)` returns a response
object with the fields agent code actually reads (.id, .choices[0].message
.content, .usage).
"""
# areal-lint: disable=dead-module user-facing OpenAI-compat facade imported by agent code outside the tree (reference parity: areal/experimental/openai); covered by tests/test_openai_client.py

import asyncio
import itertools
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.api.io_struct import ModelRequest
from areal_tpu.utils.data import pad_sequences_to_tensors


@dataclass
class ChatMessage:
    role: str
    content: str


@dataclass
class Choice:
    index: int
    message: ChatMessage
    finish_reason: str


@dataclass
class Usage:
    prompt_tokens: int
    completion_tokens: int

    @property
    def total_tokens(self) -> int:
        return self.prompt_tokens + self.completion_tokens


@dataclass
class ChatCompletion:
    id: str
    choices: List[Choice]
    usage: Usage
    model: str = "areal-tpu"
    object: str = "chat.completion"


@dataclass
class CompletionWithTokenLogpReward:
    """Cached training-side record of one chat completion
    (reference: experimental/openai/types.py)."""

    id: str
    messages: List[Dict[str, str]]  # the INPUT conversation
    input_tokens: List[int]
    output_tokens: List[int]
    output_logprobs: List[float]
    output_versions: List[int]
    text: str
    created: int
    reward: Optional[float] = None
    metadata: Dict[str, Any] = field(default_factory=dict)

    def to_trajectory(self) -> Dict[str, np.ndarray]:
        n_in, n_out = len(self.input_tokens), len(self.output_tokens)
        return dict(
            input_ids=np.array(self.input_tokens + self.output_tokens, np.int32),
            logprobs=np.array([0.0] * n_in + self.output_logprobs, np.float32),
            loss_mask=np.array([0] * n_in + [1] * n_out, np.int32),
            versions=np.array([-1] * n_in + self.output_versions, np.int32),
            rewards=np.float32(self.reward if self.reward is not None else 0.0),
        )


class _AsyncChatCompletions:
    def __init__(self, client: "ArealOpenAI"):
        self._client = client

    async def create(
        self,
        messages: List[Dict[str, str]],
        max_completion_tokens: int = 512,
        max_tokens: Optional[int] = None,
        temperature: float = 1.0,
        top_p: float = 1.0,
        stop: Optional[List[str]] = None,
        **_: Any,
    ) -> ChatCompletion:
        c = self._client
        input_ids = c._render(messages)
        gconfig = GenerationHyperparameters(
            n_samples=1,
            max_new_tokens=max_tokens or max_completion_tokens,
            temperature=temperature,
            top_p=top_p,
            stop=list(stop or []),
        )
        resp = await c.engine.agenerate(
            ModelRequest(
                rid=str(uuid.uuid4()),
                input_ids=input_ids,
                gconfig=gconfig,
                tokenizer=c.tokenizer,
            )
        )
        text = (
            c.tokenizer.decode(resp.output_tokens)
            if c.tokenizer is not None
            else ""
        )
        cid = f"chatcmpl-{uuid.uuid4().hex}"
        c._cache[cid] = CompletionWithTokenLogpReward(
            id=cid,
            messages=[dict(m) for m in messages],
            input_tokens=list(resp.input_tokens),
            output_tokens=list(resp.output_tokens),
            output_logprobs=list(resp.output_logprobs),
            output_versions=list(resp.output_versions),
            text=text,
            created=next(c._counter),
        )
        finish = "stop" if resp.stop_reason == "stop" else "length"
        return ChatCompletion(
            id=cid,
            choices=[
                Choice(0, ChatMessage(role="assistant", content=text), finish)
            ],
            usage=Usage(len(resp.input_tokens), len(resp.output_tokens)),
        )


class _Chat:
    def __init__(self, client: "ArealOpenAI"):
        self.completions = _AsyncChatCompletions(client)


class ArealOpenAI:
    """client.chat.completions.create over an InferenceEngine, with reward
    bookkeeping for RL export."""

    def __init__(self, engine, tokenizer=None, enable_thinking: bool = False):
        self.engine = engine
        self.tokenizer = tokenizer
        self.enable_thinking = enable_thinking
        self._cache: Dict[str, CompletionWithTokenLogpReward] = {}
        self._counter = itertools.count()
        self.chat = _Chat(self)

    # -- rendering -----------------------------------------------------
    def _render(self, messages: List[Dict[str, str]]) -> List[int]:
        if self.tokenizer is None:
            raise ValueError("ArealOpenAI needs a tokenizer")
        try:
            return self.tokenizer.apply_chat_template(
                messages,
                add_generation_prompt=True,
                tokenize=True,
                enable_thinking=self.enable_thinking,
            )
        except TypeError:  # tokenizers without the enable_thinking kwarg
            return self.tokenizer.apply_chat_template(
                messages, add_generation_prompt=True, tokenize=True
            )

    # -- reward bookkeeping (reference :250-310) -----------------------
    def get_completions(self, cid: str) -> Optional[CompletionWithTokenLogpReward]:
        return self._cache.get(cid)

    def set_reward(self, cid: str, reward: float) -> None:
        if cid not in self._cache:
            raise KeyError(f"completion {cid} not in cache")
        self._cache[cid].reward = reward

    def apply_reward_discount(
        self, turn_discount: float = 1.0
    ) -> Dict[str, CompletionWithTokenLogpReward]:
        """Backward geometric credit assignment along each conversation's
        prefix chain: every completion's reward flows to its parent turn
        scaled by turn_discount (cascading, so a leaf reaches its
        grandparent as discount^2).  Parents are resolved with the same
        prefix rule as export_completions, so interleaved independent
        conversations never leak reward into each other."""
        ordered = sorted(self._cache.values(), key=lambda c: c.created)
        parent: Dict[str, CompletionWithTokenLogpReward] = {}
        for b in ordered:
            best = None
            for a in ordered:
                if a is b or not _is_prefix_ancestor(a, b):
                    continue
                # deepest ancestor wins; among equal-depth duplicates
                # (re-sampled identical turns) prefer the latest created
                if best is None or len(a.messages) >= len(best.messages):
                    best = a
            if best is not None:
                parent[b.id] = best
        for comp in ordered:
            if comp.reward is None:
                comp.reward = 0.0
        # reverse creation order: children resolve before their parents, so
        # discounted reward cascades leaf -> ... -> root
        for comp in reversed(ordered):
            p = parent.get(comp.id)
            if p is not None:
                p.reward += comp.reward * turn_discount
        return dict(self._cache)

    # -- export (reference :311-420) -----------------------------------
    def export_completions(
        self, style: str = "concat"
    ) -> Dict[str, CompletionWithTokenLogpReward]:
        """'individual': every cached completion.  'concat': build the
        conversation prefix tree (completion A is B's ancestor iff A's
        input messages + A's reply form a prefix of B's input) and return
        only leaves — one trajectory per conversation branch."""
        if style == "individual":
            return dict(self._cache)
        if style != "concat":
            raise ValueError(f"unknown export style {style!r}")
        comps = list(self._cache.values())
        has_child = set()
        for a in comps:
            for b in comps:
                if a is not b and _is_prefix_ancestor(a, b):
                    has_child.add(a.id)
                    break
        return {c.id: c for c in comps if c.id not in has_child}

    def _ancestors(self, leaf: CompletionWithTokenLogpReward):
        """Chain of cached completions whose (input + reply) token stream is
        a strict prefix of `leaf`'s input tokens, shortest first."""
        chain = []
        for c in self._cache.values():
            if c is leaf:
                continue
            full = c.input_tokens + c.output_tokens
            if len(full) <= len(leaf.input_tokens) and leaf.input_tokens[
                : len(full)
            ] == full:
                chain.append(c)
        return sorted(chain, key=lambda c: len(c.input_tokens))

    def _chain_trajectory(self, leaf: CompletionWithTokenLogpReward):
        """Leaf trajectory with every ancestor turn's reply span trained
        (stored logprobs/versions restored at its token positions) — valid
        whenever turns extend the conversation by exact token concatenation;
        otherwise only the leaf's reply is trainable (re-tokenised chat
        templates break position tracking, the same restriction the
        reference's 'concat' export enforces, client.py:311)."""
        traj = leaf.to_trajectory()
        for anc in self._ancestors(leaf):
            start = len(anc.input_tokens)
            end = start + len(anc.output_tokens)
            traj["loss_mask"][start:end] = 1
            traj["logprobs"][start:end] = anc.output_logprobs
            traj["versions"][start:end] = anc.output_versions
        return traj

    def export_batch(self, style: str = "concat") -> Dict[str, np.ndarray]:
        """Padded trajectory batch for the train engines.  'concat' rows
        train on every turn of each conversation branch (ancestor replies
        included); 'individual' emits one row per completion."""
        comps = sorted(
            self.export_completions(style).values(), key=lambda c: c.created
        )
        if not comps:
            raise ValueError("no completions cached")
        if style == "concat":
            return pad_sequences_to_tensors(
                [self._chain_trajectory(c) for c in comps]
            )
        return pad_sequences_to_tensors([c.to_trajectory() for c in comps])


def _is_prefix_ancestor(
    a: CompletionWithTokenLogpReward, b: CompletionWithTokenLogpReward
) -> bool:
    """True iff a's input messages + a's reply form a prefix of b's input —
    i.e. b continues the conversation that produced a."""
    a_full = a.messages + [{"role": "assistant", "content": a.text}]
    return len(a_full) <= len(b.messages) and all(
        a_full[i] == b.messages[i] for i in range(len(a_full))
    )
