"""areal-lint v3 (ISSUE 18): cross-process wire-contract checking.

The fleet is 4+ processes glued together by string-keyed JSON bodies,
lifecycle event names, metric names, and GenServerConfig→argparse→engine
plumbing — seams no type checker sees.  Three checkers close them, driven
by the checked-in contract registry `areal_tpu/analysis/wire_contracts.json`:

- C8  `payload-contract` / `payload-silent-default`
      Per HTTP endpoint, producer key-sets (dict literals and
      `payload["k"] = ...` writes flowing into utils/http helpers,
      `session.post(..., json=...)`, `web.json_response(...)`) are checked
      against consumer key-sets (`body["k"]` / `body.get("k", d)` reads in
      handlers and clients) through the registry.  A hard read of a key no
      producer writes is an error; a `.get` with a silent literal default
      on a key every producer writes is a warning (the silent-0 class);
      response contracts are checked in the reverse direction.
- C9  `metric-contract` / `event-contract`
      Every Counter/Gauge/Histogram name constructed anywhere must be
      pinned in tests/data/metrics_schema.json and vice versa (no orphans
      either way); every event name passed to `telemetry.emit` must be one
      obs/trace.py's parser consumes and vice versa.
- C10 `config-plumbing`
      GenServerConfig field ↔ build_cmd flag ↔ gen/server.py argparse flag
      ↔ GenEngine kwarg must line up end-to-end (the /generate-body leg of
      each chain is covered by the C8 `generate` contract).

Registry self-consistency problems (unreadable JSON, keys nothing produces
or consumes, declared-but-never-emitted events) surface as
`wire-registry-stale` anchored at the registry file itself — those are
fixed by editing the registry, not suppressed in code.
"""

import ast
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

from areal_tpu.analysis.core import Finding, SourceFile, apply_suppression

CONTRACTS_PATH = os.path.join("areal_tpu", "analysis", "wire_contracts.json")
SCHEMA_PATH = os.path.join("tests", "data", "metrics_schema.json")
FAKE_SERVER_REL = os.path.join("tests", "fake_server.py")
TRACE_REL = os.path.join("areal_tpu", "obs", "trace.py")

RULE_PAYLOAD = "payload-contract"
RULE_SILENT = "payload-silent-default"
RULE_METRIC = "metric-contract"
RULE_EVENT = "event-contract"
RULE_CONFIG = "config-plumbing"
RULE_REGISTRY = "wire-registry-stale"

WIRE_RULES = (
    RULE_PAYLOAD, RULE_SILENT, RULE_METRIC, RULE_EVENT, RULE_CONFIG,
    RULE_REGISTRY,
)

# JSON-returning post helpers available everywhere (utils/http.py).
_GLOBAL_HELPERS = {
    "arequest_with_retry": {"endpoint_arg": 1, "payload_arg": 2,
                            "returns": "json"},
    "request_with_retry_sync": {"endpoint_arg": 1, "payload_arg": 2,
                                "returns": "json"},
}


# --------------------------------------------------------------------------
# small AST helpers
# --------------------------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _call_attr(call: ast.Call) -> str:
    """Trailing name of the called function — works even when the receiver
    is itself a call (self._get_session().post(...))."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _unwrap(node: ast.AST) -> ast.AST:
    """Peel await / ternary / `or {}` / dict(...) wrappers so payload and
    view sources are recognized through the common idioms."""
    while True:
        if isinstance(node, ast.Await):
            node = node.value
        elif isinstance(node, ast.IfExp):
            node = node.body
        elif isinstance(node, ast.BoolOp) and isinstance(node.op, ast.Or):
            node = node.values[0]
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "dict"
            and len(node.args) == 1
            and not node.keywords
        ):
            node = node.args[0]
        else:
            return node


def _path_from_url(node: ast.AST) -> Optional[str]:
    """Endpoint path from a URL expression: a constant, or an f-string
    whose trailing constant part carries the path (f"http://{addr}/x").
    Fully dynamic paths (f"{addr}{path}") resolve to None and the site is
    skipped."""
    s = _const_str(node)
    if s is not None:
        i = s.find("://")
        if i >= 0:
            j = s.find("/", i + 3)
            return s[j:].split("?")[0] if j >= 0 else None
        return s.split("?")[0] if s.startswith("/") else None
    if isinstance(node, ast.JoinedStr) and node.values:
        last = node.values[-1]
        ls = _const_str(last)
        if ls is not None and "/" in ls:
            return ls[ls.find("/"):].split("?")[0]
    return None


def _iter_functions(tree: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(qualname, node) for every function/method, including nested ones.
    Each is scanned as its own unit."""
    out: List[Tuple[str, ast.AST]] = []

    def rec(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child))
                rec(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                rec(child, f"{prefix}{child.name}.")
            else:
                rec(child, prefix)

    rec(tree, "")
    return out


def _dict_keys(node: ast.Dict, prefix: str = "") -> Tuple[Dict[str, int], bool]:
    """Constant keys (dotted for one nesting level) -> lineno; the bool is
    True when the dict is `open` (has ** spreads or computed keys)."""
    keys: Dict[str, int] = {}
    open_ = False
    for k, v in zip(node.keys, node.values):
        ks = _const_str(k) if k is not None else None
        if ks is None:
            open_ = True
            continue
        keys[prefix + ks] = getattr(k, "lineno", node.lineno)
        if isinstance(v, ast.Dict) and not prefix:
            sub, sub_open = _dict_keys(v, prefix=ks + ".")
            keys.update(sub)
            open_ = open_ or sub_open
    return keys, open_


# --------------------------------------------------------------------------
# contract registry
# --------------------------------------------------------------------------

class _Key:
    __slots__ = ("required", "tolerant_ok", "external")

    def __init__(self, spec: Any):
        spec = spec if isinstance(spec, dict) else {}
        self.required = bool(spec.get("required", False))
        self.tolerant_ok = bool(spec.get("tolerant_reads_ok", False))
        self.external = bool(spec.get("external_producer", False))


class _Contract:
    def __init__(self, cid: str, spec: Dict[str, Any]):
        self.cid = cid
        self.path = spec["path"]
        self.app = spec.get("app", "gen")
        self.request = {k: _Key(v) for k, v in spec.get("request", {}).items()}
        self.response = {k: _Key(v) for k, v in spec.get("response", {}).items()}
        # "<cid>#request"/"<cid>#response": this direction's body is the
        # verbatim body of another contract direction (KV handoff relay)
        self.forwarded = {
            "request": spec.get("request_forwarded_from"),
            "response": spec.get("response_forwarded_from"),
        }

    def keys(self, direction: str) -> Dict[str, _Key]:
        return self.request if direction == "request" else self.response


class WireContracts:
    def __init__(self, doc: Dict[str, Any]):
        self.doc = doc
        self.contracts: Dict[str, _Contract] = {
            cid: _Contract(cid, spec)
            for cid, spec in doc.get("endpoints", {}).items()
        }
        self.by_path: Dict[str, List[_Contract]] = {}
        for c in self.contracts.values():
            self.by_path.setdefault(c.path, []).append(c)
        self.apps: Dict[str, str] = doc.get("apps", {})
        self.client_targets: Dict[str, str] = doc.get("client_targets", {})
        self.helpers: Dict[str, Dict[str, Any]] = dict(_GLOBAL_HELPERS)
        for h in doc.get("post_helpers", []):
            self.helpers[h["method"]] = h
        self.bindings: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
        for b in doc.get("bindings", []):
            fn = b["function"]
            file, _, qual = fn.partition("::")
            self.bindings.setdefault(
                (os.path.normpath(file), qual), []
            ).append(b)
        ev = doc.get("events", {})
        self.events: Dict[str, Dict[str, Any]] = {
            e["name"]: e for e in ev.get("names", [])
        }
        met = doc.get("metrics", {})
        self.dynamic_metric_files: Dict[str, str] = {
            os.path.normpath(d["file"]): d.get("reason", "")
            for d in met.get("dynamic_sites", [])
        }
        self.dynamic_patterns: List[re.Pattern] = [
            re.compile(p["pattern"]) for p in met.get("dynamic_patterns", [])
        ]
        self.unpinned_metrics: Dict[str, str] = {
            u["name"]: u.get("reason", "")
            for u in met.get("unpinned", [])
        }
        self.config_chains: Dict[str, Any] = doc.get("config_chains", {})
        self.train_config_chains: Dict[str, Any] = doc.get(
            "train_config_chains", {}
        )

    @classmethod
    def load(cls, root: str) -> "WireContracts":
        with open(os.path.join(root, CONTRACTS_PATH), encoding="utf-8") as f:
            return cls(json.load(f))

    def resolve(self, path: str, app_hint: str) -> Optional[_Contract]:
        cands = self.by_path.get(path)
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        for c in cands:
            if c.app == app_hint:
                return c
        return cands[0]


# --------------------------------------------------------------------------
# C8: payload contracts
# --------------------------------------------------------------------------

class _Payload:
    """A producer-side JSON body being built in a function."""

    def __init__(self, keys: Dict[str, int], open_: bool):
        self.keys = dict(keys)
        self.open = open_


class _View:
    """A consumer-side body (request body in a handler, parsed response in
    a client); reads on it are contract reads."""

    def __init__(self, contract: _Contract, direction: str, prefix: str = ""):
        self.contract = contract
        self.direction = direction
        self.prefix = prefix


class _Site:
    def __init__(self, contract, direction, sf, line, payload=None):
        self.contract = contract
        self.direction = direction
        self.sf = sf
        self.line = line
        self.payload = payload  # _Payload (closed or open) or None


class _Read:
    def __init__(self, contract, direction, key, kind, sf, line):
        self.contract = contract
        self.direction = direction
        self.key = key
        self.kind = kind  # "hard" | "silent" | "tolerant" | "membership"
        self.sf = sf
        self.line = line


class _C8Scanner:
    def __init__(self, wc: WireContracts):
        self.wc = wc
        self.producers: List[_Site] = []
        self.reads: List[_Read] = []
        self.augment_writes: List[_Read] = []  # key writes on open bodies
        self.findings: List[Finding] = []

    # -- handler registration maps ------------------------------------

    def _handler_map(self, sf: SourceFile) -> Dict[str, str]:
        """method name -> endpoint path, from app.router.add_post/add_get
        calls in this file."""
        out: Dict[str, str] = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _dotted(node.func) or ""
            if not fn.endswith((".add_post", ".add_get")):
                continue
            if len(node.args) != 2:
                continue
            path = _const_str(node.args[0])
            h = node.args[1]
            if path and isinstance(h, ast.Attribute):
                out[h.attr] = path
            elif path and isinstance(h, ast.Name):
                out[h.id] = path
        return out

    # -- per-file scan -------------------------------------------------

    def scan_file(self, sf: SourceFile):
        if sf.tree is None:
            return
        handlers = self._handler_map(sf)
        # handler contracts only resolve for files whose serving app is
        # declared in the registry; an aiohttp app in an undeclared file
        # would otherwise steal contracts for colliding paths (/health)
        app_hint = self.wc.apps.get(os.path.normpath(sf.rel))
        if app_hint is None and handlers:
            for path in sorted(set(handlers.values())):
                if path in self.wc.by_path:
                    self.findings.append(Finding(
                        RULE_PAYLOAD, sf.rel, 1,
                        f"file serves '{path}' but is not mapped to an app "
                        f"in wire_contracts.json 'apps' — its handlers are "
                        f"unchecked",
                    ))
        for qual, fn in _iter_functions(sf.tree):
            self._scan_function(sf, qual, fn, handlers, app_hint)

    def _client_contract(self, sf, qual, path) -> Optional[_Contract]:
        key = f"{os.path.normpath(sf.rel)}::{qual}"
        hint = self.wc.client_targets.get(key, "gen")
        return self.wc.resolve(path, hint)

    def _scan_function(self, sf, qual, fn, handlers, app_hint):
        env: Dict[str, Any] = {}  # name -> _Payload | _View
        resp_env: Dict[str, _Contract] = {}
        method_name = fn.name
        handler_contract: Optional[_Contract] = None
        if method_name in handlers and app_hint is not None:
            handler_contract = self.wc.resolve(handlers[method_name], app_hint)
        producer_return: Optional[Tuple[_Contract, str]] = None
        for b in self.wc.bindings.get((os.path.normpath(sf.rel), qual), []):
            c = self.wc.contracts.get(b["contract"])
            if c is None:
                continue
            if b["role"] == "consumer":
                for var in b.get("vars", []):
                    env[var] = _View(c, b["direction"])
            elif b["role"] == "producer" and b.get("returns"):
                producer_return = (c, b["direction"])

        def record_payload(contract, direction, node, line):
            node = _unwrap(node)
            if isinstance(node, ast.Dict):
                keys, open_ = _dict_keys(node)
                self.producers.append(
                    _Site(contract, direction, sf, line,
                          _Payload(keys, open_))
                )
                return
            if isinstance(node, ast.Name):
                info = env.get(node.id)
                if isinstance(info, _Payload):
                    self.producers.append(
                        _Site(contract, direction, sf, line, info)
                    )
                    return
                if isinstance(info, _View):
                    return  # passthrough forward: augment writes cover it
            # unresolvable (call result, attribute, ...) — not checkable

        def endpoint_of_call(call) -> Tuple[Optional[_Contract], Optional[ast.AST]]:
            """(contract, payload_node) when `call` posts JSON to a
            statically-known endpoint; (None, None) otherwise."""
            attr = _call_attr(call)
            # session.post(url, json=...) / requests.post(url, json=...)
            if attr in ("post", "get") and call.args:
                path = _path_from_url(call.args[0])
                if path is None:
                    return None, None
                c = self._client_contract(sf, qual, path)
                if c is None:
                    self.findings.append(Finding(
                        RULE_PAYLOAD, sf.rel, call.lineno,
                        f"HTTP {attr.upper()} to '{path}' but no contract "
                        f"for that endpoint in wire_contracts.json",
                    ))
                    return None, None
                payload = None
                for kw in call.keywords:
                    if kw.arg == "json":
                        payload = kw.value
                return c, payload
            if attr == "urlopen" and call.args:
                path = _path_from_url(call.args[0])
                if path is None:
                    return None, None
                return self._client_contract(sf, qual, path), None
            helper = self.wc.helpers.get(attr)
            if helper is not None:
                ep = None
                payload = None
                for kw in call.keywords:
                    if kw.arg == "endpoint":
                        ep = _const_str(kw.value)
                    elif kw.arg in ("payload", "json"):
                        payload = kw.value
                ei, pi = helper["endpoint_arg"], helper.get("payload_arg")
                if ep is None and len(call.args) > ei:
                    ep = _const_str(call.args[ei])
                if payload is None and pi is not None and len(call.args) > pi:
                    payload = call.args[pi]
                if ep is None:
                    return None, None
                c = self._client_contract(sf, qual, ep)
                if c is None:
                    self.findings.append(Finding(
                        RULE_PAYLOAD, sf.rel, call.lineno,
                        f"{attr}() targets '{ep}' but no contract for that "
                        f"endpoint in wire_contracts.json",
                    ))
                return c, payload
            return None, None

        def handle_call(call: ast.Call):
            attr = _call_attr(call)
            # producer: HttpRequest(endpoint=..., payload=...)
            if attr == "HttpRequest":
                ep = payload = None
                for kw in call.keywords:
                    if kw.arg == "endpoint":
                        ep = _const_str(kw.value)
                    elif kw.arg == "payload":
                        payload = kw.value
                if ep and payload is not None:
                    c = self._client_contract(sf, qual, ep)
                    if c is None:
                        self.findings.append(Finding(
                            RULE_PAYLOAD, sf.rel, call.lineno,
                            f"HttpRequest targets '{ep}' but no contract "
                            f"for that endpoint in wire_contracts.json",
                        ))
                    else:
                        record_payload(c, "request", payload, call.lineno)
                return
            # producer: web.json_response({...}) in a handler/bound fn
            if attr == "json_response":
                ctx_contract = handler_contract or (
                    producer_return[0] if producer_return else None
                )
                if ctx_contract is None or not call.args:
                    return
                for kw in call.keywords:
                    if kw.arg == "status":
                        sv = kw.value
                        if (isinstance(sv, ast.Constant)
                                and isinstance(sv.value, int)
                                and sv.value >= 400):
                            return  # error path: not the success contract
                record_payload(ctx_contract, "response", call.args[0],
                               call.lineno)
                return
            # producer: posts through helpers / session.post
            c, payload = endpoint_of_call(call)
            if c is not None and payload is not None:
                record_payload(c, "request", payload, call.lineno)
            # consumer: X.get("k"[, default])
            if attr == "get" and call.args:
                key = _const_str(call.args[0])
                recv = call.func.value if isinstance(call.func, ast.Attribute) else None
                view = self._view_of(recv, env, resp_env)
                if key is not None and view is not None:
                    if len(call.args) < 2:
                        kind = "silent"
                    elif isinstance(call.args[1], ast.Constant):
                        kind = "silent"
                    elif (isinstance(call.args[1],
                                     (ast.List, ast.Tuple, ast.Dict, ast.Set))
                            and not getattr(call.args[1], "elts",
                                            getattr(call.args[1], "keys", ()))):
                        # .get("k", []) / .get("k", {}) — an empty container
                        # literal degrades exactly like a constant default
                        kind = "silent"
                    else:
                        kind = "tolerant"
                    self.reads.append(_Read(
                        view.contract, view.direction, view.prefix + key,
                        kind, sf, call.lineno,
                    ))

        def handle_subscript(sub: ast.Subscript):
            key = _const_str(sub.slice)
            if key is None:
                return
            if not isinstance(sub.value, ast.Name):
                # r.json()["k"] / (await resp.json())["k"] direct reads
                view = self._view_of(sub.value, env, resp_env)
                if view is not None and isinstance(sub.ctx, ast.Load):
                    self.reads.append(_Read(
                        view.contract, view.direction, view.prefix + key,
                        "hard", sf, sub.lineno,
                    ))
                return
            info = env.get(sub.value.id)
            if isinstance(info, _View):
                if isinstance(sub.ctx, ast.Load):
                    self.reads.append(_Read(
                        info.contract, info.direction, info.prefix + key,
                        "hard", sf, sub.lineno,
                    ))
                elif isinstance(sub.ctx, ast.Store):
                    self.augment_writes.append(_Read(
                        info.contract, info.direction, info.prefix + key,
                        "write", sf, sub.lineno,
                    ))
            elif isinstance(info, _Payload) and isinstance(sub.ctx, ast.Store):
                info.keys.setdefault(key, sub.lineno)

        def handle_compare(cmp: ast.Compare):
            if (len(cmp.ops) == 1 and isinstance(cmp.ops[0], (ast.In, ast.NotIn))
                    and isinstance(cmp.comparators[0], ast.Name)):
                info = env.get(cmp.comparators[0].id)
                key = _const_str(cmp.left)
                if isinstance(info, _View) and key is not None:
                    self.reads.append(_Read(
                        info.contract, info.direction, info.prefix + key,
                        "membership", sf, cmp.lineno,
                    ))

        def _register_with_item(item):
            ce = item.context_expr
            if not isinstance(ce, ast.Call):
                return
            c, _payload = endpoint_of_call(ce)
            if c is not None and item.optional_vars is not None:
                if isinstance(item.optional_vars, ast.Name):
                    resp_env[item.optional_vars.id] = c

        def handle_assign(target, value):
            if not isinstance(target, ast.Name):
                # tuple unpack of status_json helpers:
                #   status, body = await self._leg_post(addr, "/x", payload, n)
                if (isinstance(target, ast.Tuple)
                        and len(target.elts) == 2
                        and isinstance(target.elts[1], ast.Name)):
                    vv = _unwrap(value)
                    if isinstance(vv, ast.Call):
                        helper = self.wc.helpers.get(_call_attr(vv))
                        if helper and helper.get("returns") == "status_json":
                            c, _p = endpoint_of_call(vv)
                            if c is not None:
                                env[target.elts[1].id] = _View(c, "response")
                return
            name = target.id
            vv = _unwrap(value)
            # view: body = await request.json() (handler)
            if isinstance(vv, ast.Call):
                attr = _call_attr(vv)
                if attr == "json" and isinstance(vv.func, ast.Attribute):
                    recv = vv.func.value
                    if (isinstance(recv, ast.Name)
                            and recv.id == "request"
                            and handler_contract is not None):
                        env[name] = _View(handler_contract, "request")
                        return
                    if isinstance(recv, ast.Name) and recv.id in resp_env:
                        env[name] = _View(resp_env[recv.id], "response")
                        return
                if attr == "loads":
                    # m = json.loads(r.read()) under `with urlopen(...) as r`
                    inner = vv.args[0] if vv.args else None
                    while isinstance(inner, ast.Call):
                        inner = (inner.func.value
                                 if isinstance(inner.func, ast.Attribute)
                                 else None)
                    if isinstance(inner, ast.Name) and inner.id in resp_env:
                        env[name] = _View(resp_env[inner.id], "response")
                        return
                # view: raw = await arequest_with_retry(endpoint="/x", ...)
                helper = self.wc.helpers.get(attr)
                if helper and helper.get("returns") == "json":
                    c, _p = endpoint_of_call(vv)
                    if c is not None:
                        env[name] = _View(c, "response")
                        return
                # response object: r = session.post(url, ...) / a helper
                # returning a requests.Response — r.json()["k"] reads later
                if (attr in ("post", "get")
                        or (helper and helper.get("returns") == "respobj")):
                    c, _p = endpoint_of_call(vv)
                    if c is not None:
                        resp_env[name] = c
                        return
                # sub-view: sp = body.get("sampling_params", {})
                if attr == "get" and isinstance(vv.func, ast.Attribute):
                    view = self._view_of(vv.func.value, env, resp_env)
                    key = _const_str(vv.args[0]) if vv.args else None
                    if view is not None and key is not None:
                        pref = view.prefix + key + "."
                        if any(k.startswith(pref)
                               for k in view.contract.keys(view.direction)):
                            env[name] = _View(view.contract, view.direction,
                                              pref)
                            return
            if isinstance(vv, ast.Subscript) and isinstance(vv.value, ast.Name):
                view = env.get(vv.value.id)
                key = _const_str(vv.slice)
                if isinstance(view, _View) and key is not None:
                    pref = view.prefix + key + "."
                    if any(k.startswith(pref)
                           for k in view.contract.keys(view.direction)):
                        env[name] = _View(view.contract, view.direction, pref)
                        return
            if isinstance(vv, ast.Dict):
                keys, open_ = _dict_keys(vv)
                env[name] = _Payload(keys, open_)
                return
            if isinstance(vv, ast.Name) and vv.id in env:
                info = env[vv.id]
                if isinstance(info, _Payload):
                    env[name] = _Payload(info.keys, info.open)
                else:
                    env[name] = _View(info.contract, info.direction,
                                      info.prefix)
                return

        def walk_node(node: ast.AST):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                walk_node(node.value)
                handle_assign(node.targets[0], node.value)
                if isinstance(node.targets[0], ast.Subscript):
                    handle_subscript(node.targets[0])
                return
            if (isinstance(node, ast.AnnAssign) and node.value is not None):
                walk_node(node.value)
                handle_assign(node.target, node.value)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    walk_node(item.context_expr)
                    _register_with_item(item)
                for stmt in node.body:
                    walk_node(stmt)
                return
            if isinstance(node, ast.Call):
                handle_call(node)
            elif isinstance(node, ast.Subscript):
                handle_subscript(node)
            elif isinstance(node, ast.Compare):
                handle_compare(node)
            elif isinstance(node, ast.Return) and producer_return is not None:
                if node.value is not None:
                    c, d = producer_return
                    record_payload(c, d, node.value, node.lineno)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested functions scan as their own unit
                walk_node(child)

        for stmt in fn.body:
            walk_node(stmt)

    @staticmethod
    def _view_of(node, env, resp_env) -> Optional[_View]:
        node = _unwrap(node) if node is not None else None
        if isinstance(node, ast.Name):
            info = env.get(node.id)
            if isinstance(info, _View):
                return info
            return None
        # (await resp.json()).get(...)
        if isinstance(node, ast.Call):
            if _call_attr(node) == "json" and isinstance(
                    node.func, ast.Attribute):
                recv = node.func.value
                if isinstance(recv, ast.Name) and recv.id in resp_env:
                    return _View(resp_env[recv.id], "response")
        return None


def check_payload_contracts(
    files: Dict[str, SourceFile],
    root: Optional[str] = None,
    contracts: Optional[WireContracts] = None,
    fake_server: Optional[SourceFile] = None,
) -> List[Finding]:
    wc = contracts or WireContracts.load(root)
    scanner = _C8Scanner(wc)
    scan_files = dict(files)
    # tests/ is excluded from the default scan, but the fake server IS a
    # wire producer/consumer the real clients run against — load it
    # explicitly so its contract drift is caught (the PR-17 class).
    if fake_server is not None:
        scan_files[FAKE_SERVER_REL] = fake_server
    elif root is not None:
        fp = os.path.join(root, FAKE_SERVER_REL)
        if os.path.exists(fp):
            scan_files[FAKE_SERVER_REL] = SourceFile.from_path(
                fp, rel=FAKE_SERVER_REL
            )
    for sf in scan_files.values():
        scanner.scan_file(sf)

    findings = list(scanner.findings)
    produced: Dict[Tuple[str, str, str], int] = {}
    hard_reads: Dict[Tuple[str, str, str], _Read] = {}
    soft_reads: Dict[Tuple[str, str, str], _Read] = {}

    for site in scanner.producers:
        c, d, p = site.contract, site.direction, site.payload
        spec = c.keys(d)
        for key, line in p.keys.items():
            produced[(c.cid, d, key)] = produced.get((c.cid, d, key), 0) + 1
            if key not in spec:
                findings.append(apply_suppression(site.sf, Finding(
                    RULE_PAYLOAD, site.sf.rel, line,
                    f"producer writes key '{key}' not in the {c.path} "
                    f"{d} contract (renamed or stale? update "
                    f"wire_contracts.json or the producer)",
                )))
        if not p.open:
            for key, kspec in spec.items():
                if kspec.required and key not in p.keys:
                    findings.append(apply_suppression(site.sf, Finding(
                        RULE_PAYLOAD, site.sf.rel, site.line,
                        f"producer for {c.path} {d} omits required key "
                        f"'{key}' (every producer must write it)",
                    )))

    for w in scanner.augment_writes:
        produced[(w.contract.cid, w.direction, w.key)] = (
            produced.get((w.contract.cid, w.direction, w.key), 0) + 1
        )
        if w.key not in w.contract.keys(w.direction):
            findings.append(apply_suppression(w.sf, Finding(
                RULE_PAYLOAD, w.sf.rel, w.line,
                f"writes key '{w.key}' into a forwarded {w.contract.path} "
                f"{w.direction} body but the contract has no such key",
            )))

    for r in scanner.reads:
        spec = r.contract.keys(r.direction)
        if r.key not in spec:
            findings.append(apply_suppression(r.sf, Finding(
                RULE_PAYLOAD, r.sf.rel, r.line,
                f"reads key '{r.key}' from the {r.contract.path} "
                f"{r.direction} body but no producer writes it (not in "
                f"the contract)",
            )))
            continue
        kspec = spec[r.key]
        if r.kind == "hard":
            hard_reads.setdefault((r.contract.cid, r.direction, r.key), r)
        elif r.kind in ("silent", "tolerant"):
            soft_reads.setdefault((r.contract.cid, r.direction, r.key), r)
        if (r.kind == "silent" and kspec.required and not kspec.tolerant_ok):
            findings.append(apply_suppression(r.sf, Finding(
                RULE_SILENT, r.sf.rel, r.line,
                f".get('{r.key}') with a silent default, but every "
                f"producer of {r.contract.path} {r.direction} writes it — "
                f"a rename would silently degrade instead of failing "
                f"(mark tolerant_reads_ok in wire_contracts.json if "
                f"intentional)",
            )))

    # registry health: every contract key must have a producer somewhere
    for c in wc.contracts.values():
        for d in ("request", "response"):
            src_cid, src_dir = c.cid, d
            fwd = c.forwarded.get(d)
            if fwd:
                src_cid, _, src_dir = fwd.partition("#")
            for key, kspec in c.keys(d).items():
                n = produced.get((src_cid, src_dir, key), 0)
                if n or kspec.external:
                    continue
                hr = hard_reads.get((c.cid, d, key))
                sr = soft_reads.get((c.cid, d, key))
                if hr is not None:
                    findings.append(apply_suppression(hr.sf, Finding(
                        RULE_PAYLOAD, hr.sf.rel, hr.line,
                        f"required read of '{key}' from {c.path} {d} but "
                        f"NO producer writes that key anywhere",
                    )))
                elif sr is not None:
                    findings.append(apply_suppression(sr.sf, Finding(
                        RULE_SILENT, sr.sf.rel, sr.line,
                        f"reads '{key}' from {c.path} {d} with a default "
                        f"but no producer writes it — always the default",
                    )))
                else:
                    findings.append(Finding(
                        RULE_REGISTRY, CONTRACTS_PATH, 1,
                        f"contract key '{key}' on {c.path} {d} is neither "
                        f"produced nor consumed by any scanned code — "
                        f"stale registry entry",
                    ))
    return findings


# --------------------------------------------------------------------------
# C9: telemetry-name contracts (metrics + lifecycle events)
# --------------------------------------------------------------------------

_REGISTRY_PREFIX = {"GEN": "areal_gen_", "ROUTER": "areal_router_",
                    "TRAIN": "areal_train_"}
_METRIC_CTORS = ("counter", "gauge", "histogram")


def _metric_candidates(call: ast.Call, aliases: Dict[str, str]) -> Optional[List[str]]:
    """Fully-qualified candidate names for a metric construction, or None
    when the receiver is statically unresolvable (parametric registry)."""
    name = _const_str(call.args[0]) if call.args else None
    if name is None:
        return None
    if name.startswith("areal_"):
        return [name]
    recv = call.func.value if isinstance(call.func, ast.Attribute) else None
    d = _dotted(recv) if recv is not None else None
    if d is not None:
        tail = d.rsplit(".", 1)[-1]
        tail = aliases.get(tail, tail)
        if tail in _REGISTRY_PREFIX:
            return [_REGISTRY_PREFIX[tail] + name]
    return [p + name for p in _REGISTRY_PREFIX.values()]


def check_telemetry_contracts(
    files: Dict[str, SourceFile],
    root: Optional[str] = None,
    contracts: Optional[WireContracts] = None,
    schema: Optional[Dict[str, List[str]]] = None,
    trace_sf: Optional[SourceFile] = None,
) -> List[Finding]:
    wc = contracts or WireContracts.load(root)
    findings: List[Finding] = []
    if schema is None:
        try:
            with open(os.path.join(root, SCHEMA_PATH), encoding="utf-8") as f:
                schema = json.load(f)
        except FileNotFoundError:
            # Scratch --root trees (CLI drives, fixtures) carry no pinned
            # schema; degrade to "nothing pinned" so constructed metrics
            # still surface as findings instead of crashing the suite.
            schema = {}
    pinned = {name for names in schema.values() for name in names}

    # ---- metric constructions ---------------------------------------
    covered: set = set()
    for sf in files.values():
        if sf.tree is None:
            continue
        # registry aliases (`reg = telemetry.TRAIN`) are tracked per scope:
        # a function's alias must not leak into a sibling that takes the
        # registry as a parameter (register_staleness-style helpers)
        ctor_calls: List[Tuple[ast.Call, Dict[str, str]]] = []

        def _collect(node: ast.AST, aliases: Dict[str, str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _collect(child, dict(aliases))
                    continue
                if (isinstance(child, ast.Assign) and len(child.targets) == 1
                        and isinstance(child.targets[0], ast.Name)):
                    d = _dotted(child.value)
                    if (d is not None
                            and d.rsplit(".", 1)[-1] in _REGISTRY_PREFIX):
                        aliases[child.targets[0].id] = d.rsplit(".", 1)[-1]
                if (isinstance(child, ast.Call)
                        and isinstance(child.func, ast.Attribute)
                        and child.func.attr in _METRIC_CTORS):
                    ctor_calls.append((child, dict(aliases)))
                _collect(child, aliases)

        _collect(sf.tree, {})
        for node, aliases in ctor_calls:
            if not node.args:
                continue
            raw_name = _const_str(node.args[0])
            if raw_name is None:
                if os.path.normpath(sf.rel) not in wc.dynamic_metric_files:
                    findings.append(apply_suppression(sf, Finding(
                        RULE_METRIC, sf.rel, node.lineno,
                        "dynamically-named metric construction in a file "
                        "not allowlisted under metrics.dynamic_sites in "
                        "wire_contracts.json — pin the name or register "
                        "the site with a reason",
                    )))
                continue
            cands = _metric_candidates(node, aliases)
            covered.update(cands)
            if raw_name in wc.unpinned_metrics:
                continue
            if not any(c in pinned for c in cands):
                findings.append(apply_suppression(sf, Finding(
                    RULE_METRIC, sf.rel, node.lineno,
                    f"metric '{raw_name}' (candidates: {sorted(cands)}) is "
                    f"constructed here but not pinned in "
                    f"tests/data/metrics_schema.json — scrape tests will "
                    f"never notice it disappearing",
                )))

    for name in sorted(pinned):
        if name in covered:
            continue
        if any(p.match(name) for p in wc.dynamic_patterns):
            continue
        findings.append(Finding(
            RULE_METRIC, SCHEMA_PATH, 1,
            f"metrics_schema.json pins '{name}' but no code constructs it "
            f"(orphaned schema entry)",
        ))

    # ---- lifecycle events -------------------------------------------
    if trace_sf is None:
        trace_sf = files.get(os.path.normpath(TRACE_REL))
    emitted: Dict[str, Tuple[SourceFile, int]] = {}
    for sf in files.values():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "emit"):
                continue
            recv = _dotted(node.func.value) or ""
            tail = recv.rsplit(".", 1)[-1]
            if tail not in ("telemetry", "EVENTS"):
                continue
            name = _const_str(node.args[0]) if node.args else None
            if name is not None:
                emitted.setdefault(name, (sf, node.lineno))

    consumed: Dict[str, int] = {}
    if trace_sf is not None and trace_sf.tree is not None:
        for node in ast.walk(trace_sf.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and re.match(r"^_[A-Z_]*EVENTS$", node.targets[0].id)
                    and isinstance(node.value, ast.Tuple)):
                for elt in node.value.elts:
                    s = _const_str(elt)
                    if s is not None:
                        consumed.setdefault(s, node.lineno)
            if isinstance(node, ast.Compare):
                left = node.left
                is_event_expr = (
                    (isinstance(left, ast.Name) and left.id == "name")
                    or (isinstance(left, ast.Subscript)
                        and _const_str(left.slice) == "event")
                    or (isinstance(left, ast.Call)
                        and isinstance(left.func, ast.Attribute)
                        and left.func.attr == "get" and left.args
                        and _const_str(left.args[0]) == "event")
                )
                if not is_event_expr:
                    continue
                for comp in node.comparators:
                    s = _const_str(comp)
                    if s is not None:
                        consumed.setdefault(s, node.lineno)
                    elif isinstance(comp, ast.Tuple):
                        for elt in comp.elts:
                            es = _const_str(elt)
                            if es is not None:
                                consumed.setdefault(es, node.lineno)

    declared = wc.events
    for name, (sf, line) in sorted(emitted.items()):
        if name not in declared:
            findings.append(apply_suppression(sf, Finding(
                RULE_EVENT, sf.rel, line,
                f"telemetry.emit('{name}') but the event is not declared "
                f"in wire_contracts.json — trace reconstruction will drop "
                f"it silently",
            )))
    if trace_sf is not None:
        for name, line in sorted(consumed.items()):
            if name not in declared:
                findings.append(apply_suppression(trace_sf, Finding(
                    RULE_EVENT, trace_sf.rel, line,
                    f"obs/trace.py parses event '{name}' that is not "
                    f"declared in wire_contracts.json (parsed-but-never-"
                    f"emitted ghost?)",
                )))
    for name, spec in sorted(declared.items()):
        if name not in emitted and not spec.get("emit_exempt"):
            findings.append(Finding(
                RULE_REGISTRY, CONTRACTS_PATH, 1,
                f"event '{name}' is declared but nothing emits it "
                f"(add emit_exempt with a reason, or delete it)",
            ))
        if trace_sf is not None and name not in consumed and not spec.get(
                "consume_exempt"):
            anchor = emitted.get(name)
            if anchor is not None:
                findings.append(apply_suppression(anchor[0], Finding(
                    RULE_EVENT, anchor[0].rel, anchor[1],
                    f"event '{name}' is emitted but obs/trace.py never "
                    f"consumes it — an emitted-but-never-parsed span",
                )))
            else:
                findings.append(Finding(
                    RULE_REGISTRY, CONTRACTS_PATH, 1,
                    f"event '{name}' is declared but obs/trace.py never "
                    f"consumes it (add consume_exempt with a reason)",
                ))
    return findings


# --------------------------------------------------------------------------
# C10: GenServerConfig -> argparse -> engine kwarg plumbing
# --------------------------------------------------------------------------

def _collect_flags(fn_node: ast.AST) -> Dict[str, int]:
    """--flag strings appearing in a function body, from constants and
    f-string heads; '=value' suffixes stripped."""
    flags: Dict[str, int] = {}

    def add(s: str, line: int):
        for piece in s.split():
            if piece.startswith("--"):
                flags.setdefault(piece.split("=")[0], line)

    for node in ast.walk(fn_node):
        s = _const_str(node)
        if s is not None and s.startswith("--"):
            add(s, node.lineno)
        elif isinstance(node, ast.JoinedStr) and node.values:
            head = _const_str(node.values[0])
            if head is not None and head.startswith("--"):
                add(head, node.lineno)
    return flags


def check_config_plumbing(
    files: Dict[str, SourceFile],
    root: Optional[str] = None,
    contracts: Optional[WireContracts] = None,
) -> List[Finding]:
    wc = contracts or WireContracts.load(root)
    cc = wc.config_chains
    if not cc:
        return []
    findings: List[Finding] = []
    f = cc.get("files", {})
    cfg_sf = files.get(os.path.normpath(f.get("config", "")))
    srv_sf = files.get(os.path.normpath(f.get("server", "")))
    eng_sf = files.get(os.path.normpath(f.get("engine", "")))
    if cfg_sf is None or srv_sf is None or eng_sf is None:
        return [Finding(
            RULE_REGISTRY, CONTRACTS_PATH, 1,
            f"config_chains.files points at missing files "
            f"({sorted(f.values())})",
        )]

    # -- config fields + build_cmd flags --
    cfg_fields: Dict[str, int] = {}
    build_flags: Dict[str, int] = {}
    cls_line = 1
    for node in ast.walk(cfg_sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == f.get(
                "config_class", "GenServerConfig"):
            cls_line = node.lineno
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name):
                    cfg_fields[item.target.id] = item.lineno
                elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if item.name == f.get("build_cmd", "build_cmd"):
                        build_flags = _collect_flags(item)

    # -- server argparse flags + engine call kwargs --
    arg_flags: Dict[str, int] = {}
    engine_call_kwargs: Dict[str, int] = {}
    dict_literals: Dict[str, Dict[str, int]] = {}
    engine_cls = f.get("engine_class", "GenEngine")
    for node in ast.walk(srv_sf.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args):
            flag = _const_str(node.args[0])
            if flag and flag.startswith("--"):
                arg_flags.setdefault(flag, node.lineno)
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            v = node.value
            if isinstance(v, ast.Dict):
                keys, _open = _dict_keys(v)
                dict_literals[node.targets[0].id] = keys
            elif (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                    and v.func.id == "dict"):
                dict_literals[node.targets[0].id] = {
                    kw.arg: kw.value.lineno
                    for kw in v.keywords if kw.arg is not None
                }
    for node in ast.walk(srv_sf.tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func) or ""
        if d.rsplit(".", 1)[-1] != engine_cls:
            continue
        for kw in node.keywords:
            if kw.arg is not None:
                engine_call_kwargs.setdefault(kw.arg, node.lineno)
            elif isinstance(kw.value, ast.Name):  # **tier_kw splat
                for k, ln in dict_literals.get(kw.value.id, {}).items():
                    engine_call_kwargs.setdefault(k, ln)

    # -- engine __init__ params --
    engine_params: Dict[str, int] = {}
    for node in ast.walk(eng_sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == engine_cls:
            for item in node.body:
                if (isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and item.name == "__init__"):
                    a = item.args
                    for p in (a.posonlyargs + a.args + a.kwonlyargs):
                        engine_params[p.arg] = item.lineno

    chains = cc.get("chains", [])
    chained_fields = {c["field"] for c in chains if c.get("field")}
    chained_flags = {c["flag"] for c in chains if c.get("flag")}

    for chain in chains:
        field = chain.get("field")
        flag = chain.get("flag")
        kwarg = chain.get("engine_kwarg")
        label = field or flag or kwarg
        if field and field not in cfg_fields:
            findings.append(apply_suppression(cfg_sf, Finding(
                RULE_CONFIG, cfg_sf.rel, cls_line,
                f"chain '{label}': GenServerConfig has no field '{field}' "
                f"(renamed without updating wire_contracts.json?)",
            )))
        if flag and flag not in arg_flags:
            findings.append(apply_suppression(srv_sf, Finding(
                RULE_CONFIG, srv_sf.rel, 1,
                f"chain '{label}': gen/server.py argparse has no '{flag}' "
                f"flag",
            )))
        if flag and (field or chain.get("build_emits")) \
                and flag not in build_flags:
            findings.append(apply_suppression(cfg_sf, Finding(
                RULE_CONFIG, cfg_sf.rel, cfg_fields.get(field, cls_line),
                f"chain '{label}': build_cmd never emits '{flag}' — "
                f"launchers silently drop the configured value",
            )))
        if kwarg:
            if kwarg not in engine_params:
                findings.append(apply_suppression(eng_sf, Finding(
                    RULE_CONFIG, eng_sf.rel,
                    engine_params.get("__any__", 1),
                    f"chain '{label}': GenEngine.__init__ has no "
                    f"'{kwarg}' parameter",
                )))
            if kwarg not in engine_call_kwargs:
                findings.append(apply_suppression(srv_sf, Finding(
                    RULE_CONFIG, srv_sf.rel, 1,
                    f"chain '{label}': gen/server.py main() never passes "
                    f"'{kwarg}' to {engine_cls} — the flag is parsed but "
                    f"dropped",
                )))

    for flag, line in sorted(arg_flags.items()):
        if flag not in chained_flags:
            findings.append(apply_suppression(srv_sf, Finding(
                RULE_CONFIG, srv_sf.rel, line,
                f"argparse flag '{flag}' is not covered by any "
                f"config_chains entry in wire_contracts.json — add a "
                f"chain (or a server_only entry with a reason)",
            )))
    for field, line in sorted(cfg_fields.items()):
        if field not in chained_fields:
            findings.append(apply_suppression(cfg_sf, Finding(
                RULE_CONFIG, cfg_sf.rel, line,
                f"GenServerConfig.{field} is not covered by any "
                f"config_chains entry in wire_contracts.json — add a "
                f"chain (or a config_only entry with a reason)",
            )))
    for flag, line in sorted(build_flags.items()):
        if flag not in arg_flags:
            findings.append(apply_suppression(cfg_sf, Finding(
                RULE_CONFIG, cfg_sf.rel, line,
                f"build_cmd emits '{flag}' but gen/server.py argparse "
                f"does not accept it — launched servers will crash",
            )))
        if flag not in chained_flags:
            findings.append(apply_suppression(cfg_sf, Finding(
                RULE_CONFIG, cfg_sf.rel, line,
                f"build_cmd flag '{flag}' is not covered by any "
                f"config_chains entry in wire_contracts.json",
            )))
    return findings


# --------------------------------------------------------------------------
# C10 (train half): TrainEngineConfig -> bench flag -> model-config replace
# --------------------------------------------------------------------------

def _class_ann_fields(
    sf: SourceFile, cls_name: str
) -> Tuple[Dict[str, int], int]:
    fields: Dict[str, int] = {}
    cls_line = 1
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            cls_line = node.lineno
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name):
                    fields[item.target.id] = item.lineno
    return fields, cls_line


def check_train_config_plumbing(
    files: Dict[str, SourceFile],
    root: Optional[str] = None,
    contracts: Optional[WireContracts] = None,
) -> List[Finding]:
    """The train-side config chains (ISSUE 20): each declared
    TrainEngineConfig knob must (a) exist as a config field, (b) be
    exposed AND read by the e2e bench's argparse when a flag is declared,
    and (c) — when it steers the backbone — exist on TransformerConfig
    and be plumbed through a `.replace(` call in the train engine.  Unlike
    the GenServer chain this is a DECLARED-chains check, not an
    exhaustive-coverage sweep: TrainEngineConfig has dozens of fields with
    their own plumbing idioms; the registry lists the chains whose silent
    breakage has bitten (a flag parsed but dropped trains a different
    model than the artifact records)."""
    wc = contracts or WireContracts.load(root)
    tc = wc.train_config_chains
    if not tc:
        return []
    f = tc.get("files", {})
    cfg_sf = files.get(os.path.normpath(f.get("config", "")))
    bench_sf = files.get(os.path.normpath(f.get("bench", "")))
    model_sf = files.get(os.path.normpath(f.get("model_config", "")))
    eng_sf = files.get(os.path.normpath(f.get("engine", "")))
    if cfg_sf is None or bench_sf is None or model_sf is None \
            or eng_sf is None:
        return [Finding(
            RULE_REGISTRY, CONTRACTS_PATH, 1,
            f"train_config_chains.files points at missing files "
            f"({sorted(f.values())})",
        )]

    cfg_fields, cfg_line = _class_ann_fields(
        cfg_sf, f.get("config_class", "TrainEngineConfig"))
    model_fields, _ = _class_ann_fields(
        model_sf, f.get("model_class", "TransformerConfig"))

    # bench argparse flags + `args.<dest>` reads (a parsed-but-never-read
    # flag silently trains the default)
    bench_flags: Dict[str, int] = {}
    args_reads: Dict[str, int] = {}
    for node in ast.walk(bench_sf.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args):
            flag = _const_str(node.args[0])
            if flag and flag.startswith("--"):
                bench_flags.setdefault(flag, node.lineno)
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "args"):
            args_reads.setdefault(node.attr, node.lineno)

    # model-config kwargs the engine plumbs via `.replace(...)`
    replace_kwargs: Dict[str, int] = {}
    for node in ast.walk(eng_sf.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "replace"):
            for kw in node.keywords:
                if kw.arg is not None:
                    replace_kwargs.setdefault(kw.arg, node.lineno)

    findings: List[Finding] = []
    for chain in tc.get("chains", []):
        field = chain.get("field")
        flag = chain.get("flag")
        mfield = chain.get("model_field")
        label = field or flag or mfield
        if field and field not in cfg_fields:
            findings.append(apply_suppression(cfg_sf, Finding(
                RULE_CONFIG, cfg_sf.rel, cfg_line,
                f"train chain '{label}': TrainEngineConfig has no field "
                f"'{field}' (renamed without updating "
                f"wire_contracts.json?)",
            )))
        if flag:
            if flag not in bench_flags:
                findings.append(apply_suppression(bench_sf, Finding(
                    RULE_CONFIG, bench_sf.rel, 1,
                    f"train chain '{label}': {bench_sf.rel} argparse has "
                    f"no '{flag}' flag",
                )))
            else:
                dest = flag.lstrip("-").replace("-", "_")
                if dest not in args_reads:
                    findings.append(apply_suppression(bench_sf, Finding(
                        RULE_CONFIG, bench_sf.rel, bench_flags[flag],
                        f"train chain '{label}': '{flag}' is parsed but "
                        f"`args.{dest}` is never read — the flag is "
                        f"silently dropped",
                    )))
        if mfield:
            if mfield not in model_fields:
                findings.append(apply_suppression(model_sf, Finding(
                    RULE_CONFIG, model_sf.rel, 1,
                    f"train chain '{label}': TransformerConfig has no "
                    f"field '{mfield}'",
                )))
            if mfield not in replace_kwargs:
                findings.append(apply_suppression(eng_sf, Finding(
                    RULE_CONFIG, eng_sf.rel, 1,
                    f"train chain '{label}': {eng_sf.rel} never plumbs "
                    f"'{mfield}' into a model-config .replace(...) — the "
                    f"engine knob cannot reach the backbone",
                )))
    return findings


# --------------------------------------------------------------------------
# suite entry point
# --------------------------------------------------------------------------

def check_wire_contracts(
    files: Dict[str, SourceFile], root: str
) -> List[Finding]:
    try:
        wc = WireContracts.load(root)
    except (OSError, ValueError, KeyError) as e:
        return [Finding(
            RULE_REGISTRY, CONTRACTS_PATH, 1,
            f"wire_contracts.json unreadable: {e}",
        )]
    findings: List[Finding] = []
    findings.extend(check_payload_contracts(files, root, contracts=wc))
    findings.extend(check_telemetry_contracts(files, root, contracts=wc))
    findings.extend(check_config_plumbing(files, root, contracts=wc))
    findings.extend(check_train_config_plumbing(files, root, contracts=wc))
    return findings
