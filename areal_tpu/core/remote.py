"""HTTP client core for remote generation servers.

Behavioral counterpart of the reference's `RemoteInfEngine`
(areal/core/remote_inf_engine.py:192) and `RemoteInfBackendProtocol` (:40):

- server discovery: explicit addrs -> name_resolve -> AREAL_LLM_SERVER_ADDRS
  env (remote_inf_engine.py:254-307);
- round-robin / least-inflight scheduling with rid->server affinity for KV
  reuse (:339, :404-413);
- the **interruption loop**: when a server aborts generation for a weight
  update, the client re-submits the request with all accumulated tokens as
  the new prompt and records per-token weight versions — the raw signal for
  decoupled PPO (:428-478);
- weight-update fan-out to every server over HTTP (the reference needs a
  ProcessPoolExecutor to bypass NCCL/GIL issues; the TPU path is pure HTTP
  + filesystem, so plain async fan-out suffices).
"""

import asyncio
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Protocol

import aiohttp

from areal_tpu.analysis.lockcheck import lock_guarded
from areal_tpu.api.config import InferenceEngineConfig
from areal_tpu.api.engine import InferenceEngine
from areal_tpu.api.io_struct import (
    HttpGenerationResult,
    HttpRequest,
    ModelRequest,
    ModelResponse,
    WeightUpdateMeta,
    WeightUpdateRequests,
)
from areal_tpu.api.workflow import RolloutWorkflow
from areal_tpu.core.executor import TrajectoryLostError, WorkflowExecutor
from areal_tpu.utils import logging, name_resolve, names, telemetry
from areal_tpu.utils.http import (
    HttpRequestError,
    arequest_with_retry,
    get_default_connector,
)

logger = logging.getLogger("remote_engine")

RID_CACHE_SIZE = 128


class FleetStalenessGate:
    """Client stub for the router's fleet-wide staleness gate.

    The reference's GserverManager gates rollout admission globally across
    every rollout worker (realhf/system/gserver_manager.py:334 `is_staled`,
    :175-191): N clients against one fleet must share one staleness budget,
    not apply N local ones.  `allocate` polls `/allocate_request` until the
    router grants a lease (409 = fleet staleness-bound); `finish` returns the
    lease via `/finish_request`.  If the router becomes unreachable the gate
    degrades to a no-op so rollout falls back to the local StalenessManager
    rather than deadlocking.
    """

    def __init__(
        self,
        router_addr: str,
        poll_interval: float = 0.5,
        max_failures: int = 5,
    ):
        self.router_addr = router_addr
        self.poll_interval = poll_interval
        self.max_failures = max_failures
        self._failures = 0
        self._disabled = False
        # lazily bound to the runner's event loop on first use
        self._session: Optional[aiohttp.ClientSession] = None

    def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=60.0, sock_connect=15.0),
                connector=get_default_connector(),
            )
        return self._session

    async def allocate(self, qid: str) -> Optional[str]:
        """Block until the fleet grants an admission; returns the lease id
        (None when the gate is unreachable/disabled)."""
        while not self._disabled:
            try:
                async with self._get_session().post(
                    f"http://{self.router_addr}/allocate_request",
                    json={"qid": qid},
                ) as resp:
                    if resp.status == 200:
                        self._failures = 0
                        return (await resp.json()).get("alloc_id")
                    if resp.status == 409:  # fleet staleness-bound: the
                        # router is alive and answering — not a failure
                        self._failures = 0
                    else:
                        raise RuntimeError(f"allocate -> HTTP {resp.status}")
            except Exception as e:  # noqa: BLE001 — degrade, don't deadlock
                self._failures += 1
                if self._failures >= self.max_failures:
                    logger.warning(
                        f"fleet staleness gate unreachable ({e}); falling "
                        f"back to the local StalenessManager"
                    )
                    self._disabled = True
                    return None
            await asyncio.sleep(self.poll_interval)
        return None

    async def finish(self, alloc_id: Optional[str], accepted: bool) -> None:
        # a KNOWN lease is returned even after the gate degraded — leaving
        # it to the TTL would eat a fleet admission for up to an hour
        if alloc_id is None:
            return
        try:
            async with self._get_session().post(
                f"http://{self.router_addr}/finish_request",
                json={"alloc_id": alloc_id, "accepted": accepted},
            ) as resp:
                resp.raise_for_status()
        except Exception as e:  # noqa: BLE001 — the router TTLs the lease
            logger.warning(f"finish_request failed (lease will expire): {e}")

    async def aclose(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


class RemoteInfBackendProtocol(Protocol):
    """Builds/parses the HTTP wire format of a server family."""

    def build_generation_request(self, req: ModelRequest) -> HttpRequest: ...

    def parse_generation_response(
        self, resp: Dict[str, Any]
    ) -> HttpGenerationResult: ...

    def build_pause_request(self) -> HttpRequest: ...

    def build_resume_request(self) -> HttpRequest: ...

    def build_weight_update_requests(
        self, meta: WeightUpdateMeta
    ) -> WeightUpdateRequests: ...


@lock_guarded
class RemoteInfEngine(InferenceEngine):
    """Client of N generation servers; owns the WorkflowExecutor."""

    # scheduling/version state shared between the rollout event loop and
    # the trainer's control-plane thread (areal-lint C1; runtime-validated
    # under AREAL_DEBUG_LOCKS=1)
    _GUARDED_FIELDS = {
        "_version": "_lock",
        "_server_idx": "_lock",
        "_rid_to_addr": "_lock",
        "_inflight": "_lock",
        "_failed": "_lock",
    }

    def __init__(self, config: InferenceEngineConfig, backend: RemoteInfBackendProtocol):
        self.config = config
        self.backend = backend
        self.addresses: List[str] = []
        self._server_idx = 0
        self._version = 0
        self._lock = threading.Lock()
        self._rid_to_addr: "OrderedDict[str, str]" = OrderedDict()
        self._inflight: Dict[str, int] = {}
        # failover bookkeeping: addr -> monotonic time of last observed
        # failure; recently-failed servers are excluded from re-placement
        # for config.failover_cooldown seconds
        self._failed: Dict[str, float] = {}
        self.executor = WorkflowExecutor(config, inference_engine=self)

    # --- lifecycle / discovery ---
    def initialize(
        self,
        addr: Optional[str | List[str]] = None,
        train_data_parallel_size: Optional[int] = None,
    ):
        if addr:
            self.addresses = [addr] if isinstance(addr, str) else list(addr)
        else:
            self.addresses = self._discover_servers()
        if not self.addresses:
            raise RuntimeError("no generation servers found")
        with self._lock:
            # the executor's rollout loop may already be probing inflight
            # counts; publishing the fresh table must be atomic with them
            self._inflight = {a: 0 for a in self.addresses}
        logger.info(f"remote engine using servers: {self.addresses}")
        router_addr = self._discover_router()
        if router_addr:
            logger.info(f"fleet staleness gate via router at {router_addr}")
            self.executor.fleet_gate = FleetStalenessGate(router_addr)
        self.executor.initialize()

    def _discover_router(self) -> Optional[str]:
        """Non-blocking router discovery: env override, then name_resolve.
        A registered router means this client is one of possibly many sharing
        a generation fleet, so admission must be gated fleet-wide."""
        env = os.environ.get("AREAL_GEN_ROUTER_ADDR")
        if env:
            return env or None
        try:
            return name_resolve.get(
                names.gen_router(
                    self.config.experiment_name, self.config.trial_name
                )
            )
        except Exception:  # noqa: BLE001 — no router registered
            return None

    def _discover_servers(self) -> List[str]:
        env = os.environ.get("AREAL_LLM_SERVER_ADDRS")
        if env:
            return env.split(",")
        key = names.gen_servers(self.config.experiment_name, self.config.trial_name)
        deadline = time.monotonic() + self.config.setup_timeout
        while time.monotonic() < deadline:
            found = name_resolve.get_subtree(key)
            if found:
                return sorted(found)
            time.sleep(0.5)
        raise TimeoutError(
            f"no generation servers registered under {key} within "
            f"{self.config.setup_timeout}s"
        )

    def destroy(self):
        self.executor.destroy()

    # --- versioning ---
    def set_version(self, version: int):
        with self._lock:
            self._version = version

    def get_version(self) -> int:
        with self._lock:
            return self._version

    # --- scheduling ---
    def _choose_locked(self) -> str:  # holds: _lock
        if self.config.schedule_policy == "least_requests":
            # read the table under the lock, not from inside the
            # lambda (a closure offers no static guarantee about when
            # it runs relative to the lock)
            inflight = self._inflight
            return min(self.addresses, key=lambda a: inflight.get(a, 0))
        addr = self.addresses[self._server_idx % len(self.addresses)]
        self._server_idx += 1
        return addr

    def choose_server(self) -> str:
        with self._lock:
            return self._choose_locked()

    def _server_for_rid(self, rid: str) -> str:
        # single critical section: the lookup-miss -> choose -> insert
        # sequence must be atomic, or two threads racing on the same rid
        # can pin it to different servers and split its KV affinity
        # (areal-lint C5 atomicity-split)
        with self._lock:
            if rid in self._rid_to_addr:
                self._rid_to_addr.move_to_end(rid)
                return self._rid_to_addr[rid]
            addr = self._choose_locked()
            if len(self._rid_to_addr) >= RID_CACHE_SIZE:
                self._rid_to_addr.popitem(last=False)
            self._rid_to_addr[rid] = addr
            return addr

    def _failover_server(self, dead: str, key: str) -> str:
        """Re-place `key` (group id or rid) after `dead` failed mid-request:
        mark the failure, evict EVERY affinity pinned to the dead server (a
        GRPO group's siblings all ride the group key, so the whole group
        reroutes together and fan-out prefix sharing re-forms on the new
        replica), and pin the key to a server that hasn't failed within the
        cooldown window.  When everyone is cooling down, place anyway —
        retrying a maybe-recovered server beats losing the trajectory."""
        now = time.monotonic()
        with self._lock:
            self._failed[dead] = now
            for r in [r for r, a in self._rid_to_addr.items() if a == dead]:
                del self._rid_to_addr[r]
            cooldown = self.config.failover_cooldown
            pool = [
                a
                for a in self.addresses
                if (t := self._failed.get(a)) is None or now - t > cooldown
            ] or self.addresses
            if self.config.schedule_policy == "least_requests":
                inflight = self._inflight
                addr = min(pool, key=lambda a: inflight.get(a, 0))
            else:
                addr = pool[self._server_idx % len(pool)]
                self._server_idx += 1
            if key:
                if len(self._rid_to_addr) >= RID_CACHE_SIZE:
                    self._rid_to_addr.popitem(last=False)
                self._rid_to_addr[key] = addr
            return addr

    # --- generation with interruption loop ---
    async def agenerate(self, req: ModelRequest) -> ModelResponse:
        req = req.copy()
        if not req.trace_id:
            req.trace_id = req.rid
        gconfig = req.gconfig
        if gconfig.n_samples != 1:
            raise ValueError(
                "agenerate handles a single sample; issue n_samples calls"
            )
        max_new = gconfig.max_new_tokens
        if max_new <= 0:
            raise RuntimeError(f"max_new_tokens={max_new} must be positive")

        # group-affinity: siblings of one GRPO group must share a replica
        # so the engine can fan their common prefix KV out across slots;
        # the group key (when declared) outranks the per-request rid
        addr = self._server_for_rid(req.group_id or req.rid)
        if telemetry.is_enabled():
            telemetry.emit(
                "rollout_submit", trace_id=req.trace_id, rid=req.rid,
                group_id=req.group_id, input_len=len(req.input_ids),
                server=addr,
            )
        attempt = 0
        failovers = 0
        start = time.perf_counter()
        out_tokens: List[int] = []
        out_logprobs: List[float] = []
        out_versions: List[int] = []
        input_len = len(req.input_ids)
        stop_reason = None
        ttft = float("inf")
        resubmitted = False  # next /generate is a failover resubmission
        # counter-keyed sampler stream (ISSUE 17): the first response pins
        # it; interruption resumes and failover resubmits pass it back so
        # the continuation samples the exact keys the uninterrupted run
        # would have used, on any server
        stream_id = 0

        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=self.config.request_timeout,
                sock_connect=min(60.0, self.config.request_timeout),
            ),
            read_bufsize=10 * 1024 * 1024,
            connector=get_default_connector(),
        ) as session:
            while (
                stop_reason not in ("stop", "length", "tool_calls")
                and len(out_tokens) < max_new
            ):
                # back off while the client is paused for a weight update
                while self.executor.is_paused():
                    await asyncio.sleep(0.25)
                attempt += 1
                if attempt > 1 and telemetry.is_enabled():
                    # resuming after a server-side interrupt: accumulated
                    # tokens travel back as the new prompt
                    telemetry.emit(
                        "resume", trace_id=req.trace_id, attempt=attempt,
                        generated=len(out_tokens),
                        prompt_len=len(req.input_ids),
                    )
                http_req = self.backend.build_generation_request(req)
                if stream_id:
                    http_req.payload["stream_id"] = stream_id
                next_addr: Optional[str] = None
                with self._lock:
                    self._inflight[addr] = self._inflight.get(addr, 0) + 1
                try:
                    # /generate is NOT idempotent (server-side slot + version
                    # accounting per call): the retry helper only replays
                    # never-sent connection failures; everything else raises
                    # into the failover path below, which resubmits with the
                    # accumulated tokens — the same resume contract the
                    # interruption loop already relies on
                    raw = await arequest_with_retry(
                        addr=addr,
                        endpoint=http_req.endpoint,
                        payload=http_req.payload,
                        method=http_req.method,
                        max_retries=self.config.request_retries,
                        timeout=self.config.request_timeout,
                        session=session,
                        idempotent=False,
                    )
                except HttpRequestError as e:
                    failovers += 1
                    if failovers > self.config.failover_retries:
                        if telemetry.is_enabled():
                            telemetry.emit(
                                "rollout_lost", trace_id=req.trace_id,
                                rid=req.rid, group_id=req.group_id,
                                server=addr, generated=len(out_tokens),
                                failovers=failovers,
                            )
                        raise TrajectoryLostError(
                            f"rid {req.rid}: no healthy server after "
                            f"{failovers} failovers (last: {e})"
                        ) from e
                    next_addr = self._failover_server(
                        addr, req.group_id or req.rid
                    )
                    logger.warning(
                        f"rid {req.rid}: {addr} failed ({e}); resubmitting "
                        f"to {next_addr} with {len(out_tokens)} tokens "
                        f"generated"
                    )
                    if telemetry.is_enabled():
                        # a RESUBMIT span, not a fresh submit: it joins the
                        # original trace_id so the lifecycle reconstruction
                        # shows one trajectory surviving a server death
                        telemetry.emit(
                            "resubmit", trace_id=req.trace_id, rid=req.rid,
                            group_id=req.group_id, from_server=addr,
                            to_server=next_addr, generated=len(out_tokens),
                            attempt=attempt,
                        )
                    telemetry.CLIENT_RESUBMISSIONS.inc()
                    resubmitted = True
                finally:
                    with self._lock:
                        self._inflight[addr] = max(
                            0, self._inflight.get(addr, 1) - 1
                        )
                if next_addr is not None:
                    addr = next_addr
                    continue
                result = self.backend.parse_generation_response(raw)
                if isinstance(raw, dict):
                    stream_id = int(raw.get("stream_id", stream_id) or stream_id)
                if resubmitted:
                    # did the retried trajectory warm-start on the new
                    # server's radix cache instead of cold-prefilling?
                    resubmitted = False
                    if result.cache_hit_tokens > 0:
                        telemetry.CLIENT_RESUBMIT_CACHE_HITS.inc()
                        if telemetry.is_enabled():
                            telemetry.emit(
                                "resubmit_cache_hit",
                                trace_id=req.trace_id, rid=req.rid,
                                server=addr,
                                hit_tokens=result.cache_hit_tokens,
                            )
                stop_reason = result.stop_reason
                version = (
                    result.version if result.version >= 0 else self.get_version()
                )
                if ttft == float("inf") and result.output_tokens:
                    ttft = time.perf_counter() - start
                out_tokens.extend(result.output_tokens)
                out_logprobs.extend(result.output_logprobs)
                out_versions.extend([version] * len(result.output_tokens))
                # interruption: resume with accumulated tokens as prompt
                req.input_ids = req.input_ids + result.output_tokens
                req.gconfig = req.gconfig.new(
                    max_new_tokens=max_new - len(out_tokens)
                )
        if stop_reason == "abort" or stop_reason == "interrupt":
            stop_reason = "length"  # exited loop on budget during interruption
        if telemetry.is_enabled():
            telemetry.emit(
                "gen_done", trace_id=req.trace_id,
                stop_reason=stop_reason or "length",
                output_len=len(out_tokens), attempts=attempt,
                latency_s=time.perf_counter() - start,
                ttft_s=ttft if ttft != float("inf") else None,
            )
        return ModelResponse(
            input_tokens=req.input_ids[:input_len],
            output_tokens=out_tokens,
            output_logprobs=out_logprobs,
            output_versions=out_versions,
            stop_reason=stop_reason or "length",
            tokenizer=req.tokenizer,
            latency=time.perf_counter() - start,
            ttft=ttft,
        )

    # --- weight updates ---
    def _fanout(self, build: Callable[[], WeightUpdateRequests], timeout: float):
        async def _one(addr: str, r: HttpRequest):
            return await arequest_with_retry(
                addr=addr,
                endpoint=r.endpoint,
                payload=r.payload,
                method=r.method,
                max_retries=self.config.request_retries,
                timeout=timeout,
            )

        async def _all():
            # per-server outcomes: one dead server must not wedge the whole
            # control-plane action behind its timeout, and the trainer needs
            # to know who missed the publish (the router's rejoin path
            # force-reloads them before they serve again)
            reqs = build().requests
            pairs = [(a, r) for a in self.addresses for r in reqs]
            results = await asyncio.gather(
                *[_one(a, r) for a, r in pairs], return_exceptions=True
            )
            failed = {}
            for (a, _), res in zip(pairs, results):
                if isinstance(res, BaseException):
                    failed[a] = res
            for a, exc in failed.items():
                logger.warning(f"control-plane fanout to {a} failed: {exc!r}")
            if failed:
                telemetry.PUBLISH_PARTIAL_FAILURES.inc(len(failed))
            if len(failed) == len(self.addresses):
                raise RuntimeError(
                    f"control-plane fanout reached no server: "
                    f"{sorted(failed)}"
                )

        # run on a private loop in this (caller) thread: pause/update/resume
        # is a blocking control-plane action for the trainer
        asyncio.run(_all())

    def pause_generation(self):
        self._fanout(
            lambda: WeightUpdateRequests(requests=[self.backend.build_pause_request()]),
            timeout=60.0,
        )
        if self.config.pause_grace_period > 0:
            time.sleep(self.config.pause_grace_period)

    def continue_generation(self):
        self._fanout(
            lambda: WeightUpdateRequests(
                requests=[self.backend.build_resume_request()]
            ),
            timeout=60.0,
        )

    def update_weights(self, meta: WeightUpdateMeta) -> None:
        """Fan the weight-update request out to every server.

        The caller (train loop) advances the client's version explicitly with
        `set_version(...)` after the update completes — same contract as the
        reference's examples (gsm8k_grpo.py) — so staleness accounting stays
        in the trainer's hands."""
        self._fanout(
            lambda: self.backend.build_weight_update_requests(meta),
            timeout=self.config.request_timeout,
        )

    # --- rollout surface: delegate to the executor ---
    def submit(self, data, workflow=None, workflow_builder=None, should_accept=None):
        self.executor.submit(data, workflow, workflow_builder, should_accept)

    def wait(self, count: int, timeout: Optional[float] = None):
        return self.executor.wait(count, timeout)

    def rollout_batch(
        self, data, workflow=None, workflow_builder=None, should_accept=None
    ):
        return self.executor.rollout_batch(
            data, workflow, workflow_builder, should_accept
        )

    def prepare_batch(
        self, dataloader, workflow=None, workflow_builder=None, should_accept=None
    ):
        return self.executor.prepare_batch(
            dataloader, workflow, workflow_builder, should_accept
        )

    def pause(self):
        self.executor.pause()

    def resume(self):
        self.executor.resume()
