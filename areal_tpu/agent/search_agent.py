"""Search-QA agent: interleaved retrieval-and-reasoning episodes.

Capability counterpart of the reference's search-agent example
(examples/search-agent + the ASearcher workflow it wires): the model emits
`<search>query</search>` tags mid-generation; the agent runs the query
against the episode's environment (`search` tool — LocalSearchEnv's BM25
corpus here, a retrieval service in production), injects the hits back as
an `<information>...</information>` block, and generation continues with
the evidence in context.  Injected tokens carry loss_mask 0 / logprob 0 —
the policy trains only on what it wrote (same convention as the TIR
agent, whose generate→detect→execute→inject loop this class reuses).
"""

import re
from typing import Optional

from areal_tpu.agent.api import register_agent
from areal_tpu.agent.tir_agent import TIRMathAgent
from areal_tpu.api.config import GenerationHyperparameters

_SEARCH_RE = re.compile(r"<search>(.*?)</search>", re.DOTALL)


@register_agent("search-qa")
class SearchQAAgent(TIRMathAgent):
    def __init__(
        self,
        gconfig: GenerationHyperparameters,
        tokenizer=None,
        max_tool_calls: int = 4,
        top_k: int = 3,
        tool_output_chars: int = 2048,
    ):
        super().__init__(
            gconfig,
            tokenizer=tokenizer,
            max_tool_calls=max_tool_calls,
            tool_output_chars=tool_output_chars,
        )
        self.top_k = top_k

    def _find_call(self, text: str):
        m = _SEARCH_RE.search(text)
        return (m.group(1), m.end()) if m else (None, None)

    async def _run_tool(self, query: str, env=None) -> str:
        if env is None:
            hits: list = []
        else:
            hits, _, _ = await env.aexecute_tool(
                "search", {"query": query.strip(), "k": self.top_k}
            )
        out = "\n".join(str(h) for h in hits)[: self.tool_output_chars]
        return f"\n<information>\n{out}\n</information>\n"
