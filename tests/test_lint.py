"""areal-lint (ISSUE 3 + 9 + 18): fixture coverage for all ten
checkers, the mutation acceptance cases (fixture AND real code: deleted
locks, reordered acquisitions, off-ladder statics, double-free, renamed
wire keys, dropped schema metrics, broken config chains), the
signature-budget math cross-checks, the suppression-hygiene rules, the
AREAL_DEBUG_LOCKS runtime assertions, the CLI output formats, and the
tier-1 repo-clean gate."""

import asyncio
import json
import os
import threading

import pytest

from areal_tpu.analysis.async_blocking import check_async_blocking
from areal_tpu.analysis.core import (
    SourceFile,
    load_files,
    run_suite,
    suppression_hygiene,
    unsuppressed,
)
from areal_tpu.analysis.dead_modules import check_dead_modules
from areal_tpu.analysis.host_sync import check_host_sync
from areal_tpu.analysis.jit_signatures import (
    BUDGET_PATH,
    budget_drift,
    check_jit_signatures,
    compute_budgets,
    ladder_values,
    pow2_row_counts,
)
from areal_tpu.analysis.lock_discipline import check_lock_discipline
from areal_tpu.analysis.lock_order import check_lock_order
from areal_tpu.analysis.lockcheck import LockDisciplineError, lock_guarded
from areal_tpu.analysis.typestate import check_typestate
from areal_tpu.analysis.wire_contracts import (
    WireContracts,
    check_config_plumbing,
    check_payload_contracts,
    check_telemetry_contracts,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "lint")


def _fixture(name: str) -> SourceFile:
    return SourceFile.from_path(
        os.path.join(FIXTURES, name + ".py"), rel=name
    )


@pytest.fixture(scope="module")
def repo_findings():
    return run_suite(REPO)


@pytest.fixture(scope="module")
def repo_files():
    return load_files(REPO)


# ------------------------------- C1 ---------------------------------


def test_lock_positive_fixture_flags_every_violation():
    findings = check_lock_discipline(_fixture("lock_pos"))
    assert all(f.rule == "unlocked-field" for f in findings)
    # one finding per VIOLATION-marked line, nothing else
    src = open(os.path.join(FIXTURES, "lock_pos.py")).read()
    expected = {
        i + 1
        for i, line in enumerate(src.split("\n"))
        if "VIOLATION" in line
    }
    assert {f.line for f in findings} == expected


def test_lock_negative_fixture_is_clean():
    assert check_lock_discipline(_fixture("lock_neg")) == []


def test_deleting_with_lock_is_caught_in_fixture():
    """Acceptance: stripping `with self._lock:` from the clean fixture
    must produce findings for the now-unguarded accesses."""
    src = open(os.path.join(FIXTURES, "lock_neg.py")).read()
    assert "with self._lock:" in src
    mutated = src.replace("async with self._lock:", "if True:").replace(
        "with self._lock:", "if True:"
    )
    sf = SourceFile("lock_neg_mutated", mutated, rel="lock_neg_mutated")
    assert sf.tree is not None, sf.error
    findings = check_lock_discipline(sf)
    assert findings, "removing the lock guard went undetected"
    assert {f.rule for f in findings} == {"unlocked-field"}
    assert any("_queue" in f.message for f in findings)


def test_deleting_with_lock_is_caught_in_real_engine():
    """Acceptance: the same mutation against the REAL gen engine — every
    `with self._lock:` becomes a no-op block — must trip C1 on the
    engine's declared guarded fields."""
    path = os.path.join(REPO, "areal_tpu", "gen", "engine.py")
    src = open(path).read()
    assert src.count("with self._lock:") >= 5
    mutated = src.replace("with self._lock:", "if True:")
    findings = check_lock_discipline(
        SourceFile("engine_mutated", mutated, rel="engine_mutated")
    )
    hit_fields = {
        field
        for f in findings
        for field in ("_holdback", "_abort_gen")
        if field in f.message
    }
    assert hit_fields == {"_holdback", "_abort_gen"}, findings


def test_holds_annotation_requires_the_named_lock():
    src = (
        "import threading\n"
        "class C:\n"
        "    _GUARDED_FIELDS = {'_x': '_lock'}\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._x = 0\n"
        "    def wrong(self):  # holds: _other_lock\n"
        "        return self._x\n"
    )
    findings = check_lock_discipline(SourceFile("inline", src, rel="inline"))
    assert len(findings) == 1 and findings[0].line == 8


# ------------------------------- C2 ---------------------------------


def test_hostsync_positive_fixture():
    findings = check_host_sync(_fixture("hostsync_pos"))
    rules = sorted(f.rule for f in findings)
    assert rules == [
        "host-item",
        "host-sync",
        "host-sync",
        "host-sync",
        "host-upload",
        "unbucketed-shape",
        "unbucketed-shape",
    ]


def test_hostsync_negative_fixture_is_clean():
    assert check_host_sync(_fixture("hostsync_neg")) == []


def test_hostsync_only_applies_to_hot_files():
    src = open(os.path.join(FIXTURES, "hostsync_pos.py")).read()
    cold = src.replace("# areal-lint: hot-path", "")
    assert check_host_sync(SourceFile("cold", cold, rel="cold")) == []


# ------------------------------- C3 ---------------------------------


def test_async_positive_fixture():
    findings = check_async_blocking(_fixture("async_pos"))
    src = open(os.path.join(FIXTURES, "async_pos.py")).read()
    expected = {
        i + 1
        for i, line in enumerate(src.split("\n"))
        if "VIOLATION" in line
    }
    assert {f.line for f in findings} == expected


def test_async_negative_fixture_is_clean():
    assert check_async_blocking(_fixture("async_neg")) == []


# ------------------------------- C4 ---------------------------------


def test_dead_modules_fixture_tree():
    root = os.path.join(FIXTURES, "deadmod_tree")
    findings = check_dead_modules(root, load_files(root), package="myproj")
    by_mod = {f.path: f for f in findings}
    # flagged: the test-only module, the internal cycle, the suppressed
    # library surface — and nothing that a root actually reaches
    assert set(by_mod) == {
        "myproj/dead.py",
        "myproj/cycle_a.py",
        "myproj/cycle_b.py",
        "myproj/vendored.py",
    }
    assert not by_mod["myproj/dead.py"].suppressed  # test import ≠ alive
    assert by_mod["myproj/vendored.py"].suppressed
    assert "downstream" in by_mod["myproj/vendored.py"].suppress_reason


def test_gsm8k_synth_has_a_real_importer(repo_findings):
    """The satellite fix: dataset/gsm8k_synth.py is alive via the
    bench_e2e_grpo --dataset gsm8k-synth path, not via suppression."""
    synth = [
        f for f in repo_findings if "gsm8k_synth" in f.path
    ]
    assert synth == [], synth


# --------------------------- suppressions ----------------------------


def test_suppression_without_reason_is_flagged():
    src = "x = 1  # areal-lint: disable=host-sync\n"
    findings = suppression_hygiene(SourceFile("s", src, rel="s"))
    assert [f.rule for f in findings] == ["bad-suppression"]


def test_suppression_with_unknown_rule_is_flagged():
    src = "x = 1  # areal-lint: disable=no-such-rule because reasons\n"
    findings = suppression_hygiene(SourceFile("s", src, rel="s"))
    assert [f.rule for f in findings] == ["bad-suppression"]


def test_every_repo_suppression_carries_a_reason(repo_findings):
    for f in repo_findings:
        if f.suppressed:
            assert len(f.suppress_reason) > 10, f.render()


# ------------------------- runtime assertions ------------------------


def _make_guarded_class():
    @lock_guarded
    class Box:
        _GUARDED_FIELDS = {"_items": "_lock"}

        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def locked_append(self, x):
            with self._lock:
                self._items.append(x)

        def _append_holding(self, x):  # holds: _lock
            self._items.append(x)

        def locked_via_helper(self, x):
            with self._lock:
                self._append_holding(x)

        def unlocked_read(self):
            return self._items

    return Box


def test_runtime_guards_off_by_default(monkeypatch):
    monkeypatch.delenv("AREAL_DEBUG_LOCKS", raising=False)
    box = _make_guarded_class()()
    assert box.unlocked_read() == []  # no checking, no overhead


def test_runtime_guards_catch_unlocked_access(monkeypatch):
    monkeypatch.setenv("AREAL_DEBUG_LOCKS", "1")
    box = _make_guarded_class()()
    box.locked_append(1)
    box.locked_via_helper(2)  # holds:-style callee under the caller's lock
    with box._lock:
        assert box._items == [1, 2]
    with pytest.raises(LockDisciplineError):
        box.unlocked_read()
    with pytest.raises(LockDisciplineError):
        box._items = []


def test_runtime_guards_other_thread_cannot_satisfy(monkeypatch):
    monkeypatch.setenv("AREAL_DEBUG_LOCKS", "1")
    box = _make_guarded_class()()
    box._lock.acquire()  # main thread holds
    errors = []

    def probe():
        try:
            box.unlocked_read()
        except LockDisciplineError as e:
            errors.append(e)

    t = threading.Thread(target=probe)
    t.start()
    t.join()
    box._lock.release()
    assert len(errors) == 1


def test_runtime_guards_asyncio_flavor(monkeypatch):
    monkeypatch.setenv("AREAL_DEBUG_LOCKS", "1")

    @lock_guarded
    class Gate:
        _GUARDED_FIELDS = {"_running": "_lock"}

        def __init__(self):
            self._lock = asyncio.Lock()
            self._running = {}

        async def grant(self, k):
            async with self._lock:
                self._running[k] = 1

        def bare(self):
            return self._running

    async def run():
        g = Gate()
        await g.grant("a")
        with pytest.raises(LockDisciplineError):
            g.bare()  # nobody holds the lock: caught
        async with g._lock:
            assert g.bare() == {"a": 1}

    asyncio.run(run())


def test_gen_engine_annotations_match_runtime(monkeypatch):
    """The real engine's _GUARDED_FIELDS registry, exercised dynamically:
    direct unlocked access to a guarded field raises, the engine's own
    (lock-disciplined) paths pass — the same property the whole
    test_gen_engine module validates with the env flag on."""
    monkeypatch.setenv("AREAL_DEBUG_LOCKS", "1")
    import jax

    from areal_tpu.gen.engine import GenEngine, GenRequest
    from areal_tpu.models.model_config import tiny_config

    cfg = tiny_config(vocab_size=61, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    eng = GenEngine(cfg, n_slots=2, max_seq_len=64, prompt_bucket=16,
                    seed=0)
    assert type(eng).__name__.endswith("LockChecked")
    with pytest.raises(LockDisciplineError):
        _ = eng._holdback
    with pytest.raises(LockDisciplineError):
        eng._abort_gen += 1
    req = GenRequest(rid="r", input_ids=[1, 2, 3], max_new_tokens=4,
                     temperature=0.0)
    eng.generate_blocking([req])  # submit -> admit -> decode under guards
    assert req.stop_reason
    assert eng.abort_all() == 0  # abort path touches both guarded fields
    with eng._lock:
        assert eng._holdback == []


# ------------------------------- C5 ---------------------------------


def _violation_lines(name: str) -> set:
    src = open(os.path.join(FIXTURES, name + ".py")).read()
    return {
        i + 1
        for i, line in enumerate(src.split("\n"))
        if "# VIOLATION" in line
    }


def test_lockorder_positive_fixture():
    sf = _fixture("lockorder_pos")
    findings = check_lock_order({"lockorder_pos": sf})
    assert {f.line for f in findings} == _violation_lines("lockorder_pos")
    rules = {f.rule for f in findings}
    assert rules == {"lock-order", "blocking-under-lock", "atomicity-split"}


def test_lockorder_negative_fixture_is_clean():
    sf = _fixture("lockorder_neg")
    assert check_lock_order({"lockorder_neg": sf}) == []


def test_lock_reorder_is_caught_in_fixture():
    """Acceptance: inverting the declared `_flush -> _state` nesting in
    the clean fixture closes a cycle against the declaration."""
    src = open(os.path.join(FIXTURES, "lockorder_neg.py")).read()
    mutated = (
        src.replace("with self._flush:", "with self.__tmp__:")
        .replace("with self._state:", "with self._flush:")
        .replace("with self.__tmp__:", "with self._state:")
    )
    sf = SourceFile("m", mutated, rel="m")
    assert sf.tree is not None, sf.error
    findings = check_lock_order({"m": sf})
    assert any(
        f.rule == "lock-order" and "cycle" in f.message for f in findings
    ), findings


def test_lock_reorder_is_caught_in_real_router():
    """Acceptance: the same inversion against the REAL router — its
    `# lock-order: _flush_lock -> _lock` declaration makes the swapped
    nesting in _flush_and_update a cycle."""
    path = os.path.join(REPO, "areal_tpu", "gen", "router.py")
    src = open(path).read()
    assert "async with self._flush_lock:" in src
    mutated = (
        src.replace("async with self._flush_lock:", "async with self.__t__:")
        .replace("async with self._lock:", "async with self._flush_lock:")
        .replace("async with self.__t__:", "async with self._lock:")
    )
    sf = SourceFile("router_mutated", mutated, rel="router_mutated")
    assert sf.tree is not None, sf.error
    findings = check_lock_order({"router_mutated": sf})
    assert any(
        f.rule == "lock-order" and "cycle" in f.message for f in findings
    ), findings
    # the unmutated router is clean under the same single-file analysis
    clean = SourceFile(path, src, rel="router.py")
    assert check_lock_order({"router.py": clean}) == []


def test_holdback_overwrite_is_caught_in_real_engine():
    """Acceptance: reverting the _admit fix (merge -> blind overwrite of
    the guarded _holdback) re-trips the atomicity-split rule."""
    path = os.path.join(REPO, "areal_tpu", "gen", "engine.py")
    src = open(path).read()
    assert "self._holdback = leftover + self._holdback" in src
    mutated = src.replace(
        "self._holdback = leftover + self._holdback",
        "self._holdback = leftover",
    )
    findings = check_lock_order(
        {"engine.py": SourceFile("m", mutated, rel="engine.py")}
    )
    assert any(
        f.rule == "atomicity-split" and "_holdback" in f.message
        for f in findings
    ), findings
    clean = SourceFile(path, src, rel="engine.py")
    assert (
        check_lock_order({"engine.py": clean}) == []
    ), "unmutated engine must be C5-clean"


# ------------------------------- C6 ---------------------------------


def test_jitsig_positive_fixture():
    sf = _fixture("jitsig_pos")
    findings = check_jit_signatures({"jitsig_pos": sf})
    assert {f.line for f in findings} == _violation_lines("jitsig_pos")
    assert {f.rule for f in findings} == {"off-ladder-static"}


def test_jitsig_negative_fixture_is_clean():
    sf = _fixture("jitsig_neg")
    assert check_jit_signatures({"jitsig_neg": sf}) == []


def test_jitsig_only_applies_to_hot_files():
    src = open(os.path.join(FIXTURES, "jitsig_pos.py")).read()
    cold = src.replace("# areal-lint: hot-path", "#")
    sf = SourceFile("cold", cold, rel="cold")
    assert check_jit_signatures({"cold": sf}) == []


def test_off_ladder_keywindow_is_caught_in_real_engine():
    """Acceptance: an out-of-ladder key_window literal in the REAL decode
    dispatch is caught — the soak tests' runtime assertion, as a static
    proof."""
    path = os.path.join(REPO, "areal_tpu", "gen", "engine.py")
    src = open(path).read()
    anchor = (
        "key_window = round_up_to_bucket(\n"
        "                        span + n, self.prompt_bucket, M\n"
        "                    )"
    )
    assert anchor in src, "decode key_window bucketing moved; update test"
    mutated = src.replace(anchor, "key_window = 100")
    findings = check_jit_signatures(
        {"engine.py": SourceFile("m", mutated, rel="engine.py")}
    )
    assert any(
        f.rule == "off-ladder-static" and "key_window" in f.message
        for f in findings
    ), findings
    clean = SourceFile(path, src, rel="engine.py")
    assert check_jit_signatures({"engine.py": clean}) == []


def test_ladder_mirror_matches_runtime_bucketing():
    """The pure-python budget math must equal the runtime ladder exactly:
    the image of round_up_to_bucket over every feasible length is the
    enumerated ladder, and row padding counts match the pow2 rule."""
    from areal_tpu.utils.datapack import round_up_to_bucket

    for q, m in ((16, 256), (32, 256), (128, 2048)):
        image = {round_up_to_bucket(n, q, m) for n in range(1, m + 1)}
        assert image == set(ladder_values(q, m)), (q, m)
    for slots in (1, 2, 4, 8, 64):
        pads = {1 << max(0, (k - 1)).bit_length() for k in range(1, slots + 1)}
        assert len(pads) == pow2_row_counts(slots), slots


def test_signature_budget_is_fresh(repo_findings):
    """The checked-in budget matches the ladder math (the same condition
    `signature-budget-stale` enforces), and tampering is detected."""
    with open(os.path.join(REPO, BUDGET_PATH)) as f:
        doc = json.load(f)
    assert budget_drift(doc) == []
    ref = doc["reference_configs"]["tiered_decode_soak"]
    assert ref["budgets"] == compute_budgets(ref["config"])
    tampered = json.loads(json.dumps(doc))
    tampered["reference_configs"]["tiered_decode_soak"]["budgets"][
        "decode"
    ] += 1
    assert budget_drift(tampered) != []


# ------------------------------- C7 ---------------------------------


def test_typestate_positive_fixture():
    sf = _fixture("typestate_pos")
    findings = check_typestate({"typestate_pos": sf})
    assert {f.line for f in findings} == _violation_lines("typestate_pos")
    assert {f.rule for f in findings} == {
        "slot-double-free",
        "slot-lifecycle",
        "retained-unversioned",
    }


def test_typestate_negative_fixture_is_clean():
    sf = _fixture("typestate_neg")
    assert check_typestate({"typestate_neg": sf}) == []


def test_double_free_is_caught_in_real_engine():
    """Acceptance: turning _free's retained-prefix settle into a second
    `slot_req[s] = None` is a double-free of a retained cache row — the
    exact hazard the radix-refactor must not introduce."""
    path = os.path.join(REPO, "areal_tpu", "gen", "engine.py")
    src = open(path).read()
    anchor = (
        "self.retained_len[s] = 0 if self._slot_vlm[s] else self.lengths[s]"
    )
    assert src.count(anchor) == 1, "update the _free mutation anchor"
    mutated = src.replace(anchor, "self.slot_req[s] = None")
    findings = check_typestate(
        {"engine.py": SourceFile("m", mutated, rel="engine.py")}
    )
    assert any(f.rule == "slot-double-free" for f in findings), findings
    clean = SourceFile(path, src, rel="engine.py")
    assert check_typestate({"engine.py": clean}) == []


# ------------------------------- CLI ---------------------------------


def _load_cli():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "areal_lint_cli", os.path.join(REPO, "scripts", "lint.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_sarif_and_fingerprints(repo_findings):
    cli = _load_cli()
    active = unsuppressed(repo_findings)
    # fingerprints are line-drift-stable: same (path, rule, message)
    # hashes equal regardless of the line attribute
    for f in repo_findings[:5]:
        moved = type(f)(f.rule, f.path, f.line + 40, f.message)
        assert cli.fingerprint(f) == cli.fingerprint(moved)
    sarif = cli.to_sarif(repo_findings)
    assert sarif["version"] == "2.1.0"
    results = sarif["runs"][0]["results"]
    assert len(results) == len(repo_findings)
    for r in results:
        loc = r["locations"][0]["physicalLocation"]
        assert loc["region"]["startLine"] >= 1
        assert r["partialFingerprints"]["arealLint/v1"]
    assert active == []  # and the repo itself stays SARIF-empty


def test_cli_baseline_roundtrip(tmp_path):
    """--write-baseline then --baseline suppresses exactly the recorded
    findings; a new finding still fails --check."""
    cli = _load_cli()
    from areal_tpu.analysis.core import Finding

    known = Finding("lock-order", "pkg/a.py", 10, "cycle via _lock")
    new = Finding("lock-order", "pkg/a.py", 20, "cycle via _other")
    baseline = {"fingerprints": [cli.fingerprint(known)]}
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(baseline))
    loaded = set(json.loads(bl.read_text())["fingerprints"])
    assert cli.fingerprint(known) in loaded
    assert cli.fingerprint(new) not in loaded


def test_cli_write_budget_is_idempotent(tmp_path):
    cli = _load_cli()
    doc = cli.render_budget_doc(cli.REFERENCE_CONFIGS)
    with open(os.path.join(REPO, BUDGET_PATH)) as f:
        checked_in = json.load(f)
    assert doc == checked_in, (
        "signature_budget.json is stale — run "
        "`python scripts/lint.py --write-budget`"
    )


def test_cli_explain_prints_wire_checker_catalog(capsys):
    """`--explain C8|C9|C10` prints the catalog entry and exits 0 without
    running the suite (ISSUE 18 satellite)."""
    cli = _load_cli()
    for checker, rule in (
        ("C8", "payload-contract"),
        ("C9", "metric-contract"),
        ("C10", "config-plumbing"),
    ):
        assert cli.main(["--explain", checker]) == 0
        out = capsys.readouterr().out
        assert rule in out
        assert "wire_contracts.json" in out


# ------------------------------ the gate -----------------------------


def test_repo_clean(repo_findings):
    """Tier-1 gate: zero unsuppressed findings on the real tree — the
    same condition as `python scripts/lint.py --check`."""
    active = unsuppressed(repo_findings)
    assert active == [], "\n" + "\n".join(f.render() for f in active)


# ----------------------- C8/C9/C10 (ISSUE 18) ------------------------


def _wire_doc(apps, echo=True):
    response = {"y": {"required": True}}
    if echo:
        response["echo"] = {}
    return {
        "endpoints": {
            "ping": {
                "path": "/ping",
                "app": "gen",
                "request": {"x": {"required": True}, "opt": {}},
                "response": response,
            }
        },
        "apps": apps,
    }


def test_wire_payload_negative_fixture_is_clean():
    sf = _fixture("wire_neg")
    wc = WireContracts(_wire_doc({"wire_neg": "gen"}))
    assert check_payload_contracts({sf.rel: sf}, contracts=wc) == []


def test_wire_payload_positive_fixture_flags_every_drift_class():
    sf = _fixture("wire_pos")
    wc = WireContracts(_wire_doc({"wire_pos": "gen"}, echo=False))
    findings = check_payload_contracts({sf.rel: sf}, contracts=wc)
    msgs = [f.message for f in findings]
    assert sum(f.rule == "payload-silent-default" for f in findings) == 1
    assert any("'ghost'" in m for m in msgs)  # read no producer writes
    assert any("'bogus'" in m for m in msgs)  # write not in contract
    assert any("'zzz'" in m for m in msgs)  # response read no one writes
    assert any("omits required key 'x'" in m for m in msgs)
    assert len(findings) == 5, "\n".join(f.render() for f in findings)


def test_renaming_wire_key_is_caught_in_fixture():
    """Acceptance: renaming the produced key in the CLEAN fixture must
    produce both an unknown-write and a missing-required finding."""
    src = open(os.path.join(FIXTURES, "wire_neg.py")).read()
    assert 'json={"x": 1, "opt": "o"}' in src
    mutated = src.replace('json={"x": 1, "opt": "o"}',
                          'json={"x_new": 1, "opt": "o"}')
    sf = SourceFile("wire_neg_mut", mutated, rel="wire_neg_mut")
    wc = WireContracts(_wire_doc({"wire_neg_mut": "gen"}))
    findings = check_payload_contracts({sf.rel: sf}, contracts=wc)
    msgs = [f.message for f in findings]
    assert any("'x_new'" in m for m in msgs)
    assert any("omits required key 'x'" in m for m in msgs)


def test_renaming_real_fake_server_key_is_caught(repo_files):
    """Acceptance (real code): renaming output_versions in the fake
    server — the exact PR-17 drift class this checker exists for."""
    src = open(os.path.join(REPO, "tests", "fake_server.py")).read()
    assert '"output_versions"' in src
    mutated = src.replace('"output_versions"', '"output_versionz"')
    sf = SourceFile("fake_server_mut", mutated,
                    rel=os.path.join("tests", "fake_server.py"))
    findings = check_payload_contracts(repo_files, REPO, fake_server=sf)
    active = [f for f in findings if not f.suppressed]
    assert any("output_versionz" in f.message for f in active)
    assert any("omits required key 'output_versions'" in f.message
               for f in active)


def test_metric_event_negative_fixture_is_clean():
    sf = _fixture("metric_neg")
    wc = WireContracts({"events": {"names": [{"name": "ev_done"}]}})
    findings = check_telemetry_contracts(
        {sf.rel: sf}, contracts=wc,
        schema={"gen": ["areal_gen_good_total"]},
        trace_sf=_fixture("event_trace"),
    )
    assert findings == [], "\n".join(f.render() for f in findings)


def test_metric_event_positive_fixture_flags_all():
    sf = _fixture("metric_pos")
    wc = WireContracts({"events": {"names": [
        {"name": "ev_unparsed"}, {"name": "ev_never"},
    ]}})
    findings = check_telemetry_contracts(
        {sf.rel: sf}, contracts=wc,
        schema={"gen": ["areal_gen_orphan_total"]},
        trace_sf=_fixture("event_trace"),
    )
    msgs = [f.message for f in findings]
    # metric side: unpinned static name, dynamic name, schema orphan
    assert any("'bad_total'" in m for m in msgs)
    assert any("dynamically-named" in m for m in msgs)
    assert any("orphaned schema entry" in m for m in msgs)
    # event side: undeclared emit, emitted-but-never-parsed,
    # declared-but-never-emitted/consumed (both directions of ev_never),
    # parsed-but-undeclared ghost in the trace fixture
    assert any("'ghost_ev'" in m for m in msgs)
    assert any("'ev_unparsed' is emitted but" in m for m in msgs)
    assert any("'ev_never' is declared but nothing emits" in m for m in msgs)
    assert any("'ev_never' is declared but obs/trace.py never" in m
               for m in msgs)
    assert any("parses event 'ev_done'" in m for m in msgs)
    assert len(findings) == 8, "\n".join(f.render() for f in findings)


def test_dropping_real_metric_from_schema_is_caught(repo_files):
    """Acceptance (real code): removing a pinned metric the code still
    constructs must flag the construction site."""
    with open(os.path.join(REPO, "tests", "data",
                           "metrics_schema.json")) as fh:
        schema = json.load(fh)
    schema = {
        surface: [n for n in names if n != "areal_train_recover_total"]
        for surface, names in schema.items()
    }
    findings = check_telemetry_contracts(repo_files, REPO, schema=schema)
    assert any(
        f.rule == "metric-contract" and "areal_train_recover_total"
        in f.message and not f.suppressed
        for f in findings
    )


def test_orphan_schema_metric_is_caught(repo_files):
    with open(os.path.join(REPO, "tests", "data",
                           "metrics_schema.json")) as fh:
        schema = json.load(fh)
    schema["train"] = schema["train"] + ["areal_train_ghost_metric"]
    findings = check_telemetry_contracts(repo_files, REPO, schema=schema)
    assert any(
        "orphaned schema entry" in f.message
        and "areal_train_ghost_metric" in f.message
        for f in findings
    )


CFG_DOC = {
    "config_chains": {
        "files": {
            "config": "cfgchain_cfg",
            "server": "cfgchain_srv",
            "engine": "cfgchain_eng",
            "config_class": "TinyServerConfig",
            "build_cmd": "build_cmd",
            "engine_class": "TinyEngine",
        },
        "chains": [
            {"field": "depth", "flag": "--depth", "engine_kwarg": "depth"},
            {"field": "width", "flag": "--width", "engine_kwarg": "width"},
        ],
    }
}


def _cfg_files(server_fixture="cfgchain_srv"):
    files = {
        "cfgchain_cfg": _fixture("cfgchain_cfg"),
        "cfgchain_eng": _fixture("cfgchain_eng"),
    }
    files["cfgchain_srv"] = SourceFile.from_path(
        os.path.join(FIXTURES, server_fixture + ".py"), rel="cfgchain_srv"
    )
    return files


def test_config_chain_negative_fixture_is_clean():
    findings = check_config_plumbing(
        _cfg_files(), contracts=WireContracts(CFG_DOC)
    )
    assert findings == [], "\n".join(f.render() for f in findings)


def test_config_chain_positive_fixture_flags_every_break():
    findings = check_config_plumbing(
        _cfg_files("cfgchain_srv_pos"), contracts=WireContracts(CFG_DOC)
    )
    msgs = [f.message for f in findings]
    assert any("argparse has no '--width'" in m for m in msgs)
    assert any("never passes 'width'" in m for m in msgs)
    assert any("'--extra' is not covered" in m for m in msgs)
    assert any("does not accept it" in m for m in msgs)  # build vs argparse
    assert len(findings) == 4, "\n".join(f.render() for f in findings)


def test_train_config_chains_are_clean(repo_files):
    """The ISSUE 20 train chains (layer_group_size / remat_policy /
    scan_unroll / lm_head_chunk) hold on the real tree."""
    from areal_tpu.analysis.wire_contracts import check_train_config_plumbing

    findings = check_train_config_plumbing(repo_files, REPO)
    assert findings == [], "\n".join(f.render() for f in findings)


def test_breaking_real_train_chain_flag_is_caught(repo_files):
    """Acceptance (real code): renaming the bench's --layer-group-size
    flag breaks the declared train chain."""
    from areal_tpu.analysis.wire_contracts import check_train_config_plumbing

    rel = os.path.join("scripts", "bench_e2e_grpo.py")
    src = open(os.path.join(REPO, rel)).read()
    assert '"--layer-group-size"' in src
    mutated = src.replace('"--layer-group-size"', '"--layer-groupsize"')
    files = dict(repo_files)
    files[rel] = SourceFile("bench_mut", mutated, rel=rel)
    findings = check_train_config_plumbing(files, REPO)
    msgs = [f.message for f in findings if not f.suppressed]
    assert any("argparse has no '--layer-group-size'" in m for m in msgs)


def test_unread_train_chain_flag_is_caught(repo_files):
    """Acceptance (real code): a train-chain flag whose `args.<dest>` read
    disappears is parsed-but-dropped."""
    from areal_tpu.analysis.wire_contracts import check_train_config_plumbing

    rel = os.path.join("scripts", "bench_e2e_grpo.py")
    src = open(os.path.join(REPO, rel)).read()
    assert "args.lm_head_chunk" in src
    mutated = src.replace("args.lm_head_chunk", "args.lm_head_chunk_gone")
    files = dict(repo_files)
    files[rel] = SourceFile("bench_mut2", mutated, rel=rel)
    findings = check_train_config_plumbing(files, REPO)
    msgs = [f.message for f in findings if not f.suppressed]
    assert any("`args.lm_head_chunk` is never read" in m for m in msgs)


def test_dropped_model_replace_plumbing_is_caught(repo_files):
    """Acceptance (real code): the engine's model-config replace() losing
    the layer_group_size kwarg severs the chain to the backbone."""
    from areal_tpu.analysis.wire_contracts import check_train_config_plumbing

    rel = os.path.join("areal_tpu", "engine", "jax_train.py")
    src = open(os.path.join(REPO, rel)).read()
    assert "layer_group_size=" in src
    mutated = src.replace("layer_group_size=", "layer_group_size_x=")
    files = dict(repo_files)
    files[rel] = SourceFile("engine_mut", mutated, rel=rel)
    findings = check_train_config_plumbing(files, REPO)
    msgs = [f.message for f in findings if not f.suppressed]
    assert any("never plumbs 'layer_group_size'" in m for m in msgs)


def test_breaking_real_config_chain_is_caught(repo_files):
    """Acceptance (real code): renaming a gen/server.py argparse flag out
    from under its GenServerConfig chain."""
    path = os.path.join(REPO, "areal_tpu", "gen", "server.py")
    src = open(path).read()
    assert '"--host-cache-mb"' in src
    mutated = src.replace('"--host-cache-mb"', '"--host-cachemb"')
    rel = os.path.join("areal_tpu", "gen", "server.py")
    files = dict(repo_files)
    files[rel] = SourceFile("server_mut", mutated, rel=rel)
    findings = check_config_plumbing(files, REPO)
    msgs = [f.message for f in findings if not f.suppressed]
    assert any("argparse has no '--host-cache-mb'" in m for m in msgs)
    assert any("'--host-cachemb'" in m for m in msgs)  # now uncovered
