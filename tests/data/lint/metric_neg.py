"""C9 negative fixture: a pinned static metric and a declared, consumed
lifecycle event (METRIC_DOC / METRIC_SCHEMA in test_lint.py; the matching
consumer lives in event_trace.py)."""

from areal_tpu.utils import telemetry

REQS = telemetry.GEN.counter("good_total", "requests served")


def emit_done(trace_id):
    REQS.inc()
    telemetry.emit("ev_done", trace_id=trace_id)
