from areal_tpu.engine.sft.lm_engine import JaxLMEngine

__all__ = ["JaxLMEngine"]
