"""Continuous-batching generation engine on a fixed slot grid.

The TPU-native replacement for the SGLang/vLLM servers the reference wraps
(areal/launcher/sglang_server.py:117, realhf generation servers) and for the
legacy native decode loop (realhf/impl/model/nn/real_llm_generate.py).
Design for XLA's static shapes:

- `n_slots` concurrent sequences in a preallocated KV cache
  [L, S, M, Hkv, hd]; admission assigns a free slot, completion frees it —
  continuous batching without shape changes.
- TWO compiled programs: `forward_prefill` per (rows, prompt-bucket) pair
  (both power-of-two padded) and ONE `forward_decode` step advancing every
  slot; idle slots decode garbage that is never read (cheaper than
  recompiling for occupancy).
- **Batched admission**: every free slot is filled from the pending queue in
  ONE prefill call (rows padded to a power of two, dummy rows target a
  scratch cache slot) — a burst of N prompts costs O(log N) device
  round-trips, not N.
- **Model-parallel serving**: with `tp > 1` the engine owns a
  (dp=1, fsdp=1, sp=1, tp) mesh; params shard with the same
  `param_partition_specs` the trainer uses (megatron column/row layout) and
  the KV cache shards its kv-head axis, so a 7B model serves across chips
  the way the reference serves via SGLang's server-side tp
  (areal/api/alloc_mode.py:377 inference d x t x p).
- Cache and rng are donated; steady-state decode allocates nothing.
- Weight reload (`load_weights`) aborts in-flight requests with
  stop_reason="abort" — the client's interruption loop resubmits with
  accumulated tokens (reference behavior: remote_inf_engine.py:428-478) —
  then bumps `version`; per-token versions let decoupled PPO weight stale
  spans correctly.
"""

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from areal_tpu.gen.sampling import sample_tokens
from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.models.transformer import (
    forward_decode,
    forward_prefill,
    init_kv_cache,
    init_params,
    param_partition_specs,
)
from areal_tpu.models.hf import load_hf_params
from areal_tpu.parallel import build_mesh, shard_pytree
from areal_tpu.utils import logging
from areal_tpu.utils.datapack import round_up_to_bucket

logger = logging.getLogger("gen.engine")


@dataclass
class GenRequest:
    rid: str
    input_ids: List[int]
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    stop_token_ids: List[int] = field(default_factory=list)
    # filled by the engine
    output_tokens: List[int] = field(default_factory=list)
    output_logprobs: List[float] = field(default_factory=list)
    output_versions: List[int] = field(default_factory=list)
    stop_reason: str = ""
    on_done: Optional[Callable[["GenRequest"], None]] = None

    def finish(self, reason: str):
        self.stop_reason = reason
        if self.on_done is not None:
            self.on_done(self)


class GenEngine:
    def __init__(
        self,
        model_config: TransformerConfig,
        params=None,
        model_path: Optional[str] = None,
        n_slots: int = 8,
        max_seq_len: int = 2048,
        prompt_bucket: int = 128,
        kv_dtype: str = "bfloat16",
        seed: int = 0,
        decode_chunk: int = 8,
        tp: int = 1,
        devices=None,
    ):
        self.model_config = model_config.replace(remat=False)
        if params is None:
            if model_path:
                host, mc = load_hf_params(model_path, model_config, dtype="bfloat16")
                self.model_config = mc.replace(
                    dtype=model_config.dtype, param_dtype="bfloat16", remat=False
                )
                params = host
            else:
                params = init_params(self.model_config, jax.random.PRNGKey(seed))
        self.tp = tp
        if tp > 1 and self.model_config.num_kv_heads % tp != 0:
            raise ValueError(
                f"tp={tp} must divide num_kv_heads="
                f"{self.model_config.num_kv_heads} (kv-head-sharded cache)"
            )
        # serving mesh: tensor parallel only — dp across servers is the
        # client's job (core/remote.py multi-server routing), so the mesh
        # reuses the trainer's partition specs with dp=fsdp=sp=1
        self.mesh = build_mesh(dp=1, fsdp=1, sp=1, tp=tp, devices=devices)
        self._pspecs = param_partition_specs(self.model_config, tp=tp)
        self.params = shard_pytree(self.mesh, params, self._pspecs)
        self.n_slots = n_slots
        self.max_seq_len = max_seq_len
        self.prompt_bucket = prompt_bucket
        self.kv_dtype = kv_dtype
        # slot n_slots is the scratch row: dummy admission rows (power-of-two
        # padding) prefill into it, and decode advances it harmlessly
        self._cache_spec = P(None, None, None, "tp", None)
        cache = init_kv_cache(self.model_config, n_slots + 1, max_seq_len, kv_dtype)
        self.cache = {
            k: jax.device_put(v, NamedSharding(self.mesh, self._cache_spec))
            for k, v in cache.items()
        }
        self.rng = jax.random.PRNGKey(seed)
        self.version = 0

        # host-side slot state (scratch slot included, never assigned)
        S = n_slots + 1
        self.slot_req: List[Optional[GenRequest]] = [None] * S
        self.lengths = np.zeros(S, np.int32)
        self.last_tokens = np.zeros(S, np.int32)
        self.temperature = np.ones(S, np.float32)
        self.top_p = np.ones(S, np.float32)
        self.top_k = np.zeros(S, np.int32)
        self.pending: "queue.Queue[GenRequest]" = queue.Queue()
        self._lock = threading.Lock()

        # decode_chunk: tokens generated per host round-trip.  The decode scan
        # runs this many fused forward+sample steps on device before the host
        # sees anything — the host applies stop conditions in arrears and
        # discards overshoot (slots that stopped mid-chunk decode garbage that
        # is never delivered).  Chunking amortises host<->device latency,
        # which dominates when the chip is reached over a network tunnel.
        self.decode_chunk = max(1, decode_chunk)
        cfg = self.model_config

        def _prefill(params, cache, ids, plen, slot_ids, rng, temp, tp, tk):
            logits, cache = forward_prefill(params, cfg, ids, plen, cache, slot_ids)
            tok, logp = sample_tokens(logits.astype(jnp.float32), rng, temp, tk, tp)
            return tok, logp, cache

        def _decode_chunk(params, cache, tokens, lengths, rng, temp, tp, tk, n):
            def body(carry, _):
                cache, tokens, lengths, rng = carry
                logits, cache = forward_decode(params, cfg, tokens, lengths, cache)
                rng, sub = jax.random.split(rng)
                tok, logp = sample_tokens(
                    logits.astype(jnp.float32), sub, temp, tk, tp
                )
                return (cache, tok, lengths + 1, rng), (tok, logp)

            (cache, _, _, _), (toks, logps) = jax.lax.scan(
                body, (cache, tokens, lengths, rng), None, length=n
            )
            # one fused download: tokens are exactly representable in f32
            out = jnp.stack([toks.astype(jnp.float32), logps])  # [2, n, S]
            return out, cache

        self._prefill_fn = jax.jit(_prefill, donate_argnums=(1,))
        self._decode_fn = jax.jit(_decode_chunk, static_argnums=(8,),
                                  donate_argnums=(1,))

    # ------------------------------------------------------------------
    # submission / weights
    # ------------------------------------------------------------------

    def submit(self, req: GenRequest) -> None:
        if len(req.input_ids) + 1 >= self.max_seq_len:
            req.finish("length")
            return
        self.pending.put(req)

    def active_count(self) -> int:
        with self._lock:
            return sum(r is not None for r in self.slot_req) + self.pending.qsize()

    def abort_all(self, reason: str = "abort") -> int:
        """Finish every in-flight request immediately (weight update /
        shutdown). Returns how many were aborted."""
        n = 0
        with self._lock:
            for s, req in enumerate(self.slot_req):
                if req is not None:
                    req.finish(reason)
                    self.slot_req[s] = None
                    n += 1
            while True:
                try:
                    self.pending.get_nowait().finish(reason)
                    n += 1
                except queue.Empty:
                    break
        return n

    def load_weights(
        self, path: Optional[str] = None, params=None, version: Optional[int] = None
    ) -> int:
        """Swap weights; aborts in-flight generation first (interruptible
        generation: clients resubmit and the new prefill recomputes under the
        new policy). Returns the new version."""
        aborted = self.abort_all("abort")
        if aborted:
            logger.info(f"aborted {aborted} requests for weight update")
        if params is None:
            assert path is not None
            path, dir_version = self._resolve_ckpt_dir(path)
            if version is None:
                # adopt the trainer's version from the v{N} dir name — a
                # fresh server must not restart its version counter at 1
                # while the trainer is at N (staleness gates compare them)
                version = dir_version
            params, _ = load_hf_params(path, self.model_config, dtype="bfloat16")
        self.params = shard_pytree(self.mesh, params, self._pspecs)
        self.version = version if version is not None else self.version + 1
        return self.version

    @staticmethod
    def _resolve_ckpt_dir(path: str):
        """Trainers publish atomic per-version snapshots `root/v{N}`
        (jax_train.py _update_weights_disk); pick the newest and return
        (dir, version).  A plain checkpoint dir (config.json present) is
        used as-is with version None."""
        import os
        import re

        if os.path.exists(os.path.join(path, "config.json")):
            return path, None
        vs = sorted(
            (int(m.group(1)), os.path.join(path, d))
            for d in (os.listdir(path) if os.path.isdir(path) else [])
            if (m := re.fullmatch(r"v(\d+)", d))
        )
        if not vs:
            raise FileNotFoundError(f"no checkpoint under {path}")
        return vs[-1][1], vs[-1][0]

    # ------------------------------------------------------------------
    # stepping
    # ------------------------------------------------------------------

    def _admit(self) -> None:
        """Fill every free slot from the pending queue in ONE bucketed
        prefill call.  Rows are padded to a power of two; padding rows
        prefill a single token into the scratch slot (index n_slots), so
        compiled-program count stays O(log n_slots x log buckets) and a
        burst of N prompts no longer pays N sequential device round-trips
        (round-1 review weak #2)."""
        free = [s for s in range(self.n_slots) if self.slot_req[s] is None]
        admitted: List[tuple] = []  # (slot, req)
        while free:
            try:
                req = self.pending.get_nowait()
            except queue.Empty:
                break
            admitted.append((free.pop(0), req))
        if not admitted:
            return
        bucket = round_up_to_bucket(
            max(max(len(r.input_ids) for _, r in admitted), 1),
            self.prompt_bucket,
            self.max_seq_len,
        )
        S = 1 << (len(admitted) - 1).bit_length()  # power-of-two rows
        ids = np.zeros((S, bucket), np.int32)
        plens = np.ones(S, np.int32)
        slot_ids = np.full(S, self.n_slots, np.int32)  # default: scratch
        temp = np.ones(S, np.float32)
        top_p = np.ones(S, np.float32)
        top_k = np.zeros(S, np.int32)
        for i, (s, req) in enumerate(admitted):
            n = len(req.input_ids)
            ids[i, :n] = req.input_ids
            plens[i] = n
            slot_ids[i] = s
            temp[i] = req.temperature
            top_p[i] = req.top_p
            top_k[i] = req.top_k
        self.rng, sub = jax.random.split(self.rng)
        toks, logps, self.cache = self._prefill_fn(
            self.params,
            self.cache,
            ids,
            jnp.asarray(plens),
            jnp.asarray(slot_ids),
            sub,
            jnp.asarray(temp),
            jnp.asarray(top_p),
            jnp.asarray(top_k),
        )
        toks, logps = np.asarray(toks), np.asarray(logps)
        with self._lock:
            for i, (s, req) in enumerate(admitted):
                self.slot_req[s] = req
                self.lengths[s] = plens[i]
                self.last_tokens[s] = int(toks[i])
                self.temperature[s] = req.temperature
                self.top_p[s] = req.top_p
                self.top_k[s] = req.top_k
        for i, (s, req) in enumerate(admitted):
            self._record_token(s, int(toks[i]), float(logps[i]))

    def _record_token(self, s: int, tok: int, logp: float) -> None:
        req = self.slot_req[s]
        if req is None:  # aborted between decode and delivery
            return
        req.output_tokens.append(tok)
        req.output_logprobs.append(logp)
        req.output_versions.append(self.version)
        n_out = len(req.output_tokens)
        stop_ids = req.stop_token_ids or (
            [self.model_config.eos_token_id]
            if self.model_config.eos_token_id is not None
            else []
        )
        hit_stop = tok in stop_ids and n_out >= req.min_new_tokens
        total_len = self.lengths[s] + 1  # prompt + generated so far
        if hit_stop:
            self._free(s, "stop")
        elif n_out >= req.max_new_tokens or total_len + 1 >= self.max_seq_len:
            self._free(s, "length")

    def _free(self, s: int, reason: str) -> None:
        req = self.slot_req[s]
        with self._lock:
            self.slot_req[s] = None
        if req is not None:
            req.finish(reason)

    def step(self, chunk: Optional[int] = None) -> int:
        """Admit pending prompts, then advance every active slot by up to
        `chunk` tokens in one device program.  Returns generated-token count
        actually delivered (overshoot past stop conditions excluded)."""
        self._admit()
        with self._lock:
            active = [s for s in range(self.n_slots) if self.slot_req[s] is not None]
        if not active:
            return 0
        n = chunk or self.decode_chunk
        # never decode past the cache: bound by the tightest active slot.
        # n is a static jit arg, so round the clamp DOWN to a power of two —
        # O(log decode_chunk) compiled programs instead of one per length.
        cap = max(1, int(self.max_seq_len - 1 - self.lengths[active].max()))
        n = min(n, cap)
        if n < (chunk or self.decode_chunk):
            n = 1 << (n.bit_length() - 1)
        self.rng, sub = jax.random.split(self.rng)
        out, self.cache = self._decode_fn(
            self.params,
            self.cache,
            jnp.asarray(self.last_tokens),
            jnp.asarray(self.lengths),
            sub,
            jnp.asarray(self.temperature),
            jnp.asarray(self.top_p),
            jnp.asarray(self.top_k),
            n,
        )
        out = np.asarray(out)  # [2, n, S]
        toks = out[0].astype(np.int32)
        logps = out[1]
        delivered = 0
        for s in active:
            for i in range(n):
                if self.slot_req[s] is None:
                    break  # stopped mid-chunk; remaining tokens are overshoot
                self.lengths[s] += 1  # K/V for this token is in the cache
                self.last_tokens[s] = toks[i, s]
                self._record_token(s, int(toks[i, s]), float(logps[i, s]))
                delivered += 1
        return delivered

    def generate_blocking(self, reqs: List[GenRequest]) -> List[GenRequest]:
        """Synchronous helper (tests / offline eval): run until all done."""
        for r in reqs:
            self.submit(r)
        while any(not r.stop_reason for r in reqs):
            if self.step() == 0 and self.pending.qsize() == 0:
                break
            time.sleep(0)
        return reqs
