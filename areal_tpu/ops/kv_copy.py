"""Cross-slot KV prefix fan-out and device<->host prefix transfers.

GRPO samples every group as `group_size` requests over the SAME prompt, and
tree-search / multi-turn branches share a transcript prefix.  The engine
prefills one representative per prefix-cluster and then *copies* the computed
prefix K/V from the representative's cache row into every sibling slot —
one batched gather/scatter over the cache pytree for ALL clusters in the
admission pass, entirely on device — so siblings prefill only their
per-request suffix.

Shape discipline (the same O(log) compiled-program budget as admission):

- `block` (copied positions) is STATIC and always comes from the engine's
  prompt-bucket ladder (`round_up_to_bucket`), so copy programs share the
  prefill buckets' signature family instead of minting one per prefix
  length.
- `src_slots`/`dst_slots` are padded to a power of two with the scratch
  slot (a scratch->scratch self-copy is a harmless no-op), so destination
  counts bucket the same way admission rows do.
- Every cluster in a pass whose prefix shares a block bucket rides ONE
  call: `src_slots[i]` is destination i's own representative, so a pass
  admitting eight groups costs one dispatch, not eight.

Copying a full `block >= prefix_len` is safe without masking: positions in
`[prefix_len, block)` hold the representative's (or stale) K/V, but every
consumer overwrites them before they can be attended — the sibling's suffix
prefill writes `[prefix_len, prefix_len + P)` before its queries run, and
decode writes position `lengths` each step before attending `<= lengths`
(the same frontier invariant padded suffix rows already rely on).
"""
# areal-lint: hot-path

from typing import Dict

import jax
import jax.numpy as jnp


def gather_kv_prefix(
    cache: Dict[str, jax.Array],
    row: jax.Array,  # int32 scalar: physical cache row to extract
    block: int,  # STATIC bucketed prefix length (positions extracted)
) -> Dict[str, jax.Array]:
    """Extract cache positions [0, block) of one physical row for a
    host-DRAM spill: {key: [L, block, Hkv, hd]}.

    `row` is traced (one program per block bucket serves every slot) and
    `block` rides the prompt-bucket ladder, so the spill path adds one
    C6-budgeted program family of size `ladder`, not one per (slot, len).
    The caller downloads the result with np.asarray — the only host sync
    on the spill path, at the admission boundary where the engine already
    syncs its planning state.
    """
    out = {}
    for key, buf in cache.items():
        rowbuf = jax.lax.dynamic_index_in_dim(
            buf, row, axis=1, keepdims=False
        )  # [L, M, Hkv, hd]
        out[key] = jax.lax.slice_in_dim(rowbuf, 0, block, axis=1)
    return out


def scatter_kv_prefix(
    cache: Dict[str, jax.Array],
    host_kv: Dict[str, jax.Array],  # {key: [L, block, Hkv, hd]} from gather
    row: jax.Array,  # int32 scalar: physical cache row to restore into
) -> Dict[str, jax.Array]:
    """Write a host-spilled prefix back into one physical row (swap-in on
    a radix hit); returns the updated cache pytree (cache donated by the
    engine's jit wrapper, so the restore is in-place on device).

    The round trip is bit-identical: gather slices raw cache bytes, the
    host keeps them in the cache dtype, and this scatter writes them back
    untouched — a swapped-in prefix attends exactly like one that never
    left HBM, which is what keeps counter-keyed streams invariant to
    spill/swap scheduling.
    """
    out = {}
    for key, buf in cache.items():
        blk = host_kv[key].astype(buf.dtype)[:, None]  # [L, 1, block, ...]
        out[key] = jax.lax.dynamic_update_slice(
            buf, blk, (0, row, 0, 0, 0)
        )
    return out


def copy_kv_prefix(
    cache: Dict[str, jax.Array],
    src_slots: jax.Array,  # int32 [d]: source cache row per destination
    dst_slots: jax.Array,  # int32 [d]: sibling rows (scratch-padded pow2)
    block: int,  # STATIC bucketed prefix length (positions copied)
) -> Dict[str, jax.Array]:
    """Copy cache positions [0, block) of `src_slots[i]` into
    `dst_slots[i]` for every layer; returns the updated cache pytree.

    Cache layout is [L, S, M, Hkv, hd] (models/transformer.py
    init_kv_cache).  The source rows gather once ([L, d, block, Hkv, hd])
    and scatter to the destinations in one pass — jitted by the engine
    with the cache donated, this lowers to a gather + one
    dynamic-update-slice-style scatter without any host round-trip.
    """
    out = {}
    for key, buf in cache.items():
        blk = buf[:, src_slots, :block]  # [L, d, block, Hkv, hd]
        # scratch-padded rows self-copy identical values, so the scatter
        # stays deterministic even with duplicate pad indices
        out[key] = buf.at[:, dst_slots, :block].set(blk)
    return out
