"""Automatic checkpoint evaluator.

Behavioral counterpart of the legacy `AutomaticEvaluator`
(realhf/scheduler/evaluator.py:348): a sidecar that watches the saver's
checkpoint root, and for every new checkpoint spawns an evaluation job
(a user-supplied command template), one at a time in save order, recording
results so a restart never re-evaluates finished checkpoints.

The in-loop `Evaluator` (utils/evaluator.py) covers frequency-gated online
eval; this class covers the offline "evaluate every saved checkpoint on the
benchmark suite" workflow, decoupled from the trainer's pace.
"""

import json
import os
import re
import subprocess
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from areal_tpu.utils import logging

logger = logging.getLogger("auto_eval")


@dataclass
class AutoEvalConfig:
    ckpt_root: str = ""  # the Saver's save_root
    # command template; {ckpt} and {name} are substituted per checkpoint
    eval_cmd: str = ""
    output_path: str = ""  # jsonl of results (default: <ckpt_root>/autoeval.jsonl)
    poll_interval: float = 10.0
    timeout: float = 3600.0
    env: Dict[str, str] = field(default_factory=dict)


class AutomaticEvaluator:
    def __init__(self, config: AutoEvalConfig):
        if not config.ckpt_root or not config.eval_cmd:
            raise ValueError("AutoEvalConfig needs ckpt_root and eval_cmd")
        self.config = config
        self.output_path = config.output_path or os.path.join(
            config.ckpt_root, "autoeval.jsonl"
        )
        self._done = self._load_done()

    # ------------------------------------------------------------------

    def _load_done(self) -> set:
        done = set()
        if os.path.exists(self.output_path):
            with open(self.output_path) as f:
                for line in f:
                    try:
                        done.add(json.loads(line)["name"])
                    except (json.JSONDecodeError, KeyError):
                        continue
        return done

    @staticmethod
    def _step_of(name: str) -> int:
        """Sort key: trailing integer in the checkpoint dir name (the
        Saver emits .../globalstep<N> style names); unknown -> mtime order
        handled by the caller."""
        m = re.search(r"(\d+)$", name)
        return int(m.group(1)) if m else -1

    def pending_checkpoints(self) -> List[str]:
        root = self.config.ckpt_root
        if not os.path.isdir(root):
            return []
        entries = []
        for name in os.listdir(root):
            path = os.path.join(root, name)
            # a checkpoint is ready when its directory contains model files
            # (the engines write staged-then-rename, so presence = complete)
            if not os.path.isdir(path) or name in self._done:
                continue
            if not any(
                f.endswith((".safetensors", ".zarr", "config.json"))
                for f in os.listdir(path)
            ):
                continue
            entries.append(name)
        return sorted(entries, key=lambda n: (self._step_of(n), n))

    def evaluate_one(self, name: str) -> Dict:
        path = os.path.join(self.config.ckpt_root, name)
        # plain replacement, not str.format: eval commands legitimately
        # contain JSON/shell braces
        cmd = self.config.eval_cmd.replace("{ckpt}", path).replace("{name}", name)
        logger.info(f"evaluating {name}: {cmd}")
        t0 = time.time()
        # own session + killpg: a timeout must take down the eval's whole
        # process tree, or communicate() blocks on grandchildren holding the
        # pipe and the orphaned job keeps burning the accelerator
        proc = subprocess.Popen(
            cmd,
            shell=True,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            start_new_session=True,
            env={**os.environ, **self.config.env},
        )
        try:
            stdout, stderr = proc.communicate(timeout=self.config.timeout)
            # convention: the eval prints one JSON line (its metrics) last
            metrics: Optional[dict] = None
            for line in reversed(stdout.strip().splitlines()):
                try:
                    metrics = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
            result = {
                "name": name,
                "rc": proc.returncode,
                "metrics": metrics,
                "wall_s": round(time.time() - t0, 1),
            }
            if proc.returncode != 0:
                result["stderr_tail"] = stderr[-2000:]
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait()
            result = {
                "name": name,
                "rc": -1,
                "metrics": None,
                "error": "timeout",
                "wall_s": round(time.time() - t0, 1),
            }
        with open(self.output_path, "a") as f:
            f.write(json.dumps(result) + "\n")
        self._done.add(name)
        logger.info(f"eval {name} done: {result.get('metrics')}")
        return result

    def step(self) -> List[Dict]:
        """Evaluate every currently-pending checkpoint (in save order)."""
        return [self.evaluate_one(n) for n in self.pending_checkpoints()]

    def run_forever(self, stop_check=None):
        while stop_check is None or not stop_check():
            self.step()
            time.sleep(self.config.poll_interval)


def main():
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--ckpt-root", required=True)
    p.add_argument("--eval-cmd", required=True,
                   help="shell template; {ckpt}/{name} substituted")
    p.add_argument("--poll-interval", type=float, default=10.0)
    p.add_argument("--timeout", type=float, default=3600.0)
    args = p.parse_args()
    AutomaticEvaluator(
        AutoEvalConfig(
            ckpt_root=args.ckpt_root,
            eval_cmd=args.eval_cmd,
            poll_interval=args.poll_interval,
            timeout=args.timeout,
        )
    ).run_forever()


if __name__ == "__main__":
    main()
