"""TIR entry-point smoke: workflow=tir runs the full launcher loop
(reference: examples/tir), and AgentWorkflow passes episode data to
data-aware env factories."""

import asyncio
import os
import subprocess
import sys

import numpy as np
import pytest

from tests.fixtures import make_gsm8k_jsonl, make_tiny_ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_env_factory_receives_episode_data():
    from areal_tpu.agent import AgentWorkflow, MathSingleStepAgent
    from areal_tpu.agent.math_env import MathVerifyEnv
    from areal_tpu.api.config import GenerationHyperparameters

    seen = []

    def factory(data):
        seen.append(data["answer"])
        return MathVerifyEnv(answer=data["answer"])

    class _Tok:
        def encode(self, t, add_special_tokens=False):
            return [ord(c) % 256 for c in t]

        def decode(self, t):
            return "".join(chr(x) for x in t)

        def apply_chat_template(self, m, **kw):
            return self.encode("".join(x["content"] for x in m))

    class _Eng:
        async def agenerate(self, req):
            out = [ord(c) for c in "\\boxed{5}"]

            class R:
                input_tokens = list(req.input_ids)
                output_tokens = out
                output_logprobs = [-0.1] * len(out)
                output_versions = [0] * len(out)
                input_len = len(req.input_ids)
                output_len = len(out)
                stop_reason = "stop"

            return R()

    wf = AgentWorkflow(
        MathSingleStepAgent(
            GenerationHyperparameters(n_samples=1, max_new_tokens=16),
            tokenizer=_Tok(),
        ),
        env_factory=factory,
    )
    batch = asyncio.run(
        wf.arun_episode(_Eng(), {"messages": [{"role": "user", "content": "q"}],
                                 "answer": "5"})
    )
    assert seen == ["5"]
    assert (batch["rewards"] == 1.0).all()

    # zero-arg factories keep working
    wf2 = AgentWorkflow(
        MathSingleStepAgent(
            GenerationHyperparameters(n_samples=1, max_new_tokens=16),
            tokenizer=_Tok(),
        ),
        env_factory=lambda: MathVerifyEnv(answer="5"),
    )
    batch2 = asyncio.run(
        wf2.arun_episode(_Eng(), {"messages": [{"role": "user", "content": "q"}]})
    )
    assert (batch2["rewards"] == 1.0).all()


@pytest.mark.slow
def test_tir_example_end_to_end(tmp_path):
    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data = make_gsm8k_jsonl(str(tmp_path / "train.jsonl"), n=8)
    fileroot = tmp_path / "exp"
    cfg = f"""
experiment_name: tirsmoke
trial_name: t0
seed: 1
total_train_epochs: 1
total_train_steps: 1
async_training: true
workflow: tir
tokenizer_path: {ckpt}
cluster:
  fileroot: {fileroot}
allocation_mode: "jax:d1+jax:d1"
train_dataset:
  path: {data}
  type: gsm8k
  batch_size: 4
  max_length: 128
gconfig:
  n_samples: 2
  max_new_tokens: 16
  temperature: 1.0
rollout:
  max_concurrent_rollouts: 8
  consumer_batch_size: 4
  max_head_offpolicyness: 2
  request_timeout: 120
gen_server:
  model_path: {ckpt}
  max_seqs: 4
  max_context_len: 256
actor:
  path: {ckpt}
  dtype: float32
  gradient_checkpointing: false
  group_size: 2
  ppo_n_minibatches: 1
  pack_length_quantum: 64
  max_pack_length: 256
  adv_norm:
    mean_level: group
    std_level: group
  optimizer:
    lr: 1.0e-4
    warmup_steps_proportion: 0.0
saver:
  freq_steps: null
checkpointer:
  freq_steps: null
evaluator:
  freq_steps: null
recover:
  mode: disabled
stats_logger:
  fileroot: {fileroot}
"""
    cfg_path = tmp_path / "cfg.yaml"
    cfg_path.write_text(cfg)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "areal_tpu.launcher.local",
         os.path.join(REPO, "examples/math/gsm8k_grpo.py"),
         "--config", str(cfg_path)],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        out, _ = proc.communicate(timeout=540)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        pytest.fail(f"launcher timed out.\n{out[-4000:]}")

    log_dir = fileroot / "tirsmoke" / "t0" / "logs"
    trainer_log = ""
    if log_dir.exists():
        for f in sorted(log_dir.iterdir()):
            if f.name.startswith("trainer"):
                trainer_log += f.read_text()
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out[-2000:]}\n{trainer_log[-4000:]}"
    assert "Step 1/" in trainer_log and "done." in trainer_log, trainer_log[-4000:]
