"""Self-speculative decoding (ISSUE 12): prompt-lookup drafts verified in
one batched dispatch per tier, with BIT-IDENTICAL output streams to plain
decode — greedy AND sampled, tiered AND untiered, across mid-generation
tier migration, group fan-out, interrupt/resume, and a live weight publish.

The exactness contract: verification samples every draft position under the
same position-keyed PRNG plain decode would use, and the first mismatching
position's sample IS the non-speculative token — so speculation only changes
how many dispatches the stream costs, never its contents.  Also covers the
drafter/controller units, the rejected-draft KV-zeroing invariant, and the
(tier, K, D) compile-signature soak against the checked-in C6 budget."""

import numpy as np
import pytest

from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.gen.spec import SpecController, propose_draft
from areal_tpu.models import init_params
from areal_tpu.models.model_config import tiny_config


@pytest.fixture(scope="module")
def setup():
    import jax

    cfg = tiny_config(vocab_size=97, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    base = dict(n_slots=4, max_seq_len=256, prompt_bucket=16,
                kv_dtype="float32", reuse_min_tokens=4, seed=3)
    base.update(kw)
    return GenEngine(cfg, params=params, **base)


def _run(eng, reqs):
    eng.generate_blocking(reqs)
    return [(tuple(r.output_tokens), tuple(r.output_logprobs), r.stop_reason)
            for r in reqs]


def _rep_prompt(rng, seg_len, total):
    """Repetitive prompt: a random segment tiled — prompt lookup hits."""
    seg = rng.integers(0, 97, seg_len).tolist()
    return (seg * (total // seg_len + 1))[:total]


def _rep_reqs(rng, temperature):
    """Mixed lengths/budgets over repetitive prompts (drafts get proposed
    AND sometimes accepted), plus one non-repetitive request (drafts rare:
    the D=0 fall-through to the plain decode program stays exercised)."""
    specs = [(4, 12, 10, 1.0), (6, 24, 30, 0.9), (3, 9, 12, 1.0)]
    reqs = [
        GenRequest(rid=f"r{i}", input_ids=_rep_prompt(rng, s, n),
                   max_new_tokens=m, temperature=temperature, top_p=tp)
        for i, (s, n, m, tp) in enumerate(specs)
    ]
    reqs.append(GenRequest(rid="r3", input_ids=rng.integers(0, 97, 40).tolist(),
                           max_new_tokens=9, temperature=temperature))
    return reqs


# ---------------------------------------------------------------------------
# drafter + controller units
# ---------------------------------------------------------------------------


def test_propose_draft_rightmost_longest_ngram():
    h = [1, 2, 3, 9, 1, 2, 3, 5, 1, 2, 3]
    # longest suffix n-gram with an earlier occurrence is [1,2,3]; the
    # RIGHTMOST prior occurrence starts at 4, so the draft continues from 7
    d = propose_draft(np.array(h), 4)
    assert d.tolist() == [5, 1, 2, 3]
    # deterministic
    assert propose_draft(np.array(h), 4).tolist() == d.tolist()
    # max_draft truncates
    assert propose_draft(np.array(h), 2).tolist() == [5, 1]
    # on a short cycle, the overall-rightmost match cannot fill the draft;
    # the drafter steps back to the rightmost occurrence that can
    cyc = [1, 2, 3] * 4
    assert propose_draft(np.array(cyc), 6).tolist() == [1, 2, 3, 1, 2, 3]


def test_propose_draft_falls_back_to_shorter_ngrams():
    # trigram suffix [4,2,5] never recurs; bigram [2,5] doesn't either;
    # unigram [5] does (index 1) -> draft continues with what followed it
    h = [9, 5, 7, 4, 2, 5]
    assert propose_draft(np.array(h), 3).tolist() == [7, 4, 2]


def test_propose_draft_empty_and_degenerate():
    assert propose_draft(np.array([], np.int32), 4).size == 0
    assert propose_draft(np.array([7]), 4).size == 0  # nothing precedes
    assert propose_draft(np.array([1, 2, 3, 4, 5]), 4).size == 0  # no repeat
    assert propose_draft(np.array([7, 7, 7]), 0).size == 0  # D=0 pinned
    assert propose_draft(np.array([7, 7]), 3).tolist() == [7]


def test_spec_controller_ladder_selection():
    c = SpecController(ladder=(0, 3, 7), probe_every=4)
    assert c.draft_len(0) == 7  # optimistic start, no signal yet
    for _ in range(8):
        c.record(0, 7, 6)  # high acceptance
    assert c.draft_len(0) == 7
    assert c.acceptance_rate(0) == pytest.approx(6 / 7)

    mid = SpecController(ladder=(0, 3, 7), probe_every=4)
    for _ in range(8):
        mid.record(0, 7, 2)  # 0.2 <= rate < 0.5 -> bottom nonzero rung
    assert mid.draft_len(0) == 3

    cold = SpecController(ladder=(0, 3, 7), probe_every=4)
    for _ in range(8):
        cold.record(0, 7, 0)
    picks = [cold.draft_len(0) for _ in range(8)]
    assert 0 in picks  # parked on plain decode...
    assert 3 in picks  # ...but probes at the cadence so it can re-climb
    assert cold.acceptance_rate(0) == 0.0
    # per-tier isolation: tier 1 has no history, stays optimistic
    assert cold.draft_len(1) == 7


def test_spec_controller_validates_ladder():
    with pytest.raises(ValueError):
        SpecController(ladder=(0,))
    with pytest.raises(ValueError):
        SpecController(ladder=(-1, 3))


# ---------------------------------------------------------------------------
# bit-identical stream parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("temperature", [0.0, 1.0])
@pytest.mark.parametrize("layout", [dict(decode_tiers=1),
                                    dict(decode_tiers=2)])
def test_spec_on_matches_spec_off(setup, temperature, layout):
    """The core ISSUE 12 contract: the same workload with speculation on
    yields the token streams AND logprobs of the spec-off engine, bit for
    bit, greedy and sampled, untiered and tiered."""
    cfg, params = setup
    rng = np.random.default_rng(11)
    plain = _run(_engine(cfg, params, **layout), _rep_reqs(rng, temperature))
    rng = np.random.default_rng(11)
    eng = _engine(cfg, params, spec_decode=True, **layout)
    spec = _run(eng, _rep_reqs(rng, temperature))
    assert spec == plain
    # speculation actually ran: drafts were proposed and verified
    assert eng.stats["verify_calls"] > 0
    assert eng.stats["spec_drafted"] > 0


def _cyclic_params(params):
    """Zeroing the attention output projection makes greedy next-token a
    pure function of the current token: every stream settles into a short
    cycle the prompt-lookup drafter locks onto (guaranteed drafts AND
    acceptances, weight-value-independent engine cost)."""
    import jax.numpy as jnp

    cyc = dict(params)
    cyc["layers"] = dict(params["layers"])
    cyc["layers"]["attn"] = dict(params["layers"]["attn"])
    cyc["layers"]["attn"]["wo"] = jnp.zeros_like(params["layers"]["attn"]["wo"])
    return cyc


def test_spec_accepts_drafts_on_cyclic_stream(setup):
    """On a cyclic greedy stream acceptance must be substantial and the
    stream must still equal the spec-off rollout."""
    cfg, params = setup
    cyc = _cyclic_params(params)

    def reqs():
        return [GenRequest(rid="cyc", input_ids=[5, 9, 13],
                           max_new_tokens=48, temperature=0.0)]

    plain = _run(_engine(cfg, cyc), reqs())
    eng = _engine(cfg, cyc, spec_decode=True)
    spec = _run(eng, reqs())
    assert spec == plain
    assert eng.stats["spec_accepted"] > 0
    rate = eng.stats["spec_accepted"] / eng.stats["spec_drafted"]
    assert rate > 0.5, eng.stats
    # accepted tokens shrink the dispatch count: 48 tokens in well under
    # 48 - accepted model calls would be ideal; at minimum the chunked
    # decode+verify call count stays below one call per token
    calls = eng.stats["decode_calls"] + eng.stats["verify_calls"]
    assert calls < 48


def test_spec_migration_parity(setup):
    """A request that migrates between length cohorts mid-generation under
    speculation still matches the spec-off untiered stream bit for bit —
    migration copies the whole retained row, never a rejected draft's KV."""
    cfg, params = setup

    def reqs_for(rng):
        blockers = [
            GenRequest(rid=f"b{i}", input_ids=_rep_prompt(rng, 7, 30),
                       max_new_tokens=40, temperature=1.0)
            for i in range(2)
        ]
        mover = GenRequest(rid="mover", input_ids=_rep_prompt(rng, 8, 40),
                           max_new_tokens=60, temperature=1.0)
        return blockers + [mover]

    tiered = _engine(cfg, params, decode_tier_lens=[64, 256],
                     decode_tier_slots=[2, 2], decode_chunk=4,
                     spec_decode=True)
    rng = np.random.default_rng(21)
    t_out = _run(tiered, reqs_for(rng))
    assert tiered.stats["tier_migrations"] >= 1, tiered.stats
    assert tiered.stats["spec_drafted"] > 0

    untiered = _engine(cfg, params, decode_tiers=1, decode_chunk=4)
    rng = np.random.default_rng(21)
    u_out = _run(untiered, reqs_for(rng))
    assert t_out == u_out


def test_spec_group_fanout_parity(setup):
    """GRPO fan-out under speculation: every sibling rides the shared
    prefix (one prefill + one copy) and emits the solo greedy stream.
    Cyclic params + a small chunk guarantee speculation genuinely runs on
    the siblings (a big first chunk would finish the budget before any
    generated token could seed a draft)."""
    cfg, params = setup
    cyc = _cyclic_params(params)
    rng = np.random.default_rng(4)
    prompt = _rep_prompt(rng, 6, 24)

    solo = _engine(cfg, cyc, decode_chunk=2)
    ref = GenRequest(rid="ref", input_ids=list(prompt), max_new_tokens=12,
                     temperature=0.0)
    solo.generate_blocking([ref])

    eng = _engine(cfg, cyc, decode_tiers=2, decode_chunk=2, spec_decode=True)
    reqs = [
        GenRequest(rid=f"G-{i}", input_ids=list(prompt), max_new_tokens=12,
                   temperature=0.0, group_id="G", group_n=4)
        for i in range(4)
    ]
    eng.generate_blocking(reqs)
    for r in reqs:
        assert r.output_tokens == ref.output_tokens, r.rid
    assert eng.stats["prefill_calls"] == 1
    assert eng.stats["copy_calls"] == 1
    assert eng.stats["spec_drafted"] > 0


def test_spec_interrupt_resume_parity(setup):
    """Interrupt (abort at a weight-publish boundary) then client resume:
    the spec engine's pre-abort tokens plus its resumed continuation equal
    the spec-off engine's under the identical cut — the suffix prefill must
    never absorb a rejected draft's KV."""
    cfg, params = setup
    rng = np.random.default_rng(8)
    prompt = _rep_prompt(rng, 5, 20)

    spec = _engine(cfg, params, spec_decode=True, decode_chunk=2)
    r1 = GenRequest(rid="i", input_ids=list(prompt), max_new_tokens=12,
                    temperature=1.0)
    spec.submit(r1)
    while len(r1.output_tokens) < 3:
        spec.step(chunk=2)
    spec.abort_all("abort")
    cut = len(r1.output_tokens)
    assert cut > 0 and r1.stop_reason == "abort"
    r1b = GenRequest(rid="i", input_ids=prompt + r1.output_tokens,
                     max_new_tokens=12 - cut, temperature=1.0)
    spec.generate_blocking([r1b])
    assert spec.stats["suffix_calls"] >= 1  # resume reused the prefix

    plain = _engine(cfg, params, decode_chunk=1)
    r2 = GenRequest(rid="i", input_ids=list(prompt), max_new_tokens=12,
                    temperature=1.0)
    plain.submit(r2)
    while len(r2.output_tokens) < cut:  # land on the same cut, exactly
        plain.step(chunk=1)
    plain.abort_all("abort")
    assert len(r2.output_tokens) == cut
    r2b = GenRequest(rid="i", input_ids=prompt + r2.output_tokens,
                     max_new_tokens=12 - cut, temperature=1.0)
    plain.generate_blocking([r2b])

    assert r1.output_tokens + r1b.output_tokens \
        == r2.output_tokens + r2b.output_tokens
    assert r1.output_logprobs + r1b.output_logprobs \
        == r2.output_logprobs + r2b.output_logprobs


def test_spec_live_publish_parity(setup):
    """swap_weights_live mid-generation with speculation: no abort, the
    stream keeps decoding under the new policy, and tokens/logprobs/
    versions all match the spec-off engine publishing at the same token."""
    import jax

    cfg, params = setup
    new_params = init_params(cfg, jax.random.PRNGKey(123))
    rng = np.random.default_rng(17)
    prompt = _rep_prompt(rng, 6, 24)

    def run(spec_on):
        eng = _engine(cfg, params, spec_decode=spec_on,
                      decode_chunk=4 if spec_on else 1)
        r = GenRequest(rid="lp", input_ids=list(prompt), max_new_tokens=16,
                       temperature=1.0)
        eng.submit(r)
        # the spec engine publishes wherever its chunk boundary lands (it
        # may overshoot 4 tokens on an accepted draft run); the plain
        # engine then steps 1 token at a time to the identical cut
        target = 4 if spec_on else run.cut
        while len(r.output_tokens) < target:
            eng.step(chunk=eng.decode_chunk)
        if spec_on:
            run.cut = len(r.output_tokens)
        assert len(r.output_tokens) == run.cut
        eng.swap_weights_live(new_params)
        assert not r.stop_reason  # still in flight — publish aborted nothing
        while not r.stop_reason:
            eng.step(chunk=eng.decode_chunk)
        return r

    run.cut = None
    r_spec = run(True)
    r_plain = run(False)
    assert r_spec.output_tokens == r_plain.output_tokens
    assert r_spec.output_logprobs == r_plain.output_logprobs
    assert r_spec.output_versions == r_plain.output_versions
    assert set(r_spec.output_versions) == {0, 1}  # both policies contributed


# ---------------------------------------------------------------------------
# rejected-draft KV hygiene + compile-signature soak
# ---------------------------------------------------------------------------


def test_rejected_draft_kv_never_persists(setup):
    """Auditable KV hygiene: at every step boundary, cache rows at or above
    a live slot's frontier are all-zero — a rejected draft's K/V never
    outlives the verify dispatch that wrote it (it would otherwise be
    silently attended by every later chunk, retained prefix, or migration
    copy of that row).  The prompt is bucket-aligned (16 = prompt_bucket)
    so prefill writes no pad rows and the audit is exact: any nonzero row
    past the frontier can only have come from a decode/verify write."""
    cfg, params = setup
    eng = _engine(cfg, params, spec_decode=True, decode_chunk=4)
    rng = np.random.default_rng(5)
    # temperature 1.0 over a small vocab: sampled continuations repeat
    # earlier tokens often enough to trigger drafts, and those drafts are
    # then almost never what the sampler emits — exactly the rejection
    # traffic this audit needs
    req = GenRequest(rid="kv", input_ids=_rep_prompt(rng, 5, 16),
                     max_new_tokens=96, temperature=1.0)
    eng.submit(req)
    while not req.stop_reason:
        eng.step(chunk=4)
        s = next((i for i in range(eng.n_slots) if eng.slot_req[i] is req),
                 None)
        if s is None:
            continue
        frontier = int(eng.lengths[s])
        for name in ("k", "v"):
            tail = np.asarray(eng.cache[name])[:, s, frontier:]
            assert not np.any(tail), (
                f"{name}-cache rows >= frontier {frontier} are nonzero "
                f"after a verify dispatch (rejected draft KV leaked)"
            )
    # the invariant was actually exercised: some drafts were rejected
    assert eng.stats["spec_drafted"] > eng.stats["spec_accepted"]


def test_spec_compile_signature_soak(setup):
    """Steady-state spec traffic stays on the (tier, K bucket, D rung)
    program lattice: zero new decode/prefill programs after warmup and the
    verify-program count within the checked-in C6 budget for the
    spec_decode_soak reference config (ISSUE 9 discipline extended)."""
    from tests.test_tiered_decode import _signature_budget

    cfg, params = setup
    eng = _engine(cfg, params, decode_tiers=2, decode_chunk=4,
                  spec_decode=True)
    rng = np.random.default_rng(31)

    def wave(tag):
        reqs = []
        for i, (n, m) in enumerate([(8, 10), (20, 25), (40, 40), (60, 30)]):
            ids = (_rep_prompt(rng, max(2, n // 4), n) if i % 2 == 0
                   else rng.integers(0, 97, n).tolist())
            reqs.append(GenRequest(rid=f"{tag}{i}", input_ids=ids,
                                   max_new_tokens=m, temperature=1.0))
        eng.generate_blocking(reqs)

    wave("warm0")
    wave("warm1")
    sizes = {
        "decode": eng._decode_fn._cache_size(),
        "prefill": eng._prefill_fn._cache_size(),
    }
    for w in range(3):
        wave(f"soak{w}")
    # decode/prefill mint nothing new; verify may legitimately mint a
    # not-yet-seen rung (the controller adapts) but never leaves the budget
    assert eng._decode_fn._cache_size() == sizes["decode"]
    assert eng._prefill_fn._cache_size() == sizes["prefill"]
    assert eng.stats["verify_calls"] > 0

    ref = _signature_budget("spec_decode_soak")
    assert ref["config"] == {"n_slots": 4, "max_seq_len": 256,
                             "prompt_bucket": 16, "decode_tiers": 2,
                             "spec_rungs": 2}
    assert eng._verify_fn._cache_size() <= ref["budgets"]["verify"]
    assert eng._decode_fn._cache_size() <= ref["budgets"]["decode"]
    assert eng._prefill_fn._cache_size() <= ref["budgets"]["prefill"]
