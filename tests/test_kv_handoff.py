"""Disaggregated prefill/decode KV handoff (ISSUE 17) — engine-level
exactness and protocol tests.

The contract under test: a request's page set serialized on one engine
(`export_request_kv` -> wire format) and imported on ANOTHER engine
(`import_request_kv` -> host-tier entry, swap-in re-scatter) continues
the token stream bit-identically — tokens AND logprobs — to the same
two-leg split served by a single engine.  The counter-keyed sampler
makes the stream a pure function of (stream_id, position), so the only
thing the transfer may change is *where* the tail runs, never *what* it
emits.  The colocated control is the same two-leg split on one engine
(not a one-shot run): decode-vs-suffix XLA programs may legitimately
differ in the last ulp at the handoff boundary, and this suite pins the
transfer, not boundary numerics.
"""

import os

import numpy as np
import pytest

from areal_tpu.gen import kv_pool
from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.models import init_params
from areal_tpu.models.model_config import tiny_config


@pytest.fixture(scope="module", autouse=True)
def _debug_locks():
    old = os.environ.get("AREAL_DEBUG_LOCKS")
    os.environ["AREAL_DEBUG_LOCKS"] = "1"
    yield
    if old is None:
        os.environ.pop("AREAL_DEBUG_LOCKS", None)
    else:
        os.environ["AREAL_DEBUG_LOCKS"] = old


@pytest.fixture(scope="module")
def setup(_debug_locks):
    import jax

    cfg = tiny_config(vocab_size=97, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(cfg, params, **kw):
    base = dict(n_slots=2, max_seq_len=128, prompt_bucket=16,
                kv_dtype="float32", reuse_min_tokens=4)
    base.update(kw)
    return GenEngine(cfg, params=params, **base)


def _leg(eng, ids, n, *, stream_id, temp):
    r = GenRequest(rid=f"leg-{stream_id}-{len(ids)}", input_ids=list(ids),
                   max_new_tokens=n, temperature=temp, top_p=0.9,
                   stream_id=stream_id)
    eng.generate_blocking([r])
    return r


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------


def test_wire_round_trip_bit_exact(setup):
    """encode -> decode must reproduce every KV array byte-for-byte (same
    dtype, shape, bytes) plus tokens/valid_len/version — the wire is a
    host-side re-encoding, never a numeric conversion."""
    cfg, params = setup
    eng = _engine(cfg, params)
    rng = np.random.default_rng(40)
    prompt = rng.integers(0, 97, 24).tolist()
    r = _leg(eng, prompt, 1, stream_id=7, temp=0.0)
    entry = eng.export_request_kv(prompt + r.output_tokens)
    assert entry is not None
    assert eng.stats["kv_handoff_exports"] == 1

    doc = kv_pool.wire_encode_entry(entry)
    assert doc["nbytes"] > 0
    back = kv_pool.wire_decode_entry(doc)
    assert list(back["tokens"]) == list(entry["tokens"])
    assert back["valid_len"] == entry["valid_len"]
    assert back["version"] == entry["version"]
    assert set(back["kv"]) == set(entry["kv"])
    for k, a in entry["kv"].items():
        b = back["kv"][k]
        src = np.asarray(a)
        assert b.dtype == src.dtype and b.shape == src.shape
        assert src.tobytes() == np.asarray(b).tobytes()


def test_export_unknown_prefix_returns_none(setup):
    cfg, params = setup
    eng = _engine(cfg, params)
    assert eng.export_request_kv([11, 13, 17, 19] * 8) is None
    assert eng.stats["kv_handoff_failures"] >= 1


def test_import_without_host_tier_refused(setup):
    cfg, params = setup
    src = _engine(cfg, params)
    rng = np.random.default_rng(41)
    prompt = rng.integers(0, 97, 20).tolist()
    r = _leg(src, prompt, 1, stream_id=5, temp=0.0)
    entry = src.export_request_kv(prompt + r.output_tokens)
    dst = _engine(cfg, params)  # no host_offload: nowhere to install
    assert dst.import_request_kv(entry) is False
    assert dst.stats["kv_handoff_failures"] >= 1


# ---------------------------------------------------------------------------
# cross-engine continuation exactness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("leg1_n,temp", [(1, 0.0), (1, 1.0), (5, 1.0)])
def test_handoff_continuation_bit_identical(setup, leg1_n, temp):
    """The tentpole pin: leg 1 on a 'prefill' engine, export/import, leg 2
    on a 'decode' engine — versus the SAME two-leg split on one engine.
    Greedy and sampled, including a mid-generation handoff (leg 1 longer
    than one token).  Tokens and logprobs must match exactly."""
    cfg, params = setup
    rng = np.random.default_rng(42 + leg1_n)
    prompt = rng.integers(0, 97, 27).tolist()
    total, sid = 8, 90 + leg1_n

    # colocated control: both legs on one engine
    ctl = _engine(cfg, params)
    c1 = _leg(ctl, prompt, leg1_n, stream_id=sid, temp=temp)
    assert len(c1.output_tokens) == leg1_n
    ctl_ids = prompt + c1.output_tokens
    c2 = _leg(ctl, ctl_ids, total - leg1_n, stream_id=sid, temp=temp)
    assert c2.cache_hit_tokens > 0  # warm continuation, not a cold prefill

    # disaggregated: leg 1 on A, wire transfer, leg 2 on B
    ea = _engine(cfg, params)
    eb = _engine(cfg, params, host_offload=True, host_cache_mb=8,
                 host_min_tokens=8)
    a1 = _leg(ea, prompt, leg1_n, stream_id=sid, temp=temp)
    assert a1.output_tokens == c1.output_tokens
    assert a1.output_logprobs == c1.output_logprobs
    full_ids = prompt + a1.output_tokens
    doc = kv_pool.wire_encode_entry(ea.export_request_kv(full_ids))
    assert eb.import_request_kv(kv_pool.wire_decode_entry(doc)) is True
    assert eb.stats["kv_handoff_imports"] == 1
    b2 = _leg(eb, full_ids, total - leg1_n, stream_id=sid, temp=temp)

    # the import was admitted as a warm-cache hit on the decode engine
    assert b2.cache_hit_tokens > 0
    assert eb.stats["prefix_cache_host_swaps"] >= 1
    assert b2.output_tokens == c2.output_tokens
    assert b2.output_logprobs == c2.output_logprobs


def test_handoff_stream_without_pin_still_exact_same_allocation(setup):
    """Engine-allocated stream ids are written back to the request — the
    server surfaces them so leg 2 can pin what leg 1 drew.  Two engines
    seeded identically allocate the same first id, and the pinned
    continuation reproduces the unpinned engine's stream."""
    cfg, params = setup
    rng = np.random.default_rng(44)
    prompt = rng.integers(0, 97, 21).tolist()

    one = _engine(cfg, params)
    solo = GenRequest(rid="solo", input_ids=list(prompt), max_new_tokens=6,
                      temperature=1.0, top_p=0.9)
    one.generate_blocking([solo])
    assert solo.stream_id > 0  # allocation written back

    ea = _engine(cfg, params)
    eb = _engine(cfg, params, host_offload=True, host_cache_mb=8,
                 host_min_tokens=8)
    a1 = GenRequest(rid="a1", input_ids=list(prompt), max_new_tokens=1,
                    temperature=1.0, top_p=0.9)
    ea.generate_blocking([a1])
    assert a1.stream_id == solo.stream_id
    full = prompt + a1.output_tokens
    eb.import_request_kv(ea.export_request_kv(full))
    b2 = _leg(eb, full, 5, stream_id=a1.stream_id, temp=1.0)
    assert a1.output_tokens + b2.output_tokens == solo.output_tokens


# ---------------------------------------------------------------------------
# page-granular partial prefix hits (satellite)
# ---------------------------------------------------------------------------


def test_partial_hit_page_floored_accounting(setup):
    """A request whose device match loses its donor slot to a longer match
    — and that can't ride the batch's cluster fan-out, because the
    winners cluster among their own declared group — still inherits the
    donor prefix up to a page (prompt-bucket) boundary: counted in
    prefix_cache_partial_hits, credited page-floored in
    cache_hit_tokens, and bit-identical to a cold run."""
    cfg, params = setup
    rng = np.random.default_rng(45)
    base = rng.integers(0, 97, 37).tolist()

    eng = _engine(cfg, params, n_slots=4)
    r0 = _leg(eng, base, 4, stream_id=11, temp=0.0)
    transcript = base + r0.output_tokens
    # one batch: the declared group's two continuations cluster together
    # (one wins r0's slot in place, the sibling fans out from it), and
    # the groupless loser — divergent at 37 — finds its only donor
    # claimed, so it copy-shares 32 tokens (37 floored to the 16-page
    # grid) instead of cold-prefilling
    w1 = GenRequest(rid="w1", input_ids=transcript + [1],
                    max_new_tokens=4, temperature=0.0, stream_id=12,
                    group_id="gw", group_n=2)
    w2 = GenRequest(rid="w2", input_ids=transcript + [2],
                    max_new_tokens=4, temperature=0.0, stream_id=14,
                    group_id="gw", group_n=2)
    loser = GenRequest(rid="l", input_ids=base[:37] + [7, 8, 9],
                       max_new_tokens=4, temperature=0.0, stream_id=13)
    eng.generate_blocking([w1, w2, loser])
    assert eng.stats["prefix_cache_partial_hits"] == 1
    assert loser.cache_hit_tokens == 32  # 37 floored to the 16-page grid

    cold = _engine(cfg, params, n_slots=4)
    ref = GenRequest(rid="ref", input_ids=base[:37] + [7, 8, 9],
                     max_new_tokens=4, temperature=0.0, stream_id=13)
    cold.generate_blocking([ref])
    assert loser.output_tokens == ref.output_tokens
    assert cold.stats["prefix_cache_partial_hits"] == 0
    eng.pool.check_page_table()


# ---------------------------------------------------------------------------
# tp parity (satellite): the handoff is sharding-invariant
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("temp", [0.0, 1.0])
def test_tp2_handoff_stream_parity(setup, temp):
    """tp=2 vs tp=1, each serving the same two-leg handoff: token streams
    must be bit-identical across shardings (logprobs to float tolerance,
    matching the existing tp-parity pin) — the exported page set is
    gathered/scattered per-shard but represents the same prefix."""
    cfg, params = setup
    rng = np.random.default_rng(46)
    prompt = rng.integers(0, 97, 18).tolist()
    streams = {}
    for tp in (1, 2):
        ea = _engine(cfg, params, tp=tp)
        eb = _engine(cfg, params, tp=tp, host_offload=True,
                     host_cache_mb=8, host_min_tokens=8)
        a1 = _leg(ea, prompt, 1, stream_id=33, temp=temp)
        full = prompt + a1.output_tokens
        doc = kv_pool.wire_encode_entry(ea.export_request_kv(full))
        assert eb.import_request_kv(kv_pool.wire_decode_entry(doc))
        b2 = _leg(eb, full, 5, stream_id=33, temp=temp)
        streams[tp] = (a1.output_tokens + b2.output_tokens,
                       a1.output_logprobs + b2.output_logprobs)
    toks1, lp1 = streams[1]
    toks2, lp2 = streams[2]
    assert toks1 == toks2
    np.testing.assert_allclose(lp2, lp1, rtol=1e-4, atol=1e-4)
