"""Model architecture config.

One dataclass covers the decoder-only families the reference supports via its
per-arch HF converters (reference: realhf/api/from_hf/{llama,qwen2,qwen3,
mistral,gemma,gpt2,mixtral}.py and lite's AutoModelForCausalLM path,
areal/engine/base_hf_engine.py:46): llama/mistral (no qkv bias, untied),
qwen2 (qkv bias), qwen3 (qk-norm, explicit head_dim), gemma/gemma2 (scaled
embeddings, zero-centred norms, sandwich norms, logit softcaps, alternating
sliding/full layers), gpt2 (LayerNorm+bias, learned positions, non-gated
gelu MLP, fused-qkv checkpoints).  MoE fields cover the mixtral/qwen3-moe
family.

TPU-first: the config is a frozen, hashable pytree-static object so it can be
closed over by `jax.jit` without retracing.
"""

import json
import os
from dataclasses import dataclass, replace
from typing import Optional


@dataclass(frozen=True)
class VisionConfig:
    """ViT vision tower (Qwen2-VL family shape: patchified pixels in,
    spatially-merged embeddings at the text width out)."""

    patch_size: int = 14
    temporal_patch_size: int = 2
    in_channels: int = 3
    hidden_size: int = 1280
    intermediate_size: int = 5120
    num_layers: int = 32
    num_heads: int = 16
    spatial_merge_size: int = 2  # 2x2 patches -> one embedding
    out_hidden_size: int = 4096  # text model width
    rms_norm_eps: float = 1e-6
    # Qwen2.5-VL windowed attention: blocks NOT in fullatt_block_indexes
    # attend only within window_size x window_size pixel tiles of their
    # image.  window_size == 0 means full attention in every block
    # (Qwen2-VL behavior).
    window_size: int = 0
    fullatt_block_indexes: tuple = ()

    @property
    def patch_dim(self) -> int:
        return self.in_channels * self.temporal_patch_size * self.patch_size**2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def replace(self, **kw) -> "VisionConfig":
        return replace(self, **kw)


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None  # defaults to hidden_size // num_heads
    max_position_embeddings: int = 32768
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    qkv_bias: bool = False  # qwen2
    qk_norm: bool = False  # qwen3
    attn_logit_softcap: Optional[float] = None  # gemma2
    sliding_window: Optional[int] = None
    # per-layer attention kinds (gemma2/3 alternate sliding/full): tuple of
    # bools, True = this layer uses the sliding window.  None = uniform
    # (every layer slides iff sliding_window is set, the mistral behavior).
    layer_is_sliding: Optional[tuple] = None

    # gemma-family structure knobs (reference keeps a gemma converter,
    # realhf/api/from_hf/gemma.py; defaults reproduce the llama family)
    hidden_act: str = "silu"  # silu | gelu_pytorch_tanh | gelu
    scale_embeddings: bool = False  # multiply embeds by sqrt(hidden_size)
    norm_unit_offset: bool = False  # RMSNorm weight stored zero-centered
    sandwich_norms: bool = False  # gemma2: extra norms on attn/ffn outputs
    final_logit_softcap: Optional[float] = None  # gemma2 lm-head tanh cap
    query_pre_attn_scalar: Optional[float] = None  # softmax scale = qpas^-0.5

    # gpt2-family structure knobs (reference: realhf/api/from_hf/gpt2.py)
    norm_type: str = "rmsnorm"  # rmsnorm | layernorm (mean-centred + bias)
    pos_emb: str = "rope"  # rope | learned (wpe table added to embeds)
    mlp_gated: bool = True  # False: w_up -> act -> w_down (no gate branch)
    attn_output_bias: bool = False  # bias on the attention out-projection
    mlp_bias: bool = False  # biases on the MLP projections

    # MoE (mixtral / qwen3-moe); num_experts == 0 means dense
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_intermediate_size: Optional[int] = None
    moe_capacity_factor: float = 1.25  # per-expert token budget multiplier
    moe_aux_coef: float = 0.01  # Switch load-balance loss coefficient
    # "dropless": exact HF Mixtral/Qwen3-MoE semantics — every routed token
    # reaches its expert (sort + lax.ragged_dot grouped GEMM).  "capacity":
    # GShard capacity-bounded dense dispatch (tokens beyond the per-expert
    # budget are dropped under routing imbalance; cheapest under ep
    # sharding).  HF-loaded checkpoints default to dropless so logits match
    # the source model regardless of batch size (ADVICE r3).
    moe_impl: str = "capacity"  # capacity | dropless

    # LoRA (0 = off); targets use HF module names (models/lora.py TARGET_MAP)
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple = ()

    # numerics
    dtype: str = "bfloat16"  # compute/activation dtype
    param_dtype: str = "float32"  # master weights
    remat: bool = True  # jax.checkpoint each layer (or layer group)
    # "full": recompute everything in backward (min HBM);
    # "dots": save matmul outputs, recompute elementwise only — trades HBM
    # for ~the forward matmul FLOPs of the backward recompute;
    # "save_attn"/"save_mlp": keep only the tagged attention/MLP outputs
    # (checkpoint_name in _layer_forward) — the selective rungs between;
    # "carry_offload": save the tagged attention AND MLP outputs but park
    # them in pinned host memory (save_and_offload_only_these_names) —
    # trades the HBM pressure that kills selective rungs at long context
    # for PCIe/host traffic the backward overlaps with recompute
    remat_policy: str = "full"  # full | dots | save_attn | save_mlp | carry_offload
    # two-level layer scan: the outer lax.scan runs num_layers /
    # layer_group_size steps, each step an unrolled chain of
    # layer_group_size layers wrapped in ONE jax.checkpoint at the group
    # boundary.  Only inter-group activations are saved (within-group ones
    # are recomputed), so the backward scan-transpose carry shrinks ~G× in
    # entry count — the sqrt-remat regime a per-layer checkpoint cannot
    # express.  Must divide num_layers (rejected loudly otherwise); 1
    # reproduces the classic per-layer scan exactly.
    layer_group_size: int = 1
    # outer-scan unroll factor: >1 trades compile time for less per-step
    # scan overhead (dynamic-update-slice carry traffic); must divide the
    # outer scan length (num_layers / layer_group_size) — non-divisors
    # warn loudly and fall back to 1 (models/transformer.py
    # effective_scan_unroll)
    scan_unroll: int = 1
    # lax.scan(_split_transpose=...): split the backward (transposed) layer
    # scan into two passes — XLA can then overlap the grad-accumulation
    # carry writes differently; measured per-hardware, off by default
    scan_split_transpose: bool = False
    # attention implementation: "auto" picks the Pallas splash kernel on TPU
    # when shapes allow and the naive einsum path elsewhere; "ring" shards
    # K/V along the sequence over the sp axis with rotating blocks — the
    # context-parallel regime for contexts too long for per-chip whole-K/V
    # (ops/attention.py ring_attention; falls back to auto — with a
    # warning — without an sp>1 mesh axis or with per-layer sliding
    # windows, which are mask-based)
    attn_impl: str = "auto"  # auto | splash | naive | ring

    # vision-language (None = text-only); Qwen2-VL-style mrope: the rope
    # frequency bands are split into (temporal, height, width) sections
    vision: Optional[VisionConfig] = None
    image_token_id: Optional[int] = None
    mrope_section: Optional[tuple] = None  # e.g. (16, 24, 24); sums to hd/2

    # bookkeeping
    hf_architecture: str = "LlamaForCausalLM"
    bos_token_id: Optional[int] = 1
    eos_token_id: Optional[int] = 2

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim_

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim_

    def replace(self, **kw) -> "TransformerConfig":
        return replace(self, **kw)

    # ------------------------------------------------------------------
    # HF interop
    # ------------------------------------------------------------------

    @classmethod
    def from_hf(cls, path_or_dict) -> "TransformerConfig":
        """Build from an HF `config.json` (path to a checkpoint dir, a json
        file, or an already-parsed dict)."""
        if isinstance(path_or_dict, dict):
            d = path_or_dict
        else:
            p = path_or_dict
            if os.path.isdir(p):
                p = os.path.join(p, "config.json")
            with open(p) as f:
                d = json.load(f)
        archs = d.get("architectures") or ["LlamaForCausalLM"]
        arch = archs[0]
        model_type = d.get("model_type", "llama")
        if model_type == "gpt2":
            # entirely different key names (n_embd/n_layer/...) and block
            # structure: LayerNorm, learned positions, fused-qkv Conv1D,
            # non-gated gelu MLP, biases throughout, always-tied head
            act = d.get("activation_function", "gelu_new")
            return cls(
                vocab_size=d["vocab_size"],
                hidden_size=d["n_embd"],
                intermediate_size=d.get("n_inner") or 4 * d["n_embd"],
                num_layers=d["n_layer"],
                num_heads=d["n_head"],
                num_kv_heads=d["n_head"],
                max_position_embeddings=d.get("n_positions", 1024),
                rms_norm_eps=float(d.get("layer_norm_epsilon", 1e-5)),
                tie_word_embeddings=True,
                qkv_bias=True,
                attn_output_bias=True,
                mlp_bias=True,
                mlp_gated=False,
                norm_type="layernorm",
                pos_emb="learned",
                # pass unknown activations through: _act raises loudly for
                # unsupported ones instead of silently running gelu
                hidden_act=(
                    "gelu_pytorch_tanh" if act in ("gelu_new", "gelu_pytorch_tanh")
                    else act
                ),
                hf_architecture=arch,
                bos_token_id=d.get("bos_token_id", 50256),
                eos_token_id=d.get("eos_token_id", 50256),
            )
        qkv_bias = bool(d.get("attention_bias", False))
        qk_norm = False
        if model_type == "qwen2":
            # qwen2 HF configs carry no attention_bias flag; bias is implied
            qkv_bias = d.get("attention_bias", True)
        if model_type in ("qwen3", "qwen3_moe"):
            qkv_bias = bool(d.get("attention_bias", False))
            qk_norm = True
        gemma = model_type.startswith("gemma")
        if gemma and model_type not in ("gemma", "gemma2"):
            # gemma3+ adds qk-norm / local-rope / different layer_types
            # semantics — loading it with gemma1/2 structure would run but
            # silently produce wrong logits
            raise ValueError(
                f"unsupported gemma variant {model_type!r}: only gemma and "
                "gemma2 checkpoints are implemented"
            )
        num_layers = d["num_hidden_layers"]
        layer_is_sliding = None
        sliding_window = (
            d.get("sliding_window")
            if d.get("use_sliding_window", model_type == "mistral")
            else None
        )
        if model_type == "gemma2":
            # alternating local/global attention; HF encodes it as
            # layer_types, older configs imply sliding on even layers
            sliding_window = d.get("sliding_window")
            lt = d.get("layer_types")
            if lt is not None:
                layer_is_sliding = tuple(t == "sliding_attention" for t in lt)
            else:
                layer_is_sliding = tuple(
                    i % 2 == 0 for i in range(num_layers)
                )
            if sliding_window is None or not any(layer_is_sliding):
                # no layer actually slides: drop the window entirely so the
                # uniform-window (mistral) path can't window every layer
                layer_is_sliding = None
                sliding_window = None
        num_heads = d["num_attention_heads"]
        n_experts = d.get("num_local_experts", d.get("num_experts", 0)) or 0
        if (
            n_experts > 0
            and model_type.startswith("qwen")
            and not d.get("norm_topk_prob", False)
        ):
            # this repo's router always renormalizes top-k gates (the
            # mixtral/released-qwen-moe convention); a checkpoint trained
            # with norm_topk_prob=false has different routing semantics
            import warnings

            warnings.warn(
                "checkpoint config has norm_topk_prob=false but this "
                "runtime renormalizes top-k gates — routing semantics "
                "will diverge from the original model",
                stacklevel=2,
            )
        eos = d.get("eos_token_id", 2)
        if isinstance(eos, list):
            eos = eos[0]
        # activation key precedence per model type, matching transformers
        # >=4.57: Gemma2MLP reads config.hidden_activation (default tanh),
        # GemmaMLP reads config.hidden_act only (hidden_activation ignored,
        # legacy 'gelu' runs EXACT gelu), everything else reads hidden_act —
        # pinned by test_legacy_gemma_act_parity
        if model_type == "gemma2":
            hidden_act = d.get("hidden_activation") or "gelu_pytorch_tanh"
        elif gemma:
            hidden_act = d.get("hidden_act") or "gelu_pytorch_tanh"
        else:
            hidden_act = d.get("hidden_act") or "silu"
        return cls(
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d.get("intermediate_size", 4 * d["hidden_size"]),
            num_layers=num_layers,
            num_heads=num_heads,
            num_kv_heads=d.get("num_key_value_heads", num_heads),
            head_dim=d.get("head_dim", 256 if gemma else None),
            max_position_embeddings=d.get("max_position_embeddings", 32768),
            rope_theta=float(d.get("rope_theta", 10000.0)),
            rms_norm_eps=float(d.get("rms_norm_eps", 1e-6)),
            tie_word_embeddings=bool(d.get("tie_word_embeddings", gemma)),
            qkv_bias=qkv_bias,
            qk_norm=qk_norm,
            sliding_window=sliding_window,
            layer_is_sliding=layer_is_sliding,
            hidden_act=hidden_act,
            scale_embeddings=gemma,
            norm_unit_offset=gemma,
            sandwich_norms=model_type == "gemma2",
            final_logit_softcap=(
                d.get("final_logit_softcapping")
                if model_type == "gemma2"
                else None
            ),
            attn_logit_softcap=(
                d.get("attn_logit_softcapping")
                if model_type == "gemma2"
                else None
            ),
            query_pre_attn_scalar=(
                float(d["query_pre_attn_scalar"])
                if d.get("query_pre_attn_scalar") is not None
                and model_type == "gemma2"
                else None
            ),
            num_experts=d.get("num_local_experts", d.get("num_experts", 0)) or 0,
            num_experts_per_tok=d.get("num_experts_per_tok", 2),
            moe_intermediate_size=d.get("moe_intermediate_size"),
            # real HF MoE checkpoints (mixtral/qwen3-moe) are dropless;
            # running them through the capacity path silently drops tokens
            # under routing imbalance and makes logits batch-size-dependent
            moe_impl="dropless" if n_experts > 0 else "capacity",
            hf_architecture=arch,
            bos_token_id=d.get("bos_token_id", 1),
            eos_token_id=eos,
            # qwen2-VL-style vision config (this repo's saver emits the same
            # shape, so VLM checkpoints round-trip)
            vision=(
                VisionConfig(
                    patch_size=vd.get("patch_size", 14),
                    temporal_patch_size=vd.get("temporal_patch_size", 2),
                    in_channels=vd.get("in_channels", 3),
                    hidden_size=vd.get("hidden_size", 1280),
                    intermediate_size=vd.get("intermediate_size", 5120),
                    num_layers=vd.get("depth", vd.get("num_hidden_layers", 32)),
                    num_heads=vd.get("num_heads", 16),
                    spatial_merge_size=vd.get("spatial_merge_size", 2),
                    out_hidden_size=vd.get("out_hidden_size", d["hidden_size"]),
                    window_size=vd.get("window_size", 0) or 0,
                    fullatt_block_indexes=tuple(
                        vd.get("fullatt_block_indexes", ()) or ()
                    ),
                )
                if (vd := d.get("vision_config")) is not None
                else None
            ),
            image_token_id=d.get("image_token_id"),
            mrope_section=(
                tuple(d["rope_scaling"]["mrope_section"])
                if isinstance(d.get("rope_scaling"), dict)
                and d["rope_scaling"].get("mrope_section")
                else None
            ),
        )

    def to_hf_dict(self) -> dict:
        """Emit an HF-compatible config dict (for saving checkpoints that
        inference servers / transformers can load back)."""
        arch = self.hf_architecture
        if arch == "GPT2LMHeadModel":
            return {
                "architectures": [arch],
                "model_type": "gpt2",
                "vocab_size": self.vocab_size,
                "n_embd": self.hidden_size,
                "n_inner": self.intermediate_size,
                "n_layer": self.num_layers,
                "n_head": self.num_heads,
                "n_positions": self.max_position_embeddings,
                "n_ctx": self.max_position_embeddings,
                "layer_norm_epsilon": self.rms_norm_eps,
                "activation_function": (
                    "gelu_new" if self.hidden_act == "gelu_pytorch_tanh"
                    else self.hidden_act
                ),
                "tie_word_embeddings": True,
                "torch_dtype": "bfloat16",
                "bos_token_id": self.bos_token_id,
                "eos_token_id": self.eos_token_id,
            }
        model_type = {
            "LlamaForCausalLM": "llama",
            "Qwen2ForCausalLM": "qwen2",
            "Qwen3ForCausalLM": "qwen3",
            "MistralForCausalLM": "mistral",
            "Qwen3MoeForCausalLM": "qwen3_moe",
            "MixtralForCausalLM": "mixtral",
            "GemmaForCausalLM": "gemma",
            "Gemma2ForCausalLM": "gemma2",
        }.get(arch, "llama")
        d = {
            "architectures": [arch],
            "model_type": model_type,
            "vocab_size": self.vocab_size,
            "hidden_size": self.hidden_size,
            "intermediate_size": self.intermediate_size,
            "num_hidden_layers": self.num_layers,
            "num_attention_heads": self.num_heads,
            "num_key_value_heads": self.num_kv_heads,
            "max_position_embeddings": self.max_position_embeddings,
            "rope_theta": self.rope_theta,
            "rms_norm_eps": self.rms_norm_eps,
            "tie_word_embeddings": self.tie_word_embeddings,
            "hidden_act": self.hidden_act,
            "torch_dtype": "bfloat16",
            "bos_token_id": self.bos_token_id,
            "eos_token_id": self.eos_token_id,
        }
        if self.head_dim is not None:
            d["head_dim"] = self.head_dim
        if model_type in ("qwen2", "qwen3", "mistral", "llama", "qwen3_moe"):
            d["attention_bias"] = self.qkv_bias
        if model_type.startswith("gemma"):
            # transformers' gemma configs read hidden_activation
            d["hidden_activation"] = self.hidden_act
            d["attention_bias"] = self.qkv_bias
        if model_type == "gemma2":
            if self.query_pre_attn_scalar is not None:
                d["query_pre_attn_scalar"] = self.query_pre_attn_scalar
            if self.attn_logit_softcap is not None:
                d["attn_logit_softcapping"] = self.attn_logit_softcap
            if self.final_logit_softcap is not None:
                d["final_logit_softcapping"] = self.final_logit_softcap
            if self.sliding_window is not None:
                d["sliding_window"] = self.sliding_window
            if self.layer_is_sliding is not None:
                d["layer_types"] = [
                    "sliding_attention" if s else "full_attention"
                    for s in self.layer_is_sliding
                ]
        if self.num_experts > 0:
            key = "num_local_experts" if model_type == "mixtral" else "num_experts"
            d[key] = self.num_experts
            d["num_experts_per_tok"] = self.num_experts_per_tok
            d["norm_topk_prob"] = True  # the routing this repo computes
            if self.moe_intermediate_size is not None:
                d["moe_intermediate_size"] = self.moe_intermediate_size
        if self.sliding_window is not None and model_type != "gemma2":
            d["sliding_window"] = self.sliding_window
            d["use_sliding_window"] = True
        if self.vision is not None:
            v = self.vision
            d["vision_config"] = {
                "patch_size": v.patch_size,
                "temporal_patch_size": v.temporal_patch_size,
                "in_channels": v.in_channels,
                "hidden_size": v.hidden_size,
                "intermediate_size": v.intermediate_size,
                "depth": v.num_layers,
                "num_heads": v.num_heads,
                "spatial_merge_size": v.spatial_merge_size,
                "out_hidden_size": v.out_hidden_size,
            }
            if v.window_size:
                d["vision_config"]["window_size"] = v.window_size
                d["vision_config"]["fullatt_block_indexes"] = list(
                    v.fullatt_block_indexes
                )
            if self.image_token_id is not None:
                d["image_token_id"] = self.image_token_id
            if self.mrope_section is not None:
                d["rope_scaling"] = {
                    "type": "mrope",
                    "mrope_section": list(self.mrope_section),
                }
        return d


# Handy presets for tests / benchmarks ------------------------------------

def tiny_config(**kw) -> TransformerConfig:
    base = dict(
        vocab_size=512,
        hidden_size=64,
        intermediate_size=128,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        max_position_embeddings=512,
        remat=False,
        dtype="float32",
    )
    base.update(kw)
    return TransformerConfig(**base)


def qwen25_1p5b() -> TransformerConfig:
    """Qwen2.5-1.5B shapes — the reference's small benchmark model class
    (BASELINE.md: 1.5B R1-Distill)."""
    return TransformerConfig(
        vocab_size=151936,
        hidden_size=1536,
        intermediate_size=8960,
        num_layers=28,
        num_heads=12,
        num_kv_heads=2,
        max_position_embeddings=32768,
        rope_theta=1000000.0,
        tie_word_embeddings=True,
        qkv_bias=True,
        hf_architecture="Qwen2ForCausalLM",
    )


def qwen2_0p6b_ctx() -> TransformerConfig:
    """Qwen2-class ~0.6B with head_dim 128 (splash-eligible): the largest
    shape whose 32k-context train step fits a 16G v5e chip — the on-chip
    long-context evidence model (VERDICT r2 #8).  Qwen2.5-0.5B itself has
    head_dim 64, which the splash kernel cannot tile."""
    return TransformerConfig(
        vocab_size=151936,
        hidden_size=1024,
        intermediate_size=5504,
        num_layers=24,
        num_heads=8,
        num_kv_heads=2,
        max_position_embeddings=32768,
        rope_theta=1000000.0,
        tie_word_embeddings=True,
        qkv_bias=True,
        hf_architecture="Qwen2ForCausalLM",
    )


def qwen25_7b() -> TransformerConfig:
    return TransformerConfig(
        vocab_size=152064,
        hidden_size=3584,
        intermediate_size=18944,
        num_layers=28,
        num_heads=28,
        num_kv_heads=4,
        max_position_embeddings=32768,
        rope_theta=1000000.0,
        qkv_bias=True,
        hf_architecture="Qwen2ForCausalLM",
    )
