"""Local search environment (reference: examples/search-agent capability
at corpus scale)."""

import asyncio

import pytest

from areal_tpu.agent.search_env import LocalSearchEnv

CORPUS = [
    "The capital of France is Paris, a major European city.",
    "Mount Everest is the highest mountain above sea level.",
    "The Pacific Ocean is the largest ocean on Earth.",
    "Paris hosted the Summer Olympics in 1900, 1924 and 2024.",
]


def test_search_ranking_and_misses():
    env = LocalSearchEnv(CORPUS, answer="Paris")
    hits = env.search("capital of France", k=2)
    assert hits and "Paris" in hits[0]
    assert env.search("quantum chromodynamics") == []
    assert env.n_searches == 2

    # both Paris passages rank above unrelated ones
    hits = env.search("Paris", k=4)
    assert all("Paris" in h for h in hits[:2])


def test_tool_surface():
    async def go():
        async with LocalSearchEnv(CORPUS, answer="Paris") as env:
            names = [t["name"] for t in env.list_tools()]
            assert names == ["search", "verify_answer"]
            hits, r, done = await env.aexecute_tool(
                "search", {"query": "highest mountain"}
            )
            assert not done and r == 0.0 and "Everest" in hits[0]
            _, reward, done = await env.aexecute_tool(
                "verify_answer",
                {"completion": "The answer is \\boxed{Paris}"},
            )
            assert done and reward == 1.0
            _, reward, _ = await env.aexecute_tool(
                "verify_answer", {"completion": "\\boxed{London}"}
            )
            assert reward == 0.0

    asyncio.run(go())
