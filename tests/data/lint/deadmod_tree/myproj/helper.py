"""Alive transitively: imported by used.py, which a root imports."""


def add(a, b):
    return a + b
