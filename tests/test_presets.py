"""Preset/auto-allocation tests (reference: experiments/common auto
device-mesh heuristics)."""

import pytest

from areal_tpu.api.alloc import AllocationMode
from areal_tpu.api.config import GRPOConfig, load_expr_config
from areal_tpu.api.presets import auto_allocation, list_presets, preset


def test_auto_allocation_small_model_many_chips():
    # 1.5B on 8 v5e chips: tp=1 suffices for serving; training needs
    # 1.5e9*10B ~ 15G > 14G -> tp=2
    expr = auto_allocation(8, 1.5e9, device_kind="TPU v5 lite")
    mode = AllocationMode.from_str(expr)
    assert mode.gen is not None and mode.train is not None
    assert mode.gen_world_size + mode.train_world_size <= 8
    assert mode.train_world_size >= 2


def test_auto_allocation_7b():
    expr = auto_allocation(32, 7.6e9, device_kind="TPU v5 lite")
    mode = AllocationMode.from_str(expr)
    # serving a 7B needs 7.6e9*3B ~ 23G -> tp=2 on 14G chips; training
    # state (~76G) shards over tp*fsdp (ZeRO-3), so the SHARD PRODUCT must
    # cover it — the search may trade tp for fsdp freely
    assert mode.gen_instance_size >= 2
    shards = mode.train.tensor_parallel_size * mode.train.fsdp_parallel_size
    assert 7.6e9 * 10 / shards <= 14 * 1024**3
    assert mode.gen_world_size + mode.train_world_size <= 32


def test_search_allocation_long_context_shards_activations():
    from areal_tpu.api.presets import search_allocation

    short = search_allocation(32, 7.6e9, ctx_len=4096, device_kind="TPU v5 lite")
    long = search_allocation(32, 7.6e9, ctx_len=32768, device_kind="TPU v5 lite")
    # 32k activations force more intra-replica sharding (tp and/or sp) on
    # the train side, and the KV budget forces wider serving tp
    assert (
        long["train_tp"] * long["train_sp"]
        > short["train_tp"] * short["train_sp"]
    )
    assert long["gen_tp"] > short["gen_tp"]
    # scored search keeps the system generation-bound balance: neither side
    # gets starved entirely
    assert long["n_gen"] >= long["n_train"]


def test_auto_allocation_infeasible():
    with pytest.raises(ValueError):
        auto_allocation(2, 70e9, device_kind="TPU v5 lite")
    with pytest.raises(ValueError):
        auto_allocation(1, 1e9)


def test_presets_are_loadable_configs(tmp_path):
    import yaml

    for name in list_presets():
        d = preset(name)
        assert AllocationMode.from_str(d["allocation_mode"])
        cfg_path = tmp_path / f"{name}.yaml"
        cfg_path.write_text(yaml.safe_dump(d))
        cfg, _ = load_expr_config(["--config", str(cfg_path)], GRPOConfig)
        assert cfg.actor.use_decoupled_loss
        assert cfg.train_dataset.batch_size > 0
