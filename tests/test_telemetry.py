"""Unified telemetry (ISSUE 10): registry/exposition units, the trajectory
event log + Chrome-trace export, and the three Prometheus /metrics surfaces
(gen server, router, trainer endpoint) scraped over real HTTP.

The metric-name sets served by each surface are pinned in
tests/data/metrics_schema.json — a missing name is a silent observability
regression even when nothing else fails."""

import json
import os
import urllib.request

import numpy as np
import pytest

from areal_tpu.utils import telemetry
from areal_tpu.utils.telemetry import (
    EventLog,
    Histogram,
    Registry,
    parse_prometheus_text,
    trace_key,
)

SCHEMA_PATH = os.path.join(os.path.dirname(__file__), "data",
                           "metrics_schema.json")


@pytest.fixture()
def enabled():
    """Enable telemetry for one test; restore flag + event log after."""
    was = telemetry.is_enabled()
    telemetry.set_enabled(True)
    telemetry.EVENTS.clear()
    yield
    telemetry.set_enabled(was)
    telemetry.EVENTS.clear()


def _type_lines(text: str):
    """{metric_name} declared via '# TYPE' — the schema unit (histograms
    expand to _bucket/_sum/_count sample names)."""
    out = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            out[name] = kind
    return out


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


def test_trace_key_stable_nonnegative_int64():
    k1 = trace_key("traj-0")
    assert k1 == trace_key("traj-0")  # deterministic across calls
    assert k1 != trace_key("traj-1")
    assert 0 <= k1 < 2**63
    assert isinstance(k1, int)
    # survives an int64 round-trip (how it rides inside batches)
    assert int(np.int64(k1)) == k1


def test_registry_render_parse_roundtrip():
    reg = Registry("t1")
    reg.counter("reqs_total", "requests").inc(3)
    reg.counter("reqs_total").inc(2, server="a")
    reg.gauge("depth", "queue depth").set(7)
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render_prometheus()
    parsed = parse_prometheus_text(text)
    assert parsed["areal_t1_reqs_total"][""] == 3
    assert parsed["areal_t1_reqs_total"]['{server="a"}'] == 2
    assert parsed["areal_t1_depth"][""] == 7
    # cumulative buckets + +Inf + sum/count
    b = parsed["areal_t1_lat_seconds_bucket"]
    assert b['{le="0.1"}'] == 1
    assert b['{le="1"}'] == 2
    assert b['{le="+Inf"}'] == 3
    assert parsed["areal_t1_lat_seconds_count"][""] == 3
    assert parsed["areal_t1_lat_seconds_sum"][""] == pytest.approx(5.55)
    kinds = _type_lines(text)
    assert kinds["areal_t1_reqs_total"] == "counter"
    assert kinds["areal_t1_depth"] == "gauge"
    assert kinds["areal_t1_lat_seconds"] == "histogram"


def test_registry_get_or_create_and_kind_conflict():
    reg = Registry("t2")
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")
    # already-prefixed names are not double-prefixed
    assert reg.counter("areal_custom_total").name == "areal_custom_total"


def test_collector_errors_do_not_fail_scrape():
    reg = Registry("t3")
    reg.add_collector(lambda: 1 / 0)
    ok = {"n": 0}

    def good():
        ok["n"] += 1
        reg.gauge("fine").set(1)

    reg.add_collector(good)
    text = reg.render_prometheus()
    assert "areal_t3_fine 1" in text
    assert reg.collector_errors == 1 and ok["n"] == 1


def test_histogram_staleness_buckets():
    h = Histogram("s", "", buckets=telemetry.STALENESS_BUCKETS)
    for v in (0, 0, 1, 5, 100):
        h.observe(v)
    samples = {(s, lab.get("le")): v for s, lab, v in h.samples()}
    assert samples[("_bucket", "0")] == 2
    assert samples[("_bucket", "1")] == 3
    assert samples[("_bucket", "6")] == 4
    assert samples[("_bucket", "+Inf")] == 5
    assert samples[("_count", None)] == 5


def test_event_log_disabled_is_noop():
    telemetry.set_enabled(False)
    log = EventLog(capacity=4)
    log.emit("submit", trace_id="t")
    assert len(log) == 0


def test_event_log_bounded_with_dropped_count(enabled):
    log = EventLog(capacity=4)
    for i in range(7):
        log.emit("e", trace_id=f"t{i}", idx=i)
    assert len(log) == 4
    assert log.dropped == 3
    evs = log.snapshot()
    assert [e["idx"] for e in evs] == [3, 4, 5, 6]  # oldest fell off
    assert all(e["trace_key"] == trace_key(e["trace_id"]) for e in evs)


def test_event_log_jsonl_and_chrome_trace(enabled, tmp_path):
    log = EventLog(capacity=64)
    log.emit("rollout_submit", trace_id="tr-1", input_len=8)
    log.emit("decode_chunk", tier=0, latency_s=0.25, trace_ids=["tr-1"])
    log.emit("gen_done", trace_id="tr-1", latency_s=1.0)
    jl = tmp_path / "events.jsonl"
    assert log.dump_jsonl(str(jl)) == 3
    lines = [json.loads(ln) for ln in jl.read_text().splitlines()]
    assert [e["event"] for e in lines] == ["rollout_submit", "decode_chunk",
                                          "gen_done"]
    trace = log.to_chrome_trace()
    by_name = {}
    for ev in trace["traceEvents"]:
        by_name.setdefault(ev["name"], []).append(ev)
    assert by_name["process_name"][0]["ph"] == "M"
    assert by_name["rollout_submit"][0]["ph"] == "i"  # instant
    done = by_name["gen_done"][0]
    assert done["ph"] == "X" and done["dur"] == pytest.approx(1e6)
    assert done["tid"] == trace_key("tr-1") % (2**31)
    ct = tmp_path / "trace.json"
    assert log.dump_chrome_trace(str(ct)) == 3
    json.loads(ct.read_text())  # valid JSON on disk


def test_publish_train_stats_mirrors_scalars(enabled):
    reg = telemetry.TRAIN
    before = reg.snapshot().get("areal_train_steps_total", 0)
    telemetry.publish_train_stats({
        "loss": 0.5, "grad_norm": 1.25, "step_time": 0.1,
        "total_loss_weight": 128.0, "not_a_number": object(),
    })
    snap = reg.snapshot()
    assert snap["areal_train_steps_total"] == before + 1
    assert snap["areal_train_step_loss"] == 0.5
    assert snap["areal_train_step_grad_norm"] == 1.25


# ---------------------------------------------------------------------------
# staleness manager export + capacity formula
# ---------------------------------------------------------------------------


def test_staleness_capacity_formula_and_metrics_export():
    from areal_tpu.core.staleness import StalenessManager

    bs, eta = 4, 2
    m = StalenessManager(max_concurrent_rollouts=64, consumer_batch_size=bs,
                         max_staleness=eta)
    reg = Registry("stale_t")
    m.register_metrics(reg)

    version = 0
    # churn through submit/accept/reject and check the invariant at every
    # step: accepted + running <= (eta + version + 1) * bs
    rng = np.random.default_rng(0)
    for step in range(200):
        cap = m.get_capacity(version)
        if cap > 0:
            m.on_rollout_submitted()
        else:
            st = m.get_stats()
            if st.running:
                (m.on_rollout_accepted if rng.integers(2)
                 else m.on_rollout_rejected)()
            else:
                version += 1  # trainer consumed a batch
        st = m.get_stats()
        assert st.accepted + st.running <= (eta + version + 1) * bs, (
            step, st, version
        )
    snap = reg.snapshot()
    st = m.get_stats()
    assert snap["areal_stale_t_rollout_submitted"] == st.submitted
    assert snap["areal_stale_t_rollout_running"] == st.running
    assert snap["areal_stale_t_rollout_accepted"] == st.accepted


# ---------------------------------------------------------------------------
# the three HTTP surfaces
# ---------------------------------------------------------------------------


def _scrape(addr_or_url: str):
    url = (addr_or_url if addr_or_url.startswith("http")
           else f"http://{addr_or_url}/metrics?format=prometheus")
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.status == 200
        return resp.read().decode()


@pytest.fixture(scope="module")
def gen_server():
    import jax

    from areal_tpu.gen.engine import GenEngine
    from areal_tpu.models import init_params
    from areal_tpu.models.model_config import tiny_config

    from tests.test_gen_server_integration import _boot_server

    cfg = tiny_config(vocab_size=89, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = GenEngine(cfg, params=params, n_slots=4, max_seq_len=96,
                       prompt_bucket=16)
    server, addr, stop = _boot_server(engine)
    yield engine, server, addr
    stop()


def _generate(addr, rid, n_new=4):
    req = urllib.request.Request(
        f"http://{addr}/generate",
        data=json.dumps({
            "rid": rid,
            "input_ids": [5, 6, 7],
            "sampling_params": {"max_new_tokens": n_new,
                                "temperature": 0.0},
        }).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_gen_server_prometheus_and_json_coexist(gen_server):
    engine, server, addr = gen_server
    _generate(addr, "m-0")
    # default stays the legacy JSON dict
    with urllib.request.urlopen(f"http://{addr}/metrics", timeout=10) as r:
        legacy = json.loads(r.read())
    assert "decode_steps" in legacy and "prefill_tokens" in legacy
    # Prometheus by query param and by Accept header
    text = _scrape(addr)
    parsed = parse_prometheus_text(text)
    assert parsed["areal_gen_prefill_tokens_total"][""] > 0
    assert "areal_gen_pause_window_seconds" in _type_lines(text)
    req = urllib.request.Request(f"http://{addr}/metrics",
                                 headers={"Accept": "text/plain"})
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.read().decode().startswith("# ")


def test_gen_server_counters_never_decrease(gen_server):
    _, _, addr = gen_server
    before = parse_prometheus_text(_scrape(addr))
    for i in range(3):
        _generate(addr, f"mono-{i}")
    after = parse_prometheus_text(_scrape(addr))
    checked = 0
    for name, series in before.items():
        if not name.endswith("_total"):
            continue
        for labels, v in series.items():
            assert after[name][labels] >= v, (name, labels)
            checked += 1
    assert checked > 5
    # activity moved the generation counters
    assert (after["areal_gen_tokens_generated_total"][""]
            > before["areal_gen_tokens_generated_total"][""])


def test_gen_server_json_metrics_survive_missing_stats_key(gen_server):
    """Satellite 1: a stats-key rename must degrade the counter to 0, not
    500 the whole scrape."""
    engine, _, addr = gen_server
    removed = engine.stats.pop("reservations_lapsed")
    try:
        with urllib.request.urlopen(f"http://{addr}/metrics",
                                    timeout=10) as r:
            assert r.status == 200
            legacy = json.loads(r.read())
        assert legacy["reservations_lapsed"] == 0
        # the Prometheus side mirrors the dict generically: still 200
        assert "areal_gen_prefill_calls_total" in _scrape(addr)
    finally:
        engine.stats["reservations_lapsed"] = removed


def test_gen_server_spec_decode_telemetry(enabled):
    """Spec decode (ISSUE 12): draft/accept counters, the per-tier
    acceptance-rate gauge, and spec_verify lifecycle spans all ride the
    gen surface when speculative decoding is live."""
    import jax

    from areal_tpu.gen.engine import GenEngine
    from areal_tpu.models import init_params
    from areal_tpu.models.model_config import tiny_config

    from tests.test_gen_server_integration import _boot_server

    cfg = tiny_config(vocab_size=89, qkv_bias=True,
                      hf_architecture="Qwen2ForCausalLM", eos_token_id=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    engine = GenEngine(cfg, params=params, n_slots=4, max_seq_len=96,
                       prompt_bucket=16, spec_decode=True, spec_draft_len=3)
    _, addr, stop = _boot_server(engine)
    try:
        req = urllib.request.Request(
            f"http://{addr}/generate",
            data=json.dumps({
                "rid": "spec-tel-0",
                "input_ids": [5, 6, 7] * 4,  # periodic: prompt lookup hits
                "sampling_params": {"max_new_tokens": 12,
                                    "temperature": 0.0},
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
        assert len(out["output_tokens"]) == 12
        assert engine.stats["spec_drafted"] > 0
        assert engine.stats["verify_calls"] > 0
        parsed = parse_prometheus_text(_scrape(addr))
        assert parsed["areal_gen_spec_drafted_total"][""] > 0
        assert parsed["areal_gen_verify_calls_total"][""] > 0
        rate = parsed["areal_gen_spec_acceptance_rate"]
        assert "" in rate  # lifetime rate
        assert any(lab.startswith('{tier=') for lab in rate)
        # the legacy JSON dict carries the same accounting
        with urllib.request.urlopen(f"http://{addr}/metrics",
                                    timeout=10) as r:
            legacy = json.loads(r.read())
        assert legacy["spec_drafted"] == engine.stats["spec_drafted"]
        assert 0.0 <= legacy["spec_acceptance_rate"] <= 1.0
        # every verify dispatch leaves a spec_verify lifecycle span
        evs = [e for e in telemetry.EVENTS.snapshot()
               if e["event"] == "spec_verify"]
        assert evs, "no spec_verify lifecycle events recorded"
        assert evs[0]["drafted"] >= 1
        assert "latency_s" in evs[0] and "tier" in evs[0]
    finally:
        stop()


@pytest.fixture()
def router_addr():
    from areal_tpu.gen.router import Router, RouterConfig

    from tests.fake_server import FakeGenServer
    from tests.test_router import RouterHarness

    backends = [FakeGenServer(completion=[1, 2]) for _ in range(2)]
    addrs = [s.start() for s in backends]
    router = Router(RouterConfig(train_batch_size=2, schedule_policy="round_robin"),
                    addresses=addrs)
    h = RouterHarness(router)
    yield h.start()
    h.stop()
    for s in backends:
        s.stop()


def test_router_prometheus_exposition(router_addr):
    addr = router_addr
    # route traffic + take a lease so every ledger field is non-trivial
    req = urllib.request.Request(
        f"http://{addr}/generate",
        data=json.dumps({"rid": "r0", "input_ids": [1, 2, 3],
                         "sampling_params": {"max_new_tokens": 4}}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 200
    alloc = urllib.request.Request(
        f"http://{addr}/allocate_request", data=b"{}",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(alloc, timeout=10) as r:
        assert json.loads(r.read())["staled"] is False
    # JSON default unchanged
    with urllib.request.urlopen(f"http://{addr}/metrics", timeout=10) as r:
        legacy = json.loads(r.read())
    assert sum(legacy["requests_routed"].values()) == 1
    assert legacy["running"] == 1
    text = _scrape(addr)
    parsed = parse_prometheus_text(text)
    assert sum(parsed["areal_router_requests_routed_total"].values()) == 1
    assert parsed["areal_router_rollout_running"][""] == 1
    # capacity = (0 + 0 + 1) * 2 - 1 lease
    assert parsed["areal_router_admission_capacity"][""] == 1


def test_trainer_metrics_endpoint(enabled):
    reg = Registry("train_ep")
    reg.counter("steps_total", "steps").inc(3)
    reg.histogram("staleness_at_consumption", "s",
                  buckets=telemetry.STALENESS_BUCKETS).observe(1)
    srv, port = telemetry.start_metrics_server(reg)
    try:
        text = _scrape(f"http://127.0.0.1:{port}/metrics")
        parsed = parse_prometheus_text(text)
        assert parsed["areal_train_ep_steps_total"][""] == 3
        assert (parsed["areal_train_ep_staleness_at_consumption_count"][""]
                == 1)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics?format=json", timeout=10
        ) as r:
            snap = json.loads(r.read())
        assert snap["areal_train_ep_steps_total"] == 3
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=10
        ) as r:
            assert json.loads(r.read())["status"] == "ok"
    finally:
        srv.shutdown()


def test_metrics_schema_pinned(gen_server, router_addr, enabled):
    """Every name in tests/data/metrics_schema.json must be served by its
    surface — renames/deletions break dashboards silently otherwise."""
    with open(SCHEMA_PATH) as f:
        schema = json.load(f)
    _, _, gaddr = gen_server
    _generate(gaddr, "schema-0")
    # touch every router ledger so the labeled series exist in this process
    req = urllib.request.Request(
        f"http://{router_addr}/generate",
        data=json.dumps({"rid": "schema-r", "input_ids": [1, 2, 3],
                         "sampling_params": {"max_new_tokens": 4}}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.status == 200
    telemetry.publish_train_stats({"loss": 0.1, "grad_norm": 1.0,
                                   "step_time": 0.01,
                                   "total_loss_weight": 8.0})
    srv, port = telemetry.start_metrics_server(telemetry.TRAIN)
    try:
        surfaces = {
            "gen": _type_lines(_scrape(gaddr)),
            "router": _type_lines(_scrape(router_addr)),
            "train": _type_lines(_scrape(f"http://127.0.0.1:{port}/metrics")),
        }
    finally:
        srv.shutdown()
    for surface, pinned in schema.items():
        served = surfaces[surface]
        missing = [n for n in pinned if n not in served]
        assert not missing, f"{surface} /metrics lost {missing}"
        assert all(n.startswith("areal_") for n in served)


def test_router_backend_state_gauge_tracks_breaker():
    """ISSUE 11: areal_router_backend_state must expose the circuit-breaker
    code per backend (0=closed, 2=open) so dashboards can see a dead fleet
    member.  Runs after the exposition test above: a labeled scrape leaves
    per-server samples in the shared ROUTER registry, which would skew that
    test's exact-sum assertions if scraped earlier."""
    import time

    from areal_tpu.gen.router import Router, RouterConfig

    from tests.fake_server import FakeGenServer
    from tests.test_router import RouterHarness

    backends = [FakeGenServer(completion=[1, 2]) for _ in range(2)]
    addrs = [s.start() for s in backends]
    router = Router(
        RouterConfig(
            schedule_policy="round_robin",
            health_check_interval=0.1,
            health_failure_threshold=1,
            health_probe_timeout=0.5,
        ),
        addresses=addrs,
    )
    h = RouterHarness(router)
    raddr = h.start()
    try:
        backends[0].stop()
        deadline = time.monotonic() + 10
        text = ""
        while time.monotonic() < deadline:
            text = _scrape(raddr)
            if f'areal_router_backend_state{{server="{addrs[0]}"}} 2' in text:
                break
            time.sleep(0.05)
        assert f'areal_router_backend_state{{server="{addrs[0]}"}} 2' in text
        assert f'areal_router_backend_state{{server="{addrs[1]}"}} 0' in text
        parsed = parse_prometheus_text(text)
        assert "areal_router_failovers_total" in parsed
        assert "areal_publish_partial_failures_total" in parsed
    finally:
        h.stop()
        backends[1].stop()


# ---------------------------------------------------------------------------
# lifecycle events through the live server
# ---------------------------------------------------------------------------


def test_trace_id_rides_the_wire_and_events_join(gen_server, enabled):
    import asyncio

    from areal_tpu.api.config import (
        GenerationHyperparameters,
        InferenceEngineConfig,
    )
    from areal_tpu.api.io_struct import ModelRequest
    from areal_tpu.engine.jax_remote import RemoteJaxEngine

    _, _, addr = gen_server
    client = RemoteJaxEngine(InferenceEngineConfig(
        experiment_name="tt", trial_name="t", consumer_batch_size=2,
        max_concurrent_rollouts=8, request_timeout=30,
        max_head_offpolicyness=100,
    ))
    client.initialize(addr=addr)
    try:
        resp = asyncio.run(client.agenerate(ModelRequest(
            rid="wire-1", trace_id="wire-1", input_ids=[5, 6, 7],
            gconfig=GenerationHyperparameters(max_new_tokens=4, greedy=True),
        )))
        assert len(resp.output_tokens) == 4
    finally:
        client.destroy()
    evs = telemetry.EVENTS.snapshot()
    mine = [e for e in evs if e.get("trace_id") == "wire-1"]
    names = [e["event"] for e in mine]
    # client-side submit + completion spans...
    assert "rollout_submit" in names and "gen_done" in names
    # ...joined with SERVER-side admission/prefill spans via the wire id
    assert "admission" in names and "prefill" in names
    prefill = next(e for e in mine if e["event"] == "prefill")
    assert prefill["total_tokens"] >= 3
    assert prefill["cold_tokens"] + prefill["inherited_tokens"] == (
        prefill["total_tokens"]
    )
    done = next(e for e in mine if e["event"] == "gen_done")
    assert done["output_len"] == 4 and done["attempts"] == 1
    # decode chunks carry the trace id in their per-tier id lists
    chunks = [e for e in evs if e["event"] == "decode_chunk"]
    assert any("wire-1" in e.get("trace_ids", ()) for e in chunks)


# ---------------------------------------------------------------------------
# paired clocks + drop accounting (ISSUE 14 satellites)
# ---------------------------------------------------------------------------


def test_events_carry_paired_clocks_and_pid(enabled):
    """Every event records wall ts (cross-process joins), a perf_counter
    mono stamp (NTP-immune single-process decomposition), and the
    emitting pid so the analyzer knows when mono is comparable."""
    telemetry.emit("rollout_submit", trace_id="clk-1", input_len=4)
    telemetry.emit("gen_done", trace_id="clk-1", latency_s=0.1)
    evs = [e for e in telemetry.EVENTS.snapshot()
           if e.get("trace_id") == "clk-1"]
    assert len(evs) == 2
    for e in evs:
        assert e["pid"] == os.getpid()
        assert e["mono"] > 0 and e["ts"] > 0
    assert evs[1]["mono"] >= evs[0]["mono"]
    assert evs[1]["ts"] >= evs[0]["ts"]


def test_dump_jsonl_meta_trailer_records_drops(enabled, tmp_path):
    """A ring that overflowed must say so in the dump itself — the
    telemetry_meta trailer is what marks the log lossy for the trace
    analyzer (a lossless dump carries no trailer)."""
    log = EventLog(capacity=2)
    log.emit("e", trace_id="t0")
    jl = tmp_path / "lossless.jsonl"
    assert log.dump_jsonl(str(jl)) == 1
    assert "telemetry_meta" not in jl.read_text()

    for i in range(5):
        log.emit("e", trace_id=f"t{i}")
    jl2 = tmp_path / "lossy.jsonl"
    n = log.dump_jsonl(str(jl2))
    lines = [json.loads(ln) for ln in jl2.read_text().splitlines()]
    assert n == len(lines) == 3  # 2 events + the trailer
    meta = lines[-1]
    assert meta["event"] == "telemetry_meta"
    assert meta["dropped_events"] == log.dropped == 4
    assert meta["capacity"] == 2


def test_events_dropped_total_on_all_three_surfaces(enabled):
    """areal_telemetry_events_dropped_total mirrors EVENTS.dropped on the
    gen, router, AND train registries (scrape-time collector), so any
    surface can alarm on lifecycle-evidence loss."""
    name = "areal_telemetry_events_dropped_total"
    before = telemetry.EVENTS.dropped
    try:
        telemetry.EVENTS.dropped = before + 7
        for reg in (telemetry.GEN, telemetry.ROUTER, telemetry.TRAIN):
            snap = reg.snapshot()
            assert snap[name] == before + 7, reg.namespace
            parsed = parse_prometheus_text(reg.render_prometheus())
            assert parsed[name][""] == before + 7
    finally:
        telemetry.EVENTS.dropped = before


def test_partial_failure_counter_registered_eagerly():
    """Regression (ISSUE 18 / C9 metric-contract): the control-plane
    fanout partial-failure counter must be a module-level pinned metric —
    the lazy per-failure construction left it off the scrape surface
    until the first failure, unverifiable by the schema pin."""
    text = telemetry.TRAIN.render_prometheus()
    assert "areal_train_publish_partial_failures_total" in text
    # get-or-create resolves to the same eagerly-registered instance
    assert telemetry.PUBLISH_PARTIAL_FAILURES is telemetry.TRAIN.counter(
        "publish_partial_failures_total"
    )
