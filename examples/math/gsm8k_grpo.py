"""GSM8K GRPO — the canonical train loop.

Line-for-line behavioral counterpart of the reference's
`examples/math/gsm8k_grpo.py:34-255`: load config → connect rollout client
(+ a dedicated eval-rollout client with unlimited staleness, :79-90) →
init actor (+ optional ref model when kl_ctl > 0, :89-93) → per step:
prepare_batch (async) or rollout_batch (sync), recompute prox logp, ref
logp, compute advantages, ppo_update, push weights, save/recover, evaluate
each save (:222-240), log stats.

Launch:  python examples/math/gsm8k_grpo.py --config examples/math/gsm8k_grpo.yaml
(or via the launcher, which also starts generation servers:
 python -m areal_tpu.launcher.local examples/math/gsm8k_grpo.py --config ...)
"""

import os
import sys

import numpy as np

from areal_tpu.api.config import GRPOConfig, load_expr_config, to_dict
from areal_tpu.api.io_struct import FinetuneSpec, StepInfo, WeightUpdateMeta
from areal_tpu.engine.jax_remote import RemoteJaxEngine
from areal_tpu.engine.ppo import JaxPPOActor
from areal_tpu.dataset import get_custom_dataset
from areal_tpu.reward import gsm8k_reward_fn
from areal_tpu.utils import logging, seeding, stats
from areal_tpu.utils.dataloader import StatefulDataLoader
from areal_tpu.utils.evaluator import Evaluator
from areal_tpu.utils.recover import (
    RecoverHandler,
    check_if_recover,
    config_fingerprint,
)
from areal_tpu.utils.saver import Saver
from areal_tpu.utils.shutdown import PreemptionGuard, preempt_exit
from areal_tpu.utils.stats_logger import StatsLogger
from areal_tpu.workflow.rlvr import RLVRWorkflow

logger = logging.getLogger("gsm8k_grpo")


def main(argv):
    config, _ = load_expr_config(argv, GRPOConfig)
    seeding.set_random_seed(config.seed, "trainer")
    # SIGTERM/SIGINT -> dump + resume-code exit at the next step boundary
    guard = PreemptionGuard().install()

    tokenizer = None
    if config.tokenizer_path:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(config.tokenizer_path)

    train_dataset = get_custom_dataset(
        path=config.train_dataset.path,
        type=config.train_dataset.type,
        split="train",
        tokenizer=tokenizer,
        max_length=config.train_dataset.max_length,
    )
    dataloader = StatefulDataLoader(
        train_dataset,
        batch_size=config.train_dataset.batch_size,
        shuffle=config.train_dataset.shuffle,
        drop_last=config.train_dataset.drop_last,
        seed=config.seed,
    )
    ft_spec = FinetuneSpec(
        total_train_epochs=config.total_train_epochs,
        dataset_size=len(train_dataset),
        train_batch_size=config.train_dataset.batch_size,
    )

    # rollout client against the generation servers
    rollout = RemoteJaxEngine(config.rollout)
    rollout.initialize(train_data_parallel_size=1)

    # dedicated eval client: eval has no off-policyness control (reference:
    # examples/math/gsm8k_grpo.py:79-83)
    import copy

    eval_rollout = RemoteJaxEngine(copy.deepcopy(config.rollout))
    eval_rollout.config.max_head_offpolicyness = int(1e12)
    eval_rollout.initialize(train_data_parallel_size=1)

    valid_dataset = get_custom_dataset(
        path=config.valid_dataset.path,
        type=config.valid_dataset.type,
        split="test",
        tokenizer=tokenizer,
        max_length=config.valid_dataset.max_length,
    ) if config.valid_dataset is not None else None

    actor = JaxPPOActor(config.actor)
    actor.create_process_group()
    actor.initialize(ft_spec=ft_spec)

    # frozen reference model for the KL-regularized reward (reference:
    # examples/math/gsm8k_grpo.py:89-93)
    ref = None
    if config.actor.kl_ctl > 0 and config.ref is not None:
        from areal_tpu.engine.jax_train import JaxTrainEngine

        ref = JaxTrainEngine(config.ref)
        ref.create_process_group()
        ref.initialize(ft_spec=ft_spec)

    if config.weight_update_mode == "transfer":
        weight_meta = WeightUpdateMeta.from_transfer(
            config.experiment_name, config.trial_name,
            live_commit=config.weight_update_live_commit,
        )
    else:
        weight_meta = WeightUpdateMeta.from_disk(
            config.experiment_name, config.trial_name, config.cluster.fileroot
        )

    from areal_tpu.api.reward import prewarm_reward_pool

    prewarm_reward_pool()
    if config.workflow == "multi_turn":
        from areal_tpu.workflow.multi_turn import MultiTurnWorkflow

        workflow = MultiTurnWorkflow(
            reward_fn=gsm8k_reward_fn,
            gconfig=config.gconfig,
            tokenizer=tokenizer,
            max_turns=config.max_turns,
            turn_discount=config.turn_discount,
        )
    elif config.workflow == "tir":
        # tool-integrated reasoning: sandboxed ```python execution
        # mid-rollout (agent/tir_agent.py; reference: examples/tir)
        from areal_tpu.agent import AgentWorkflow, TIRMathAgent
        from areal_tpu.agent.math_env import MathVerifyEnv

        workflow = AgentWorkflow(
            TIRMathAgent(config.gconfig, tokenizer=tokenizer),
            env_factory=lambda data: MathVerifyEnv(answer=data["answer"]),
        )
    elif config.workflow == "countdown":
        # arithmetic-game RL (reference: examples/countdown) — dataset rows
        # carry (numbers, target); the env verifies the boxed expression
        from areal_tpu.agent import AgentWorkflow, MathSingleStepAgent
        from areal_tpu.agent.countdown_env import CountdownEnv

        workflow = AgentWorkflow(
            MathSingleStepAgent(config.gconfig, tokenizer=tokenizer),
            env_factory=lambda data: CountdownEnv(
                data["numbers"], data["target"]
            ),
        )
    elif config.workflow == "search":
        # search-agent RL (reference: examples/search-agent) — the model
        # issues <search> queries against the episode's corpus mid-rollout
        from areal_tpu.agent import AgentWorkflow, SearchQAAgent
        from areal_tpu.agent.search_env import LocalSearchEnv

        workflow = AgentWorkflow(
            SearchQAAgent(config.gconfig, tokenizer=tokenizer),
            # the dataset attaches one shared BM25 index; per-row corpora
            # (no index) still work, just slower
            env_factory=lambda data: LocalSearchEnv(
                data["corpus"], data["answer"],
                index=data.get("_search_index"),
            ),
        )
    elif config.workflow != "rlvr":
        raise ValueError(
            f"unknown workflow {config.workflow!r}; use 'rlvr', "
            "'multi_turn', 'tir', 'countdown', or 'search'"
        )
    else:
        workflow = RLVRWorkflow(
            reward_fn=gsm8k_reward_fn,
            gconfig=config.gconfig,
            tokenizer=tokenizer,
            dump_dir=os.path.join(
                StatsLogger.get_log_path(config.stats_logger), "generated"
            ),
        )
    # greedy single-sample workflow for eval (reference :109-117)
    eval_workflow = RLVRWorkflow(
        reward_fn=gsm8k_reward_fn,
        gconfig=config.gconfig.new(n_samples=1, temperature=0.0),
        tokenizer=tokenizer,
        rollout_stat_scope="eval-rollout",
        dump_dir=os.path.join(
            StatsLogger.get_log_path(config.stats_logger), "generated-eval"
        ),
    )

    saver = Saver(config.saver, ft_spec)
    checkpointer = Saver(config.checkpointer, ft_spec, for_recover=True)
    evaluator = Evaluator(config.evaluator, ft_spec)
    stats_logger = StatsLogger(config.stats_logger)
    recover = RecoverHandler(
        config.recover, ft_spec, fingerprint=config_fingerprint(to_dict(config))
    )
    # everything a force-dump needs, shared by the periodic dump and the
    # preemption retreat
    dump_kwargs = dict(
        saver=saver, evaluator=evaluator, stats_logger=stats_logger,
        dataloader=dataloader, tokenizer=tokenizer, inference_engine=rollout,
    )

    start_step = 0
    if check_if_recover(config.recover, run_id=int(os.environ.get("AREAL_RUN_ID", 0))):
        info = recover.load(
            actor,
            saver=saver,
            evaluator=evaluator,
            stats_logger=stats_logger,
            dataloader=dataloader,
            inference_engine=rollout,
            weight_update_meta=weight_meta,
        )
        if info is not None:
            start_step = info.recover_start.global_step

    if config.warm_pack_shapes:
        # AOT-compile the expected pack signatures so the first steps don't
        # stall on XLA compiles as rollout lengths vary
        actor.warm_shapes([tuple(s) for s in config.warm_pack_shapes])

    total_steps = config.total_train_steps or ft_spec.total_train_steps
    steps_per_epoch = ft_spec.steps_per_epoch

    for global_step in range(start_step, total_steps):
        epoch = global_step // steps_per_epoch
        epoch_step = global_step % steps_per_epoch
        step_info = StepInfo(
            epoch=epoch, epoch_step=epoch_step, global_step=global_step,
            steps_per_epoch=steps_per_epoch,
        )

        with stats.record_timing("rollout"):
            if config.async_training:
                batch = rollout.prepare_batch(dataloader, workflow=workflow)
            else:
                batch = rollout.rollout_batch(
                    next(iter_or_cycle(dataloader)), workflow=workflow
                )

        if config.actor.recompute_logprob:
            with stats.record_timing("recompute_logp"):
                batch["prox_logp"] = actor.compute_logp(batch)

        if ref is not None:
            with stats.record_timing("ref_logp"):
                batch["ref_logp"] = ref.forward(batch)

        with stats.record_timing("compute_advantages"):
            actor.compute_advantages(batch)

        with stats.record_timing("ppo_update"):
            train_stats = actor.ppo_update(batch)
            actor.step_lr_scheduler()

        # the expensive half (snapshot write / chunk streaming) runs while
        # generation continues; only the swap needs the pause — timed
        # separately so the pause-window cost stays visible in the stats
        with stats.record_timing("stage_weights"):
            actor.set_version(global_step + 1)
            actor.stage_weights(weight_meta)
        with stats.record_timing("update_weights"):
            # a live transfer commit swaps without aborting — the server
            # keeps decoding through the publish, so the client pipeline
            # need not pause; only the abort choreography drains in-flight
            live = (weight_meta.type == "transfer"
                    and weight_meta.live_commit)
            if not live:
                rollout.pause()
            actor.update_weights(weight_meta)
            rollout.update_weights(weight_meta)
            rollout.set_version(global_step + 1)
            eval_rollout.set_version(global_step + 1)
            if not live:
                rollout.resume()

        with stats.record_timing("save_eval"):
            saver.save(actor, epoch, epoch_step, global_step, tokenizer=tokenizer)
            if checkpointer.freq.check(epoch, global_step):
                recover.dump(actor, step_info, **dump_kwargs)

        with stats.record_timing("eval"):
            # evaluate the freshly pushed weights on the held-out split
            # (reference :222-240: submit every eval prompt, wait for all)
            def evaluate_fn():
                if valid_dataset is None:
                    return None
                eval_batch = eval_rollout.rollout_batch(
                    list(valid_dataset), workflow=eval_workflow
                )
                rew = np.asarray(eval_batch["rewards"], np.float32)
                result = {"eval_reward_mean": float(rew.mean()),
                          "eval_n": int(rew.size)}
                stats.scalar(**result)
                return result

            evaluator.evaluate(evaluate_fn, epoch, epoch_step, global_step)

        # async_stats: materialise any deferred train-step stats (their
        # tracker commits run here) before exporting the step's metrics —
        # by now the device has finished, so this costs one cheap transfer
        actor.flush_stats()
        reward_mean = float(np.mean(batch["rewards"])) if "rewards" in batch else 0.0
        stats.scalar(reward=reward_mean, n_seqs=len(batch.get("rewards", [])))
        stats_logger.commit(
            epoch, epoch_step, global_step,
            [stats.export()] + train_stats,
        )
        logger.info(
            f"Epoch {epoch + 1}/{config.total_train_epochs} "
            f"Step {epoch_step + 1}/{steps_per_epoch} "
            f"(global {global_step + 1}/{total_steps}) done. "
            f"reward={reward_mean:.3f}"
        )

        if guard.requested:
            # preemption announced: the step just completed is the dump
            # point, so the relaunch loses zero steps
            preempt_exit(
                recover, actor, step_info,
                rollout_engines=(rollout, eval_rollout),
                dump_kwargs=dump_kwargs,
            )

    rollout.destroy()
    eval_rollout.destroy()
    stats_logger.close()


def iter_or_cycle(dataloader):
    while True:
        yield from dataloader


if __name__ == "__main__":
    main(sys.argv[1:])
