"""Code-verifier service: sandboxed execution on a separate host.

Counterpart of the reference's FaaS verification path
(/root/reference/functioncall/ — code verification runs as a remote
service so untrusted generated code never shares the rollout host;
VERDICT r3 missing #5).  The local rlimit sandbox
(reward/code_verifier.py) stays the fallback and the execution engine;
this module adds the deployment seam:

    python -m areal_tpu.reward.code_verifier_service --port 8391

    AREAL_CODE_VERIFIER_ADDR=host:8391  # reward fns now POST /verify

Wire format (POST /verify):
    {"generation": str, "problem": {...}, "timeout": float?,
     "max_cases": int?}
 -> {"reward": 0.0|1.0, "results": [{"passed": bool, "reason": str}, ...]}

Verification subprocesses are CPU-bound and blocking, so the handler runs
them on a thread pool sized to the host; the aiohttp loop stays free to
absorb the rollout fleet's bursts.
"""

import argparse
import asyncio
import concurrent.futures
import os
from dataclasses import asdict

from areal_tpu.reward.code_verifier import (
    DEFAULT_TIMEOUT,
    verify_code,
)
from areal_tpu.utils import logging, network

logger = logging.getLogger("code_verifier_service")


class CodeVerifierService:
    def __init__(self, max_workers: int = 4):
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="verify"
        )
        self.n_served = 0

    async def verify(self, request):
        from aiohttp import web

        try:
            payload = await request.json()
            generation = payload["generation"]
            problem = payload["problem"]
        except (KeyError, ValueError) as e:
            return web.json_response(
                {"error": f"bad request: {e}"}, status=400
            )
        loop = asyncio.get_running_loop()
        try:
            results = await loop.run_in_executor(
                self._pool,
                lambda: verify_code(
                    generation,
                    problem,
                    timeout=float(payload.get("timeout", DEFAULT_TIMEOUT)),
                    max_cases=payload.get("max_cases"),
                ),
            )
        except ValueError as e:  # malformed problem spec
            return web.json_response({"error": str(e)}, status=400)
        self.n_served += 1
        return web.json_response(
            {
                "reward": 1.0 if results and all(r.passed for r in results) else 0.0,
                "results": [
                    {k: v for k, v in asdict(r).items() if k != "stdout"}
                    for r in results
                ],
            }
        )

    async def health(self, request):
        from aiohttp import web

        return web.json_response({"status": "ok", "served": self.n_served})

    def app(self):
        from aiohttp import web

        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_post("/verify", self.verify)
        app.router.add_get("/health", self.health)
        return app


import threading as _threading

_session_local = _threading.local()


def remote_verify_reward(
    addr: str,
    generation: str,
    problem,
    timeout: float = DEFAULT_TIMEOUT,
    max_cases=None,
    request_timeout: float = 120.0,
) -> float:
    """Client half: POST the submission to a verifier service.  Raises on
    transport errors so the caller can fall back to the local sandbox (or
    fail closed under AREAL_CODE_VERIFIER_STRICT).  Reward calls are the
    hot path, so connections keep alive via a thread-local session."""
    import requests

    session = getattr(_session_local, "session", None)
    if session is None:
        session = _session_local.session = requests.Session()
    r = session.post(
        f"http://{addr}/verify",
        json={
            "generation": generation,
            "problem": problem,
            "timeout": timeout,
            "max_cases": max_cases,
        },
        timeout=request_timeout,
    )
    r.raise_for_status()
    return float(r.json()["reward"])


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--max-workers", type=int, default=max(2, os.cpu_count() or 2))
    args = p.parse_args()
    from aiohttp import web

    port = args.port or network.find_free_port()
    logger.info(f"code verifier service on :{port}")
    web.run_app(
        CodeVerifierService(max_workers=args.max_workers).app(),
        port=port,
        print=None,
    )


if __name__ == "__main__":
    main()
