"""Device profiling + FLOPs/MFU accounting.

Capability counterpart of the reference's monitoring stack
(realhf/base/monitor.py:404-678 kineto CUDA kernel-time categorisation,
realhf/base/flops_counter.py): on TPU the device timeline comes from
`jax.profiler` (xplane traces viewable in TensorBoard/Perfetto) and FLOPs
from the analytic transformer model below, folded into per-step MFU that
the train engine reports with every batch.
"""

import contextlib
from typing import Optional

import jax

from areal_tpu.models.model_config import TransformerConfig

# peak bf16 TFLOP/s by device kind (known TPU generations)
PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def device_peak_tflops(device=None) -> Optional[float]:
    kind = (device or jax.devices()[0]).device_kind
    for k in sorted(PEAK_TFLOPS, key=len, reverse=True):
        if kind.startswith(k):
            return PEAK_TFLOPS[k]
    return None


def param_count(cfg: TransformerConfig) -> int:
    """Analytic parameter count of the dense/MoE transformer."""
    D, F, V, L = (
        cfg.hidden_size,
        cfg.intermediate_size,
        cfg.vocab_size,
        cfg.num_layers,
    )
    attn = D * (cfg.q_size + 2 * cfg.kv_size) + cfg.q_size * D
    if cfg.num_experts > 0:
        Fm = cfg.moe_intermediate_size or F
        ffn = cfg.num_experts * 3 * D * Fm + D * cfg.num_experts
    else:
        ffn = 3 * D * F
    embed = V * D * (1 if cfg.tie_word_embeddings else 2)
    return L * (attn + ffn + 2 * D) + embed + D


def train_flops_per_token(cfg: TransformerConfig, ctx_len: int) -> float:
    """fwd+bwd FLOPs per trained token: the standard 6P matmul estimate
    (active params only for MoE) plus causal attention's 6*L*D_attn*ctx
    term, which dominates at long context."""
    P = param_count(cfg)
    if cfg.num_experts > 0:
        Fm = cfg.moe_intermediate_size or cfg.intermediate_size
        dense_share = cfg.num_experts_per_tok * 3 * cfg.hidden_size * Fm
        all_experts = cfg.num_experts * 3 * cfg.hidden_size * Fm
        P = P - cfg.num_layers * (all_experts - dense_share)
    attn = 6 * cfg.num_layers * cfg.q_size * ctx_len / 2  # causal half
    return 6.0 * P + 2.0 * attn  # qk^T and pv matmuls, fwd+bwd


def mfu(
    tokens_per_sec: float,
    cfg: TransformerConfig,
    ctx_len: int,
    n_chips: int = 1,
    peak_tflops: Optional[float] = None,
) -> Optional[float]:
    peak = peak_tflops or device_peak_tflops()
    if not peak:
        return None
    achieved = tokens_per_sec * train_flops_per_token(cfg, ctx_len) / 1e12
    return achieved / (peak * n_chips)


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """jax.profiler device trace scope; no-op when log_dir is falsy.  View
    with TensorBoard's profile plugin or Perfetto."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield
