from areal_tpu.evaluation.run_eval import evaluate_checkpoint

__all__ = ["evaluate_checkpoint"]
