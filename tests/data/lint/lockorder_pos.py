"""C5 positive fixture: every VIOLATION-marked line must be flagged.

Covers the full rule family: a two-lock acquisition cycle, lexical and
through-a-callee re-acquisition of a non-reentrant lock, asyncio nesting,
await / blocking-call / user-callback / blocking-callee under a threading
lock, and the check-then-act atomicity split on a guarded field.
"""

import asyncio
import threading
import time


class Worker:
    _GUARDED_FIELDS = {"_jobs": "_lock_a"}

    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()
        self._jobs = []

    def ab(self):
        with self._lock_a:
            with self._lock_b:  # VIOLATION lock-order (cycle with ba)
                pass

    def ba(self):
        with self._lock_b:
            with self._lock_a:  # VIOLATION lock-order (cycle with ab)
                pass

    def reenter(self):
        with self._lock_a:
            with self._lock_a:  # VIOLATION lock-order (re-acquire)
                pass

    def reenter_via_helper(self):
        with self._lock_a:
            self.locked_len()  # VIOLATION lock-order (callee re-acquires)

    def locked_len(self):
        with self._lock_a:
            return len(self._jobs)

    def sleeps_locked(self):
        with self._lock_a:
            time.sleep(0.01)  # VIOLATION blocking-under-lock

    def finishes_locked(self, req):
        with self._lock_a:
            req.finish("abort")  # VIOLATION blocking-under-lock (callback)

    def calls_blocker_locked(self):
        with self._lock_b:
            self.do_io()  # VIOLATION blocking-under-lock (via callee)

    def do_io(self):
        time.sleep(0.01)

    async def awaits_locked(self):
        with self._lock_a:
            await asyncio.sleep(0)  # VIOLATION blocking-under-lock (await)

    def split_overwrite(self, extra):
        with self._lock_a:
            jobs = list(self._jobs)
        merged = jobs + extra
        with self._lock_a:
            self._jobs = merged  # VIOLATION atomicity-split


class AioPool:
    def __init__(self):
        self._alock = asyncio.Lock()

    async def nested(self):
        async with self._alock:
            async with self._alock:  # VIOLATION lock-order (asyncio)
                pass
