"""CPU smoke for the primary-metric instrument (VERDICT r6 #7): the
scripts/bench_e2e_grpo.py subprocess must produce a well-formed result
JSON on the REAL fleet slice (--transport remote: GenServer over HTTP +
RemoteJaxEngine + transfer-mode publish) in BOTH publish modes, so the
bench cannot rot silently between on-chip runs.

Tiny model, 2 measured steps each — the full-size numbers live in
E2E_GRPO_BENCH_r*.json; this only proves the instrument still runs
end-to-end.  The abort-mode run doubles as the gsm8k-synth dataset path
(the satellite importer for dataset/gsm8k_synth.py), exercising the real
math reward through the rollout loop."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "scripts", "bench_e2e_grpo.py")

_COMMON = [
    "--model", "tiny",
    "--transport", "remote",
    "--modes", "async",
    "--steps", "2",
    "--warmup", "1",
    "--batch-size", "4",
    "--group-size", "2",
    "--n-slots", "8",
    "--max-seq-len", "256",
    "--max-new-tokens", "32",
]


def _run_bench(extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, BENCH] + _COMMON + extra,
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # the result is the last stdout line that parses as a JSON object
    for line in reversed(proc.stdout.strip().split("\n")):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    pytest.fail(f"no JSON result line in stdout: {proc.stdout[-500:]}")


def test_remote_live_publish_smoke():
    out = _run_bench(["--publish-mode", "live",
                      "--prompt-len", "32"])
    assert out["transport"] == "remote" and out["publish_mode"] == "live"
    a = out["async"]
    assert a["steps"] == 2 and a["trajectories"] > 0
    assert a["trajs_per_sec_per_chip"] > 0
    # live commit: the pause window is a pointer swap, not a placement
    assert a["pause_window_s_mean"] < 1.0
    # group fan-out accounting rode along (group_size 2)
    assert out["shared_prefill"]["shared_tokens"] > 0


def test_remote_abort_publish_gsm8k_synth_smoke():
    out = _run_bench(["--publish-mode", "abort",
                      "--dataset", "gsm8k-synth"])
    assert out["publish_mode"] == "abort"
    assert out["dataset"] == "gsm8k-synth"
    a = out["async"]
    assert a["steps"] == 2 and a["trajectories"] > 0
    # the real math reward ran (a from-scratch tiny model scores ~0, but
    # the field must exist and be a finite fraction)
    assert 0.0 <= a["reward_mean"] <= 1.0
