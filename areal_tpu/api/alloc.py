"""Device-allocation DSL: how chips are split between generation and training.

Capability counterpart of the reference's `areal/api/alloc_mode.py` (lark
grammar at alloc_mode.py:316-358, `ParallelStrategy` at :35, `AllocationMode`
at :245).  This is a fresh TPU-first design: a small hand-written
recursive-descent parser (no lark dependency) over a dialect whose axes map
directly onto a `jax.sharding.Mesh`:

    d  data parallel            mesh axis "dp"   (pure replication)
    f  fsdp / zero parallel     mesh axis "fsdp" (param+optimizer sharding)
    t  tensor parallel          mesh axis "tp"
    s  sequence parallel        mesh axis "sp"   (Ulysses-style head/seq a2a)
    c  context parallel         mesh axis "sp"   (ring attention; alias of s
                                                  on the mesh, different attn impl)
    p  pipeline parallel        stage axis (rarely needed on TPU slices)
    e  expert parallel          mesh axis "ep" (MoE)

Expression forms (mirroring the reference's surface):

    "jax:d4t2"                     generation servers only
    "jax:d4t2+jax:d2f4"            disaggregated: gen chips + train chips
    "jax:d2t4|jax:d2t4"            colocated: same chips serve both
    "jax:d4t2+eval"                gen + CPU eval procs
    "d2f2t2"                       train-only (e.g. SFT); backend defaults to jax
    "jax:(attn:d2c2|ffn:d2e2)"     MoE-folded hybrid train layout

Backend aliases: "sglang"/"vllm" (gen) and "fsdp"/"megatron" (train) are
accepted for config compatibility with the reference and normalized to the
same parallel strategies; the native backend name is "jax".
"""

import enum
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

GEN_BACKENDS = ("jax", "sglang", "vllm")
TRAIN_BACKENDS = ("jax", "fsdp", "megatron")

# dimension letter -> ParallelStrategy field
_DIM_FIELDS = {
    "d": "data_parallel_size",
    "f": "fsdp_parallel_size",
    "t": "tensor_parallel_size",
    "s": "sequence_parallel_size",
    "c": "context_parallel_size",
    "p": "pipeline_parallel_size",
    "e": "expert_parallel_size",
    "x": "expert_tensor_parallel_size",
}
_GEN_DIMS = frozenset("dtp")
_ATTN_DIMS = frozenset("dtscp")
_FFN_DIMS = frozenset("dtpex")


class AllocationType(enum.Enum):
    COLOCATE = 0
    DECOUPLED_TRAIN = 1
    LLM_SERVER_ONLY = 2
    DECOUPLED_EVAL = 3


class InvalidAllocationModeError(ValueError):
    pass


@dataclass
class ParallelStrategy:
    """N-D parallel layout; product of all axes is the slice's chip count.

    TPU-first: `fsdp` and `sequence` are first-class axes (they are distinct
    mesh axes for GSPMD), unlike the reference where ZeRO-sharding is implied
    by the backend (fsdp_engine.py) rather than the expression.
    """

    data_parallel_size: int = 1
    fsdp_parallel_size: int = 1
    tensor_parallel_size: int = 1
    sequence_parallel_size: int = 1
    context_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    expert_parallel_size: int = 1
    expert_tensor_parallel_size: int = 1

    # --- short aliases, mirroring reference property names ---
    @property
    def dp_size(self) -> int:
        return self.data_parallel_size

    @property
    def fsdp_size(self) -> int:
        return self.fsdp_parallel_size

    @property
    def tp_size(self) -> int:
        return self.tensor_parallel_size

    @property
    def sp_size(self) -> int:
        return self.sequence_parallel_size

    @property
    def cp_size(self) -> int:
        return self.context_parallel_size

    @property
    def pp_size(self) -> int:
        return self.pipeline_parallel_size

    @property
    def ep_size(self) -> int:
        return self.expert_parallel_size

    @property
    def etp_size(self) -> int:
        return self.expert_tensor_parallel_size

    @property
    def world_size(self) -> int:
        return (
            self.data_parallel_size
            * self.fsdp_parallel_size
            * self.tensor_parallel_size
            * self.sequence_parallel_size
            * self.context_parallel_size
            * self.pipeline_parallel_size
        )

    def __post_init__(self):
        for name, v in self.__dict__.items():
            if not (isinstance(v, int) and v >= 1):
                raise InvalidAllocationModeError(f"{name}={v!r} must be int >= 1")
        if self.sequence_parallel_size > 1 and self.context_parallel_size > 1:
            raise InvalidAllocationModeError(
                "s (Ulysses) and c (ring) both shard the sequence; pick one"
            )

    def validate_folded_experts(self):
        """For a *plain* (non-hybrid) section, expert axes fold into the dense
        world: ep*etp must divide it.  Hybrid sections instead treat ep/etp as
        chip axes of the ffn half (HybridTrainStrategy checks chip counts)."""
        if self.expert_parallel_size > 1:
            emp = self.expert_parallel_size * self.expert_tensor_parallel_size
            if self.world_size % emp != 0:
                raise InvalidAllocationModeError(
                    f"expert parallel size {emp} must divide world size "
                    f"{self.world_size}"
                )

    def __str__(self) -> str:
        out = []
        for letter, fname in _DIM_FIELDS.items():
            v = getattr(self, fname)
            if v != 1:
                out.append(f"{letter}{v}")
        return "".join(out) or "d1"

    def mesh_shape(self) -> Dict[str, int]:
        """Logical mesh axis sizes for this strategy (sp covers both s and c)."""
        return {
            "dp": self.data_parallel_size,
            "fsdp": self.fsdp_parallel_size,
            "sp": self.sequence_parallel_size * self.context_parallel_size,
            "tp": self.tensor_parallel_size,
        }


@dataclass
class HybridTrainStrategy:
    """MoE-folded layout: independent strategies for attention vs. expert FFN.

    Counterpart of the reference's `(attn:d2c2|ffn:d2e2)` grammar branch
    (alloc_mode.py:332-346).  Both halves must occupy the same chip count.
    """

    attn: ParallelStrategy
    ffn: ParallelStrategy

    def __post_init__(self):
        # In the ffn section expert axes are chip axes (Megatron MoE folding):
        # the same chips that serve (sp, cp, tp) for attention re-fold as
        # (ep, etp) for the expert FFN.
        ffn_chips = self.ffn.world_size * self.ffn.ep_size * self.ffn.etp_size
        if self.attn.world_size != ffn_chips:
            raise InvalidAllocationModeError(
                f"attn world size {self.attn.world_size} != ffn world size "
                f"{ffn_chips}"
            )

    @property
    def world_size(self) -> int:
        return self.attn.world_size


@dataclass
class AllocationMode:
    """Parsed allocation expression (reference: alloc_mode.py:245)."""

    type_: AllocationType
    gen: Optional[ParallelStrategy] = None
    train: Optional[ParallelStrategy] = None
    train_hybrid: Optional[HybridTrainStrategy] = None
    gen_backend: Optional[str] = None
    train_backend: Optional[str] = None

    @property
    def gen_instance_size(self) -> int:
        """Chips per generation server instance (everything but its dp axis)."""
        if self.gen is None:
            return 0
        return self.gen.world_size // self.gen.data_parallel_size

    @property
    def gen_world_size(self) -> int:
        return self.gen.world_size if self.gen is not None else 0

    @property
    def train_world_size(self) -> int:
        if self.train is not None:
            return self.train.world_size
        if self.train_hybrid is not None:
            return self.train_hybrid.world_size
        return 0

    @property
    def world_size(self) -> int:
        if self.type_ == AllocationType.COLOCATE and self.gen is not None:
            return max(self.gen_world_size, self.train_world_size)
        return self.gen_world_size + self.train_world_size

    @classmethod
    def from_str(cls, s: str) -> "AllocationMode":
        return _Parser(s).parse()


class _Parser:
    """Recursive descent over:

    expr          := section (("+" | "|") section)*
    section       := [backend ":"] (dims | hybrid) | "eval" | "cpu"
    hybrid        := "(" "attn" ":" dims "|" "ffn" ":" dims ")"
    dims          := (DIM_LETTER NUMBER)+
    """

    _TOKEN_RE = re.compile(
        r"\s*(?:(?P<name>[A-Za-z_][A-Za-z0-9_]*)|(?P<num>[0-9]+)|(?P<sym>[+|():.]))"
    )

    def __init__(self, text: str):
        self.text = text.strip()
        self.tokens = self._lex(self.text)
        self.pos = 0

    def _lex(self, text: str) -> List[Tuple[str, str]]:
        tokens, i = [], 0
        while i < len(text):
            m = self._TOKEN_RE.match(text, i)
            if not m or m.end() == i:
                raise InvalidAllocationModeError(
                    f"bad character at {i} in allocation expr {text!r}"
                )
            for kind in ("name", "num", "sym"):
                if m.group(kind) is not None:
                    tokens.append((kind, m.group(kind)))
            i = m.end()
        return tokens

    def _peek(self, k: int = 0):
        return self.tokens[self.pos + k] if self.pos + k < len(self.tokens) else None

    def _next(self):
        tok = self._peek()
        if tok is None:
            raise InvalidAllocationModeError(f"unexpected end of expr {self.text!r}")
        self.pos += 1
        return tok

    def _expect(self, kind: str, value: Optional[str] = None):
        tok = self._next()
        if tok[0] != kind or (value is not None and tok[1] != value):
            raise InvalidAllocationModeError(
                f"expected {value or kind}, got {tok[1]!r} in {self.text!r}"
            )
        return tok

    # --- grammar ---
    def parse(self) -> AllocationMode:
        if not self.tokens:
            raise InvalidAllocationModeError("empty allocation expression")
        sections = [self._section()]
        seps = []
        while self._peek() is not None:
            kind, sym = self._next()
            if kind != "sym" or sym not in "+|":
                raise InvalidAllocationModeError(
                    f"expected '+' or '|' between sections, got {sym!r}"
                )
            seps.append(sym)
            sections.append(self._section())
        return self._assemble(sections, seps)

    def _section(self):
        tok = self._peek()
        if tok and tok[0] == "name" and tok[1] in ("eval", "cpu"):
            self._next()
            return ("eval", None, None)
        backend = None
        if (
            tok
            and tok[0] == "name"
            and tok[1] in GEN_BACKENDS + TRAIN_BACKENDS
            and self._peek(1) is not None
            and self._peek(1)[0] == "sym"
            and self._peek(1)[1] in ":."
        ):
            backend = self._next()[1]
            self._next()  # ":" or legacy "."
        nxt = self._peek()
        if nxt is not None and nxt[0] == "sym" and nxt[1] == "(":
            return ("hybrid", backend, self._hybrid())
        return ("plain", backend, self._dims(allowed=frozenset(_DIM_FIELDS)))

    def _hybrid(self) -> HybridTrainStrategy:
        self._expect("sym", "(")
        self._expect("name", "attn")
        self._expect("sym", ":")
        attn = self._dims(allowed=_ATTN_DIMS)
        self._expect("sym", "|")
        self._expect("name", "ffn")
        self._expect("sym", ":")
        ffn = self._dims(allowed=_FFN_DIMS)
        self._expect("sym", ")")
        return HybridTrainStrategy(attn=attn, ffn=ffn)

    _DIMS_RE = re.compile(r"([a-z])([1-9][0-9]*)")

    def _dims(self, allowed: frozenset) -> ParallelStrategy:
        # a dims run like "d4t2" lexes as a single name token (letters+digits)
        tok = self._peek()
        if tok is None or tok[0] != "name":
            raise InvalidAllocationModeError(
                f"expected parallel dims, got {tok and tok[1]!r} in {self.text!r}"
            )
        text = self._next()[1]
        pairs = self._DIMS_RE.findall(text)
        if "".join(l + n for l, n in pairs) != text or not pairs:
            raise InvalidAllocationModeError(
                f"malformed parallel dims {text!r} in {self.text!r}"
            )
        kwargs: Dict[str, int] = {}
        for letter, num in pairs:
            if letter not in _DIM_FIELDS:
                raise InvalidAllocationModeError(
                    f"unknown parallel dim {letter!r} in {self.text!r}"
                )
            if letter not in allowed:
                raise InvalidAllocationModeError(
                    f"dim {letter!r} not allowed in this section of {self.text!r}"
                )
            fname = _DIM_FIELDS[letter]
            if fname in kwargs:
                raise InvalidAllocationModeError(f"duplicate dim {letter!r}")
            kwargs[fname] = int(num)
        return ParallelStrategy(**kwargs)

    def _assemble(self, sections, seps) -> AllocationMode:
        if len(sections) > 2:
            raise InvalidAllocationModeError(
                f"at most two sections supported, got {len(sections)}"
            )

        def is_gen(sec) -> bool:
            return sec[1] in ("jax", "sglang", "vllm") and sec[1] is not None

        if len(sections) == 1:
            kind, backend, strat = sections[0]
            if kind == "eval":
                raise InvalidAllocationModeError("bare 'eval' is not an allocation")
            if kind == "hybrid":
                return AllocationMode(
                    type_=AllocationType.COLOCATE,
                    train_hybrid=strat,
                    train_backend=backend or "jax",
                )
            if is_gen(sections[0]) and backend in GEN_BACKENDS and backend != "jax":
                # sglang:d4t2 / vllm:d2t4 — inference-only
                self._check_gen(strat)
                return AllocationMode(
                    type_=AllocationType.LLM_SERVER_ONLY,
                    gen=strat,
                    gen_backend=backend,
                )
            if backend == "jax":
                # ambiguous: "jax:d4t2" alone means an LLM-server-only slice
                self._check_gen(strat)
                return AllocationMode(
                    type_=AllocationType.LLM_SERVER_ONLY, gen=strat, gen_backend="jax"
                )
            # bare dims -> train-only colocate (SFT-style)
            strat.validate_folded_experts()
            return AllocationMode(
                type_=AllocationType.COLOCATE,
                train=strat,
                train_backend=backend or "jax",
            )

        (k1, b1, s1), (k2, b2, s2) = sections
        sep = seps[0]
        if k2 == "eval":
            if k1 != "plain" or b1 not in GEN_BACKENDS:
                raise InvalidAllocationModeError(
                    "eval must follow a generation section"
                )
            self._check_gen(s1)
            return AllocationMode(
                type_=AllocationType.DECOUPLED_EVAL, gen=s1, gen_backend=b1 or "jax"
            )
        if k1 == "eval":
            raise InvalidAllocationModeError("eval section must come last")
        if b1 is None or b1 not in GEN_BACKENDS:
            raise InvalidAllocationModeError(
                f"first section of a two-part expr must name a gen backend "
                f"({'/'.join(GEN_BACKENDS)}): {self.text!r}"
            )
        self._check_gen(s1)
        if b2 is not None and b2 not in TRAIN_BACKENDS:
            raise InvalidAllocationModeError(
                f"second section backend must be a train backend "
                f"({'/'.join(TRAIN_BACKENDS)}), got {b2!r}"
            )
        type_ = (
            AllocationType.DECOUPLED_TRAIN if sep == "+" else AllocationType.COLOCATE
        )
        mode = AllocationMode(type_=type_, gen=s1, gen_backend=b1)
        if k2 == "hybrid":
            mode.train_hybrid = s2
        else:
            s2.validate_folded_experts()
            mode.train = s2
        mode.train_backend = b2 or "jax"
        return mode

    @staticmethod
    def _check_gen(strat: ParallelStrategy):
        for letter, fname in _DIM_FIELDS.items():
            if letter not in _GEN_DIMS and getattr(strat, fname) != 1:
                raise InvalidAllocationModeError(
                    f"generation sections only support dims d/t/p, got {letter!r}"
                )
