"""Trainer-kill recovery e2e (ISSUE 15 acceptance): SIGKILL a real async
training loop mid-run, relaunch it the way the launchers do (AREAL_RUN_ID
incremented), and prove the resume contract end to end:

- step continuity: the union of steps.jsonl across runs is strictly
  increasing — no step trained twice, at most one step lost;
- the staleness ledger invariant holds on every logged step, including the
  first post-recovery one (in-flight-at-crash trajectories settled);
- the surviving gen server's FIRST post-crash interaction is the pinned
  weight reload at the RECOVERED version — before any re-admitted generate;
- the stitched lifecycle JSONL (run0 + run1) passes obs/trace.py
  completeness and carries exactly one run_restart boundary event;
- kill-mid-dump (SIGKILL between staging fsync and the atomic rename)
  leaves only a .tmp-* dir: the relaunch resumes from the previous intact
  generation — the at-most-one-step-lost case.

The trainer runs in a subprocess (tests/mp/recover_trainer.py) so the kill
is a REAL SIGKILL; the FakeGenServer lives in THIS process and therefore
survives the trainer's death, exactly like a disaggregated rollout fleet.
"""

import json
import os
import signal
import subprocess
import sys

from areal_tpu.obs.trace import analyze

from tests.fake_server import FakeGenServer

_HARNESS = os.path.join(os.path.dirname(__file__), "mp", "recover_trainer.py")


class _Run:
    def __init__(self, returncode, log_path):
        self.returncode = returncode
        self.log_path = log_path

    @property
    def output(self):
        with open(self.log_path) as f:
            return f.read()


def _launch(tmp_path, addr, run_id, steps, extra_env=None, timeout=240):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "AREAL_FAKE_SERVER_ADDR": addr,
        "AREAL_RUN_ID": str(run_id),
        "RECOVER_FILEROOT": str(tmp_path),
        "RECOVER_STEPS": str(steps),
        "RECOVER_STEPS_LOG": str(tmp_path / "steps.jsonl"),
        "RECOVER_EVENTS_PATH": str(tmp_path / f"events_run{run_id}.jsonl"),
    }
    env.pop("AREAL_FAULT_POINTS", None)
    env.update(extra_env or {})
    # log to a FILE, not pipes: the trainer's reward-pool workers inherit
    # its stdio and outlive the SIGKILL, so communicate() on a pipe would
    # block on the orphans long after the trainer itself is dead
    log_path = tmp_path / f"trainer_run{run_id}.log"
    with open(log_path, "a") as log_f:
        proc = subprocess.Popen(
            [sys.executable, _HARNESS],
            env=env, stdout=log_f, stderr=subprocess.STDOUT,
        )
        rc = proc.wait(timeout=timeout)
    return _Run(rc, log_path)


def _read_steps(tmp_path):
    path = tmp_path / "steps.jsonl"
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _stitched_events(tmp_path, run_ids):
    events = []
    for rid in run_ids:
        with open(tmp_path / f"events_run{rid}.jsonl") as f:
            events.extend(json.loads(line) for line in f if line.strip())
    return events


def test_sigkill_trainer_then_relaunch_resumes(tmp_path):
    server = FakeGenServer(completion=list(range(100, 106)), chunk_size=2)
    addr = server.start()
    try:
        # run 0: dies with SIGKILL at the end of step 1 (steps 0-1 trained)
        p0 = _launch(tmp_path, addr, run_id=0, steps=4,
                     extra_env={"RECOVER_KILL_AT_STEP": "1"})
        assert p0.returncode == -signal.SIGKILL, (
            f"rc={p0.returncode}\n{p0.output}"
        )
        n_before_relaunch = len(server.log)
        assert n_before_relaunch > 0

        # relaunch the way launcher/local.py does: AREAL_RUN_ID += 1
        p1 = _launch(tmp_path, addr, run_id=1, steps=4)
        assert p1.returncode == 0, (
            f"rc={p1.returncode}\n{p1.output}"
        )

        # 1. step continuity: no step trained twice, none skipped
        lines = _read_steps(tmp_path)
        assert [ln["global_step"] for ln in lines] == [0, 1, 2, 3]
        assert [ln["run_id"] for ln in lines] == [0, 0, 1, 1]
        # the resumed run continues the version sequence, not restarts it
        assert [ln["version"] for ln in lines] == [1, 2, 3, 4]

        # 2. ledger invariant on every step, including the first recovered
        assert all(ln["ledger_ok"] for ln in lines), lines
        post = lines[2]["ledger"]
        assert post["submitted"] == (
            post["accepted"] + post["rejected"] + post["running"]
        )

        # 3. the first post-crash server interaction is the PINNED weight
        # reload at the recovered version (last dumped step 1 -> version 2),
        # before any re-admitted generate
        post_crash = server.log[n_before_relaunch:]
        assert post_crash, "relaunch never reached the gen server"
        kind, body = post_crash[0]
        assert kind == "update_weights", post_crash[:3]
        assert body["version"] == 2
        assert any(k == "generate" for k, _ in post_crash[1:])
        # the run's final publish left the fleet at the final version
        assert server.version == 4

        # 4. stitched lifecycle log: complete, with ONE restart boundary
        events = _stitched_events(tmp_path, (0, 1))
        report = analyze(events)
        assert report.completeness.complete, report.completeness
        assert len(report.restarts) == 1
        boundary = report.restarts[0]
        assert boundary["run_id"] == 1
        assert boundary["recovered_step"] == 1
        assert boundary["resume_step"] == 2
        assert boundary["weight_version"] == 2
    finally:
        server.stop()


def test_sigkill_mid_dump_resumes_from_previous_generation(tmp_path):
    """The torn-dump case, with a REAL SIGKILL between the staging fsync
    and the atomic rename (fault point `recover_mid_dump`, 2nd hit = the
    step-1 dump).  gen-00000000 stays intact; the relaunch replays step 1 —
    at most one step lost, never a torn checkpoint consumed."""
    server = FakeGenServer(completion=list(range(100, 106)), chunk_size=2)
    addr = server.start()
    try:
        p0 = _launch(tmp_path, addr, run_id=0, steps=3,
                     extra_env={"AREAL_FAULT_POINTS": "recover_mid_dump@2:kill"})
        assert p0.returncode == -signal.SIGKILL, (
            f"rc={p0.returncode}\n{p0.output}"
        )
        recover_root = tmp_path / "recover-e2e" / "t" / "recover"
        assert (recover_root / "gen-00000000").is_dir()
        assert (recover_root / ".tmp-00000001").is_dir()  # the torn dump
        assert not (recover_root / "gen-00000001").exists()
        # only step 0 ever hit steps.jsonl (the log line follows the dump)
        assert [ln["global_step"] for ln in _read_steps(tmp_path)] == [0]

        p1 = _launch(tmp_path, addr, run_id=1, steps=3)
        assert p1.returncode == 0, (
            f"rc={p1.returncode}\n{p1.output}"
        )
        lines = _read_steps(tmp_path)
        # step 1 is REPLAYED from gen-00000000 (it never completed a dump);
        # still strictly increasing — nothing trained twice
        assert [ln["global_step"] for ln in lines] == [0, 1, 2]
        assert [ln["run_id"] for ln in lines] == [0, 1, 1]
        assert all(ln["ledger_ok"] for ln in lines)
        # recovered from step 0 -> pinned reload at version 1
        events = _stitched_events(tmp_path, (0, 1))
        report = analyze(events)
        assert report.completeness.complete, report.completeness
        assert len(report.restarts) == 1
        assert report.restarts[0]["recovered_step"] == 0
        assert report.restarts[0]["weight_version"] == 1
        # the torn staging dir was swept by the first successful dump
        assert not (recover_root / ".tmp-00000001").exists()
    finally:
        server.stop()
