"""Preset/auto-allocation tests (reference: experiments/common auto
device-mesh heuristics)."""

import pytest

from areal_tpu.api.alloc import AllocationMode
from areal_tpu.api.config import GRPOConfig, load_expr_config
from areal_tpu.api.presets import auto_allocation, list_presets, preset


def test_auto_allocation_small_model_many_chips():
    # 1.5B on 8 v5e chips: tp=1 suffices for serving; training needs
    # 1.5e9*10B ~ 15G > 14G -> tp=2
    expr = auto_allocation(8, 1.5e9, device_kind="TPU v5 lite")
    mode = AllocationMode.from_str(expr)
    assert mode.gen is not None and mode.train is not None
    assert mode.gen_world_size + mode.train_world_size <= 8
    assert mode.train_world_size >= 2


def test_auto_allocation_7b():
    expr = auto_allocation(32, 7.6e9, device_kind="TPU v5 lite")
    mode = AllocationMode.from_str(expr)
    # serving a 7B needs 7.6e9*3B ~ 23G -> tp=2 on 14G chips; train tp >= 8
    assert mode.gen_instance_size >= 2
    assert mode.train.tensor_parallel_size >= 4
    assert mode.gen_world_size + mode.train_world_size <= 32


def test_auto_allocation_infeasible():
    with pytest.raises(ValueError):
        auto_allocation(2, 70e9, device_kind="TPU v5 lite")
    with pytest.raises(ValueError):
        auto_allocation(1, 1e9)


def test_presets_are_loadable_configs(tmp_path):
    import yaml

    for name in list_presets():
        d = preset(name)
        assert AllocationMode.from_str(d["allocation_mode"])
        cfg_path = tmp_path / f"{name}.yaml"
        cfg_path.write_text(yaml.safe_dump(d))
        cfg, _ = load_expr_config(["--config", str(cfg_path)], GRPOConfig)
        assert cfg.actor.use_decoupled_loss
        assert cfg.train_dataset.batch_size > 0
