"""Mixture-of-Experts feed-forward block, GShard/Switch style.

Capability counterpart of the reference's MoE stack
(realhf/impl/model/modules/moe/{experts,router,grouped GEMM} and the
Megatron EP path, areal/engine/megatron_engine.py:451-535;
alloc grammar e/etp dims, areal/api/alloc_mode.py:80-117).  TPU-first
design:

- **Dense dispatch/combine tensors** ([tokens, E, C] one-hot): token
  routing becomes three einsums that XLA tiles straight onto the MXU —
  replacing the reference's grouped-GEMM CUDA kernels and permutation
  indices.  Capacity C bounds each expert's work, keeping every shape
  static under jit.
- Expert weights live as [E, D, F] leaves sharded over the mesh's `ep`
  axis (partition specs in transformer.param_partition_specs); the
  dispatch einsum's contraction over tokens is what GSPMD turns into the
  all-to-all the reference drives through NCCL EP groups.
- Top-k routing with renormalised gates (mixtral convention), plus the
  Switch-style load-balancing auxiliary loss E * sum(f_i * P_i), threaded
  functionally through the layer scan (no global state).
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from areal_tpu.models.model_config import TransformerConfig

Params = Dict[str, jax.Array]


def expert_capacity(
    n_tokens: int, num_experts: int, top_k: int, capacity_factor: float = 1.25
) -> int:
    """Static per-expert token budget; multiples of 8 for TPU tiling."""
    c = int(n_tokens * top_k / num_experts * capacity_factor) + 1
    return max(8, (c + 7) // 8 * 8)


def moe_ffn(
    cfg: TransformerConfig,
    lp: Params,  # router [D, E], w_gate/w_up [E, D, Fm], w_down [E, Fm, D]
    h: jax.Array,  # [B, T, D]
    dtype,
) -> Tuple[jax.Array, jax.Array]:
    """Returns (output [B, T, D], load-balance aux loss scalar fp32)."""
    B, T, D = h.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    N = B * T
    C = expert_capacity(N, E, k, cfg.moe_capacity_factor)
    x = h.reshape(N, D)

    router_logits = jnp.einsum(
        "nd,de->ne", x.astype(jnp.float32), lp["router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [N, E] fp32
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [N, k]
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # position-in-expert assignment, choice-major priority (first choices
    # beat second choices for capacity, standard GShard ordering)
    dispatch = jnp.zeros((N, E, C), jnp.float32)
    combine = jnp.zeros((N, E, C), jnp.float32)
    fill = jnp.zeros((E,), jnp.float32)
    for j in range(k):  # k is tiny and static
        oh = jax.nn.one_hot(gate_idx[:, j], E, dtype=jnp.float32)  # [N, E]
        pos = jnp.cumsum(oh, axis=0) - 1 + fill[None, :]  # [N, E]
        keep = oh * (pos < C)
        slot = jax.nn.one_hot(
            jnp.sum(pos * oh, axis=-1).astype(jnp.int32), C, dtype=jnp.float32
        )  # [N, C]
        d_j = keep[:, :, None] * slot[:, None, :]  # [N, E, C]
        dispatch = dispatch + d_j
        combine = combine + d_j * gate_vals[:, j, None, None]
        fill = fill + jnp.sum(oh, axis=0)

    xe = jnp.einsum("nec,nd->ecd", dispatch.astype(dtype), x)  # [E, C, D]
    gate = jnp.einsum("ecd,edf->ecf", xe, lp["w_gate"].astype(dtype))
    up = jnp.einsum("ecd,edf->ecf", xe, lp["w_up"].astype(dtype))
    ye = jnp.einsum(
        "ecf,efd->ecd", jax.nn.silu(gate) * up, lp["w_down"].astype(dtype)
    )  # [E, C, D]
    out = jnp.einsum("nec,ecd->nd", combine.astype(dtype), ye)

    # Switch load-balancing loss: E * sum_i f_i * P_i where f_i is the
    # fraction of tokens whose FIRST choice is expert i and P_i the mean
    # router probability for i
    first = jax.nn.one_hot(gate_idx[:, 0], E, dtype=jnp.float32)
    f = jnp.mean(first, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = jnp.asarray(E, jnp.float32) * jnp.sum(f * p)
    return out.reshape(B, T, D), aux
