"""Lifecycle-JSONL trace analytics: records, completeness, decomposition.

Input is the event stream produced by :mod:`areal_tpu.utils.telemetry`
(``EventLog.dump_jsonl`` or ``EVENTS.snapshot()``).  Three consumers
live here:

1. **Per-trajectory records** (:class:`TrajectoryRecord`): every trace
   id's events are folded through a small state machine into a stage
   partition of the ``[rollout_submit, gen_done]`` wall span —
   ``admission_wait`` → ``prefill`` → ``decode`` (per-tier chunk
   latencies included) → ``interrupted`` (publish aborts and failover
   windows) → ``tail`` (delivery + client return) — plus reward latency
   and train-consume staleness joined via ``trace_key``.

2. **Completeness linter** (:class:`Completeness`): a log is only
   trustworthy if every referenced span has its opening record — no
   orphan trace ids (events whose submit/admission fell off the ring),
   every ``resubmit`` joins an earlier ``rollout_submit`` for the same
   trace, interrupts on closed traces are followed by a resume or
   re-admission, and the ring itself reports zero dropped events
   (``telemetry_meta`` trailer, written by ``dump_jsonl`` on overflow).
   Open (in-flight at dump time) traces are normal under the async
   executor and are reported, not failed, unless ``strict_open``.

3. **Accounting identity**: the stage partition is built purely from
   event timestamps, while ``gen_done.latency_s`` is measured
   independently by the client around its HTTP/engine call
   (perf_counter delta in `core/remote.py`).  For every closed
   trajectory the two must agree: ``|sum(stages) - latency_s|`` within
   ``tolerance`` (relative) or ``abs_floor_s`` — a broken identity
   means the decomposition is lying and the report says so.

Clock discipline: events carry paired clocks (wall ``ts`` + monotonic
``mono`` with the emitting ``pid``).  When every event of a trajectory
comes from one process the monotonic clock is used (immune to NTP
steps); otherwise wall time joins across processes.  Chunk *durations*
(``latency_s``) are perf_counter deltas either way.

Everything is stdlib-only and strictly post-hoc: this module reads
dumped JSONL, never engine internals.
"""

import dataclasses
import json
import math
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

# Events that open a trajectory span (roots).  A client-side log has
# rollout_submit; a server-only log (bench_serving) roots at admission.
_ROOT_EVENTS = ("rollout_submit", "admission")
# Events that close a trajectory span.
_TERMINAL_EVENTS = ("gen_done", "rollout_lost")
# Per-trace events that require a root to be meaningful; seeing one for
# a trace with no root means the head of the log was lost.
_REQUIRES_ROOT = (
    "prefill", "resume", "resubmit", "resubmit_cache_hit", "interrupt",
    "reward", "gen_done", "rollout_lost", "handoff",
)
# Global (traceless) events: never orphan candidates.  run_restart marks
# a trainer relaunch resuming from a recover generation (utils/recover.py)
# — the boundary event a stitched multi-run log must carry to stay
# complete.
_GLOBAL_EVENTS = (
    "pause", "episode", "trajectory_lost", "telemetry_meta", "run_restart",
)

EventSource = Union[str, Iterable[Dict[str, Any]]]


def iter_events(source: EventSource) -> List[Dict[str, Any]]:
    """Load events from a JSONL path or pass an event list through.
    Blank lines are skipped; a malformed line raises (a trace log is
    evidence — silently skipping corrupt records would undercount)."""
    if isinstance(source, str):
        out: List[Dict[str, Any]] = []
        with open(source) as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out
    return list(source)


def dist_summary(values: Iterable[float]) -> Optional[Dict[str, float]]:
    """{count, mean, min, p50, p90, p99, max} or None for no samples.
    Percentiles are linear-interpolated on the sorted sample."""
    vals = sorted(float(v) for v in values
                  if v is not None and math.isfinite(v))
    if not vals:
        return None

    def pct(q: float) -> float:
        if len(vals) == 1:
            return vals[0]
        pos = q * (len(vals) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(vals) - 1)
        frac = pos - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac

    return {
        "count": len(vals),
        "mean": sum(vals) / len(vals),
        "min": vals[0],
        "p50": pct(0.50),
        "p90": pct(0.90),
        "p99": pct(0.99),
        "max": vals[-1],
    }


@dataclasses.dataclass
class TrajectoryRecord:
    """One trace id's reconstructed lifecycle."""

    trace_id: str
    trace_key: Optional[int] = None
    group_id: Optional[str] = None
    server: Optional[str] = None
    input_len: Optional[int] = None
    output_len: Optional[int] = None
    stop_reason: Optional[str] = None
    attempts: int = 1
    resubmits: int = 0
    resubmit_cache_hits: int = 0
    resubmit_cache_hit_tokens: int = 0
    interrupts: int = 0
    handoffs: int = 0
    handoff_bytes: int = 0
    closed: bool = False
    lost: bool = False
    has_submit: bool = False
    has_admission: bool = False
    clock: str = "mono"            # which clock built the stage partition
    # Stage partition of [root, terminal] in seconds.  Keys among:
    # admission_wait / prefill / decode / handoff / interrupted / tail
    # / opaque.
    stages: Dict[str, float] = dataclasses.field(default_factory=dict)
    span_s: Optional[float] = None       # terminal - root, event clocks
    e2e_s: Optional[float] = None        # gen_done.latency_s (client)
    identity_err_s: Optional[float] = None
    identity_rel: Optional[float] = None
    ttft_s: Optional[float] = None
    inter_token_s: Optional[float] = None
    n_chunks: int = 0
    tiers: List[int] = dataclasses.field(default_factory=list)
    prefill_kinds: List[str] = dataclasses.field(default_factory=list)
    cold_tokens: int = 0
    inherited_tokens: int = 0
    reward: Optional[float] = None
    reward_latency_s: Optional[float] = None
    staleness: Optional[float] = None
    consume_latency_s: Optional[float] = None

    def stage_sum(self) -> float:
        return sum(self.stages.values())


@dataclasses.dataclass
class Completeness:
    """Result of the trace completeness linter."""

    complete: bool = True
    dropped_events: int = 0
    n_events: int = 0
    n_traces: int = 0
    open_traces: int = 0
    orphan_traces: List[str] = dataclasses.field(default_factory=list)
    unjoined_resubmits: int = 0
    incomplete_interrupts: int = 0
    unmatched_consumes: int = 0
    strict_open: bool = False
    errors: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TraceReport:
    records: List[TrajectoryRecord]
    completeness: Completeness
    pauses: List[Dict[str, Any]]
    chunk_latency_by_tier: Dict[int, List[float]]
    wall_span_s: float
    # run_restart boundary events (utils/recover.py): one per trainer
    # relaunch that resumed from a recover generation — the seam where a
    # stitched multi-run log changes pid
    restarts: List[Dict[str, Any]] = dataclasses.field(default_factory=list)

    @property
    def closed(self) -> List[TrajectoryRecord]:
        return [r for r in self.records if r.closed and not r.lost]


def _clock_picker(events: List[Dict[str, Any]]) -> Tuple[str, Any]:
    """Choose the boundary clock for one trajectory's events: monotonic
    when every event has one and all share a pid, else wall time."""
    pids = set()
    for e in events:
        if "mono" not in e or "pid" not in e:
            return "ts", (lambda e: float(e["ts"]))
        pids.add(e["pid"])
    if len(pids) == 1:
        return "mono", (lambda e: float(e["mono"]))
    return "ts", (lambda e: float(e["ts"]))


def _build_record(trace_id: str, events: List[Dict[str, Any]]) -> TrajectoryRecord:
    """Fold one trace's events (log order) into a TrajectoryRecord via
    the stage state machine described in the module docstring."""
    rec = TrajectoryRecord(trace_id=trace_id)
    clock_name, t_of = _clock_picker(events)
    rec.clock = clock_name

    submit = next((e for e in events if e["event"] == "rollout_submit"), None)
    terminal = next((e for e in events if e["event"] in _TERMINAL_EVENTS), None)
    rec.has_submit = submit is not None
    rec.has_admission = any(e["event"] == "admission" for e in events)
    root = submit
    if root is None:
        root = next((e for e in events if e["event"] == "admission"), None)
    if root is None:
        return rec  # orphan: caller records it via completeness

    rec.trace_key = root.get("trace_key")
    if submit is not None:
        rec.group_id = submit.get("group_id") or None
        rec.server = submit.get("server")
        rec.input_len = submit.get("input_len")

    # --- stage state machine -------------------------------------------
    cursor = t_of(root)
    t_root = cursor
    # With a submit root the first segment is queue time; with an
    # admission root we are already in prefill.
    state = "admission_wait" if submit is not None else "prefill"
    stages: Dict[str, float] = {}
    first_chunk_end: Optional[float] = None
    last_chunk_end: Optional[float] = None

    def close(upto: float, into: str) -> float:
        nonlocal cursor
        seg = max(0.0, upto - cursor)
        if seg:
            stages[into] = stages.get(into, 0.0) + seg
        cursor = max(cursor, upto)
        return seg

    for e in events:
        name = e["event"]
        t = t_of(e)
        if name == "admission":
            close(t, state)
            state = "prefill"
            rec.has_admission = True
        elif name == "prefill":
            rec.prefill_kinds.append(str(e.get("kind", "")))
            rec.cold_tokens += int(e.get("cold_tokens", 0) or 0)
            rec.inherited_tokens += int(e.get("inherited_tokens", 0) or 0)
        elif name in ("decode_chunk", "spec_verify"):
            lat = float(e.get("latency_s", 0.0) or 0.0)
            start = max(cursor, t - lat)
            close(start, state)
            close(t, "decode")
            state = "decode"
            rec.n_chunks += 1
            tier = e.get("tier")
            if tier is not None and tier not in rec.tiers:
                rec.tiers.append(tier)
            if first_chunk_end is None:
                first_chunk_end = t
            last_chunk_end = t
        elif name == "handoff":
            # Disaggregated prefill->decode transfer (ISSUE 17): the
            # router measures the full export+import leg and stamps it
            # as latency_s; everything before the leg stays in the
            # prior stage (decode chunks served on the prefill server),
            # and the leg itself becomes its own stage so SLO reports
            # can band it.
            lat = float(e.get("latency_s", 0.0) or 0.0)
            start = max(cursor, t - lat)
            close(start, state)
            close(t, "handoff")
            state = "handoff"
            rec.handoffs += 1
            rec.handoff_bytes += int(e.get("bytes", 0) or 0)
        elif name == "interrupt":
            close(t, state)
            state = "interrupted"
            rec.interrupts += 1
        elif name in ("resume", "resubmit"):
            close(t, state)
            state = "interrupted"
            if name == "resubmit":
                rec.resubmits += 1
        elif name == "resubmit_cache_hit":
            # A failover resubmit whose replacement server warm-started the
            # accumulated prefix through the radix/paged cache (ISSUE 16).
            # Pure annotation on the in-flight attempt: no stage boundary.
            rec.resubmit_cache_hits += 1
            rec.resubmit_cache_hit_tokens += int(e.get("hit_tokens", 0) or 0)
        elif name in _TERMINAL_EVENTS:
            # Delivery + HTTP return after the last decode chunk is its
            # own "tail" stage; any other state closes into itself
            # (e.g. a trace lost while queued stays admission_wait).
            close(t, "tail" if state == "decode" else state)
            rec.closed = True
            rec.lost = name == "rollout_lost"
            if name == "gen_done":
                rec.output_len = e.get("output_len")
                rec.stop_reason = e.get("stop_reason")
                rec.attempts = int(e.get("attempts", 1) or 1)
                lat = e.get("latency_s")
                rec.e2e_s = float(lat) if lat is not None else None
                ttft = e.get("ttft_s")
                if ttft is not None and math.isfinite(float(ttft)):
                    rec.ttft_s = float(ttft)
            break

    if terminal is not None and rec.closed:
        rec.span_s = max(0.0, t_of(terminal) - t_root)
        # A client-only log (no server-side spans in this process's
        # ring, e.g. the chaos harness's fake servers) has nothing to
        # decompose: report the whole span as opaque server time rather
        # than mislabeling it queue wait.
        if not rec.has_admission and rec.n_chunks == 0:
            stages = {"opaque": rec.span_s}
        rec.stages = stages
        if rec.e2e_s is not None:
            rec.identity_err_s = abs(rec.stage_sum() - rec.e2e_s)
            rec.identity_rel = rec.identity_err_s / max(rec.e2e_s, 1e-9)
    else:
        rec.stages = stages  # open trace: partial partition up to last event

    if rec.ttft_s is None and first_chunk_end is not None and submit is not None:
        rec.ttft_s = max(0.0, first_chunk_end - t_root)
    if (rec.e2e_s is not None and rec.ttft_s is not None
            and rec.output_len and rec.output_len > 1):
        rec.inter_token_s = max(0.0, rec.e2e_s - rec.ttft_s) / (rec.output_len - 1)

    # Post-terminal joins (reward, train consumption) use wall time:
    # they may legitimately come from another process.
    if terminal is not None:
        t_done_wall = float(terminal["ts"])
        reward_e = next((e for e in events if e["event"] == "reward"), None)
        if reward_e is not None:
            rec.reward = reward_e.get("reward")
            rec.reward_latency_s = max(0.0, float(reward_e["ts"]) - t_done_wall)
        consume = next((e for e in events if e["event"] == "train_consume"), None)
        if consume is not None:
            rec.staleness = consume.get("staleness")
            rec.consume_latency_s = max(0.0, float(consume["ts"]) - t_done_wall)
    return rec


_ORPHAN_CAP = 32  # keep completeness reports bounded


def analyze(source: EventSource, *, strict_open: bool = False,
            dropped_events: Optional[int] = None) -> TraceReport:
    """Parse a lifecycle event log into per-trajectory records plus a
    completeness verdict.

    ``dropped_events`` overrides drop detection (pass ``EVENTS.dropped``
    when analyzing a live snapshot; JSONL dumps carry a
    ``telemetry_meta`` trailer instead).  ``strict_open`` additionally
    fails completeness on traces still in flight at dump time — use it
    when the producer is known to have drained (tail-truncation check).
    """
    events = iter_events(source)
    comp = Completeness(strict_open=strict_open, n_events=len(events))

    dropped = 0
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    by_key: Dict[int, str] = {}
    submit_seen: set = set()
    pauses: List[Dict[str, Any]] = []
    restarts: List[Dict[str, Any]] = []
    chunk_by_tier: Dict[int, List[float]] = {}
    unmatched_consumes = 0
    for e in events:
        name = e.get("event")
        if name == "telemetry_meta":
            dropped += int(e.get("dropped_events", 0) or 0)
            continue
        if name == "pause":
            pauses.append(e)
            continue
        if name == "run_restart":
            restarts.append(e)
            continue
        if name == "train_consume":
            tid = by_key.get(e.get("trace_key"))
            if tid is None:
                unmatched_consumes += 1
            else:
                by_trace[tid].append(e)
            continue
        tids: List[str] = []
        if e.get("trace_id"):
            tids = [e["trace_id"]]
        elif name in ("decode_chunk", "spec_verify"):
            tids = list(e.get("trace_ids") or [])
            lat = e.get("latency_s")
            if lat is not None:
                chunk_by_tier.setdefault(int(e.get("tier", -1) or -1),
                                         []).append(float(lat))
        elif name not in _GLOBAL_EVENTS:
            comp.errors.append(f"traceless event: {name}")
            continue
        for tid in tids:
            by_trace.setdefault(tid, []).append(e)
            if name == "rollout_submit":
                submit_seen.add(tid)
                if e.get("trace_key") is not None:
                    by_key[e["trace_key"]] = tid
            elif name == "resubmit" and tid not in submit_seen:
                # every failover resubmit must join a trace whose
                # original submit is still in the log, *earlier*
                comp.unjoined_resubmits += 1

    if dropped_events is not None:
        dropped = max(dropped, int(dropped_events))
    comp.dropped_events = dropped
    comp.unmatched_consumes = unmatched_consumes

    records: List[TrajectoryRecord] = []
    for tid, evs in by_trace.items():
        rec = _build_record(tid, evs)
        records.append(rec)
        if not any(ev["event"] in _ROOT_EVENTS for ev in evs):
            if len(comp.orphan_traces) < _ORPHAN_CAP:
                comp.orphan_traces.append(tid)
            else:
                comp.errors.append("orphan list capped")
        elif not rec.closed:
            comp.open_traces += 1
        elif not rec.lost and rec.interrupts:
            # on a closed, delivered trace every interrupt must have
            # been followed by a resume or re-admission before gen_done
            seq = [ev["event"] for ev in evs]
            for i, name in enumerate(seq):
                if name == "interrupt" and not any(
                        s in ("resume", "resubmit", "admission")
                        for s in seq[i + 1:]):
                    comp.incomplete_interrupts += 1
    comp.n_traces = len(records)

    comp.complete = (
        comp.dropped_events == 0
        and not comp.orphan_traces
        and comp.unjoined_resubmits == 0
        and comp.incomplete_interrupts == 0
        and not comp.errors
        and (not strict_open or comp.open_traces == 0)
    )

    wall = [float(e["ts"]) for e in events if "ts" in e]
    span = (max(wall) - min(wall)) if wall else 0.0
    return TraceReport(records=records, completeness=comp, pauses=pauses,
                       chunk_latency_by_tier=chunk_by_tier, wall_span_s=span,
                       restarts=restarts)


@dataclasses.dataclass
class AccountingCheck:
    ok: bool
    tolerance: float
    abs_floor_s: float
    checked: int
    violations: int
    max_rel_err: Optional[float]
    mean_rel_err: Optional[float]


def check_accounting(records: List[TrajectoryRecord], *,
                     tolerance: float = 0.05,
                     abs_floor_s: float = 0.025) -> AccountingCheck:
    """Verify the accounting identity over all closed trajectories that
    carry a client-measured end-to-end: the event-derived stage sum must
    match ``gen_done.latency_s`` within ``tolerance`` (relative) or
    ``abs_floor_s`` (absolute — sub-floor jitter on very fast CPU-rig
    trajectories is measurement noise, not a broken decomposition)."""
    rels: List[float] = []
    violations = 0
    for r in records:
        if r.identity_rel is None or r.identity_err_s is None:
            continue
        rels.append(r.identity_rel)
        if r.identity_rel > tolerance and r.identity_err_s > abs_floor_s:
            violations += 1
    return AccountingCheck(
        ok=violations == 0,
        tolerance=tolerance,
        abs_floor_s=abs_floor_s,
        checked=len(rels),
        violations=violations,
        max_rel_err=max(rels) if rels else None,
        mean_rel_err=sum(rels) / len(rels) if rels else None,
    )
