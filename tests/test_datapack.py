import numpy as np
import pytest

from areal_tpu.utils.datapack import (
    allocate_balanced_mbs,
    balanced_partition,
    ffd_allocate,
    round_up_to_bucket,
)


def test_ffd_respects_capacity():
    sizes = [5, 3, 8, 2, 7, 1]
    bins = ffd_allocate(sizes, capacity=10)
    seen = sorted(i for b in bins for i in b)
    assert seen == list(range(len(sizes)))
    for b in bins:
        assert sum(sizes[i] for i in b) <= 10


def test_ffd_oversize_item_gets_own_bin():
    bins = ffd_allocate([100, 1], capacity=10)
    assert any(b == [0] for b in bins)


def test_ffd_min_groups():
    bins = ffd_allocate([1, 1, 1, 1], capacity=100, min_groups=3)
    assert len(bins) >= 3
    assert all(b for b in bins)


def test_balanced_partition_balance():
    sizes = np.random.randint(1, 100, size=64)
    groups = balanced_partition(sizes, 4)
    loads = [sum(sizes[i] for i in g) for g in groups]
    assert max(loads) - min(loads) <= max(sizes)
    assert sorted(i for g in groups for i in g) == list(range(64))


def test_allocate_balanced_mbs_modes():
    sizes = [4, 4, 4, 4]
    assert len(allocate_balanced_mbs(sizes, None, 2)) == 2
    bins = allocate_balanced_mbs(sizes, max_tokens_per_mb=8)
    assert all(sum(sizes[i] for i in b) <= 8 for b in bins)


def test_round_up_to_bucket():
    assert round_up_to_bucket(1, 512) == 512
    assert round_up_to_bucket(512, 512) == 512
    assert round_up_to_bucket(513, 512) == 1024
    assert round_up_to_bucket(1500, 512) == 2048
    assert round_up_to_bucket(5000, 512, max_len=4096) == 4096


def test_min_groups_too_many():
    with pytest.raises(ValueError):
        ffd_allocate([1], capacity=10, min_groups=2)
