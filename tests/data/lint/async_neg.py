"""C3 negative fixture: the loop-safe versions.  Zero findings expected."""

import asyncio
import time


async def handler(request, session, loop):
    await asyncio.sleep(0.1)  # cooperative wait

    def blocking_read():  # executor fodder: sync nested def is exempt
        with open("/tmp/state.json") as f:
            return f.read()

    data = await loop.run_in_executor(None, blocking_read)
    async with session.get("http://backend/health") as resp:
        body = await resp.json()
    return body, data


def sync_helper():
    time.sleep(0.1)  # blocking is fine off the loop
