"""Algorithm engines: PPO actor (GRPO), critic, SFT, reward model.

Ports the reference's algorithm-level checks (areal/tests/test_train_engine.py
and the grpo/sft integration suites) to the CPU mesh: advantage math,
decoupled-loss updates that actually move the policy toward rewarded
sequences, critic value regression, and BT reward-model separation."""

import numpy as np
import pytest

from areal_tpu.api.config import (
    MeshConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
    PPOCriticConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.engine.ppo import JaxPPOActor, JaxPPOCritic
from areal_tpu.engine.rw import JaxRewardModelEngine
from areal_tpu.engine.sft import JaxLMEngine
from areal_tpu.models.model_config import tiny_config

MODEL_CFG = tiny_config(vocab_size=64, qkv_bias=True, hf_architecture="Qwen2ForCausalLM")


def _base_kwargs(mesh=None, n_mbs=1, lr=5e-3):
    return dict(
        experiment_name="t",
        trial_name="t",
        init_from_scratch=True,
        dtype="float32",
        gradient_checkpointing=False,
        mesh=mesh or MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=n_mbs),
        optimizer=OptimizerConfig(lr=lr, warmup_steps_proportion=0.0, weight_decay=0.0),
        pack_length_quantum=16,
    )


def _rollout_batch(rng, B=8, L=16, prompt_len=4):
    """Fake RLVR trajectories: group_size=4, reward 1 for sequences whose
    first completion token is even, else 0."""
    ids = rng.integers(0, MODEL_CFG.vocab_size, (B, L)).astype(np.int32)
    mask = np.ones((B, L), bool)
    loss_mask = np.zeros((B, L), np.float32)
    loss_mask[:, prompt_len:] = 1.0
    rewards = (ids[:, prompt_len] % 2 == 0).astype(np.float32)
    logprobs = rng.normal(-1.0, 0.1, (B, L)).astype(np.float32) * loss_mask
    return {
        "input_ids": ids,
        "attention_mask": mask,
        "loss_mask": loss_mask,
        "logprobs": logprobs,
        "rewards": rewards,
        "versions": np.zeros((B, L), np.int32),
    }


def _actor(group_size=4, **kw):
    kw.setdefault(
        "adv_norm",
        NormConfig(mean_level="group", std_level="group", group_size=group_size),
    )
    cfg = PPOActorConfig(
        **_base_kwargs(),
        group_size=group_size,
        ppo_n_minibatches=2,
        eps_clip=0.2,
        **kw,
    )
    actor = JaxPPOActor(cfg, model_config=MODEL_CFG)
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    return actor


def test_compute_advantages_group_norm():
    rng = np.random.default_rng(0)
    actor = _actor()
    batch = _rollout_batch(rng)
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    adv, mask = batch["advantages"], batch["loss_mask"]
    assert adv.shape == mask.shape
    # group-normalised advantages: ~zero mean within each group's tokens
    g = adv.reshape(2, 4, -1)
    gm = mask.reshape(2, 4, -1)
    for i in range(2):
        m = (g[i] * gm[i]).sum() / gm[i].sum()
        assert abs(m) < 0.2, m
    # constant-per-sequence advantages under gamma=lam=1 with terminal reward
    per_seq = [np.unique(np.round(adv[b][mask[b] > 0], 5)) for b in range(8)]
    assert all(len(u) == 1 for u in per_seq)


def test_advantage_alignment_predictor_positions():
    """The terminal reward must land at the predictor position of the final
    completion token (t = last-1 token-aligned)."""
    actor = _actor(group_size=1, adv_norm=None)
    B, L = 1, 8
    batch = {
        "input_ids": np.arange(L, dtype=np.int32)[None],
        "attention_mask": np.ones((B, L), bool),
        "loss_mask": np.concatenate([np.zeros(4), np.ones(4)]).astype(np.float32)[None],
        "logprobs": np.zeros((B, L), np.float32),
        "rewards": np.array([1.0], np.float32),
        "versions": np.zeros((B, L), np.int32),
    }
    actor.compute_advantages(batch)
    mask = batch["loss_mask"]
    np.testing.assert_array_equal(mask[0], [0, 0, 0, 1, 1, 1, 1, 0])
    # gamma=lam=1, values=0: advantage == reward-to-go == 1 on all completion
    np.testing.assert_allclose(batch["advantages"][0][mask[0] > 0], 1.0, atol=1e-6)


def test_ppo_update_moves_policy_toward_reward():
    rng = np.random.default_rng(1)
    actor = _actor()
    batch = _rollout_batch(rng)
    batch["prox_logp"] = actor.compute_logp(batch)
    before = batch["prox_logp"].copy()
    actor.compute_advantages(batch)
    for _ in range(4):
        stats = actor.ppo_update(batch)
    after = actor.compute_logp(batch)
    mask, adv = batch["loss_mask"], batch["advantages"]
    delta = (after - before) * mask
    corr = np.corrcoef(delta[mask > 0], adv[mask > 0])[0, 1]
    assert corr > 0.2, corr  # positive-advantage tokens got more likely
    assert all(np.isfinite(s["loss"]) for s in stats)


def test_dynamic_sampling_filters_uniform_groups():
    rng = np.random.default_rng(2)
    actor = _actor(dynamic_sampling=True)
    batch = _rollout_batch(rng)
    batch["rewards"][:4] = 1.0  # first group uniform -> dropped
    batch["rewards"][4:] = np.array([0, 1, 0, 1], np.float32)
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    keep = actor.actor._dynamic_filter(batch)
    assert keep is not None and list(keep) == [4, 5, 6, 7]
    stats = actor.ppo_update(batch)
    assert len(stats) == 2


def test_critic_trains_and_predicts_returns():
    rng = np.random.default_rng(3)
    cfg = PPOCriticConfig(**_base_kwargs(lr=1e-2), ppo_n_minibatches=2)
    critic = JaxPPOCritic(cfg, model_config=MODEL_CFG)
    critic.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    B, L = 8, 12
    batch = {
        "input_ids": rng.integers(0, 64, (B, L)).astype(np.int32),
        "attention_mask": np.ones((B, L), bool),
        "loss_mask": np.ones((B, L), np.float32),
        "returns": np.tile(
            (np.arange(B) % 2).astype(np.float32)[:, None], (1, L)
        ),
    }
    batch["values"] = critic.compute_values(batch)
    assert batch["values"].shape == (B, L)
    first = np.abs(batch["values"] - batch["returns"]).mean()
    for _ in range(30):
        batch["values"] = critic.compute_values(batch)
        critic.ppo_update(batch)
    last = np.abs(critic.compute_values(batch) - batch["returns"]).mean()
    assert last < first * 0.5, (first, last)


def test_sft_engine_ppl_drops():
    rng = np.random.default_rng(4)
    eng = JaxLMEngine(TrainEngineConfig(**_base_kwargs(lr=1e-2)), model_config=MODEL_CFG)
    eng.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    B, L = 8, 12
    batch = {
        "input_ids": rng.integers(0, 64, (B, L)).astype(np.int32),
        "attention_mask": np.ones((B, L), bool),
        "loss_mask": np.ones((B, L), np.float32),
    }
    ppls = [eng.train_lm(batch)["ppl"] for _ in range(6)]
    assert ppls[-1] < ppls[0] * 0.5, ppls
    ev = eng.evaluate_lm(batch)
    assert ev["ppl"] < ppls[0]


def test_reward_model_separates_pairs():
    rng = np.random.default_rng(5)
    cfg = PPOCriticConfig(**_base_kwargs(lr=1e-2))
    rw = JaxRewardModelEngine(cfg, model_config=MODEL_CFG)
    rw.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    B, L = 8, 10
    # chosen rows (even) start with token 1, rejected with token 2
    ids = rng.integers(3, 64, (B, L)).astype(np.int32)
    ids[0::2, 0] = 1
    ids[1::2, 0] = 2
    batch = {
        "input_ids": ids,
        "attention_mask": np.ones((B, L), bool),
    }
    accs = [rw.train_rw(batch)["acc"] for _ in range(25)]
    assert accs[-1] == 1.0, accs


def test_critic_values_not_shifted_by_advantage_pipeline():
    """values go through compute_advantages un-rolled: the value head output
    at position t is already V(state before token t+1)."""
    actor = _actor(group_size=1, adv_norm=None, kl_ctl=0.0)
    B, L = 1, 6
    values = np.array([[0.1, 0.2, 0.3, 0.4, 0.5, 0.6]], np.float32)
    batch = {
        "input_ids": np.arange(L, dtype=np.int32)[None],
        "attention_mask": np.ones((B, L), bool),
        "loss_mask": np.array([[0, 0, 1, 1, 1, 1]], np.float32),
        "logprobs": np.zeros((B, L), np.float32),
        "rewards": np.array([1.0], np.float32),
        "versions": np.zeros((B, L), np.int32),
        "values": values.copy(),
    }
    actor.compute_advantages(batch)
    mask = batch["loss_mask"]  # predictor-aligned: positions 1..4
    np.testing.assert_array_equal(mask[0], [0, 1, 1, 1, 1, 0])
    # gamma=lam=1: returns[t] = sum future rewards = 1 at all masked t,
    # advantages = returns - values at the SAME (unshifted) positions
    np.testing.assert_allclose(
        batch["advantages"][0][1:5], 1.0 - values[0][1:5], atol=1e-5
    )
    np.testing.assert_allclose(batch["returns"][0][1:5], 1.0, atol=1e-5)


def test_reward_model_handles_wide_padding():
    """Batch padded far wider than its longest sequence must not crash row
    preparation (padded width > bucketed row_len)."""
    rng = np.random.default_rng(7)
    cfg = PPOCriticConfig(**_base_kwargs(lr=1e-2))
    rw = JaxRewardModelEngine(cfg, model_config=MODEL_CFG)
    rw.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    B, L = 4, 64  # quantum=16 -> row_len 16 < L
    ids = rng.integers(3, 64, (B, L)).astype(np.int32)
    mask = np.zeros((B, L), bool)
    mask[:, :10] = True
    stats = rw.train_rw({"input_ids": ids, "attention_mask": mask})
    assert np.isfinite(stats["loss"])


def test_warm_shapes_precompiles_without_side_effects():
    """warm_shapes runs the full logp/advantage/update pipeline for each
    shape signature, then restores params + optimizer state exactly —
    production loops call it up front so varying rollout lengths never
    trigger an XLA compile inside the timed training path."""
    import jax

    actor = _actor(recompute_logprob=True, use_decoupled_loss=True)
    p0 = jax.tree_util.tree_map(np.asarray, actor.params)
    actor.warm_shapes([(8, 16), (8, 32)])
    # params and optimizer state restored bit-exactly
    for a, b in zip(
        jax.tree_util.tree_leaves(p0),
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, actor.params)
        ),
    ):
        np.testing.assert_array_equal(a, b)
    # a real update afterwards still works and DOES move params
    rng = np.random.default_rng(5)
    batch = _rollout_batch(rng)
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    actor.ppo_update(batch)
    actor.flush_stats()
    moved = any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(p0),
            jax.tree_util.tree_leaves(actor.params),
        )
    )
    assert moved
    # group-size divisibility is enforced
    import pytest as _pytest

    with _pytest.raises(ValueError):
        actor.warm_shapes([(6, 16)])


def test_warm_shapes_covers_real_batch_signature():
    """The program warm_shapes AOT-compiles must be the one the REAL loop
    requests: a rollout batch carrying extra wire keys (versions, rewards)
    and int32 loss_mask must present the SAME filtered jit signature as the
    warm batch (forward() filters to FORWARD_KEYS — regression: the warm
    compiled a float32-loss_mask/no-extras signature no real call hit)."""
    actor = _actor(recompute_logprob=True, use_decoupled_loss=True)
    eng = actor  # JaxPPOActor IS the engine

    def fwd_signature(batch):
        rp, data, row_len = eng._prepare_rows(dict(batch), 1)
        view = eng._forward_batch_view(data)
        return row_len, {
            k: (v.shape, str(np.asarray(v).dtype)) for k, v in view.items()
        }

    # the synthetic warm batch for signature (8, 16)
    rng0 = np.random.default_rng(0)
    warm_batch = {
        "input_ids": rng0.integers(0, MODEL_CFG.vocab_size, (8, 16)).astype(
            np.int32),
        "attention_mask": np.ones((8, 16), bool),
        "loss_mask": np.concatenate(
            [np.zeros((8, 4), np.float32), np.ones((8, 12), np.float32)], 1),
        "logprobs": np.zeros((8, 16), np.float32),
        "rewards": np.zeros(8, np.float32),
    }
    # a real rollout batch with wire extras + int32 loss_mask
    real_batch = _rollout_batch(np.random.default_rng(11), B=8, L=16)
    assert "versions" in real_batch
    assert fwd_signature(warm_batch) == fwd_signature(real_batch)

    # and end to end: warm, then the real pipeline runs without error and
    # repeated calls do not grow the forward jit cache
    actor.warm_shapes([(8, 16)])
    [fwd] = [f for k, f in actor._forward_cache.items() if k[0] == "fwd"]
    for seed in (11, 12):
        b = _rollout_batch(np.random.default_rng(seed), B=8, L=16)
        b["prox_logp"] = actor.compute_logp(b)
        actor.compute_advantages(b)
        actor.ppo_update(b)
    actor.flush_stats()
    assert fwd._cache_size() <= 1, "forward retraced across identical shapes"
