from areal_tpu.controller.batch import DistributedBatch
from areal_tpu.controller.train_controller import TrainController

__all__ = ["DistributedBatch", "TrainController"]
