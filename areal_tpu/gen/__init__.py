from areal_tpu.gen.engine import GenEngine, GenRequest

__all__ = ["GenEngine", "GenRequest"]
