"""C7 positive fixture: every VIOLATION-marked line must be flagged."""


class Pool:
    _SLOT_TYPESTATE = {
        "owner": "slot_req",
        "acquire_writes": ["lengths", "temperature"],
        "release_writes": ["_reserved_until"],
        "version_field": "kv_version",
        "retained_field": "retained_len",
    }

    def __init__(self, n):
        self.slot_req = [None] * n
        self.lengths = [0] * n
        self.temperature = [1.0] * n
        self.retained_len = [0] * n
        self.kv_version = [0] * n
        self._reserved_until = [0.0] * n

    def double_free(self, s):
        self.slot_req[s] = None
        self.retained_len[s] = self.lengths[s]
        self.slot_req[s] = None  # VIOLATION slot-double-free

    def leaky_acquire(self, s, req):
        self.slot_req[s] = req  # VIOLATION slot-lifecycle (missing writes)

    def free_without_retain(self, s):
        self.slot_req[s] = None  # VIOLATION slot-lifecycle (no retained)

    def write_after_free(self, s):
        self.slot_req[s] = None
        self.retained_len[s] = self.lengths[s]
        self.lengths[s] = 0  # VIOLATION slot-lifecycle (use after free)

    def reuse_unversioned(self, s, req):  # VIOLATION retained-unversioned
        if self.retained_len[s] > 4:
            self.slot_req[s] = req
            self.lengths[s] = self.retained_len[s]
            self.temperature[s] = 1.0
