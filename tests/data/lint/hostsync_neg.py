"""C2 negative fixture (marked hot): the disciplined versions.

Zero findings expected: bucketed shape ints, host-only numpy work, and a
reassignment that makes a former device result host-resident before any
conversion.
"""
# areal-lint: hot-path

import numpy as np

from areal_tpu.utils.datapack import round_up_to_bucket


def disciplined(self, prompts):
    bucket = round_up_to_bucket(
        max(len(r) for r in prompts), self.prompt_bucket, self.max_seq_len
    )
    rows = 1 << (len(prompts) - 1).bit_length()  # pow2 ladder: bucketed
    ids = np.zeros((rows, bucket), np.int32)  # host-side staging is free
    toks, cache = self._prefill_fn(self.params, ids, bucket)
    self.cache = cache  # stays on device
    plens = np.ones(rows, np.int32)
    total = int(plens.sum())  # int() on host numpy: no fence
    return toks, total


def sync_then_chain(self):
    import jax.numpy as jnp

    # uploads of standing state live in a dedicated sync (NOT a jit-call
    # argument), and the steady-state loop chains device results instead
    self._dev_lengths = jnp.asarray(self.lengths)
    out, self._dev_lengths = self._decode_fn(self.params, self._dev_lengths)
    # per-batch LOCALS as jit args are new data, not a re-upload
    rows = np.zeros(4, np.int32)
    return self._prefill_fn(self.params, jnp.asarray(rows), out)


def host_only(batch):
    mask = np.asarray(batch["mask"])  # wire data, never device-resident
    return float(mask.mean())
