"""StalenessManager tests — parity with reference test_staleness_manager.py
(the capacity formula at staleness_manager.py:96 is the contract)."""

import threading

from areal_tpu.core.staleness import StalenessManager


def test_concurrency_cap():
    m = StalenessManager(max_concurrent_rollouts=4, consumer_batch_size=100,
                         max_staleness=100)
    assert m.get_capacity(0) == 4
    for _ in range(4):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    m.on_rollout_accepted()
    assert m.get_capacity(0) == 1


def test_staleness_limit_zero():
    # η=0: at version v, total samples allowed = (v+1)*B
    B = 4
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=B,
                         max_staleness=0)
    assert m.get_capacity(0) == B
    for _ in range(B):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    # accepting does not free budget at the same version
    for _ in range(B):
        m.on_rollout_accepted()
    assert m.get_capacity(0) == 0
    # version bump frees exactly one more batch
    assert m.get_capacity(1) == B


def test_staleness_limit_eta():
    B, eta = 2, 3
    m = StalenessManager(max_concurrent_rollouts=1000, consumer_batch_size=B,
                         max_staleness=eta)
    assert m.get_capacity(0) == (eta + 1) * B
    for _ in range((eta + 1) * B):
        m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    assert m.get_capacity(2) == 2 * B


def test_rejected_rollouts_free_capacity():
    m = StalenessManager(max_concurrent_rollouts=10, consumer_batch_size=2,
                         max_staleness=0)
    m.on_rollout_submitted()
    m.on_rollout_submitted()
    assert m.get_capacity(0) == 0
    m.on_rollout_rejected()
    # rejected sample no longer counts against staleness budget
    assert m.get_capacity(0) == 1


def test_negative_capacity():
    m = StalenessManager(max_concurrent_rollouts=2, consumer_batch_size=1,
                         max_staleness=0)
    for _ in range(2):
        m.on_rollout_submitted()
    # staleness budget of 1 sample, 2 running -> negative
    assert m.get_capacity(0) < 0


def test_min_clamps():
    m = StalenessManager(max_concurrent_rollouts=0, consumer_batch_size=0,
                         max_staleness=0)
    # clamped to 1 concurrent & batch size 1
    assert m.get_capacity(0) == 1


def test_thread_safety():
    m = StalenessManager(max_concurrent_rollouts=10**6,
                         consumer_batch_size=10**6, max_staleness=10)
    n, iters = 8, 500

    def work():
        for _ in range(iters):
            m.on_rollout_submitted()
            m.on_rollout_accepted()

    threads = [threading.Thread(target=work) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    s = m.get_stats()
    assert s.submitted == n * iters
    assert s.accepted == n * iters
    assert s.running == 0
