"""Rank-0 experiment metric logging (reference: areal/utils/stats_logger.py).

Sinks: JSONL file (always), tensorboard (if available), wandb (if available —
not in this image, so it degrades to a no-op with a warning).
"""

import json
import os
import time
from typing import Dict, List, Optional

from areal_tpu.api.config import StatsLoggerConfig
from areal_tpu.api.io_struct import StepInfo
from areal_tpu.utils import logging

logger = logging.getLogger("stats_logger")


class StatsLogger:
    def __init__(self, config: StatsLoggerConfig, is_main: bool = True):
        self.config = config
        self.is_main = is_main
        self._start = time.monotonic()
        self._jsonl = None
        self._tb = None
        if not is_main:
            return
        root = self.get_log_path(config)
        os.makedirs(root, exist_ok=True)
        self._jsonl = open(os.path.join(root, "stats.jsonl"), "a")
        tb_dir = config.tensorboard_dir
        if tb_dir:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir=tb_dir)
            except Exception as e:  # pragma: no cover
                logger.warning(f"tensorboard unavailable: {e}")

    @staticmethod
    def get_log_path(config: StatsLoggerConfig) -> str:
        return os.path.join(
            config.fileroot or "/tmp/areal_tpu",
            "logs",
            config.experiment_name,
            config.trial_name,
        )

    def commit(
        self,
        epoch: int,
        step: int,
        global_step: int,
        data: Dict[str, float] | List[Dict[str, float]],
    ):
        if isinstance(data, list):
            merged: Dict[str, float] = {}
            for d in data:
                merged.update(d)
            data = merged
        if not self.is_main:
            return
        elapsed = time.monotonic() - self._start
        logger.info(
            f"Epoch {epoch + 1} step {step + 1} (global {global_step + 1}) "
            f"[{elapsed:.1f}s]: "
            + " ".join(f"{k}={v:.4g}" for k, v in sorted(data.items()))
        )
        rec = {"epoch": epoch, "step": step, "global_step": global_step, **data}
        self._jsonl.write(json.dumps(rec) + "\n")
        self._jsonl.flush()
        if self._tb is not None:
            for k, v in data.items():
                self._tb.add_scalar(k, v, global_step)

    def commit_step_info(self, step_info: StepInfo, data):
        self.commit(step_info.epoch, step_info.epoch_step, step_info.global_step, data)

    def close(self):
        if self._jsonl is not None:
            self._jsonl.close()
            self._jsonl = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    # state_dict/load for recover parity with the reference
    def state_dict(self):
        return {"start_offset": time.monotonic() - self._start}

    def load_state_dict(self, state):
        self._start = time.monotonic() - state.get("start_offset", 0.0)
