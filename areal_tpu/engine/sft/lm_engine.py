"""Supervised finetuning engine.

Behavioral counterpart of the reference's `LMEngine`/`FSDPLMEngine`
(areal/engine/sft/lm_engine.py): token cross-entropy over completion tokens,
globally normalised by valid-token count.
"""

from typing import Dict

import numpy as np

from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.ops.functional import sft_loss_fn


def _weight(batch: Dict[str, np.ndarray]) -> float:
    return float(np.sum(batch["loss_mask"]))


class JaxLMEngine(JaxTrainEngine):
    """Trajectory convention: `loss_mask[t] = 1` iff token t is a completion
    token; the engine shifts it to predictor alignment internally."""

    @staticmethod
    def _predictor_align(batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = dict(batch)
        mask = np.roll(batch["loss_mask"].astype(np.float32), -1, axis=-1)
        mask[:, -1] = 0.0
        out["loss_mask"] = mask * batch["attention_mask"]
        return out

    def train_lm(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        batch = self._predictor_align(batch)
        stats = self.train_batch(batch, sft_loss_fn, _weight)
        n = max(stats.get("n_valid_tokens", 1.0), 1.0)
        stats["ppl"] = float(np.exp(min(stats["loss_sum"] / n, 30.0)))
        stats["token_acc"] = stats.get("correct_tokens", 0.0) / n
        return stats

    def evaluate_lm(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        batch = self._predictor_align(batch)
        stats = self.eval_batch(batch, sft_loss_fn, _weight)
        n = max(stats.get("n_valid_tokens", 1.0), 1.0)
        stats["ppl"] = float(np.exp(min(stats["loss_sum"] / n, 30.0)))
        return stats
