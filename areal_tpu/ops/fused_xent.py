"""Fused LM-head cross-entropy / logprob with a hand-written VJP.

VERDICT r3 #2: the chunked-scan head cost ~196 ms/step against a ~155 ms
4-matmul-pass floor (fwd + bwd logits recompute + dx + dW; storing [T, V]
logits for a 3-pass backward needs ~5 GB and cannot fit next to the
resident optimizer state).  The ~40 ms gap was pure overhead: fp32 logits
materialisation, the scan transpose shuttling a [D, V] fp32 head-cotangent
carry through every token chunk, and entropy/argmax work that re-read the
logits — all for outputs the GRPO loss uses as *stats only*.

This implementation is the TPU counterpart of the reference's
vocab-parallel cross-entropy (realhf/impl/model/parallelism/
tensor_parallel/modules.py:1180 vocab_parallel_cross_entropy) — same
discipline (never hold full fp32 logits), achieved by **vocab chunking
with an online softmax** instead of sharding vocab across ranks:

- forward: one `lax.scan` over vocab chunks keeps running (max, sumexp,
  sum(exp*l), picked-logit, argmax) carries of size [T] — logits exist
  only as a [T, cv] bf16 block inside each step;
- backward: recomputes each vocab chunk's logits once, forms
  dlogits = g_lp * (onehot - p) in-register, accumulates dx in a [T, D]
  fp32 carry (~100 MB — vs the [D, V] ~933 MB carry the token-chunked
  scan transpose dragged through every step) and writes each dW vocab
  slice exactly once;
- entropy is returned for stats but its gradient term is only computed
  when the caller actually trains on it (`entropy_grad`); the argmax
  "correct" output is always gradient-free.
"""
# areal-lint: hot-path

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _vocab_chunk(v: int, target: int) -> int:
    """MXU-friendly chunk width: a multiple of 128 (the systolic array's
    lane width — an exact-divisor rule would hand Qwen's 151936 = 2^7*1187
    vocab a 4748-wide chunk that tiles terribly), capped at the padded
    vocab size.  The final partial chunk is handled by masking."""
    return min(_round_up(v, 128), _round_up(target, 128))


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _fused_xent(inv_t, cv, with_entropy, entropy_grad, h, head, labels):
    out, _ = _fused_xent_fwd(inv_t, cv, with_entropy, entropy_grad, h, head, labels)
    return out


def _fused_xent_fwd(inv_t, cv, with_entropy, entropy_grad, h, head, labels):
    N, D = h.shape
    V = head.shape[1]
    nv = -(-V // cv)
    neg = jnp.float32(-1e30)
    wp = _pad_head(head, nv * cv)

    def one_chunk(carry, i):
        m, s, mu_un, picked, amax_v, amax_i = carry
        wc = jax.lax.dynamic_slice_in_dim(wp, i * cv, cv, axis=1)
        logits = (h @ wc).astype(jnp.float32) * inv_t
        # mask the padded tail of the last chunk out of the softmax
        logits = jnp.where(i * cv + jnp.arange(cv) < V, logits, neg)
        cm = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, cm)
        alpha = jnp.exp(m - m_new)
        ex = jnp.exp(logits - m_new[:, None])
        s = s * alpha + jnp.sum(ex, axis=-1)
        rel = labels - i * cv
        inrange = (rel >= 0) & (rel < cv)
        got = jnp.take_along_axis(
            logits, jnp.clip(rel, 0, cv - 1)[:, None], axis=1
        )[:, 0]
        picked = jnp.where(inrange, got, picked)
        if with_entropy:
            mu_un = mu_un * alpha + jnp.sum(ex * logits, axis=-1)
            ci = jnp.argmax(logits, axis=-1) + i * cv
            better = cm > amax_v
            amax_v = jnp.where(better, cm, amax_v)
            amax_i = jnp.where(better, ci, amax_i)
        return (m_new, s, mu_un, picked, amax_v, amax_i), None

    init = (
        jnp.full((N,), neg),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.full((N,), neg),
        jnp.zeros((N,), jnp.int32),
    )
    (m, s, mu_un, picked, _, amax_i), _ = jax.lax.scan(
        one_chunk, init, jnp.arange(nv)
    )
    logz = m + jnp.log(s)
    logp = picked - logz
    if with_entropy:
        mu = mu_un / s
        ent = logz - mu
        corr = (amax_i == labels).astype(jnp.float32)
    else:
        mu = jnp.zeros_like(logz)
        ent = jnp.zeros_like(logz)
        corr = jnp.zeros_like(logz)
    return (logp, ent, corr), (h, head, labels, logz, mu)


def _pad_head(head, vp: int):
    V = head.shape[1]
    if vp == V:
        return head
    return jnp.pad(head, ((0, 0), (0, vp - V)))


def _fused_xent_bwd(inv_t, cv, with_entropy, entropy_grad, res, g):
    h, head, labels, logz, mu = res
    g_lp, g_ent, _ = g  # corr is gradient-free by construction
    N, D = h.shape
    V = head.shape[1]
    nv = -(-V // cv)
    wp = _pad_head(head, nv * cv)
    g_lp = g_lp.astype(jnp.float32)
    g_ent = g_ent.astype(jnp.float32)

    def one(dx, i):
        wc = jax.lax.dynamic_slice_in_dim(wp, i * cv, cv, axis=1)
        logits = (h @ wc).astype(jnp.float32) * inv_t
        # padded-tail logits produce p=0 via the same mask the fwd used
        logits = jnp.where(
            i * cv + jnp.arange(cv) < V, logits, jnp.float32(-1e30)
        )
        p = jnp.exp(logits - logz[:, None])  # [N, cv]
        rel = labels - i * cv
        onehot = jnp.arange(cv)[None, :] == rel[:, None]
        d = g_lp[:, None] * (onehot.astype(jnp.float32) - p)
        if entropy_grad:
            # d ent / d logit_v = p_v * (mu - logit_v)
            d = d + g_ent[:, None] * p * (mu[:, None] - logits)
        draw = (d * inv_t).astype(h.dtype)  # back through the scale + cast
        dx = dx + jnp.einsum(
            "nc,dc->nd", draw, wc, preferred_element_type=jnp.float32
        )
        dwc = jnp.einsum(
            "nd,nc->dc", h, draw, preferred_element_type=jnp.float32
        )
        return dx, dwc

    dx, dws = jax.lax.scan(one, jnp.zeros((N, D), jnp.float32), jnp.arange(nv))
    # dws [nv, D, cv] -> [D, Vp] -> [D, V]; each slice was written once
    dhead = (
        jnp.swapaxes(dws, 0, 1).reshape(D, nv * cv)[:, :V].astype(head.dtype)
    )
    return (
        dx.astype(h.dtype),
        dhead,
        np.zeros(labels.shape, dtype=jax.dtypes.float0),
    )


_fused_xent.defvjp(_fused_xent_fwd, _fused_xent_bwd)


def fused_logprobs_entropy(
    hidden: jax.Array,  # [N, D]
    head: jax.Array,  # [D, V]
    labels: jax.Array,  # int [N]
    temperature: float = 1.0,
    vocab_chunk: int = 8192,
    with_entropy: bool = True,
    entropy_grad: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(logprobs, entropy, argmax-correct) of `labels`, fp32 [N] each.

    `entropy_grad=False` (the GRPO default: entropy_coef == 0 means the
    entropy is logged, never trained on) drops the p*(mu - logits) term
    from the backward — one less elementwise pass over each recomputed
    logits block.  Entropy values are still exact either way.
    """
    cv = _vocab_chunk(head.shape[1], vocab_chunk)
    return _fused_xent(
        float(1.0 / temperature), cv, bool(with_entropy), bool(entropy_grad),
        hidden, head, labels.astype(jnp.int32),
    )
