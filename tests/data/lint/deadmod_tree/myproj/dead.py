"""DEAD: only tests/test_app.py imports this — test importers never
count, so the checker must flag it."""


def unreachable():
    return 42
