"""Sequence bin-packing for balanced micro-batches.

Capability counterpart of the reference's `areal/utils/datapack.py` (FFD
allocation used by `allocate_balanced_mbs`).  The packing runs per batch in
the rollout->train handoff, so the assignment loops dispatch to the native
C++ dataplane (areal_tpu/native) when it is available; the numpy paths
below are the semantics reference and the fallback.
"""

from typing import List, Optional, Sequence

import numpy as np

from areal_tpu import native


def ffd_allocate(
    sizes: Sequence[int],
    capacity: int,
    min_groups: int = 1,
) -> List[List[int]]:
    """First-fit-decreasing: pack items (by index) into the fewest bins of
    `capacity`, with at least `min_groups` bins.  Items larger than capacity
    get singleton bins."""
    sizes = np.asarray(sizes)
    if len(sizes) == 0:
        return [[] for _ in range(min_groups)]
    if min_groups > len(sizes):
        raise ValueError(
            f"cannot split {len(sizes)} items into {min_groups} non-empty groups"
        )
    order = np.argsort(-sizes, kind="stable")
    bins: List[List[int]] = []
    loads: List[int] = []
    bin_of = native.ffd_assign(sizes, capacity)
    if bin_of is not None:
        n_bins = int(bin_of.max()) + 1 if len(bin_of) else 0
        bins = [[] for _ in range(n_bins)]
        loads = [0] * n_bins
        for idx in order:  # same placement order as the Python loop
            b = int(bin_of[idx])
            bins[b].append(int(idx))
            loads[b] += int(sizes[idx])
    else:
        for idx in order:
            size = int(sizes[idx])
            placed = False
            for b in range(len(bins)):
                if loads[b] + size <= capacity:
                    bins[b].append(int(idx))
                    loads[b] += size
                    placed = True
                    break
            if not placed:
                bins.append([int(idx)])
                loads.append(size)
    while len(bins) < min_groups:
        # steal the last item of the heaviest multi-item bin
        donor = max(
            (b for b in range(len(bins)) if len(bins[b]) > 1),
            key=lambda b: loads[b],
        )
        item = bins[donor].pop()
        loads[donor] -= int(sizes[item])
        bins.append([item])
        loads.append(int(sizes[item]))
    return bins


def balanced_partition(sizes: Sequence[int], k: int) -> List[List[int]]:
    """Split items into exactly k groups minimizing the max group load
    (greedy LPT).  Used to balance sequences across dp ranks."""
    sizes = np.asarray(sizes)
    if k <= 0:
        raise ValueError("k must be positive")
    groups: List[List[int]] = [[] for _ in range(k)]
    group_of = native.lpt_assign(sizes, k)
    if group_of is not None:
        for idx in np.argsort(-sizes, kind="stable"):
            groups[int(group_of[idx])].append(int(idx))
        return groups
    loads = np.zeros(k, dtype=np.int64)
    for idx in np.argsort(-sizes, kind="stable"):
        b = int(np.argmin(loads))
        groups[b].append(int(idx))
        loads[b] += int(sizes[idx])
    return groups


def allocate_balanced_mbs(
    sizes: Sequence[int],
    max_tokens_per_mb: Optional[int] = None,
    n_mbs: int = 1,
) -> List[List[int]]:
    """Micro-batch allocation: FFD under a token cap when given, else an even
    LPT split into n_mbs groups (reference: datapack.py allocate_balanced_mbs)."""
    if max_tokens_per_mb and max_tokens_per_mb > 0:
        return ffd_allocate(sizes, max_tokens_per_mb, min_groups=max(1, n_mbs))
    return balanced_partition(sizes, max(1, n_mbs))


def round_up_to_bucket(n: int, quantum: int, max_len: Optional[int] = None) -> int:
    """Bucket a length to limit distinct XLA compilations: round up to the
    next power-of-two multiple of `quantum` ({1,2,4,...}*quantum)."""
    if n <= 0:
        return quantum
    bucket = quantum
    while bucket < n:
        bucket *= 2
    if max_len is not None:
        bucket = min(bucket, max_len)
    return bucket
