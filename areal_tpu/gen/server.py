"""HTTP generation server.

Serves the wire protocol the client backend speaks
(areal_tpu/engine/jax_remote.py) — the role SGLang's HTTP server plays for
the reference (areal/engine/sglang_remote.py:22 builds /generate,
/update_weights_from_disk, /pause_generation against it):

    POST /generate                 {rid, input_ids, sampling_params} ->
                                   {output_tokens, output_logprobs,
                                    output_versions, stop_reason, version}
    POST /pause_generation         decode loop parks (weight-update window)
    POST /continue_generation
    POST /update_weights_from_disk {path, version?}
    POST /update_weights_chunk     {name, dtype, shape, data_b64, commit?}
    GET  /health, /metrics

A dedicated worker thread owns all device computation (admission, decode
steps, weight swaps) so the asyncio loop never blocks on XLA; handlers talk
to it through the engine's queues and concurrent futures.  Registration in
name_resolve mirrors the reference's server wrappers
(areal/launcher/sglang_server.py registers its address for discovery).
"""

import argparse
import asyncio
import base64
import threading
import time
import weakref
from typing import Optional

import numpy as np

from aiohttp import web

from areal_tpu.analysis.lockcheck import lock_guarded
from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.models.model_config import TransformerConfig, tiny_config
from areal_tpu.utils import logging, name_resolve, names, network, telemetry

logger = logging.getLogger("gen.server")


@lock_guarded
class GenServer:
    # the weight-update and handoff mailboxes are handed between asyncio
    # handlers and the device-worker thread; every touch must hold
    # _cmd_lock (areal-lint C1, runtime-validated under
    # AREAL_DEBUG_LOCKS=1)
    _GUARDED_FIELDS = {
        "_pending_weight_update": "_cmd_lock",
        "_pending_handoffs": "_cmd_lock",
    }

    def __init__(self, engine: GenEngine, role: str = "both"):
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"unknown server role: {role!r}")
        self.engine = engine
        # Disaggregated serving (ISSUE 17): the role is a routing
        # *advertisement* — the engine itself stays fully capable either
        # way (export/import/generate all work on any role), so a router
        # can always fall back to colocated `both` semantics when a role
        # pool empties or a breaker opens.
        self.role = role
        self.paused = threading.Event()  # set => paused
        self.shutdown = threading.Event()
        self._weight_futures: "list" = []
        self._chunk_buf = {}
        self._unstaged_params = None  # (host tree, version) staging fallback
        self._last_committed_version: Optional[int] = None
        self._cmd_lock = threading.Lock()
        self._pending_weight_update: Optional[dict] = None
        # KV-handoff mailbox: /kv_export and /kv_import enqueue here and
        # the worker thread services the engine calls (which touch the
        # device cache) between decode steps — even while paused, so a
        # weight-update window never deadlocks an in-flight handoff.
        self._pending_handoffs: "list" = []
        self.worker = threading.Thread(target=self._run, daemon=True)
        self.step_count = 0
        self.tokens_out = 0
        self.last_error: float = 0.0
        self._register_telemetry()

    def _register_telemetry(self):
        """Scrape-time collector mirroring engine/server counters into the
        shared `gen` registry (utils/telemetry.py).  Sampling happens only
        when /metrics is rendered — the decode loop never touches it.  The
        collector holds a weakref so short-lived servers (tests, benches)
        don't pin their engines through the process-global registry."""
        reg = telemetry.GEN
        self_ref = weakref.ref(self)

        def _collect():
            srv = self_ref()
            if srv is None:
                return
            eng = srv.engine
            # every engine.stats entry is a monotonic counter; mirroring the
            # dict generically keeps the exposition tolerant of key churn
            for k, v in eng.stats.items():
                try:
                    reg.counter(f"{k}_total").set_total(float(v))
                except (TypeError, ValueError):
                    continue
            reg.counter(
                "decode_steps_total", "Productive decode-loop steps"
            ).set_total(srv.step_count)
            reg.counter(
                "tokens_generated_total", "Decode tokens delivered"
            ).set_total(srv.tokens_out)
            reg.gauge("active_requests", "Occupied slots").set(
                eng.active_count()
            )
            reg.gauge("weight_version", "Live weight version").set(eng.version)
            reg.gauge(
                "last_pause_seconds",
                "Most recent weight-swap pause window (histogram: "
                "areal_gen_pause_window_seconds)",
            ).set(eng.last_pause_s)
            reg.gauge("staged_standby", "Standby weights staged (0/1)").set(
                1.0 if eng.has_standby else 0.0
            )
            reg.gauge(
                "decode_attended_fraction",
                "Attended / ceiling decode columns",
            ).set(eng.decode_attended_fraction())
            for t, occ in enumerate(eng.tier_occupancy()):
                reg.gauge(
                    "tier_occupancy", "Occupied slots per decode tier"
                ).set(occ, tier=str(t))
            # speculative decode (ISSUE 12): lifetime acceptance rate
            # (unlabeled) + the windowed per-tier rates steering each
            # tier's draft-length rung
            drafted = float(eng.stats.get("spec_drafted", 0))
            accepted = float(eng.stats.get("spec_accepted", 0))
            rate_g = reg.gauge(
                "spec_acceptance_rate",
                "Draft tokens accepted / drafted (per-tier series are "
                "the controller's acceptance window)",
            )
            rate_g.set(accepted / drafted if drafted else 0.0)
            for t, r in enumerate(eng.spec_acceptance_rates()):
                rate_g.set(r, tier=str(t))
            # unified radix/paged prefix cache (ISSUE 16): the global
            # hit-rate over all admissions (device hits + host swap-ins);
            # the underlying hits/misses/evictions/host_swaps counters
            # ride the generic engine.stats mirror above
            reg.gauge(
                "prefix_cache_hit_rate",
                "Admissions served from the radix/paged prefix cache",
            ).set(eng.prefix_cache_hit_rate())
            # ragged paged-decode attention (ISSUE 19): mean KV pages the
            # kernel gathered per collapsed dispatch; the raw counters
            # ride the generic engine.stats mirror above
            disp = float(eng.stats.get("ragged_dispatches", 0))
            pages = float(eng.stats.get("ragged_attended_pages", 0))
            reg.gauge(
                "ragged_attended_pages",
                "Mean KV pages gathered per ragged kernel dispatch",
            ).set(pages / disp if disp else 0.0)

        reg.add_collector(_collect)

    # ------------------------------ worker ------------------------------

    def start(self):
        self.worker.start()

    def _run(self):
        while not self.shutdown.is_set():
            upd = None
            with self._cmd_lock:
                if self._pending_weight_update is not None:
                    upd = self._pending_weight_update
                    self._pending_weight_update = None
            if upd is not None:
                try:
                    if upd.get("stage_params") is not None:
                        # device placement interleaves with decode steps —
                        # generation is NOT paused for staging
                        v = self.engine.stage_params(
                            upd["stage_params"], version=upd.get("version")
                        )
                    elif upd.get("commit_staged"):
                        v = self.engine.commit_staged(
                            live=bool(upd.get("live"))
                        )
                    elif upd.get("live") and upd.get("params") is not None:
                        # live commit without standby HBM: the pause is the
                        # host->device placement, but in-flight requests
                        # wait it out instead of dying
                        v = self.engine.swap_weights_live(
                            upd["params"], version=upd.get("version")
                        )
                    else:
                        v = self.engine.load_weights(
                            path=upd.get("path"),
                            params=upd.get("params"),
                            version=upd.get("version"),
                        )
                    upd["future"].set_result(v)
                except Exception as e:  # noqa: BLE001 — surface to the caller
                    upd["future"].set_exception(e)
                continue
            self._service_handoffs()
            if self.paused.is_set():
                time.sleep(0.005)
                continue
            try:
                stepped = self.engine.step()
            except Exception:  # noqa: BLE001 — the loop must survive XLA errors
                logger.exception("decode step failed; aborting in-flight requests")
                self.last_error = time.time()
                self.engine.abort_all("abort")
                continue
            self.step_count += 1 if stepped else 0
            self.tokens_out += stepped
            if not stepped:
                time.sleep(0.002)

    def _service_handoffs(self):
        """Drain the KV-handoff mailbox on the worker thread.  The
        engine's export/import methods gather/scatter against the device
        cache, so they must run where every other device touch runs —
        here, between decode steps — never on an HTTP handler thread."""
        ops = None
        with self._cmd_lock:
            if self._pending_handoffs:
                ops = self._pending_handoffs
                self._pending_handoffs = []
        if not ops:
            return
        for op in ops:
            t0 = time.perf_counter()
            try:
                if op["kind"] == "export":
                    op["future"].set_result(
                        self.engine.export_request_kv(op["input_ids"])
                    )
                else:
                    op["future"].set_result(
                        self.engine.import_request_kv(op["entry"])
                    )
                telemetry.HANDOFF.observe(
                    time.perf_counter() - t0, op=op["kind"]
                )
            except Exception as e:  # noqa: BLE001 — surface to the caller
                op["future"].set_exception(e)

    def _queue_handoff(self, **kw):
        import concurrent.futures

        fut = concurrent.futures.Future()
        with self._cmd_lock:
            self._pending_handoffs.append({"future": fut, **kw})
        return fut

    # ----------------------------- handlers -----------------------------

    @staticmethod
    def _req_from_body(body: dict, on_done) -> GenRequest:
        """Wire body -> GenRequest (shared by /generate and
        /generate_batch)."""
        sp = body.get("sampling_params", {})
        pixel_values = None
        image_grid_thw = None
        if body.get("pixel_values_b64"):
            pixel_values = np.frombuffer(
                base64.b64decode(body["pixel_values_b64"]), dtype=np.float32
            ).reshape(body["pixel_values_shape"])
            image_grid_thw = np.asarray(body["image_grid_thw"], np.int64)
        return GenRequest(
            rid=body.get("rid", ""),
            trace_id=str(body.get("trace_id", "") or ""),
            group_id=str(body.get("group_id", "") or ""),
            group_n=int(body.get("group_n", 0) or 0),
            input_ids=[int(t) for t in body["input_ids"]],
            max_new_tokens=int(sp.get("max_new_tokens", 256)),
            min_new_tokens=int(sp.get("min_new_tokens", 0)),
            temperature=float(sp.get("temperature", 1.0)),
            top_p=float(sp.get("top_p", 1.0)),
            top_k=int(sp.get("top_k", 0)),
            stop_token_ids=[int(t) for t in sp.get("stop_token_ids", [])],
            pixel_values=pixel_values,
            image_grid_thw=image_grid_thw,
            # disaggregated handoff (ISSUE 17): leg-2 resubmissions pin
            # the sampler stream so the continuation samples the exact
            # keys the colocated run would have used
            stream_id=int(body.get("stream_id", 0) or 0),
            on_done=on_done,
        )

    @staticmethod
    def _result_payload(r: GenRequest, version: int) -> dict:
        return {
            "output_tokens": r.output_tokens,
            "output_logprobs": r.output_logprobs,
            "output_versions": r.output_versions,
            "stop_reason": r.stop_reason or "stop",
            "version": version,
            "trace_id": r.trace_id,
            # prompt tokens served from resident K/V (radix device hit or
            # host swap-in) — failover clients use this to confirm a
            # resubmission warm-started instead of cold-prefilling
            "cache_hit_tokens": r.cache_hit_tokens,
            # the counter-keyed sampler stream this request decoded on;
            # a handoff leg-2 (or failover resubmit) passes it back in
            # so the continuation stays bit-identical
            "stream_id": r.stream_id,
        }

    async def generate(self, request: web.Request) -> web.Response:
        body = await request.json()
        loop = asyncio.get_running_loop()
        fut = loop.create_future()

        def on_done(r: GenRequest):
            loop.call_soon_threadsafe(fut.set_result, r)

        self.engine.submit(self._req_from_body(body, on_done))
        r: GenRequest = await fut
        return web.json_response(self._result_payload(r, self.engine.version))

    async def generate_batch(self, request: web.Request) -> web.Response:
        """Submit a whole group in one POST ({"requests": [...]}) so every
        member lands in one admission window and the engine's cluster
        fan-out shares their common prefix (GRPO groups: one prefill +
        fan-out instead of group_size prefills).  Responds with
        {"results": [...]} in request order once ALL members finish."""
        body = await request.json()
        reqs_in = body.get("requests", [])
        if not reqs_in:
            return web.json_response({"error": "empty batch"}, status=400)
        loop = asyncio.get_running_loop()
        futs = [loop.create_future() for _ in reqs_in]

        def make_done(fut):
            def on_done(r: GenRequest):
                loop.call_soon_threadsafe(fut.set_result, r)

            return on_done

        reqs = [
            self._req_from_body(b, make_done(f))
            for b, f in zip(reqs_in, futs)
        ]
        self.engine.submit_batch(reqs)
        done = await asyncio.gather(*futs)
        version = self.engine.version
        return web.json_response(
            {"results": [self._result_payload(r, version) for r in done]}
        )

    # ------------------------- KV handoff (ISSUE 17) --------------------

    _HANDOFF_TIMEOUT_S = 30.0

    async def kv_export(self, request: web.Request) -> web.Response:
        """Serialize the retained KV pages covering a prefix of
        `input_ids` to the wire format (gather on the existing bucket
        ladder -> host -> base64).  404 when neither the device radix nor
        the host tier retains a usable prefix — the router then falls
        back to a cold leg-2 prefill, which the counter-keyed sampler
        keeps bit-identical anyway."""
        from areal_tpu.gen import kv_pool

        body = await request.json()
        fut = self._queue_handoff(
            kind="export",
            input_ids=[int(t) for t in body["input_ids"]],
        )
        try:
            entry = await asyncio.wait_for(
                asyncio.wrap_future(fut), timeout=self._HANDOFF_TIMEOUT_S
            )
        except asyncio.TimeoutError:
            return web.json_response(
                {"error": "kv_export timed out"}, status=503
            )
        if entry is None:
            return web.json_response(
                {"error": "no exportable prefix"}, status=404
            )
        doc = kv_pool.wire_encode_entry(entry)
        return web.json_response(doc)

    async def kv_import(self, request: web.Request) -> web.Response:
        """Install a wire-format KV entry into the host overflow tier;
        the next admission matching its token prefix swaps it in as a
        warm-cache hit (the same path a local spill round trip takes)."""
        from areal_tpu.gen import kv_pool

        body = await request.json()
        try:
            entry = kv_pool.wire_decode_entry(body)
        except (KeyError, ValueError) as e:
            return web.json_response(
                {"error": f"malformed wire entry: {e}"}, status=400
            )
        fut = self._queue_handoff(kind="import", entry=entry)
        try:
            ok = await asyncio.wait_for(
                asyncio.wrap_future(fut), timeout=self._HANDOFF_TIMEOUT_S
            )
        except asyncio.TimeoutError:
            return web.json_response(
                {"error": "kv_import timed out"}, status=503
            )
        if not ok:
            return web.json_response(
                {"error": "no host tier on this server "
                          "(start with --host-offload)"},
                status=409,
            )
        return web.json_response(
            {"ok": True, "valid_len": int(entry["valid_len"])}
        )

    async def pause(self, request: web.Request) -> web.Response:
        self.paused.set()
        return web.json_response({"ok": True})

    async def resume(self, request: web.Request) -> web.Response:
        self.paused.clear()
        return web.json_response({"ok": True})

    def _queue_weight_update(self, **kw):
        import concurrent.futures

        fut = concurrent.futures.Future()
        with self._cmd_lock:
            self._pending_weight_update = {"future": fut, **kw}
        return fut

    async def update_weights_from_disk(self, request: web.Request) -> web.Response:
        body = await request.json()
        fut = self._queue_weight_update(
            path=body["path"], version=body.get("version")
        )
        version = await asyncio.wrap_future(fut)
        return web.json_response({"ok": True, "version": version})

    async def update_weights_chunk(self, request: web.Request) -> web.Response:
        """Transfer path: the trainer streams named arrays — whole, or as
        (offset, bytes) pieces for arrays larger than the chunk budget —
        and `commit` swaps them in (counterpart of the reference's NCCL
        broadcast bucket protocol, fsdp_engine.py:298-330, over HTTP/DCN).

        Two encodings: `application/octet-stream` carries the raw bytes in
        the body with metadata in X-Weight-* headers (the fast path — no
        base64 inflation or json parse per chunk); a JSON body with
        `data_b64` remains for legacy clients and for `commit`."""
        if "application/octet-stream" in request.headers.get("Content-Type", ""):
            h = request.headers
            import json as _json

            name = h["X-Weight-Name"]
            data = await request.read()
            entry = self._chunk_buf.setdefault(
                name,
                {
                    "buf": bytearray(int(h["X-Weight-Nbytes"])),
                    "dtype": h.get("X-Weight-Dtype", "bfloat16"),
                    "shape": _json.loads(h.get("X-Weight-Shape", "[]")),
                },
            )
            off = int(h.get("X-Weight-Offset", 0))
            entry["buf"][off : off + len(data)] = data
            return web.json_response({"ok": True, "received": name})
        body = await request.json()
        if body.get("prepare"):
            # stage onto the DEVICE while generation keeps running, so the
            # later commit is an O(abort) pointer swap instead of an
            # O(model-bytes) placement inside the pause (VERDICT r3 weak
            # #2).  Sent by the trainer's stage_weights after streaming.
            if not self._chunk_buf:
                return web.json_response(
                    {"error": "prepare without staged chunks"}, status=409
                )
            params = self._assemble_params()
            fut = self._queue_weight_update(
                stage_params=params, version=body.get("version")
            )
            staged = await asyncio.wrap_future(fut)
            if not staged:
                # no standby HBM: keep the assembled HOST tree so commit
                # can still place it (the pre-staging is an optimisation,
                # never a correctness requirement)
                self._unstaged_params = (params, body.get("version"))
            return web.json_response({"ok": True, "staged": bool(staged)})
        if body.get("commit"):
            if self.engine.has_standby and (
                body.get("version") is None
                or body["version"] == self.engine.staged_version
            ):
                # pre-staged: the swap itself runs on the worker thread —
                # which is also the stepper, so `live: true` (no abort,
                # in-flight requests keep decoding across the swap, versions
                # recorded per token) is race-free by construction
                fut = self._queue_weight_update(
                    commit_staged=True, live=bool(body.get("live"))
                )
                version = await asyncio.wrap_future(fut)
                self._last_committed_version = version
                return web.json_response({"ok": True, "version": version})
            if self._unstaged_params is not None and (
                body.get("version") is None
                or body["version"] == self._unstaged_params[1]
            ):
                params, version = self._unstaged_params
                self._unstaged_params = None
                fut = self._queue_weight_update(
                    params=params, version=version,
                    live=bool(body.get("live")),
                )
                version = await asyncio.wrap_future(fut)
                self._last_committed_version = version
                return web.json_response({"ok": True, "version": version})
            if not self._chunk_buf:
                # idempotent retry: a commit whose response was lost leaves
                # an empty buffer — if that version is already live, say so
                # instead of failing a transfer that in fact succeeded
                if (
                    body.get("version") is None
                    or body["version"] == self._last_committed_version
                ):
                    return web.json_response(
                        {"ok": True, "version": self.engine.version}
                    )
                return web.json_response(
                    {"error": "commit without staged chunks"}, status=409
                )
            params = self._assemble_params()
            fut = self._queue_weight_update(
                params=params, version=body.get("version"),
                live=bool(body.get("live")),
            )
            version = await asyncio.wrap_future(fut)
            self._last_committed_version = version
            return web.json_response({"ok": True, "version": version})
        name = body["name"]
        data = base64.b64decode(body["data_b64"])
        entry = self._chunk_buf.setdefault(
            name,
            {
                "buf": bytearray(int(body["nbytes"])),
                "dtype": body["dtype"],
                "shape": body["shape"],
            },
        )
        off = int(body["offset"])
        entry["buf"][off : off + len(data)] = data
        return web.json_response({"ok": True, "received": name})

    def _assemble_params(self):
        """Drain the chunk buffer into a host param tree."""
        from areal_tpu.models.hf import state_to_params

        host = {name: self._assemble(e) for name, e in self._chunk_buf.items()}
        self._chunk_buf = {}
        return state_to_params(
            iter(host.items()), self.engine.model_config, dtype="bfloat16"
        )

    @staticmethod
    def _assemble(entry) -> np.ndarray:
        import ml_dtypes

        dtype = (
            np.dtype(ml_dtypes.bfloat16)
            if entry["dtype"] == "bfloat16"
            else np.dtype(entry["dtype"])
        )
        # view straight over the staged bytearray — bytes(...) would copy
        # the whole model a second time on the commit path
        return np.frombuffer(entry["buf"], dtype=dtype).reshape(
            entry["shape"]
        )

    async def health(self, request: web.Request) -> web.Response:
        if not self.worker.is_alive() and not self.shutdown.is_set():
            return web.json_response({"status": "dead"}, status=500)
        return web.json_response(
            {
                "status": "paused" if self.paused.is_set() else "ok",
                "role": self.role,
                "version": self.engine.version,
                "active": self.engine.active_count(),
                "last_error": self.last_error,
            }
        )

    async def metrics(self, request: web.Request) -> web.Response:
        # Prometheus text exposition on request (?format=prometheus or an
        # Accept header asking for text/openmetrics); legacy JSON stays the
        # default for existing consumers
        if telemetry.wants_prometheus(
            request.query.get("format"), request.headers.get("Accept", "")
        ):
            return web.Response(
                text=telemetry.GEN.render_prometheus(),
                content_type="text/plain",
            )
        # engine.stats lookups go through _stat so a stats-key rename
        # degrades a counter to 0 instead of 500ing the whole scrape — but
        # every degraded lookup is counted (areal_gen_stats_key_misses_total)
        # so the drift is visible on the Prometheus surface (ISSUE 18)
        stats = self.engine.stats

        def _stat(key: str):
            if key not in stats:
                telemetry.GEN_STATS_KEY_MISSES.inc()
            return stats.get(key, 0)

        return web.json_response(
            {
                "decode_steps": self.step_count,
                "tokens_generated": self.tokens_out,
                "active": self.engine.active_count(),
                "role": self.role,
                "version": self.engine.version,
                # achieved generation-idle window of the last weight swap
                "last_pause_s": round(self.engine.last_pause_s, 4),
                "staged": self.engine.has_standby,
                # prefill-side token accounting: cold vs retained-reuse vs
                # group fan-out (shared) — the grouped-prefill savings
                "prefill_tokens": _stat("prefill_tokens"),
                "suffix_tokens": _stat("suffix_tokens"),
                "reused_tokens": _stat("reused_tokens"),
                "shared_tokens": _stat("shared_tokens"),
                "copy_calls": _stat("copy_calls"),
                # abort-reservation TTL observability (VERDICT r6 #10):
                # reservations that expired unclaimed — nonzero means
                # aborted clients are not resubmitting within
                # abort_reserve_s and the retained-prefix handoff is
                # silently degrading to fresh prefills
                "reservations_lapsed": _stat("reservations_lapsed"),
                # tiered decode (ISSUE 5): attended span / configured
                # ceiling over all decode dispatches (1.0 = paying the
                # full max_seq_len width), per-cohort occupancy, and
                # cross-tier cache-row migrations
                "decode_attended_fraction": round(
                    self.engine.decode_attended_fraction(), 4
                ),
                "tier_occupancy": self.engine.tier_occupancy(),
                "tier_slots": list(self.engine.tier_size),
                "tier_lens": list(self.engine.tier_bounds),
                "tier_migrations": _stat("tier_migrations"),
                # speculative decode (ISSUE 12): draft/accept counters and
                # the lifetime acceptance rate; per-tier windowed rates
                # live on the Prometheus surface (spec_acceptance_rate)
                "spec_drafted": _stat("spec_drafted"),
                "spec_accepted": _stat("spec_accepted"),
                "spec_acceptance_rate": round(
                    _stat("spec_accepted")
                    / max(1, _stat("spec_drafted")),
                    4,
                ),
                "verify_calls": _stat("verify_calls"),
                # unified radix/paged prefix cache (ISSUE 16): admission
                # hits/misses through the one shared mechanism, device
                # evictions, and host-DRAM spill/swap-in round trips
                "prefix_cache_hits": _stat("prefix_cache_hits"),
                "prefix_cache_misses": _stat("prefix_cache_misses"),
                "prefix_cache_evictions": _stat("prefix_cache_evictions"),
                "prefix_cache_host_swaps": _stat("prefix_cache_host_swaps"),
                "prefix_cache_hit_rate": round(
                    self.engine.prefix_cache_hit_rate(), 4
                ),
                "prefix_cache_partial_hits": _stat("prefix_cache_partial_hits"),
                # disaggregated prefill/decode handoff (ISSUE 17): the
                # router's decode-pool placement reads tier_occupancy
                # above; these counters are the transfer ledger
                "kv_handoff_exports": _stat("kv_handoff_exports"),
                "kv_handoff_imports": _stat("kv_handoff_imports"),
                "kv_handoff_bytes": _stat("kv_handoff_bytes"),
                "kv_handoff_failures": _stat("kv_handoff_failures"),
                # ragged paged-decode attention (ISSUE 19): collapsed
                # grid-wide kernel dispatches and the page-granular read
                # ledger (pages actually gathered, slots x steps)
                "ragged_dispatches": _stat("ragged_dispatches"),
                "ragged_attended_pages": _stat("ragged_attended_pages"),
            }
        )

    # ------------------------------ wiring ------------------------------

    def app(self) -> web.Application:
        app = web.Application(client_max_size=1024**3)
        app.router.add_post("/generate", self.generate)
        app.router.add_post("/generate_batch", self.generate_batch)
        app.router.add_post("/pause_generation", self.pause)
        app.router.add_post("/continue_generation", self.resume)
        app.router.add_post("/update_weights_from_disk", self.update_weights_from_disk)
        app.router.add_post("/update_weights_chunk", self.update_weights_chunk)
        app.router.add_post("/kv_export", self.kv_export)
        app.router.add_post("/kv_import", self.kv_import)
        app.router.add_get("/health", self.health)
        app.router.add_get("/metrics", self.metrics)
        return app




def serve(
    engine: GenEngine,
    host: str = "0.0.0.0",
    port: Optional[int] = None,
    experiment_name: str = "",
    trial_name: str = "",
    server_idx: int = 0,
    role: str = "both",
):
    """Blocking serve; registers the address in name_resolve for discovery
    (reference: sglang_server.py registration)."""
    port = port or network.find_free_port()
    server = GenServer(engine, role=role)
    server.start()
    if experiment_name:
        name_resolve.add(
            names.gen_server(experiment_name, trial_name, str(server_idx)),
            f"{network.gethostip()}:{port}",
            replace=True,
        )
    logger.info(f"generation server on {host}:{port}")
    web.run_app(server.app(), host=host, port=port, print=None)


def main():
    # multi-host launchers point every process at a shared rendezvous store
    name_resolve.reconfigure_from_env()
    p = argparse.ArgumentParser()
    p.add_argument("--model-path", default="")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--n-slots", type=int, default=8)
    p.add_argument("--max-seq-len", type=int, default=2048)
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree: shard the model + KV cache "
                        "over the first tp local devices")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree (MoE serving): shard the "
                        "[E, ., .] expert leaves over ep devices")
    p.add_argument("--experiment-name", default="")
    p.add_argument("--trial-name", default="")
    p.add_argument("--server-idx", type=int, default=0)
    p.add_argument("--no-decode-window", action="store_true",
                   help="disable the bucketed decode key window (attend "
                        "the full max-seq-len cache width — the legacy "
                        "ceiling-bound behavior)")
    p.add_argument("--decode-tiers", type=int, default=1,
                   help="number of length-cohort slot tiers; >1 keeps one "
                        "long rollout from inflating the short cohort's "
                        "attended window")
    p.add_argument("--decode-tier-lens", default="",
                   help="explicit per-tier length ceilings (comma list, "
                        "ascending; overrides --decode-tiers)")
    p.add_argument("--decode-tier-slots", default="",
                   help="explicit per-tier slot counts (comma list, must "
                        "sum to --n-slots)")
    p.add_argument("--spec-decode", action="store_true",
                   help="self-speculative decoding: prompt-lookup drafts "
                        "verified in one dispatch per tier; output streams "
                        "stay bit-identical to plain decode")
    p.add_argument("--spec-ladder", default="",
                   help="static draft-length ladder (comma list incl. 0, "
                        "e.g. '0,3,7'); each nonzero rung is its own "
                        "verify program per (tier, K) bucket")
    p.add_argument("--spec-draft-len", type=int, default=0,
                   help="pin the draft length instead of adapting along "
                        "the ladder (benches/tests)")
    p.add_argument("--ragged-attn", action="store_true",
                   help="fused ragged paged-decode attention (ISSUE 19): "
                        "one Pallas kernel dispatch covers the whole slot "
                        "grid (per-slot page spans via the KV page table), "
                        "collapsing the per-tier decode/verify fan-out; "
                        "output streams stay bit-identical to the dense "
                        "path (auto-falls back when the per-slot window "
                        "exceeds the kernel VMEM budget)")
    p.add_argument("--role", choices=("prefill", "decode", "both"),
                   default="both",
                   help="disaggregated-fleet role advertised to the "
                        "router: prefill servers take admissions and "
                        "export KV via /kv_export, decode servers import "
                        "via /kv_import and continue the stream; `both` "
                        "is the colocated default and the router's "
                        "fallback when a role pool is empty")
    p.add_argument("--host-offload", action="store_true",
                   help="spill evicted retained prefixes to a host-DRAM "
                        "LRU tier and swap them back on radix hits")
    p.add_argument("--host-cache-mb", type=int, default=64,
                   help="host-DRAM overflow tier capacity in MiB "
                        "(with --host-offload)")
    p.add_argument("--telemetry", action="store_true",
                   help="enable trajectory-lifecycle event emission "
                        "(utils/telemetry.py; also via AREAL_TELEMETRY=1)")
    args = p.parse_args()
    if args.telemetry:
        telemetry.set_enabled(True)
    if args.role == "decode" and not args.host_offload:
        # a decode-role server receives its work as /kv_import host-tier
        # entries; without the tier every import would 409
        logger.info("--role decode implies --host-offload; enabling it")
        args.host_offload = True
    tier_kw = dict(
        decode_window=not args.no_decode_window,
        decode_tiers=args.decode_tiers,
        decode_tier_lens=(
            [int(x) for x in args.decode_tier_lens.split(",")]
            if args.decode_tier_lens else None
        ),
        decode_tier_slots=(
            [int(x) for x in args.decode_tier_slots.split(",")]
            if args.decode_tier_slots else None
        ),
        spec_decode=args.spec_decode,
        spec_ladder=(
            [int(x) for x in args.spec_ladder.split(",")]
            if args.spec_ladder else None
        ),
        spec_draft_len=args.spec_draft_len or None,
        host_offload=args.host_offload,
        host_cache_mb=args.host_cache_mb,
        ragged_attn=args.ragged_attn,
    )
    if args.model_path:
        cfg = TransformerConfig.from_hf(args.model_path)
        engine = GenEngine(
            cfg.replace(dtype="bfloat16"),
            model_path=args.model_path,
            n_slots=args.n_slots,
            max_seq_len=args.max_seq_len,
            tp=args.tp,
            ep=args.ep,
            **tier_kw,
        )
    else:
        engine = GenEngine(tiny_config(), n_slots=args.n_slots,
                           max_seq_len=args.max_seq_len, tp=args.tp,
                           ep=args.ep, **tier_kw)
    serve(
        engine,
        port=args.port or None,
        experiment_name=args.experiment_name,
        trial_name=args.trial_name,
        server_idx=args.server_idx,
        role=args.role,
    )


if __name__ == "__main__":
    main()
