"""GSM8K-style synthetic math word problems + a closed-vocabulary tokenizer.

Purpose (VERDICT r4 #1): the primary metric's quality half needs a
reward-vs-wall-clock curve from the REAL async GRPO loop.  This rig has
zero network egress — `openai/gsm8k` and pretrained checkpoints are both
unreachable (the reference trains Qwen on HF GSM8K,
areal/examples/math/gsm8k_grpo.py) — so the honest substitute is a
generator of grade-school word problems in GSM8K's shape (1-3 arithmetic
steps, natural-language surface, numeric answer) that a small from-scratch
model can genuinely learn: SFT teaches the format, then GRPO against the
real math reward (`reward/math_parser.py gsm8k_reward_fn`, exact-match on
\\boxed{}) must move accuracy.  Everything downstream is the production
path: RLVRWorkflow, the reward pool, the serving engine, decoupled PPO.

The tokenizer is word-level over the generator's closed vocabulary with
digits split per character (so arithmetic is learnable), and decode
re-spaces punctuation so the math parser sees literal `\\boxed{N}` syntax.
"""

import re
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

NAMES = [
    "Alex", "Sara", "Ben", "Mia", "Leo", "Ana", "Tom", "Lily",
    "Omar", "Nina", "Sam", "Ruth", "Ivan", "Ada", "Hugo", "Vera",
]
OBJECTS = [
    "apples", "coins", "books", "pens", "cards", "shells", "stamps",
    "beads", "rocks", "cups", "kites", "rings", "seeds", "stars",
    "notes", "gems",
]

PROMPT_SUFFIX = (
    " Please reason step by step , and put your final answer within "
    "\\boxed{} ."
)

_TEMPLATE_WORDS = """
User: Assistant: has buys more How many does have now gives away There are
in each box boxes total shares equally among friends friend get and then
left loses of so Buying Giving leaves Each holds there starts with The
answer is Then gets Please reason step by put your final within
""".split()

_PUNCT = [".", ",", "?", "+", "-", "x", "/", "=", "\\boxed{", "}", "\n"]


class WordTokenizer:
    """Closed-vocabulary word tokenizer: words are atomic, numbers are
    digit sequences, `\\boxed{` and `}` are atomic so decode reproduces the
    exact syntax `extract_answer` parses.  Surface-compatible with the
    HF-tokenizer subset the workflows use (encode / decode /
    apply_chat_template / eos_token_id / pad_token_id)."""

    def __init__(self):
        vocab: List[str] = ["<pad>", "<eos>", "<unk>"]
        vocab += [str(d) for d in range(10)]
        vocab += _PUNCT
        seen = set(vocab)
        for w in _TEMPLATE_WORDS + NAMES + OBJECTS:
            if w not in seen:
                vocab.append(w)
                seen.add(w)
        self.vocab = vocab
        self.token_to_id = {t: i for i, t in enumerate(vocab)}
        self.pad_token_id = 0
        self.eos_token_id = 1
        self.unk_token_id = 2

    def __len__(self):
        return len(self.vocab)

    def _chunk_tokens(self, chunk: str) -> List[str]:
        """Split one whitespace-delimited chunk into vocab symbols:
        longest-match over (boxed marker | word | digit | single char)."""
        out: List[str] = []
        i = 0
        while i < len(chunk):
            if chunk.startswith("\\boxed{", i):
                out.append("\\boxed{")
                i += len("\\boxed{")
                continue
            m = re.match(r"[A-Za-z]+:?", chunk[i:])
            if m and m.group(0) in self.token_to_id:
                out.append(m.group(0))
                i += len(m.group(0))
                continue
            out.append(chunk[i])
            i += 1
        return out

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        ids: List[int] = []
        for part in text.replace("\n", " \n ").split(" "):
            if not part:
                continue
            for tok in self._chunk_tokens(part):
                ids.append(self.token_to_id.get(tok, self.unk_token_id))
        if add_special_tokens:
            ids.append(self.eos_token_id)
        return ids

    def decode(self, token_ids, skip_special_tokens: bool = True) -> str:
        toks = []
        for t in token_ids:
            t = int(t)
            if 0 <= t < len(self.vocab):
                tok = self.vocab[t]
                if skip_special_tokens and t in (
                    self.pad_token_id, self.eos_token_id, self.unk_token_id
                ):
                    continue
                toks.append(tok)
        out: List[str] = []
        for i, tok in enumerate(toks):
            if not out:
                out.append(tok)
                continue
            prev = toks[i - 1]
            no_space = (
                (tok.isdigit() and prev.isdigit())  # digit runs: "3","7"->37
                or prev == "\\boxed{"
                or tok == "}"
            )
            out.append(tok if no_space else " " + tok)
        return "".join(out)

    def apply_chat_template(
        self,
        messages: List[Dict[str, str]],
        add_generation_prompt: bool = True,
        tokenize: bool = True,
        **kw,
    ):
        text = ""
        for m in messages:
            role = "User:" if m["role"] == "user" else "Assistant:"
            text += f"{role} {m['content']}\n"
        if add_generation_prompt:
            text += "Assistant:"
        if not tokenize:
            return text
        return self.encode(text)


@dataclass
class SynthProblem:
    question: str
    solution: str  # CoT ending in \boxed{answer}
    answer: str


def _gen_one(rng: np.random.Generator) -> SynthProblem:
    name = NAMES[int(rng.integers(len(NAMES)))]
    obj = OBJECTS[int(rng.integers(len(OBJECTS)))]
    kind = int(rng.integers(6))
    if kind == 0:  # add
        a, b = int(rng.integers(3, 60)), int(rng.integers(3, 60))
        s = a + b
        q = (f"{name} has {a} {obj} . {name} buys {b} more {obj} . "
             f"How many {obj} does {name} have now ?")
        sol = (f"{name} starts with {a} {obj} . Buying {b} more gives "
               f"{a} + {b} = {s} {obj} . The answer is \\boxed{{{s}}} .")
    elif kind == 1:  # sub
        a = int(rng.integers(10, 95))
        b = int(rng.integers(2, a))
        s = a - b
        q = (f"{name} has {a} {obj} . {name} gives away {b} {obj} . "
             f"How many {obj} does {name} have left ?")
        sol = (f"{name} starts with {a} {obj} . Giving away {b} leaves "
               f"{a} - {b} = {s} {obj} . The answer is \\boxed{{{s}}} .")
    elif kind == 2:  # mul
        a, b = int(rng.integers(2, 10)), int(rng.integers(3, 25))
        s = a * b
        q = (f"There are {a} {obj} in each box . {name} has {b} boxes . "
             f"How many {obj} in total ?")
        sol = (f"Each box holds {a} {obj} and there are {b} boxes , so "
               f"{a} x {b} = {s} {obj} . The answer is \\boxed{{{s}}} .")
    elif kind == 3:  # div
        b = int(rng.integers(2, 10))
        s = int(rng.integers(2, 13))
        a = b * s
        q = (f"{name} shares {a} {obj} equally among {b} friends . "
             f"How many {obj} does each friend get ?")
        sol = (f"{a} / {b} = {s} , so each friend gets {s} {obj} . "
               f"The answer is \\boxed{{{s}}} .")
    elif kind == 4:  # add then sub
        a, b = int(rng.integers(5, 60)), int(rng.integers(5, 60))
        t = a + b
        c = int(rng.integers(2, t))
        s = t - c
        q = (f"{name} has {a} {obj} . {name} buys {b} more and then "
             f"gives away {c} . How many {obj} are left ?")
        sol = (f"{a} + {b} = {t} . Then {t} - {c} = {s} . "
               f"The answer is \\boxed{{{s}}} .")
    else:  # mul then sub
        a, b = int(rng.integers(2, 10)), int(rng.integers(3, 15))
        t = a * b
        c = int(rng.integers(2, t))
        s = t - c
        q = (f"{name} buys {b} boxes of {a} {obj} each and then loses "
             f"{c} . How many {obj} are left ?")
        sol = (f"{a} x {b} = {t} . Then {t} - {c} = {s} . "
               f"The answer is \\boxed{{{s}}} .")
    return SynthProblem(question=q, solution=sol, answer=str(s))


def generate_problems(n: int, seed: int = 0) -> List[Dict]:
    """n dataset items in the gsm8k loader's shape (dataset/gsm8k.py):
    {messages, query_id, answer} plus `solution` for SFT warm-starts."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        p = _gen_one(rng)
        out.append({
            "messages": [
                {"role": "user", "content": p.question + PROMPT_SUFFIX}
            ],
            "query_id": str(i),
            "answer": p.answer,
            "solution": p.solution,
        })
    return out


def sft_example(tokenizer: WordTokenizer, item: Dict) -> Dict[str, np.ndarray]:
    """(input_ids, loss_mask) for one SFT row: loss on the assistant
    solution + eos only — the convention JaxLMEngine.train_lm consumes."""
    prompt_ids = tokenizer.apply_chat_template(
        item["messages"], add_generation_prompt=True
    )
    sol_ids = tokenizer.encode(" " + item["solution"]) + [
        tokenizer.eos_token_id
    ]
    ids = np.asarray(prompt_ids + sol_ids, np.int32)
    mask = np.asarray(
        [0] * len(prompt_ids) + [1] * len(sol_ids), np.int32
    )
    return {
        "input_ids": ids,
        "loss_mask": mask,
        "attention_mask": np.ones_like(ids),
    }
