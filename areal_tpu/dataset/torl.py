"""ToRL tool-integrated math-RL dataset (reference:
areal/dataset/torl_data.py get_torl_data_rl_dataset).

The reference downloads the GAIR-NLP/ToRL parquet files at runtime; this
environment has no egress, so `path` must point at a local parquet/jsonl
copy.  Rows keep the reference's mapping: the ground-truth answer is
wrapped in \\boxed{} so the math verifier's boxed-answer path applies.
"""

from typing import Optional

from areal_tpu.dataset import register_dataset


@register_dataset("torl")
def get_torl_rl_dataset(
    path: str,
    split: str = "train",
    tokenizer=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    import datasets as hf_datasets

    if path.endswith(".parquet"):
        ds = hf_datasets.load_dataset("parquet", data_files=path, split="train")
    elif path.endswith(".jsonl") or path.endswith(".json"):
        ds = hf_datasets.load_dataset("json", data_files=path, split="train")
    else:
        ds = hf_datasets.load_dataset(path, split=split)

    def process(sample, idx):
        if "reward_model" in sample:  # the upstream parquet schema
            answer = sample["reward_model"]["ground_truth"]
            messages = sample["prompt"]
        else:  # pre-converted jsonl
            answer = sample["answer"]
            messages = sample["messages"]
        return {
            "messages": messages,
            "answer": f"\\boxed{{{answer}}}",
            "query_id": str(sample.get("query_id", idx)),
        }

    drop = [
        c for c in ds.column_names
        if c in ("prompt", "reward_model", "data_source", "ability", "extra_info")
    ]
    ds = ds.map(process, with_indices=True, remove_columns=drop)
    if max_length is not None and tokenizer is not None:
        ds = ds.filter(
            lambda x: len(
                tokenizer.apply_chat_template(
                    x["messages"], add_generation_prompt=True, tokenize=True
                )
                if isinstance(x["messages"], list)
                else tokenizer.encode(x["messages"])
            )
            <= max_length
        )
    return ds
