"""RPC controller layer: engine workers behind HTTP, driven by a
single-controller process (reference: areal/scheduler/rpc/ +
areal/controller/ single-controller mode)."""

import asyncio
import threading

import numpy as np
import pytest
from aiohttp import web

from areal_tpu.api.config import (
    MeshConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.controller import DistributedBatch, TrainController
from areal_tpu.engine.ppo import JaxPPOActor
from areal_tpu.models.model_config import tiny_config
from areal_tpu.scheduler import EngineRPCServer, RPCEngineClient
from areal_tpu.scheduler.rpc_client import RPCError

MODEL_CFG = tiny_config(vocab_size=64, qkv_bias=True, hf_architecture="Qwen2ForCausalLM")


def _actor(group_size=4):
    cfg = PPOActorConfig(
        experiment_name="rpc",
        trial_name="t",
        init_from_scratch=True,
        dtype="float32",
        gradient_checkpointing=False,
        mesh=MeshConfig(),
        mb_spec=MicroBatchSpec(n_mbs=1),
        optimizer=OptimizerConfig(
            lr=5e-3, warmup_steps_proportion=0.0, weight_decay=0.0
        ),
        pack_length_quantum=16,
        group_size=group_size,
        ppo_n_minibatches=1,
        adv_norm=NormConfig(
            mean_level="group", std_level="group", group_size=group_size
        ),
    )
    actor = JaxPPOActor(cfg, model_config=MODEL_CFG)
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
    return actor


def _batch(rng, B=8, L=16, prompt_len=4):
    ids = rng.integers(0, MODEL_CFG.vocab_size, (B, L)).astype(np.int32)
    loss_mask = np.zeros((B, L), np.float32)
    loss_mask[:, prompt_len:] = 1.0
    return {
        "input_ids": ids,
        "attention_mask": np.ones((B, L), bool),
        "loss_mask": loss_mask,
        "logprobs": rng.normal(-1.0, 0.1, (B, L)).astype(np.float32) * loss_mask,
        "rewards": (ids[:, prompt_len] % 2 == 0).astype(np.float32),
        "versions": np.zeros((B, L), np.int32),
    }


class ServerHarness:
    def __init__(self, worker):
        self.server = EngineRPCServer(worker)
        self._started = threading.Event()
        self.port = None

    def start(self) -> str:
        def _run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _serve():
                runner = web.AppRunner(self.server.app())
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                self.port = runner.addresses[0][1]
                self._runner = runner
                self._started.set()

            self._loop.run_until_complete(_serve())
            self._loop.run_forever()

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        assert self._started.wait(timeout=10)
        return f"127.0.0.1:{self.port}"

    def stop(self):
        async def _cleanup():
            await self._runner.cleanup()

        asyncio.run_coroutine_threadsafe(_cleanup(), self._loop).result(timeout=5)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


def test_distributed_batch_roundtrip_chunk_union():
    rng = np.random.default_rng(0)
    b = DistributedBatch(
        {
            "input_ids": rng.integers(0, 64, (6, 8)).astype(np.int32),
            "attention_mask": np.ones((6, 8), bool),
            "rewards": rng.normal(size=6).astype(np.float32),
            "note": {"task": "math"},
        }
    )
    blob = b.to_bytes()
    back = DistributedBatch.from_bytes(blob)
    assert set(back.arrays) == set(b.arrays)
    np.testing.assert_array_equal(back["input_ids"], b["input_ids"])
    assert back.meta == {"note": {"task": "math"}}

    shards = back.chunk(4)
    assert [len(s) for s in shards] == [1, 2, 1, 2]
    merged = DistributedBatch.concat(shards)
    np.testing.assert_array_equal(merged["input_ids"], b["input_ids"])

    extra = DistributedBatch({"advantages": rng.normal(size=(6, 8)).astype(np.float32)})
    joined = merged.union(extra)
    assert "advantages" in joined and "input_ids" in joined

    with pytest.raises(ValueError):
        back.chunk(7)

    # quantum keeps group boundaries intact: 6 rows, groups of 2, 3 shards
    for shard in back.chunk(3, quantum=2):
        assert len(shard) == 2
    with pytest.raises(ValueError):
        back.chunk(2, quantum=4)  # 6 % 4 != 0


def test_distributed_batch_vision_chunk():
    """Patch arrays split by per-row spans, keeping each row's images with
    its tokens (VLM dp fan-out)."""
    rng = np.random.default_rng(1)
    B, L = 4, 8
    patches_per_row = np.array([4, 8, 4, 8], np.int64)
    N = int(patches_per_row.sum())
    pv = rng.normal(size=(N, 6)).astype(np.float32)
    img_ids = np.repeat(np.arange(B), patches_per_row).astype(np.int32)
    b = DistributedBatch(
        {
            "input_ids": rng.integers(0, 64, (B, L)).astype(np.int32),
            "attention_mask": np.ones((B, L), bool),
            "pixel_values": pv,
            "patch_img_ids": img_ids,
            "patches_per_row": patches_per_row,
        }
    )
    shards = b.chunk(2)
    assert [len(s) for s in shards] == [2, 2]
    assert shards[0]["pixel_values"].shape[0] == 12  # rows 0+1: 4+8
    assert shards[1]["pixel_values"].shape[0] == 12  # rows 2+3
    np.testing.assert_array_equal(shards[0]["pixel_values"], pv[:12])
    np.testing.assert_array_equal(shards[1]["pixel_values"], pv[12:])
    np.testing.assert_array_equal(shards[1]["patch_img_ids"], img_ids[12:])

    # without the span metadata, vision chunking refuses loudly
    no_spans = DistributedBatch(
        {
            "input_ids": np.zeros((2, 4), np.int32),
            "attention_mask": np.ones((2, 4), bool),
            "pixel_values": pv[:8],
        }
    )
    with pytest.raises(ValueError, match="patches_per_row"):
        no_spans.chunk(2)


def test_rpc_engine_roundtrip():
    actor = _actor()
    h = ServerHarness(actor)
    addr = h.start()
    try:
        client = RPCEngineClient(addr)
        assert client.health()["status"] == "ok"
        rng = np.random.default_rng(1)
        batch = _batch(rng)

        logp = client.compute_logp(batch)
        local = actor.compute_logp(batch)
        np.testing.assert_allclose(logp, local, rtol=1e-5, atol=1e-5)

        batch["prox_logp"] = logp
        out = client.compute_advantages(batch)
        assert "advantages" in out
        batch.update(out)

        stats = client.ppo_update(batch)
        assert stats and np.isfinite(stats[-1]["loss"])

        client.set_version(3)
        assert client.get_version() == 3

        with pytest.raises(RPCError):
            client.call("no_such_method")
    finally:
        h.stop()
        actor.destroy()


def test_train_controller_two_workers():
    actors = [_actor(group_size=2), _actor(group_size=2)]
    harnesses = [ServerHarness(a) for a in actors]
    addrs = [h.start() for h in harnesses]
    try:
        ctl = TrainController(
            [RPCEngineClient(a) for a in addrs], chunk_quantum=2
        )
        rng = np.random.default_rng(2)
        batch = _batch(rng, B=8)

        logp = ctl.compute_logp(batch)
        assert logp.shape == batch["input_ids"].shape

        batch["prox_logp"] = logp
        ctl.compute_advantages(batch)
        assert "advantages" in batch

        stats = ctl.ppo_update(batch)
        assert stats and np.isfinite(stats[-1]["loss"])

        ctl.set_version(5)
        assert ctl.get_version() == 5
        assert all(h["status"] == "ok" for h in ctl.health())
    finally:
        for h in harnesses:
            h.stop()
        for a in actors:
            a.destroy()


def test_return_batch_without_blob_is_a_json_400():
    """ADVICE r2: return_batch=True with no batch blob must come back as a
    structured {"error": ...} 400, not a bare AttributeError 500."""
    import json
    import urllib.error
    import urllib.request

    from areal_tpu.scheduler.wire import encode_frame

    actor = _actor()
    h = ServerHarness(actor)
    addr = h.start()
    try:
        body = encode_frame(
            {"__method__": "get_version", "return_batch": True}, b""
        )
        req = urllib.request.Request(
            f"http://{addr}/call", data=body, method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
        payload = json.loads(ei.value.read())
        assert "batch blob" in payload["error"]
    finally:
        h.stop()
        actor.destroy()
