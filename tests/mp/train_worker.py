"""Multi-process worker: 2-process x 4-CPU-device distributed train step.

Spawned by tests/test_multiprocess.py (and __graft_entry__.dryrun_multiprocess)
with AREAL_COORDINATOR / AREAL_NUM_PROCESSES / AREAL_PROCESS_ID set — the
same env contract a real multi-host launcher uses.  Mirrors the reference's
torchrun-driven distributed tests (areal/tests/torchrun/run_fsdp_ulysses_
forward.py): fabricate the runtime, run real collective work, print results
for the parent to compare.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from areal_tpu.api.config import (  # noqa: E402
    MeshConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec  # noqa: E402
from areal_tpu.core.dist_rollout import DistRolloutCoordinator  # noqa: E402
from areal_tpu.engine.ppo import JaxPPOActor  # noqa: E402
from areal_tpu.models.model_config import tiny_config  # noqa: E402
from areal_tpu.parallel import distributed  # noqa: E402


class _FakeRollout:
    """Stands in for the inference engine on the head process."""

    def __init__(self, batch):
        self._batch = batch
        self.calls = 0

    def rollout_batch(self, data, **kw):
        self.calls += 1
        return self._batch


def main():
    distributed.init_distributed()
    pid = jax.process_index()
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    model_cfg = tiny_config(
        vocab_size=128,
        hidden_size=64,
        num_heads=4,
        num_kv_heads=2,
        qkv_bias=True,
        dtype="float32",
        hf_architecture="Qwen2ForCausalLM",
    )
    cfg = PPOActorConfig(
        experiment_name="mp",
        trial_name="mp",
        init_from_scratch=True,
        dtype="float32",
        param_dtype="float32",
        gradient_checkpointing=False,
        mesh=MeshConfig(
            data_parallel_size=2, fsdp_parallel_size=2, tensor_parallel_size=2
        ),
        mb_spec=MicroBatchSpec(n_mbs=1),
        optimizer=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        pack_length_quantum=64,
        max_pack_length=256,
        group_size=2,
        ppo_n_minibatches=1,
        use_decoupled_loss=True,
        adv_norm=NormConfig(mean_level="group", std_level="group", group_size=2),
    )
    actor = JaxPPOActor(cfg, model_config=model_cfg)
    actor.initialize(ft_spec=FinetuneSpec(1, 64, 4))

    # head-only rollout: only process 0 "contacts the server"; the batch
    # reaches everyone via the coordinator broadcast
    rng = np.random.default_rng(7)
    B, L = 8, 48
    head_batch = None
    if distributed.is_head():
        lens = rng.integers(24, L, B)
        am = np.zeros((B, L), bool)
        lm = np.zeros((B, L), np.float32)
        for i, n in enumerate(lens):
            am[i, :n] = True
            lm[i, n // 2 : n] = 1.0
        head_batch = {
            "input_ids": rng.integers(0, 128, (B, L)).astype(np.int32) * am,
            "attention_mask": am,
            "loss_mask": lm,
            "logprobs": (rng.normal(-1, 0.1, (B, L)) * am).astype(np.float32),
            "rewards": rng.integers(0, 2, B).astype(np.float32),
            "versions": np.zeros((B, L), np.int32),
        }
    fake = _FakeRollout(head_batch)
    coord = DistRolloutCoordinator(fake)
    batch = coord.rollout_batch([{}] * B)
    assert fake.calls == (1 if pid == 0 else 0)

    # exercises the multi-process forward path (row-sharded output must be
    # replicated before the host reads it)
    batch["prox_logp"] = actor.compute_logp(batch)
    actor.compute_advantages(batch)
    for step in range(2):
        stats = actor.ppo_update(batch)
        print(
            f"RESULT proc={pid} step={step} "
            f"loss={stats[0]['loss']:.6f} gn={stats[0]['grad_norm']:.6f}",
            flush=True,
        )
    print(f"DONE proc={pid}", flush=True)


if __name__ == "__main__":
    main()
