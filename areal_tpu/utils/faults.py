"""Deterministic fault injection for the rollout fleet (ISSUE 11).

A `FaultPlan` is an explicit table keyed by ``(endpoint, call_index)``:
the Nth call a server sees on an endpoint either proceeds normally or
hits the planned fault.  Plans are either written out literally in a
test or generated from a seed (`FaultPlan.generate`) — same seed, same
table, same injected-failure sequence, so chaos runs replay exactly and
a failure found in CI reproduces locally from one integer.

Fault kinds (what the transport layer can express in-process):

- ``http_500``   — the handler answers HTTP 500 (backend error path);
- ``slow``       — the response is delayed by ``delay_s`` (latency spike);
- ``hang``       — the response is held past any sane client timeout
                   (mid-stream stall; ``delay_s`` is the hold time);
- ``disconnect`` — the server closes the TCP transport mid-request
                   (connection reset, the ambiguous-failure case).

True connection-refused and process death cannot be faked from inside a
live handler: they come from stopping the server (tests/fake_server.py
``stop()`` on a fixed port) or from `kill_process` on a real gen-server
subprocess — the plan's job is everything short of that.

Wiring: tests/fake_server.py consults ``fault_plan.decide(endpoint)`` at
the top of each handler; `apply_fault` turns the decision into aiohttp
behavior.  ``scripts/bench_e2e_grpo.py --chaos`` mounts a `FaultProxy`
in front of a real gen server so the same plans drive real engines.

Trainer-kill chaos (ISSUE 15) extends the vocabulary past the transport:
named **fault points** are markers compiled into crash-critical code
paths (``fault_point("train_step")`` at the end of each train step,
``fault_point("recover_mid_dump")`` between a checkpoint's staging and
its atomic rename).  Arming one — in-process via `arm_fault_point` or
from outside via the ``AREAL_FAULT_POINTS`` env var — makes the Nth hit
either SIGKILL the process (no flush, no goodbye: the preemption/OOM
fault the transport plans cannot express) or raise `InjectedFault` (the
in-process variant for unit tests).  Unarmed points cost a dict lookup.
"""

import asyncio
import json
import os
import random
import signal
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

FAULT_KINDS = ("http_500", "slow", "hang", "disconnect")


@dataclass(frozen=True)
class Fault:
    kind: str  # one of FAULT_KINDS
    delay_s: float = 0.0  # slow: added latency; hang: hold duration

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")


class FaultPlan:
    """{(endpoint, call_index): Fault} plus per-endpoint call counters.

    ``decide`` is thread-safe (fake servers run handlers on their own
    loop threads) and records every injection in ``injected`` so a run
    can report — and a repeat-run test can assert — the exact sequence.
    """

    def __init__(self, plan: Optional[Dict[Tuple[str, int], Fault]] = None):
        self.plan: Dict[Tuple[str, int], Fault] = dict(plan or {})
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self.injected: List[Tuple[str, int, str]] = []

    def decide(self, endpoint: str) -> Optional[Fault]:
        """Count this call on `endpoint`; return the planned fault, if any."""
        with self._lock:
            idx = self._counts.get(endpoint, 0)
            self._counts[endpoint] = idx + 1
            fault = self.plan.get((endpoint, idx))
            if fault is not None:
                self.injected.append((endpoint, idx, fault.kind))
            return fault

    def reset_counters(self) -> None:
        with self._lock:
            self._counts.clear()
            self.injected.clear()

    def injected_log(self) -> List[Tuple[str, int, str]]:
        with self._lock:
            return list(self.injected)

    @classmethod
    def generate(
        cls,
        seed: int,
        endpoints: Sequence[str] = ("/generate",),
        n_calls: int = 64,
        rate: float = 0.15,
        kinds: Sequence[str] = ("http_500", "slow", "disconnect"),
        slow_s: float = 0.05,
        hang_s: float = 30.0,
    ) -> "FaultPlan":
        """Seeded plan over the first `n_calls` calls of each endpoint.
        `random.Random(seed)` is stable across processes and platform, so
        the table — and therefore the injected sequence — is a pure
        function of the arguments."""
        rng = random.Random(seed)
        plan: Dict[Tuple[str, int], Fault] = {}
        for ep in endpoints:
            for idx in range(n_calls):
                if rng.random() < rate:
                    kind = kinds[rng.randrange(len(kinds))]
                    delay = {"slow": slow_s, "hang": hang_s}.get(kind, 0.0)
                    plan[(ep, idx)] = Fault(kind, delay)
        return plan_or_empty(cls(plan))

    # --- serialization (bench reports / replay files) ---
    def to_dict(self) -> Dict[str, Dict[str, float | str]]:
        return {
            f"{ep}|{idx}": {"kind": f.kind, "delay_s": f.delay_s}
            for (ep, idx), f in sorted(self.plan.items())
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Dict]) -> "FaultPlan":
        plan = {}
        for key, spec in d.items():
            ep, idx = key.rsplit("|", 1)
            plan[(ep, int(idx))] = Fault(spec["kind"],
                                         float(spec.get("delay_s", 0.0)))
        return cls(plan)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)


def plan_or_empty(plan: Optional["FaultPlan"]) -> "FaultPlan":
    return plan if plan is not None else FaultPlan()


async def apply_fault(fault: Optional[Fault], request):
    """Turn a decision into aiohttp handler behavior.  Returns a Response
    for faults that answer (http_500), None for pass-through faults
    (slow delays then continues), and raises for transport-level ones —
    the caller must `return` a non-None result and propagate raises."""
    from aiohttp import web

    if fault is None:
        return None
    if fault.kind == "slow":
        await asyncio.sleep(fault.delay_s)
        return None
    if fault.kind == "http_500":
        return web.json_response(
            {"error": "injected fault: http_500"}, status=500
        )
    if fault.kind == "hang":
        # hold the request open past the client's timeout; the sleep is
        # cancelled when the client goes away or the server stops
        await asyncio.sleep(fault.delay_s or 3600.0)
        return web.json_response(
            {"error": "injected fault: hang elapsed"}, status=500
        )
    if fault.kind == "disconnect":
        # mid-stream transport kill: the client sees a connection reset,
        # the ambiguous did-it-commit failure mode
        if request.transport is not None:
            request.transport.close()
        raise ConnectionResetError("injected fault: disconnect")
    raise ValueError(f"unknown fault kind {fault.kind!r}")


class InjectedFault(RuntimeError):
    """Raised by a fault point armed with action='raise' (the in-process
    stand-in for a kill when the test wants to keep its interpreter)."""


# {name: {"action": "kill"|"raise", "at_hit": int, "hits": int}}
_FAULT_POINTS: Dict[str, Dict] = {}
_FAULT_LOCK = threading.Lock()
_ENV_PARSED = False

FAULT_POINT_ACTIONS = ("kill", "raise")


def arm_fault_point(name: str, action: str = "kill", at_hit: int = 1) -> None:
    """Arm `name` to fire on its `at_hit`-th hit.  `kill` SIGKILLs the
    process (crash-for-real, subprocess harnesses); `raise` throws
    `InjectedFault` (in-process unit tests)."""
    if action not in FAULT_POINT_ACTIONS:
        raise ValueError(f"unknown fault-point action {action!r}")
    if at_hit < 1:
        raise ValueError(f"at_hit must be >= 1, got {at_hit}")
    with _FAULT_LOCK:
        _FAULT_POINTS[name] = {"action": action, "at_hit": at_hit, "hits": 0}


def reset_fault_points() -> None:
    """Disarm everything and forget the env parse (tests)."""
    global _ENV_PARSED
    with _FAULT_LOCK:
        _FAULT_POINTS.clear()
        _ENV_PARSED = False


def kill_trainer_at_step(step: int, start_step: int = 0) -> None:
    """Arm the ``train_step`` point so the process is SIGKILLed at the end
    of absolute step `step` (the chaos-harness entry: step counting is
    relative to `start_step`, so a relaunched run arms against its own
    resume point)."""
    arm_fault_point("train_step", action="kill",
                    at_hit=step - start_step + 1)


def _parse_env_fault_points() -> None:
    """``AREAL_FAULT_POINTS="name[@N][:action],..."`` — arm points from the
    environment so subprocess harnesses (bench, CI) need no code hook.
    Parsed once, lazily, at the first fault_point() hit."""
    global _ENV_PARSED
    _ENV_PARSED = True
    spec = os.environ.get("AREAL_FAULT_POINTS", "")
    for item in filter(None, (s.strip() for s in spec.split(","))):
        action = "kill"
        if ":" in item:
            item, action = item.rsplit(":", 1)
        at_hit = 1
        if "@" in item:
            item, n = item.rsplit("@", 1)
            at_hit = int(n)
        _FAULT_POINTS[item] = {"action": action, "at_hit": at_hit, "hits": 0}


def fault_point(name: str) -> None:
    """A named crash marker.  No-op unless armed; on the armed hit either
    SIGKILLs the process or raises `InjectedFault`."""
    with _FAULT_LOCK:
        if not _ENV_PARSED:
            _parse_env_fault_points()
        entry = _FAULT_POINTS.get(name)
        if entry is None:
            return
        entry["hits"] += 1
        if entry["hits"] != entry["at_hit"]:
            return
        action = entry["action"]
    if action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
        # SIGKILL is asynchronous: never let execution proceed past the
        # crash point while delivery is pending
        while True:
            time.sleep(1.0)
    raise InjectedFault(name)


def kill_process(proc, timeout: float = 10.0) -> Optional[int]:
    """SIGKILL a real gen-server subprocess and reap it — the one fault an
    in-process injector cannot express (no flush, no goodbye, exactly like
    an OOM-killed or preempted fleet member)."""
    if proc.poll() is None:
        try:
            proc.send_signal(signal.SIGKILL)
        except ProcessLookupError:
            pass
    try:
        proc.wait(timeout=timeout)
    except Exception:  # noqa: BLE001 — caller inspects returncode
        pass
    return proc.returncode


class FaultProxy:
    """A fault-injecting HTTP forwarder for chaos runs against REAL gen
    servers: sits on its own port, applies the plan's decision for each
    (endpoint, call_index), and otherwise forwards the request verbatim
    to the upstream server.  Runs on a background thread+loop exactly
    like tests/fake_server.py so sync bench code can own it."""

    def __init__(self, upstream_addr: str, plan: FaultPlan):
        self.upstream = upstream_addr
        self.plan = plan
        self.port: Optional[int] = None
        self._runner = None
        self._session = None
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()

    async def _forward(self, request):
        from aiohttp import web

        faulted = await apply_fault(self.plan.decide(request.path), request)
        if faulted is not None:
            return faulted
        body = await request.read()
        async with self._session.request(
            request.method,
            f"http://{self.upstream}{request.path_qs}",
            data=body if body else None,
            headers={
                k: v for k, v in request.headers.items()
                if k.lower() not in ("host", "content-length")
            },
        ) as resp:
            payload = await resp.read()
            return web.Response(
                body=payload,
                status=resp.status,
                content_type=resp.content_type,
            )

    def start(self) -> str:
        import aiohttp
        from aiohttp import web

        def _run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def _serve():
                self._session = aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=600),
                    connector=aiohttp.TCPConnector(limit=0),
                )
                app = web.Application(client_max_size=1024**3)
                app.router.add_route("*", "/{tail:.*}", self._forward)
                runner = web.AppRunner(app)
                await runner.setup()
                site = web.TCPSite(runner, "127.0.0.1", 0)
                await site.start()
                self.port = runner.addresses[0][1]
                self._runner = runner
                self._started.set()

            self._loop.run_until_complete(_serve())
            self._loop.run_forever()

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("fault proxy failed to start")
        return f"127.0.0.1:{self.port}"

    def stop(self):
        if self._loop is None:
            return

        async def _cleanup():
            if self._session is not None:
                await self._session.close()
            if self._runner is not None:
                await self._runner.cleanup()

        asyncio.run_coroutine_threadsafe(_cleanup(), self._loop).result(
            timeout=5
        )
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
