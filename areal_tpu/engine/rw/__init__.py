from areal_tpu.engine.rw.rw_engine import JaxRewardModelEngine

__all__ = ["JaxRewardModelEngine"]
