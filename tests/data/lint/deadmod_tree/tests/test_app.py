"""Test importer — must NOT keep myproj.dead alive."""

from myproj.dead import unreachable


def test_unreachable():
    assert unreachable() == 42
