"""AReaL-TPU: a TPU-native asynchronous RL training framework for LLMs.

A ground-up JAX/XLA/Pallas re-design of the capability surface of AReaL
(reference: zhshgmail/AReaL): fully-asynchronous rollout generation decoupled
from training, staleness-aware capacity control, decoupled-PPO objectives,
and trainer->inference weight synchronization — built on jax.sharding meshes,
pjit/GSPMD collectives, and Pallas kernels instead of CUDA/NCCL/torch.
"""

__version__ = "0.1.0"
