"""Dataset loaders (reference: areal/dataset/__init__.py get_custom_dataset
dispatch + per-dataset modules)."""

from typing import Any, Callable, Dict, Optional

_REGISTRY: Dict[str, Callable] = {}


def register_dataset(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_custom_dataset(
    path: str,
    type: str = "",
    split: str = "train",
    tokenizer=None,
    max_length: Optional[int] = None,
    **kwargs,
):
    """Dispatch on dataset `type` (e.g. "gsm8k", "jsonl"); `path` is a local
    directory/file or an HF dataset id (works offline when cached)."""
    key = type or path
    for name, fn in _REGISTRY.items():
        if name == key or name in key:
            return fn(path=path, split=split, tokenizer=tokenizer,
                      max_length=max_length, **kwargs)
    raise ValueError(f"unknown dataset type {key!r}; known: {sorted(_REGISTRY)}")


from areal_tpu.dataset import gsm8k as _gsm8k  # noqa: E402,F401  (registers)
from areal_tpu.dataset import jsonl as _jsonl  # noqa: E402,F401
from areal_tpu.dataset import clevr as _clevr  # noqa: E402,F401
from areal_tpu.dataset import geometry3k as _geometry3k  # noqa: E402,F401
from areal_tpu.dataset import hhrlhf as _hhrlhf  # noqa: E402,F401
from areal_tpu.dataset import torl as _torl  # noqa: E402,F401
from areal_tpu.dataset import countdown as _countdown  # noqa: E402,F401
from areal_tpu.dataset import searchqa as _searchqa  # noqa: E402,F401
