"""GSM8K DAPO — GRPO with the DAPO recipe knobs.

Counterpart of the reference's `examples/experimental/dapo/gsm8k_dapo.py`
(which duplicates the whole GRPO main): here the training loop is the one
from `examples/math/gsm8k_grpo.py`, and DAPO is pure configuration —
`gsm8k_dapo.yaml` sets the recipe's four levers:

- asymmetric clipping (`eps_clip: 0.2`, `eps_clip_higher: 0.28`) — the
  "clip-higher" rule that keeps uplifting low-probability tokens
  (reference yaml: examples/experimental/dapo/gsm8k_dapo.yaml:57-58)
- soft overlong penalty (`overlong_reward_penalty`, `overlong_tokens: 512`,
  `overlong_penalty_factor: 1.0` against the generation budget)
- dynamic sampling (`dynamic_sampling: true`): all-same-reward groups are
  dropped from the update
- token-level loss over the group (group-mean reward norm, no KL)

Launch:
    python examples/experimental/dapo/gsm8k_dapo.py \
        --config examples/experimental/dapo/gsm8k_dapo.yaml
"""

import importlib.util
import os
import sys

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def _load_grpo_main():
    spec = importlib.util.spec_from_file_location(
        "gsm8k_grpo_shared",
        os.path.join(_REPO, "examples", "math", "gsm8k_grpo.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


if __name__ == "__main__":
    sys.path.insert(0, _REPO)
    _load_grpo_main()(sys.argv[1:])
