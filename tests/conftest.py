"""Test config: force an 8-virtual-device CPU platform before jax imports.

Mirrors the reference's testing approach (realhf/base/testing.py fabricates
topologies without a cluster): distributed sharding logic is exercised on a
virtual CPU mesh; real-TPU benchmarks live in bench.py, not tests.
"""

import os

# force CPU even when the ambient environment selects a TPU platform —
# tests exercise distributed sharding on 8 virtual devices
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# A site-installed TPU plugin may have forced jax_platforms at interpreter
# boot (overriding the env var), so re-force CPU at the config level too.
jax.config.update("jax_platforms", "cpu")

# Numerics tests compare against fp32 torch references; XLA:CPU's default
# (lower) einsum precision would drown parity in ~1e-3 noise.
jax.config.update("jax_default_matmul_precision", "highest")

import pytest  # noqa: E402

# areal-lint fixture trees under data/ contain test-shaped files (e.g. the
# C4 dead-module tree's tests/ dir) that are lint *inputs*, not tests
collect_ignore_glob = ["data/*"]


@pytest.fixture(autouse=True)
def _seed():
    from areal_tpu.utils import seeding

    seeding.set_random_seed(1, "test")
    yield


@pytest.fixture(autouse=True)
def _fresh_name_resolve():
    from areal_tpu.utils import name_resolve

    name_resolve.DEFAULT_REPOSITORY = name_resolve.MemoryNameRecordRepository()
    yield
    name_resolve.DEFAULT_REPOSITORY.reset()
