"""Search-agent GRPO — retrieval-augmented QA agent RL.

Behavioral counterpart of the reference's search-agent example
(`examples/search-agent/local_1.5b_example.yaml`, the ASearcher recipe):
the model interleaves `<search>query</search>` calls with reasoning;
`SearchQAAgent` executes each query against the episode's corpus
(`LocalSearchEnv` — BM25-lite over local passages; swap the env for a
retrieval service in production) and injects the hits as
`<information>` blocks, then grades the boxed answer.

This entry point delegates to the shared GRPO loop
(examples/math/gsm8k_grpo.py) with `workflow: search`.

Launch:  python examples/search_agent/search_grpo.py --config examples/search_agent/search_grpo.yaml
(or: python -m areal_tpu.launcher.local examples/search_agent/search_grpo.py --config ...)
"""

import importlib.util
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_spec = importlib.util.spec_from_file_location(
    "gsm8k_grpo", os.path.join(_REPO, "examples", "math", "gsm8k_grpo.py")
)
_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mod)


def main(argv):
    _mod.main(argv)


if __name__ == "__main__":
    main(sys.argv[1:])
