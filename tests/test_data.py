import numpy as np
import pytest

from areal_tpu.utils.data import (
    KLEstimator,
    Normalization,
    concat_padded_tensors,
    pack_tensor_dict,
    pad_packed_tensor_dict,
    pad_sequences_to_tensors,
    seq_lens,
    split_padded_tensor_dict_into_mb_list,
    unpack_sequence,
)


def _traj(n, reward=1.0):
    return {
        "input_ids": np.arange(n, dtype=np.int32),
        "logprobs": np.random.randn(n).astype(np.float32),
        "rewards": np.float32(reward),
    }


def test_pad_sequences_to_tensors():
    batch = pad_sequences_to_tensors([_traj(3), _traj(5), _traj(2)])
    assert batch["input_ids"].shape == (3, 5)
    assert batch["attention_mask"].dtype == np.bool_
    assert seq_lens(batch).tolist() == [3, 5, 2]
    assert batch["rewards"].shape == (3,)


def test_concat_padded_tensors_repads():
    b1 = pad_sequences_to_tensors([_traj(3)])
    b2 = pad_sequences_to_tensors([_traj(6), _traj(4)])
    out = concat_padded_tensors([b1, b2])
    assert out["input_ids"].shape == (3, 6)
    assert seq_lens(out).tolist() == [3, 6, 4]
    # padding must be zeros
    assert out["input_ids"][0, 3:].sum() == 0


def test_pack_unpack_roundtrip():
    batch = pad_sequences_to_tensors([_traj(3), _traj(5), _traj(2)])
    packed = pack_tensor_dict(batch)
    assert packed["input_ids"].shape == (10,)
    assert packed["cu_seqlens"].tolist() == [0, 3, 8, 10]
    assert packed["segment_ids"].tolist() == [0, 0, 0, 1, 1, 1, 1, 1, 2, 2]
    assert packed["positions"].tolist() == [0, 1, 2, 0, 1, 2, 3, 4, 0, 1]
    seqs = unpack_sequence(packed)
    assert len(seqs) == 3
    np.testing.assert_array_equal(seqs[1]["input_ids"], np.arange(5))


def test_pack_bucketed_padding():
    batch = pad_sequences_to_tensors([_traj(3), _traj(5)])
    packed = pack_tensor_dict(batch, quantum=16)
    assert packed["input_ids"].shape == (16,)
    assert (packed["segment_ids"][8:] == -1).all()
    assert int(packed["total_lens"]) == 8
    # unpack ignores filler
    seqs = unpack_sequence(packed)
    assert [len(s["input_ids"]) for s in seqs] == [3, 5]


def test_pad_packed_tensor_dict():
    batch = pad_sequences_to_tensors([_traj(4)])
    packed = pack_tensor_dict(batch)
    padded = pad_packed_tensor_dict(packed, 12)
    assert padded["input_ids"].shape == (12,)
    assert (padded["segment_ids"][4:] == -1).all()


def test_mb_split_and_merge():
    batch = pad_sequences_to_tensors([_traj(n) for n in [2, 9, 5, 7, 3, 4]])
    mbl = split_padded_tensor_dict_into_mb_list(batch, max_tokens_per_mb=10)
    for mb, g in zip(mbl.mbs, mbl.groups):
        assert seq_lens(mb).sum() <= 10 or len(g) == 1
    # merge per-row outputs back to original order
    outs = [seq_lens(mb).astype(np.float32) for mb in mbl.mbs]
    merged = mbl.merge_outputs(outs)
    np.testing.assert_array_equal(merged, [2, 9, 5, 7, 3, 4])


def test_normalization_group():
    norm = Normalization(mean_level="group", std_level="group", group_size=2)
    x = np.array([[1.0], [3.0], [10.0], [20.0]], dtype=np.float32)
    out = norm(x)
    # each group normalized to zero mean
    assert abs(out[0, 0] + out[1, 0]) < 1e-5
    assert abs(out[2, 0] + out[3, 0]) < 1e-5


def test_normalization_masked_batch():
    norm = Normalization(mean_level="batch", std_level="batch")
    x = np.array([[1.0, 99.0], [3.0, 98.0]], dtype=np.float32)
    mask = np.array([[1.0, 0.0], [1.0, 0.0]], dtype=np.float32)
    out = norm(x, mask)
    assert abs(out[0, 0] + out[1, 0]) < 1e-5
    assert out[0, 1] == 0.0  # masked positions zeroed


def test_normalization_none_levels():
    norm = Normalization(mean_level=None, std_level=None)
    x = np.random.randn(4, 3).astype(np.float32)
    np.testing.assert_allclose(norm(x), x, atol=1e-6)


def test_kl_estimators():
    logp = np.array([0.0, -1.0])
    ref = np.array([-0.5, -0.5])
    k1 = KLEstimator("k1")(logp, ref)
    np.testing.assert_allclose(k1, [0.5, -0.5])
    k2 = KLEstimator("k2")(logp, ref)
    np.testing.assert_allclose(k2, [0.125, 0.125])
    k3 = KLEstimator("k3")(logp, ref)
    assert (k3 >= 0).all()  # k3 is non-negative
    with pytest.raises(ValueError):
        KLEstimator("k9")


def test_pad_sequences_mismatched_lengths_rejected():
    with pytest.raises(ValueError):
        pad_sequences_to_tensors(
            [{"a": np.arange(3), "b": np.arange(5)}]
        )
    with pytest.raises(ValueError):
        pad_sequences_to_tensors([{"x": np.float32(1.0)}])


def test_unpack_with_short_sequences_keeps_row_keys():
    # total tokens (2) < batch size (3): per-row keys must still map by row
    batch = pad_sequences_to_tensors(
        [
            {"input_ids": np.array([7]), "rewards": np.float32(10.0)},
            {"input_ids": np.array([8]), "rewards": np.float32(20.0)},
            {"input_ids": np.array([9]), "rewards": np.float32(30.0)},
        ]
    )
    packed = pack_tensor_dict(batch)
    seqs = unpack_sequence(packed)
    assert [float(s["rewards"]) for s in seqs] == [10.0, 20.0, 30.0]
    assert [s["input_ids"].tolist() for s in seqs] == [[7], [8], [9]]


def test_pad_packed_shrink_preserves_metadata():
    batch = pad_sequences_to_tensors([_traj(2), _traj(2)])
    packed = pack_tensor_dict(batch, pad_to=16)
    shrunk = pad_packed_tensor_dict(packed, 8)
    assert shrunk["segment_ids"].shape == (8,)
    assert shrunk["cu_seqlens"].tolist() == [0, 2, 4]
    assert len(unpack_sequence(shrunk)) == 2
    with pytest.raises(ValueError):
        pad_packed_tensor_dict(packed, 3)  # below real token count


def test_to_jax_skips_string_arrays():
    from areal_tpu.utils.data import to_jax

    batch = pad_sequences_to_tensors([_traj(3)])
    packed = pack_tensor_dict(batch)
    j = to_jax(packed)
    assert j["input_ids"].shape == (3,)  # on-device
    assert j["__token_keys__"].dtype.kind == "U"  # left on host


def test_pad_packed_external_dict_heuristic():
    # external packed dict without __token_keys__: all flat buffers padded
    ext = {
        "input_ids": np.arange(5, dtype=np.int32),
        "segment_ids": np.zeros(5, np.int32),
        "positions": np.arange(5, dtype=np.int32),
        "cu_seqlens": np.array([0, 5], np.int32),
        "max_seqlen": np.asarray(5, np.int32),
        "total_lens": np.asarray(5, np.int32),
    }
    out = pad_packed_tensor_dict(ext, 12)
    assert out["input_ids"].shape == (12,)
    assert out["segment_ids"].shape == (12,)
