"""areal-lint CLI: run the project static-analysis suite (ISSUE 3).

    python scripts/lint.py              # report all findings
    python scripts/lint.py --check     # exit 1 on unsuppressed findings
                                        # (the tier-1 gate semantics)
    python scripts/lint.py --suppressed # also list suppressed findings

Checker catalog, annotation syntax (`_GUARDED_FIELDS`, `# guarded-by:`,
`# holds:`, `# areal-lint: hot-path`) and the suppression format
(`# areal-lint: disable=<rule> <reason>`): docs/lint.md.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.analysis import run_suite, unsuppressed  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        help="project root to scan (default: this repo)",
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="exit nonzero when any unsuppressed finding exists",
    )
    p.add_argument(
        "--suppressed",
        action="store_true",
        help="also print suppressed findings (they are always counted)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable output")
    args = p.parse_args(argv)

    findings = run_suite(args.root)
    active = unsuppressed(findings)
    suppressed = [f for f in findings if f.suppressed]

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [vars(f) for f in active],
                    "suppressed": [vars(f) for f in suppressed],
                }
            )
        )
    else:
        for f in active:
            print(f.render())
        if args.suppressed:
            for f in suppressed:
                print(f.render())
        print(
            f"areal-lint: {len(active)} finding(s), "
            f"{len(suppressed)} suppressed"
        )
    if args.check and active:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
