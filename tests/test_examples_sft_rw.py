"""SFT + RW example smoke tests: run the real entry points as subprocesses
on tiny fixtures and grep for step completions (the reference's
test_examples.py pattern)."""

import json
import os
import subprocess
import sys

import pytest

from tests.fixtures import make_gsm8k_jsonl, make_tiny_ckpt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(script, cfg_path, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, script), "--config", str(cfg_path)],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    return proc.stdout + proc.stderr


@pytest.mark.slow
def test_sft_example_end_to_end(tmp_path):
    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    data = make_gsm8k_jsonl(str(tmp_path / "train.jsonl"), n=16)
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        f"""
experiment_name: sft-smoke
trial_name: t0
seed: 1
total_train_epochs: 1
total_train_steps: 2
cluster:
  fileroot: {tmp_path}/exp
train_dataset:
  path: {data}
  type: gsm8k
  batch_size: 4
  max_length: 128
model:
  experiment_name: sft-smoke
  trial_name: t0
  path: {ckpt}
  dtype: float32
  gradient_checkpointing: false
  optimizer:
    lr: 1.0e-4
saver:
  experiment_name: sft-smoke
  trial_name: t0
  fileroot: {tmp_path}/exp
  freq_steps: 1000
stats_logger:
  experiment_name: sft-smoke
  trial_name: t0
  fileroot: {tmp_path}/exp
"""
    )
    out = _run_example("examples/sft/gsm8k_sft.py", cfg)
    assert "Step 1/" in out and "done." in out
    assert "ppl=" in out


@pytest.mark.slow
def test_rw_example_end_to_end(tmp_path):
    ckpt = tmp_path / "model"
    make_tiny_ckpt(str(ckpt))
    pairs = tmp_path / "pairs.jsonl"
    pairs.write_text(
        "\n".join(
            json.dumps(
                {
                    "chosen": f"a helpful answer number {i}",
                    "rejected": f"bad {i}",
                }
            )
            for i in range(8)
        )
    )
    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        f"""
experiment_name: rw-smoke
trial_name: t0
seed: 1
total_train_epochs: 1
cluster:
  fileroot: {tmp_path}/exp
train_dataset:
  path: {pairs}
  type: hhrlhf
  batch_size: 4
  max_length: 64
model:
  experiment_name: rw-smoke
  trial_name: t0
  path: {ckpt}
  dtype: float32
  gradient_checkpointing: false
  optimizer:
    lr: 1.0e-4
saver:
  experiment_name: rw-smoke
  trial_name: t0
  fileroot: {tmp_path}/exp
  freq_steps: 1000
stats_logger:
  experiment_name: rw-smoke
  trial_name: t0
  fileroot: {tmp_path}/exp
"""
    )
    out = _run_example("examples/rw/hhrlhf_rw.py", cfg)
    assert "Step 1/" in out and "done." in out
