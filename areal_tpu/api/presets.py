"""Experiment presets + automatic device-allocation heuristics.

Behavioral counterpart of the reference's experiment-preset layer
(realhf/experiments/common/common.py:627 auto device-mesh assignment,
realhf/api/quickstart/device_mesh.py:274 heuristic allocation): given a
model size and a chip budget, pick a sensible allocation expression and a
ready-to-edit config, so users start from `preset("gsm8k-grpo-1.5b")`
instead of a blank YAML.

The heuristics encode the TPU sizing rules the rest of the stack assumes:

- **tp** is chosen so one model replica's train state fits a chip's HBM
  (bf16 params + grads + AdamW moments ~ 8 bytes/param, plus ~25%
  activation headroom under remat);
- **fsdp** absorbs the remaining train chips (GSPMD ZeRO-3 over the fsdp
  axis is the default scale-out, mirroring the reference's FSDP engine);
- generation gets the larger chip share (async RL is generation-bound —
  the reference's benchmark splits ~3:1 gen:train);
- generation servers shard tp only as far as KV-cache+weights demand
  (serving needs ~2 bytes/param + KV, far less than training).
"""

import dataclasses
import math
from typing import Dict, Optional

from areal_tpu.api.alloc import AllocationMode

# per-chip usable HBM bytes (after runtime reserves), keyed by device kind
# prefix; the v5e figure matches the one real chip this repo benches on
HBM_BYTES = {
    "TPU v5 lite": 14 * 1024**3,
    "TPU v5p": 90 * 1024**3,
    "TPU v4": 28 * 1024**3,
    "default": 14 * 1024**3,
}

TRAIN_BYTES_PER_PARAM = 8.0 * 1.25  # bf16 p+g + f32 moments, remat headroom
GEN_BYTES_PER_PARAM = 2.0 * 1.5  # bf16 weights + KV/activation headroom


def _pow2_at_least(x: float, cap: int) -> int:
    p = 1
    while p < x and p < cap:
        p *= 2
    return p


def _pow2_divisors(n: int):
    p = 1
    while p <= n:
        if n % p == 0:
            yield p
        p *= 2


def search_allocation(
    n_devices: int,
    n_params: float,
    ctx_len: int = 4096,
    gen_cost_ratio: float = 3.0,
    hbm_bytes: Optional[int] = None,
    device_kind: str = "default",
    hidden_size: Optional[float] = None,
    num_layers: Optional[float] = None,
    gen_concurrency: int = 32,
) -> Dict:
    """Enumerate-and-score allocation search (the depth of the reference's
    device-mesh search, realhf/api/quickstart/device_mesh.py:274, with a
    TPU cost model instead of GPU profiles).

    Every pow-2 split of chips into gen (dp x tp) and train
    (fsdp x sp x tp) is checked for HBM feasibility and scored by a
    throughput model:

    - trainer consumption ~ n_train scaled by a collective-overhead factor
      per doubling of tp/sp (intra-replica collectives ride ICI but still
      cost bandwidth);
    - generation supply ~ n_gen similarly scaled; the system rate is
      min(train_rate, gen_rate / gen_cost_ratio) — async RL is
      generation-bound, the reference benchmarks split chips ~3:1;
    - memory: train state bytes shard over (tp x fsdp), activation bytes
      (~ctx-linear under remat) over (tp x sp); serving weights AND the
      KV cache for `gen_concurrency` sequences of ctx_len shard over the
      serving tp.

    Returns {"expr", "score", "n_gen", "n_train", ...} for the best split.
    """
    if n_devices < 2:
        raise ValueError("async RL needs >= 2 chips (gen + train)")
    hbm = hbm_bytes or HBM_BYTES.get(device_kind, HBM_BYTES["default"])
    # coarse dense-transformer shape: real models keep layers ~ hidden/128
    # (e.g. Qwen2.5-7B: 3584/28), so from n = 12*L*h^2 = 12*h^3/128:
    if hidden_size:
        hidden = hidden_size
        layers = num_layers or max(4.0, n_params / (12 * hidden * hidden))
    else:
        hidden = max(512.0, 128.0 * round((n_params * 128 / 12) ** (1 / 3) / 128))
        layers = num_layers or max(4.0, n_params / (12 * hidden * hidden))
    # per-token activation bytes under full remat: layer inputs + head
    act_bytes_per_token = 2.0 * hidden * (layers + 8)
    # per-token KV bytes (bf16 K+V, GQA kv width ~hidden/4)
    kv_bytes_per_token = 2.0 * 2.0 * layers * (hidden / 4)
    train_state = n_params * TRAIN_BYTES_PER_PARAM
    gen_state = n_params * GEN_BYTES_PER_PARAM

    def axis_eff(k: int, per_double: float) -> float:
        return 1.0 / (1.0 + per_double * math.log2(max(k, 1)))

    # KV cache for the concurrent-rollout budget shards over the serving tp
    # axis along with the weights
    gen_kv = gen_concurrency * ctx_len * kv_bytes_per_token
    best = None
    for gen_tp in _pow2_divisors(n_devices):
        if (gen_state + gen_kv) / gen_tp > hbm:
            continue
        for n_gen in range(gen_tp, n_devices, gen_tp):
            n_train = n_devices - n_gen
            gen_rate = n_gen * axis_eff(gen_tp, 0.10)
            for tp in _pow2_divisors(n_train):
                for sp in _pow2_divisors(n_train // tp):
                    fsdp = n_train // (tp * sp)
                    state_pc = train_state / (tp * fsdp)
                    act_pc = ctx_len * act_bytes_per_token / (tp * sp)
                    if state_pc + act_pc > hbm:
                        continue
                    train_rate = n_train * axis_eff(tp, 0.08) * axis_eff(sp, 0.05)
                    score = min(train_rate, gen_rate / gen_cost_ratio)
                    # prefer simpler meshes on ties (fewer sharded axes)
                    complexity = (tp > 1) + (sp > 1) + (gen_tp > 1)
                    key = (score, -complexity, n_gen)
                    if best is None or key > best["key"]:
                        gen = f"jax:d{n_gen // gen_tp}" + (
                            f"t{gen_tp}" if gen_tp > 1 else ""
                        )
                        train = "jax:" + (f"f{fsdp}" if fsdp > 1 else "d1") + (
                            f"s{sp}" if sp > 1 else ""
                        ) + (f"t{tp}" if tp > 1 else "")
                        best = {
                            "key": key,
                            "expr": f"{gen}+{train}",
                            "score": score,
                            "n_gen": n_gen,
                            "n_train": n_train,
                            "gen_tp": gen_tp,
                            "train_tp": tp,
                            "train_sp": sp,
                            "train_fsdp": fsdp,
                        }
    if best is None:
        raise ValueError(
            f"{n_devices} chips cannot host model {n_params / 1e9:.1f}B at "
            f"ctx {ctx_len} (train state {train_state / 1e9:.1f} GB)"
        )
    AllocationMode.from_str(best["expr"])  # validate against the real parser
    del best["key"]
    return best


def auto_allocation(
    n_devices: int,
    n_params: float,
    gen_fraction: float = 0.75,  # kept for API compat; the search owns the split
    hbm_bytes: Optional[int] = None,
    device_kind: str = "default",
    ctx_len: int = 4096,
) -> str:
    """Pick a disaggregated allocation expression for an async-RL run.

    Returns e.g. "jax:d6t2+jax:f2t2" — gen servers on the left of '+',
    trainer mesh on the right (api/alloc.py dialect)."""
    return search_allocation(
        n_devices,
        n_params,
        ctx_len=ctx_len,
        hbm_bytes=hbm_bytes,
        device_kind=device_kind,
    )["expr"]


# ---------------------------------------------------------------------------
# Named experiment presets
# ---------------------------------------------------------------------------


def _gsm8k_grpo(model_path: str, n_params: float, n_devices: int) -> Dict:
    """Config-dict preset mirroring examples/math/gsm8k_grpo.py + the
    reference's example YAMLs (examples/math/gsm8k_grpo.yaml)."""
    return {
        "experiment_name": "gsm8k-grpo",
        "trial_name": "trial0",
        "allocation_mode": auto_allocation(n_devices, n_params),
        "train_dataset": {
            "path": "openai/gsm8k",
            "type": "gsm8k",
            "batch_size": 8,
            "shuffle": True,
        },
        "actor": {
            "experiment_name": "gsm8k-grpo",
            "trial_name": "trial0",
            "path": model_path,
            "dtype": "bfloat16",
            "group_size": 8,
            "group_reward_norm": True,
            "use_decoupled_loss": True,
            "recompute_logprob": True,
            "ppo_n_minibatches": 2,
            "optimizer": {"lr": 1e-6, "lr_scheduler_type": "constant"},
        },
        "gconfig": {
            "max_new_tokens": 1024,
            "temperature": 1.0,
            "n_samples": 8,
        },
        "rollout": {
            "experiment_name": "gsm8k-grpo",
            "trial_name": "trial0",
            "max_concurrent_rollouts": 64,
            "max_head_offpolicyness": 4,
        },
        "gen_server": {"model_path": model_path, "max_context_len": 2048},
    }


_PRESETS = {
    "gsm8k-grpo-tiny": lambda: _gsm8k_grpo("", 5e6, 2),
    "gsm8k-grpo-1.5b": lambda: _gsm8k_grpo("Qwen/Qwen2.5-1.5B-Instruct", 1.54e9, 8),
    "gsm8k-grpo-7b": lambda: _gsm8k_grpo("Qwen/Qwen2.5-7B-Instruct", 7.6e9, 32),
}


def preset(name: str) -> Dict:
    """A ready-to-edit config dict (feed to load_expr_config via YAML dump,
    or use as overrides)."""
    if name not in _PRESETS:
        raise ValueError(f"unknown preset {name!r}; known: {sorted(_PRESETS)}")
    return _PRESETS[name]()


def list_presets():
    return sorted(_PRESETS)


def main():
    """Preset browser / allocation helper:

        python -m areal_tpu.api.presets                  # list names
        python -m areal_tpu.api.presets gsm8k-grpo-1.5b  # config as JSON
        python -m areal_tpu.api.presets --alloc 1.5e9 8  # just the
                                                         # allocation expr

    The JSON is the ready-to-edit config: dump to YAML and feed
    load_expr_config, or use as overrides."""
    import argparse
    import json

    p = argparse.ArgumentParser()
    p.add_argument("name", nargs="?", default="")
    p.add_argument(
        "--alloc",
        nargs=2,
        metavar=("N_PARAMS", "N_CHIPS"),
        help="print the auto allocation expression for a model size "
        "(params, float ok: 1.5e9) on a chip budget",
    )
    p.add_argument("--ctx-len", type=int, default=4096)
    args = p.parse_args()
    if args.alloc:
        n_params, n_devices = float(args.alloc[0]), int(args.alloc[1])
        print(auto_allocation(n_devices, n_params, ctx_len=args.ctx_len))
        return
    if not args.name:
        print("\n".join(list_presets()))
        return
    print(json.dumps(preset(args.name), indent=2, sort_keys=True))


if __name__ == "__main__":
    main()
