"""Offline benchmark evaluation harness.

Behavioral counterpart of the reference's `evaluation/` directory (offline
eval of saved checkpoints on math benchmarks, backed by the same
latex2sympy-class answer grading the reward path uses): load a checkpoint
into the in-process generation engine, sample k completions per problem
with the benchmark's template, grade with the math verifier, and report
pass@1 / pass@k / majority-vote accuracy.

It is the `eval_cmd` target the AutomaticEvaluator sidecar
(utils/auto_eval.py) is designed to spawn per checkpoint — the last stdout
line is one JSON metrics object.

Usage:
    python -m areal_tpu.evaluation.run_eval --ckpt <hf-dir> \
        --dataset <gsm8k|path.jsonl> [--split test] [--k 1] \
        [--max-new-tokens 512] [--temperature 0.6] [--limit 200]
"""

import argparse
import collections
import json
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from areal_tpu.utils import logging

logger = logging.getLogger("evaluation")


def _load_problems(
    dataset: str, dataset_type: str, split: str, limit: Optional[int]
) -> List[Dict]:
    from areal_tpu.dataset import get_custom_dataset

    ds = get_custom_dataset(
        path=dataset,
        type=dataset_type or ("gsm8k" if "gsm8k" in dataset else ""),
        split=split,
    )
    problems = list(ds)[: limit or None]
    if not problems:
        raise ValueError(f"no problems in {dataset}:{split}")
    return problems


def _messages_of(prob: Dict) -> List[Dict]:
    if "messages" in prob:
        m = prob["messages"]
        return m if isinstance(m, list) else [{"role": "user", "content": m}]
    return [{"role": "user", "content": prob["question"]}]


def _build_engine(ckpt: str, n_slots: int, max_seq_len: int, seed: int):
    from transformers import AutoTokenizer

    from areal_tpu.gen.engine import GenEngine
    from areal_tpu.models.model_config import TransformerConfig

    tokenizer = AutoTokenizer.from_pretrained(ckpt)
    cfg = TransformerConfig.from_hf(ckpt)
    engine = GenEngine(
        cfg.replace(dtype="bfloat16"),
        model_path=ckpt,
        n_slots=n_slots,
        max_seq_len=max_seq_len,
        seed=seed,
    )
    return engine, tokenizer


def _eval_problem_set(
    engine,
    tokenizer,
    problems: List[Dict],
    k: int,
    max_new_tokens: int,
    temperature: Optional[float],
    top_p: float,
    max_seq_len: int,
) -> Dict:
    from areal_tpu.gen.engine import GenRequest
    from areal_tpu.reward.math_parser import extract_answer, math_equal

    t0 = time.time()
    reqs, meta = [], []
    for i, prob in enumerate(problems):
        ids = tokenizer.apply_chat_template(
            _messages_of(prob), add_generation_prompt=True, tokenize=True
        )
        ids = ids[-(max_seq_len - max_new_tokens):]
        for s in range(k):
            reqs.append(
                GenRequest(
                    rid=f"{i}/{s}",
                    input_ids=list(ids),
                    max_new_tokens=max_new_tokens,
                    # explicit --temperature always wins; the default is
                    # greedy pass@1 / sampled pass@k
                    temperature=(
                        temperature
                        if temperature is not None
                        else (0.0 if k == 1 else 0.6)
                    ),
                    top_p=top_p,
                    stop_token_ids=(
                        [tokenizer.eos_token_id]
                        if tokenizer.eos_token_id is not None
                        else []
                    ),
                )
            )
            meta.append(i)
    engine.generate_blocking(reqs)

    per_problem: Dict[int, List[Optional[str]]] = collections.defaultdict(list)
    for req, i in zip(reqs, meta):
        text = tokenizer.decode(req.output_tokens)
        per_problem[i].append(extract_answer(text))

    pass1 = passk = maj = 0
    for i, prob in enumerate(problems):
        gold = str(prob["answer"])
        preds = per_problem[i]
        correct = [
            p is not None and math_equal(p, gold) for p in preds
        ]
        pass1 += bool(correct and correct[0])
        passk += any(correct)
        counted = collections.Counter(p for p in preds if p is not None)
        if counted:
            top_pred = counted.most_common(1)[0][0]
            maj += bool(math_equal(top_pred, gold))
    n = len(problems)
    return {
        "n_problems": n,
        "k": k,
        "pass@1": round(pass1 / n, 4),
        f"pass@{k}": round(passk / n, 4),
        "majority": round(maj / n, 4),
        "wall_s": round(time.time() - t0, 1),
        "gen_tokens": int(sum(len(r.output_tokens) for r in reqs)),
    }


def evaluate_checkpoint(
    ckpt: str,
    dataset: str,
    dataset_type: str = "",
    split: str = "test",
    k: int = 1,
    max_new_tokens: int = 512,
    temperature: Optional[float] = None,  # None: greedy at k=1, 0.6 at k>1
    top_p: float = 0.95,
    limit: Optional[int] = None,
    n_slots: int = 16,
    max_seq_len: int = 2048,
    seed: int = 0,
) -> Dict:
    """Legacy single-dataset entry (gsm8k / jsonl registry datasets)."""
    if max_new_tokens >= max_seq_len:
        raise ValueError(
            f"max_new_tokens ({max_new_tokens}) must be < max_seq_len "
            f"({max_seq_len}) to leave room for the prompt"
        )
    engine, tokenizer = _build_engine(ckpt, n_slots, max_seq_len, seed)
    problems = _load_problems(dataset, dataset_type, split, limit)
    logger.info(f"evaluating {ckpt} on {len(problems)} problems, k={k}")
    result = _eval_problem_set(
        engine, tokenizer, problems, k, max_new_tokens, temperature, top_p,
        max_seq_len,
    )
    return {"ckpt": ckpt, "dataset": dataset, **result}


def evaluate_benchmark_suite(
    ckpt: str,
    benchmarks: List[str],
    data_root: Optional[str] = None,
    k: int = 1,
    max_new_tokens: int = 512,
    temperature: Optional[float] = None,
    top_p: float = 0.95,
    limit: Optional[int] = None,
    n_slots: int = 16,
    max_seq_len: int = 2048,
    seed: int = 0,
) -> Dict:
    """One command, many benchmarks (VERDICT r3 missing #4: the reference's
    AIME/AMC/MATH suite, evaluation/eval_and_aggregate.py): the checkpoint
    loads ONCE and every benchmark runs through the same engine; the
    aggregate averages pass@1 / majority across benchmarks."""
    from areal_tpu.evaluation.benchmarks import load_benchmark

    if not benchmarks:
        raise ValueError("evaluate_benchmark_suite needs >= 1 benchmark name")
    if max_new_tokens >= max_seq_len:
        raise ValueError("max_new_tokens must be < max_seq_len")
    engine, tokenizer = _build_engine(ckpt, n_slots, max_seq_len, seed)
    per_bench: Dict[str, Dict] = {}
    for name in benchmarks:
        problems = load_benchmark(name, data_root=data_root, limit=limit)
        logger.info(f"benchmark {name}: {len(problems)} problems, k={k}")
        per_bench[name] = _eval_problem_set(
            engine, tokenizer, problems, k, max_new_tokens, temperature,
            top_p, max_seq_len,
        )
    n_b = len(per_bench)
    return {
        "ckpt": ckpt,
        "benchmarks": per_bench,
        "avg_pass@1": round(
            sum(r["pass@1"] for r in per_bench.values()) / n_b, 4
        ),
        "avg_majority": round(
            sum(r["majority"] for r in per_bench.values()) / n_b, 4
        ),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt", required=True)
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--dataset", default=None,
                     help="registry dataset (gsm8k / path.jsonl)")
    src.add_argument("--benchmark", default=None,
                     help="comma list: aime24,aime25,amc23,math_500,"
                          "gpqa_diamond")
    p.add_argument("--data-root", default=None,
                   help="benchmark data root (default: AREAL_EVAL_DATA or "
                        "<repo>/evaluation/data)")
    p.add_argument("--type", dest="dataset_type", default="",
                   help="dataset registry type (default: inferred from path)")
    p.add_argument("--split", default="test")
    p.add_argument("--k", type=int, default=1)
    p.add_argument("--max-new-tokens", type=int, default=512)
    p.add_argument("--temperature", type=float, default=None,
                   help="default: greedy when k=1, 0.6 when k>1")
    p.add_argument("--limit", type=int, default=None)
    p.add_argument("--max-seq-len", type=int, default=2048)
    p.add_argument("--n-slots", type=int, default=16)
    args = p.parse_args()
    common = dict(
        k=args.k,
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature,
        limit=args.limit,
        n_slots=args.n_slots,
        max_seq_len=args.max_seq_len,
    )
    if args.benchmark:
        result = evaluate_benchmark_suite(
            ckpt=args.ckpt,
            benchmarks=[b.strip() for b in args.benchmark.split(",") if b.strip()],
            data_root=args.data_root,
            **common,
        )
    else:
        result = evaluate_checkpoint(
            ckpt=args.ckpt,
            dataset=args.dataset,
            dataset_type=args.dataset_type,
            split=args.split,
            **common,
        )
    logger.info(f"eval result: {result}")
    print(json.dumps(result))  # last line: the AutomaticEvaluator contract


if __name__ == "__main__":
    main()
