"""C9 fixture: the trace-consumer side for the metric/event fixtures —
consumes exactly `ev_done` via both a tuple constant and a compare."""

_EVENTS = ("ev_done",)


def consume(e):
    name = e.get("event")
    if e.get("event") == "ev_done":
        return True
    return name in _EVENTS
