"""Rollout workflow interface (reference: areal/api/workflow_api.py:11)."""

import abc
from typing import TYPE_CHECKING, Any, Dict, Optional

if TYPE_CHECKING:
    from areal_tpu.api.engine import InferenceEngine


class RolloutWorkflow(abc.ABC):
    @abc.abstractmethod
    async def arun_episode(
        self, engine: "InferenceEngine", data: Dict[str, Any]
    ) -> Optional[Dict[str, Any]]:
        """Run one episode; return a padded tensor dict (see
        areal_tpu.utils.data.pad_sequences_to_tensors) or None to reject.

        May issue several `engine.agenerate` calls concurrently (e.g. GRPO
        groups, multi-turn conversations, agentic tool loops).
        """
