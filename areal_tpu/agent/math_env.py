"""Math verification environment.

Capability counterpart of the reference's single-step math env
(realhf/impl/agent/math_code_single_step_env.py): the one tool,
`verify_answer`, checks a candidate solution against the episode's ground
truth with the in-repo math verifier (reward/math_parser.py) and ends the
episode.  Verification runs in the shared reward process pool so sympy
hangs cannot block the rollout event loop.
"""

from typing import Any, Dict, List, Tuple

from areal_tpu.api.env import Environment
from areal_tpu.api.reward import AsyncRewardWrapper
from areal_tpu.reward.math_parser import math_verify_reward


class MathVerifyEnv(Environment):
    def __init__(self, answer: str):
        self.answer = str(answer)
        self._verify = AsyncRewardWrapper(math_verify_reward)

    def list_tools(self) -> List[Dict[str, Any]]:
        return [
            {
                "name": "verify_answer",
                "description": "Check a final answer against the ground truth.",
                "parameters": {
                    "type": "object",
                    "properties": {"completion": {"type": "string"}},
                    "required": ["completion"],
                },
            }
        ]

    async def aexecute_tool(
        self, tool_name: str, arguments: Dict[str, Any]
    ) -> Tuple[Any, float, bool]:
        if tool_name != "verify_answer":
            raise ValueError(f"unknown tool {tool_name!r}")
        reward = await self._verify(
            "", arguments["completion"], [], [], answer=self.answer
        )
        feedback = "correct" if reward > 0 else "incorrect"
        return feedback, float(reward), reward > 0
