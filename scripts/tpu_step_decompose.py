"""Decompose 1.5B train-step time: fwd / fwd+bwd / full step / optimizer.

Identifies where the fixed per-step overhead lives (scatter-add embedding
grads? optimizer? loss head?).
"""

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, "/root/repo")

from areal_tpu.models import forward_lm, init_params
from areal_tpu.models.model_config import qwen25_1p5b
from areal_tpu.ops.functional import grpo_loss_fn


def timeit(fn, *args, n=3, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    cfg = qwen25_1p5b().replace(
        dtype="bfloat16", param_dtype="bfloat16", remat=True
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    R, L = 8, 2048
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (R, L)).astype(np.int32)
    pos = np.broadcast_to(np.arange(L, dtype=np.int32), (R, L)).copy()
    seg = np.zeros((R, L), np.int32)
    batch = {
        "input_ids": jnp.asarray(ids),
        "positions": jnp.asarray(pos),
        "segment_ids": jnp.asarray(seg),
        "loss_mask": jnp.ones((R, L), jnp.float32),
        "logprobs": jnp.asarray(rng.normal(-1, 0.1, (R, L)), jnp.float32),
        "advantages": jnp.asarray(rng.normal(size=(R, L)), jnp.float32),
    }
    batch["prox_logp"] = batch["logprobs"]
    tokens = R * L

    def loss(p, b):
        out = forward_lm(p, cfg, b["input_ids"], b["positions"], b["segment_ids"])
        l, _ = grpo_loss_fn(out, b, eps_clip=0.2)
        return l / tokens

    fwd = jax.jit(loss)
    t = timeit(fwd, params, batch)
    print(f"fwd only:          {t * 1e3:7.0f} ms  {tokens / t:8,.0f} tok/s")

    vg = jax.jit(lambda p, b: jax.grad(loss)(p, b))
    t = timeit(vg, params, batch)
    print(f"fwd+bwd:           {t * 1e3:7.0f} ms  {tokens / t:8,.0f} tok/s")

    opt = optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(1e-5, b1=0.9, b2=0.95, eps=1e-8, weight_decay=0.01),
    )
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def full(p, s, b):
        g = jax.grad(loss)(p, b)
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    for _ in range(2):
        params, opt_state = full(params, opt_state, batch)
    jax.block_until_ready(params)
    t0 = time.perf_counter()
    for _ in range(3):
        params, opt_state = full(params, opt_state, batch)
    jax.block_until_ready(params)
    t = (time.perf_counter() - t0) / 3
    print(f"fwd+bwd+opt:       {t * 1e3:7.0f} ms  {tokens / t:8,.0f} tok/s")

    # no-remat variant
    cfg2 = cfg.replace(remat=False)

    def loss2(p, b):
        out = forward_lm(p, cfg2, b["input_ids"], b["positions"], b["segment_ids"])
        l, _ = grpo_loss_fn(out, b, eps_clip=0.2)
        return l / tokens

    try:
        vg2 = jax.jit(lambda p, b: jax.grad(loss2)(p, b))
        t = timeit(vg2, params, batch)
        print(f"fwd+bwd noremat:   {t * 1e3:7.0f} ms  {tokens / t:8,.0f} tok/s")
    except Exception as e:
        print(f"noremat: FAIL {'OOM' if 'RESOURCE_EXHAUSTED' in str(e) else str(e)[:120]}")

    # head-only cost: logits loss on detached hidden
    def loss_head_only(p, b):
        out = forward_lm(p, cfg, b["input_ids"], b["positions"], b["segment_ids"])
        out = jax.tree_util.tree_map(jax.lax.stop_gradient, out)
        l, _ = grpo_loss_fn(out, b, eps_clip=0.2)
        return l / tokens

    vg3 = jax.jit(lambda p, b: jax.grad(loss_head_only)(p, b))
    t = timeit(vg3, params, batch)
    print(f"fwd+bwd(head-only):{t * 1e3:7.0f} ms  {tokens / t:8,.0f} tok/s")


if __name__ == "__main__":
    main()
