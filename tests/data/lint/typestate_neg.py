"""C7 negative fixture: lifecycle-correct transitions that must stay
clean — full co-writes, helper delegation, the tuple-loop migration
idiom, re-acquire between frees, and version-checked retained reuse."""


class Pool:
    _SLOT_TYPESTATE = {
        "owner": "slot_req",
        "acquire_writes": ["lengths", "temperature"],
        "release_writes": ["_reserved_until"],
        "version_field": "kv_version",
        "retained_field": "retained_len",
    }

    def __init__(self, n):
        self.slot_req = [None] * n
        self.lengths = [0] * n
        self.temperature = [1.0] * n
        self.retained_len = [0] * n
        self.kv_version = [0] * n
        self._reserved_until = [0.0] * n
        self.version = 0

    def acquire(self, s, req):
        self.slot_req[s] = req
        self.lengths[s] = len(req)
        self.temperature[s] = 1.0

    def acquire_via_helper(self, s, req):
        self.slot_req[s] = req
        self.lengths[s] = len(req)
        self._warm(s)

    def _warm(self, s):
        self.temperature[s] = 0.7

    def release(self, s):
        self.slot_req[s] = None
        self.retained_len[s] = self.lengths[s]
        # reserving a freed slot is release-side bookkeeping
        self._reserved_until[s] = 1.0

    def free_then_readmit(self, s, req):
        self.slot_req[s] = None
        self.retained_len[s] = self.lengths[s]
        self.slot_req[s] = req  # re-acquire: not a double free
        self.lengths[s] = len(req)
        self.temperature[s] = 1.0

    def migrate(self, s, dst, req):
        self.slot_req[dst] = req
        self.slot_req[s] = None
        for arr in (self.lengths, self.temperature):
            arr[dst] = arr[s]
        self.retained_len[dst] = 0
        self._reserved_until[dst] = 0.0
        self.kv_version[dst] = self.version
        self.retained_len[s] = self.lengths[s]

    def reuse_versioned(self, s, req):
        if (
            self.retained_len[s] > 4
            and self.kv_version[s] == self.version
        ):
            self.slot_req[s] = req
            self.lengths[s] = self.retained_len[s]
            self.temperature[s] = 1.0
