"""VLM serving: image-conditioned prefill + mrope-offset decode in the
generation engine, and the pixel wire format through the HTTP server
(reference capability: SGLang/vLLM multimodal serving for
workflow/vision_rlvr.py)."""

import numpy as np
import pytest

from areal_tpu.gen.engine import GenEngine, GenRequest
from areal_tpu.models.model_config import VisionConfig, tiny_config

IMG_TOK = 60

VCFG = VisionConfig(
    patch_size=2,
    temporal_patch_size=1,
    in_channels=3,
    hidden_size=16,
    intermediate_size=32,
    num_layers=1,
    num_heads=2,
    spatial_merge_size=2,
    out_hidden_size=48,
)


def _vlm_cfg():
    return tiny_config(
        vocab_size=64,
        hidden_size=48,
        num_heads=4,
        num_kv_heads=2,
        qkv_bias=True,
        dtype="float32",
        param_dtype="float32",
        hf_architecture="Qwen2VLForConditionalGeneration",
    ).replace(vision=VCFG, image_token_id=IMG_TOK, mrope_section=(2, 3, 3))


def _vlm_request(rng, rid="v0", max_new=8, temperature=0.0):
    # prompt: 2 text tokens, one 4x4-patch image (4 merged placeholders),
    # 2 text tokens
    ids = [5, 6] + [IMG_TOK] * 4 + [7, 8]
    return GenRequest(
        rid=rid,
        input_ids=ids,
        max_new_tokens=max_new,
        temperature=temperature,
        pixel_values=rng.normal(size=(16, VCFG.patch_dim)).astype(np.float32),
        image_grid_thw=np.array([[1, 4, 4]]),
    )


def test_vlm_generation_end_to_end():
    rng = np.random.default_rng(0)
    engine = GenEngine(_vlm_cfg(), n_slots=2, max_seq_len=64, seed=0)
    assert engine._vlm
    reqs = [_vlm_request(rng, f"v{i}") for i in range(2)]
    engine.generate_blocking(reqs)
    for r in reqs:
        assert r.stop_reason in ("stop", "length")
        assert len(r.output_tokens) > 0
        assert len(r.output_logprobs) == len(r.output_tokens)

    # rope positions trail cache lengths on VLM slots: image run of 4
    # placeholders compressed to extent max(1,2,2)=2 -> offset 8-2-... the
    # engine freed the slots, but determinism is checked below instead


def test_vlm_pixels_change_output_text_does_not_leak():
    """Same prompt, different pixels -> different greedy continuations;
    same pixels -> identical (deterministic greedy)."""
    cfg = _vlm_cfg()
    rng = np.random.default_rng(1)
    pix = rng.normal(size=(16, VCFG.patch_dim)).astype(np.float32)

    def run(pixels):
        engine = GenEngine(cfg, n_slots=1, max_seq_len=64, seed=0)
        req = _vlm_request(rng, max_new=6)
        req.pixel_values = pixels
        engine.generate_blocking([req])
        return req.output_tokens

    out1 = run(pix)
    out2 = run(pix)
    assert out1 == out2, "greedy VLM decode must be deterministic"
    out3 = run(pix + 1.0)
    assert out3 != out1, "pixels must condition generation"


def test_text_request_on_vlm_engine_still_works():
    rng = np.random.default_rng(2)
    engine = GenEngine(_vlm_cfg(), n_slots=2, max_seq_len=64, seed=0)
    text_req = GenRequest(rid="t", input_ids=[3, 4, 5], max_new_tokens=4,
                          temperature=0.0)
    vlm_req = _vlm_request(rng)
    engine.generate_blocking([text_req, vlm_req])
    assert text_req.output_tokens and vlm_req.output_tokens


def test_pixels_on_text_only_engine_rejected_terminally():
    """Config mismatch must TERMINATE the request ("length"), not "abort" —
    abort would put the client interruption loop into infinite resubmit."""
    rng = np.random.default_rng(3)
    engine = GenEngine(
        tiny_config(vocab_size=64, qkv_bias=True), n_slots=1, max_seq_len=64
    )
    req = _vlm_request(rng)
    engine.generate_blocking([req])
    assert req.stop_reason == "length"
    assert req.output_tokens == []


def test_malformed_vlm_requests_rejected():
    rng = np.random.default_rng(5)
    engine = GenEngine(_vlm_cfg(), n_slots=2, max_seq_len=64, seed=0)
    # grid smaller than the merge size: would loop forever unguarded
    bad_grid = _vlm_request(rng)
    bad_grid.image_grid_thw = np.array([[1, 1, 1]])
    bad_grid.pixel_values = rng.normal(size=(1, VCFG.patch_dim)).astype(np.float32)
    # patch count inconsistent with the grid
    bad_count = _vlm_request(rng)
    bad_count.pixel_values = bad_count.pixel_values[:8]
    # placeholder count inconsistent with the grid
    bad_ph = _vlm_request(rng)
    bad_ph.input_ids = [5, 6, IMG_TOK, 7]  # 1 placeholder, grid implies 4
    engine.generate_blocking([bad_grid, bad_count, bad_ph])
    for r in (bad_grid, bad_count, bad_ph):
        assert r.stop_reason == "length" and r.output_tokens == []
    # the engine still serves good requests afterwards
    ok = _vlm_request(rng, rid="ok")
    engine.generate_blocking([ok])
    assert ok.output_tokens and ok.stop_reason in ("stop", "length")


def test_vlm_checkpoint_roundtrip(tmp_path):
    """visual.* weights + vision config survive save -> load, so a trained
    tower actually reaches the server (weights AND config.json)."""
    import jax

    from areal_tpu.models import init_params
    from areal_tpu.models.hf import load_hf_params, save_hf_checkpoint
    from areal_tpu.models.model_config import TransformerConfig
    from areal_tpu.models.vision import init_vision_params

    cfg = _vlm_cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    params["vision"] = init_vision_params(VCFG, jax.random.PRNGKey(1))
    out = tmp_path / "ckpt"
    save_hf_checkpoint(params, cfg, str(out), save_dtype="float32")

    cfg2 = TransformerConfig.from_hf(str(out))
    assert cfg2.vision is not None
    assert cfg2.vision.num_layers == VCFG.num_layers
    assert cfg2.image_token_id == IMG_TOK
    assert cfg2.mrope_section == (2, 3, 3)

    loaded, _ = load_hf_params(str(out), cfg2, dtype="float32")
    assert "vision" in loaded
    np.testing.assert_allclose(
        np.asarray(loaded["vision"]["patch_embed"]),
        np.asarray(params["vision"]["patch_embed"]),
        rtol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(loaded["vision"]["layers"]["wqkv"]),
        np.asarray(params["vision"]["layers"]["wqkv"]),
        rtol=1e-6,
    )


def test_vlm_http_server_roundtrip():
    """Pixel arrays survive the b64 wire format through the real server."""
    import base64
    import json
    import urllib.request

    import threading

    from areal_tpu.gen.server import GenServer
    from aiohttp import web
    import asyncio

    rng = np.random.default_rng(4)
    engine = GenEngine(_vlm_cfg(), n_slots=2, max_seq_len=64, seed=0)
    server = GenServer(engine)
    server.start()

    started = threading.Event()
    holder = {}

    def _run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def _serve():
            runner = web.AppRunner(server.app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["port"] = runner.addresses[0][1]
            holder["runner"] = runner
            started.set()

        loop.run_until_complete(_serve())
        loop.run_forever()

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    assert started.wait(10)

    pv = rng.normal(size=(16, VCFG.patch_dim)).astype(np.float32)
    payload = {
        "rid": "wire",
        "input_ids": [5, 6] + [IMG_TOK] * 4 + [7, 8],
        "sampling_params": {"max_new_tokens": 4, "temperature": 0.0},
        "pixel_values_b64": base64.b64encode(pv.tobytes()).decode(),
        "pixel_values_shape": list(pv.shape),
        "image_grid_thw": [[1, 4, 4]],
    }
    r = urllib.request.Request(
        f"http://127.0.0.1:{holder['port']}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(r, timeout=120) as resp:
        out = json.loads(resp.read())
    assert out["output_tokens"] and out["stop_reason"] in ("stop", "length")
    server.shutdown.set()
