"""CPU smoke for the primary-metric instrument (VERDICT r6 #7): the
scripts/bench_e2e_grpo.py subprocess must produce a well-formed result
JSON on the REAL fleet slice (--transport remote: GenServer over HTTP +
RemoteJaxEngine + transfer-mode publish) in BOTH publish modes, so the
bench cannot rot silently between on-chip runs.

Tiny model, 2 measured steps each — the full-size numbers live in
E2E_GRPO_BENCH_r*.json; this only proves the instrument still runs
end-to-end.  The abort-mode run doubles as the gsm8k-synth dataset path
(the satellite importer for dataset/gsm8k_synth.py), exercising the real
math reward through the rollout loop."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "scripts", "bench_e2e_grpo.py")

_COMMON = [
    "--model", "tiny",
    "--transport", "remote",
    "--modes", "async",
    "--steps", "2",
    "--warmup", "1",
    "--batch-size", "4",
    "--group-size", "2",
    "--n-slots", "8",
    "--max-seq-len", "256",
    "--max-new-tokens", "32",
]


def _run_bench(extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, BENCH] + _COMMON + extra,
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    # the result is the last stdout line that parses as a JSON object
    for line in reversed(proc.stdout.strip().split("\n")):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    pytest.fail(f"no JSON result line in stdout: {proc.stdout[-500:]}")


def test_remote_live_publish_smoke():
    out = _run_bench(["--publish-mode", "live",
                      "--prompt-len", "32"])
    assert out["transport"] == "remote" and out["publish_mode"] == "live"
    a = out["async"]
    assert a["steps"] == 2 and a["trajectories"] > 0
    assert a["trajs_per_sec_per_chip"] > 0
    # live commit: the pause window is a pointer swap, not a placement
    assert a["pause_window_s_mean"] < 1.0
    # group fan-out accounting rode along (group_size 2)
    assert out["shared_prefill"]["shared_tokens"] > 0


@pytest.mark.slow
def test_remote_interrupt_publish_smoke():
    """ISSUE 12 satellite: the remote/interrupt combination had never run
    in the suite (remote+live and remote+abort are covered below) — the
    `stale_from`-marked e2e BENCH fields kept being carried forward on
    that gap.  `interrupt` publishes over the HTTP fleet slice abort
    in-flight requests and clients resume with their accumulated tokens,
    so the bench must complete and report sane throughput/fan-out
    accounting under that storm."""
    out = _run_bench(["--publish-mode", "interrupt",
                      "--prompt-len", "32"])
    assert out["transport"] == "remote"
    assert out["publish_mode"] == "interrupt"
    a = out["async"]
    assert a["steps"] == 2 and a["trajectories"] > 0
    assert a["trajs_per_sec_per_chip"] > 0
    # group fan-out accounting rode along (group_size 2), and the
    # interrupt/resume churn keeps the token split self-consistent
    sp = out["shared_prefill"]
    assert sp["shared_tokens"] > 0
    assert sp["suffix_tokens"] >= 0 and sp["prefill_tokens"] > 0


@pytest.fixture(scope="module")
def abort_run(tmp_path_factory):
    """One abort-mode bench run shared by the smoke + lifecycle tests
    (the subprocess is the expensive part; --telemetry-dir rides along)."""
    tdir = tmp_path_factory.mktemp("telemetry")
    out = _run_bench(["--publish-mode", "abort",
                      "--dataset", "gsm8k-synth",
                      "--telemetry-dir", str(tdir)])
    return out, tdir


@pytest.mark.slow
def test_remote_chaos_smoke():
    """ISSUE 11: the chaos instrument itself — a seeded FaultProxy between
    client and server — must complete the run and report the replayable
    injected-fault log plus the trajectory-loss fraction.  Slow-marked:
    the fast kill-one-of-two chaos acceptance lives in test_chaos_e2e.py;
    this proves the bench-side harness (CI chaos-smoke runs it too)."""
    out = _run_bench(["--publish-mode", "live",
                      "--prompt-len", "32",
                      "--chaos", "--chaos-seed", "5", "--chaos-rate", "0.3"])
    chaos = out["chaos"]
    assert chaos["seed"] == 5
    assert chaos["plan_size"] > 0
    assert chaos["injected"], "rate=0.3 must inject on an exercised call"
    # every injected record is (endpoint, call_index, kind)
    assert all(ep.startswith("/") and isinstance(i, int) and kind
               for ep, i, kind in chaos["injected"])
    assert 0.0 <= chaos["trajectory_loss_fraction"] <= 1.0
    # goodput under fire: the run still made progress
    assert out["async"]["trajectories"] > 0
    assert out["async"]["trajs_per_sec_per_chip"] > 0


def test_remote_abort_publish_gsm8k_synth_smoke(abort_run):
    out, _ = abort_run
    assert out["publish_mode"] == "abort"
    assert out["dataset"] == "gsm8k-synth"
    a = out["async"]
    assert a["steps"] == 2 and a["trajectories"] > 0
    # the real math reward ran (a from-scratch tiny model scores ~0, but
    # the field must exist and be a finite fraction)
    assert 0.0 <= a["reward_mean"] <= 1.0


def test_trajectory_lifecycle_reconstructs_from_jsonl(abort_run):
    """ISSUE 10 acceptance: one full trajectory lifecycle — submit ->
    admission -> prefill -> decode -> (interrupt -> resume at the abort
    publish) -> reward -> trainer consumption with staleness — must be
    reconstructable purely from the JSONL event log."""
    out, tdir = abort_run
    tele = out["telemetry"]
    assert tele["n_events"] > 0
    events_path = tele["events_jsonl"]
    assert os.path.exists(events_path)
    with open(events_path) as f:
        evs = [json.loads(line) for line in f]
    assert len(evs) == tele["n_events"]

    by_trace = {}
    for e in evs:
        if "trace_id" in e:
            by_trace.setdefault(e["trace_id"], []).append(e)
    consumed = {e["trace_key"]: e for e in evs
                if e["event"] == "train_consume"
                and e.get("trace_key") is not None}

    # at least one trajectory shows the FULL chain, in timestamp order,
    # ending in a trainer consumption joined via trace_key
    full = []
    for tid, tes in by_trace.items():
        names = [e["event"] for e in tes]
        if not {"rollout_submit", "admission", "prefill", "gen_done",
                "reward"} <= set(names):
            continue
        order = [names.index(n) for n in
                 ("rollout_submit", "admission", "prefill", "gen_done",
                  "reward")]
        assert order == sorted(order), (tid, names)
        tk = tes[0]["trace_key"]
        if tk in consumed:
            full.append((tid, tes, consumed[tk]))
    assert full, "no trajectory with a complete, trainer-joined lifecycle"
    tid, tes, tc = full[0]
    # prefill token split is self-consistent
    pf = next(e for e in tes if e["event"] == "prefill")
    assert pf["cold_tokens"] + pf["inherited_tokens"] == pf["total_tokens"]
    # consumption evidence carries the staleness measurement
    assert tc["staleness"] >= 0
    assert tc["consumed_version"] >= tc["behavior_version"]
    # decode made progress on some traced request (chunk events carry the
    # per-tier active trace-id lists)
    chunks = [e for e in evs if e["event"] == "decode_chunk"]
    traced_in_chunks = {t for e in chunks for t in e.get("trace_ids", ())}
    assert traced_in_chunks & set(by_trace)

    # abort-mode publishes interrupt in-flight requests; every interrupted
    # trace must show a later resume or re-admission (the pause/interrupt
    # evidence ROADMAP item 4 asks for)
    interrupted = {t: es for t, es in by_trace.items()
                   if any(e["event"] == "interrupt" for e in es)}
    assert interrupted, "abort publish produced no interrupt spans"
    for t, es in interrupted.items():
        it = min(e["ts"] for e in es if e["event"] == "interrupt")
        assert any(e["ts"] >= it and e["event"] in ("resume", "admission")
                   for e in es), t

    # sidecar artifacts: Chrome trace + metrics snapshot with the two
    # evidence histograms populated
    trace = json.load(open(tele["chrome_trace"]))
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert "X" in phases and "i" in phases
    metrics = json.load(open(tele["metrics_snapshot"]))
    assert metrics["gen"]["areal_gen_pause_window_seconds_count"]["_"] >= 1
    assert (metrics["train"]
            ["areal_train_staleness_at_consumption_count"]["_"] >= 1)


def test_slo_report_reconstructs_recorded_run(abort_run):
    """ISSUE 14 acceptance: from one recorded e2e run the analyzer must
    produce an SLO report that is complete (zero dropped events, no
    orphan spans) and satisfies the accounting identity — the per-stage
    sums agree with each trajectory's client-measured end-to-end — plus
    the satellite latency percentiles in the bench JSON itself."""
    from areal_tpu.obs.slo import build_report, render_markdown

    out, _ = abort_run
    report = build_report(out["telemetry"]["events_jsonl"], run_id="smoke")
    comp = report["completeness"]
    assert comp["complete"], comp
    assert comp["dropped_events"] == 0
    acct = report["accounting"]
    assert acct["ok"], acct
    assert acct["checked"] > 0
    assert report["trajectories"]["closed"] > 0
    assert report["e2e_s"]["count"] > 0
    # real server spans in the log -> a true decomposition, not opaque
    assert "decode" in report["stages"]
    assert "admission_wait" in report["stages"]
    # abort publishes leave interrupt windows; staleness evidence joined
    assert report["staleness"] is not None
    md = render_markdown(report)
    assert "complete: **True**" in md and "stage:decode" in md

    # satellite: the bench JSON now carries client-side p50/p99 latency
    lat = out["async"]["latency"]
    assert lat["n"] > 0
    assert lat["e2e_s"]["count"] == lat["n"]
    assert 0 < lat["e2e_s"]["p50"] <= lat["e2e_s"]["p99"]
    assert lat["ttft_s"] is not None
    assert lat["ttft_s"]["p50"] <= lat["e2e_s"]["p99"]
