"""VLM groundwork tests: vision workflow plumbing, mrope position ids,
CLEVR dataset + counting reward (VERDICT round-1 next-step #10b)."""

import asyncio
import json

import numpy as np

from areal_tpu.api.config import GenerationHyperparameters
from areal_tpu.dataset import get_custom_dataset
from areal_tpu.dataset.clevr import clevr_count_reward
from areal_tpu.models.vision import mrope_position_ids
from areal_tpu.workflow.vision_rlvr import VisionRLVRWorkflow


class _FakeResp:
    def __init__(self, n_in, n_out):
        self.input_tokens = list(range(n_in))
        self.output_tokens = [7] * n_out
        self.output_logprobs = [-0.5] * n_out
        self.output_versions = [3] * n_out
        self.input_len = n_in
        self.output_len = n_out
        self.stop_reason = "stop"


class _FakeEngine:
    def __init__(self):
        self.requests = []

    async def agenerate(self, req):
        self.requests.append(req)
        return _FakeResp(len(req.input_ids), 4)


class _FakeProcessor:
    def __call__(self, images, text, padding=False):
        # 2 placeholder tokens per image + 3 text tokens
        ids = [101] * (2 * len(images)) + [5, 6, 9]
        return {"input_ids": [ids]}


def _reward_one(prompt, completions, prompt_ids, completion_ids, **kw):
    return 1.0


def test_vision_workflow_plumbs_images():
    from areal_tpu.api.reward import prewarm_reward_pool

    prewarm_reward_pool()
    wf = VisionRLVRWorkflow(
        reward_fn=_reward_one,
        gconfig=GenerationHyperparameters(n_samples=2, max_new_tokens=4),
        processor=_FakeProcessor(),
    )
    engine = _FakeEngine()
    img = np.zeros((4, 4, 3), np.uint8)
    data = {"images": [img], "messages": "count the objects", "answer": "0"}
    batch = asyncio.run(wf.arun_episode(engine, data))
    assert batch["input_ids"].shape[0] == 2  # n_samples rows
    assert all(r.image_data is not None for r in engine.requests)
    assert batch["rewards"].tolist() == [1.0, 1.0]
    # prompt tokens masked, completion unmasked
    assert batch["loss_mask"][0][:5].sum() == 0


def test_mrope_position_ids():
    IMG = 151655
    # text text [2x2 merged image = 4 tokens] text; the serving-path
    # implementation (models/vision.py) takes the grid in PATCHES, so a
    # (1, 4, 4) patch grid at merge size 2 yields the 2x2 placeholder run
    ids = [1, 2] + [IMG] * 4 + [3]
    pos = mrope_position_ids(
        np.asarray(ids), np.asarray([[1, 4, 4]]), IMG, spatial_merge_size=2
    )
    # text advances all channels together
    np.testing.assert_array_equal(pos[:, 0], [0, 0, 0])
    np.testing.assert_array_equal(pos[:, 1], [1, 1, 1])
    # image grid coords offset from pos=2: t=2; h in {2,3}; w in {2,3}
    np.testing.assert_array_equal(pos[0, 2:6], [2, 2, 2, 2])
    np.testing.assert_array_equal(pos[1, 2:6], [2, 2, 3, 3])
    np.testing.assert_array_equal(pos[2, 2:6], [2, 3, 2, 3])
    # text resumes after max extent (2 + 2 = 4)
    np.testing.assert_array_equal(pos[:, 6], [4, 4, 4])


def test_clevr_dataset_and_reward(tmp_path):
    rows = [
        {"image": "img0.png", "messages": "how many cubes?", "answer": 3},
        {"images": ["a.png", "b.png"], "messages": "count", "answer": 7,
         "query_id": "q7"},
    ]
    mf = tmp_path / "train.jsonl"
    mf.write_text("\n".join(json.dumps(r) for r in rows))
    ds = get_custom_dataset(path=str(tmp_path), type="clevr", split="train")
    assert len(ds) == 2
    assert ds[0]["answer"] == "3"
    assert ds[0]["images"][0].endswith("img0.png")
    assert ds[1]["query_id"] == "q7"

    assert clevr_count_reward("", "the answer is 3", [], [], answer="3") == 1.0
    assert clevr_count_reward("", "the answer is 4", [], [], answer="3") == 0.0
    assert clevr_count_reward("", "i see 3 things maybe", [], [], answer="3") == 0.0
