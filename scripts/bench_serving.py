"""Serving-side benchmark: decode throughput, prefill cost, KV-reuse gain.

VERDICT r3 next-step #1 (first half): the generation engine — the biggest
piece of new TPU-native machinery — gets measured on the real chip.
Prints ONE JSON line:

  {"decode": {"<n_slots>": {"tokens_per_sec": ..., "wall_s": ...}, ...},
   "prefill": {"bucket_<P>": {"tokens_per_sec": ..., "ms": ...}, ...},
   "multi_turn": {"reuse": {...}, "cold": {...}, "speedup": ...},
   "device_kind": ...}

Workloads (Qwen2.5-1.5B shapes, bf16, random weights — serving throughput
does not depend on weight values):
- decode: fill every slot, generate to a fixed budget, steady-state
  delivered tokens/sec vs slot count (the tokens/s-vs-n_slots curve of
  VERDICT weak #5);
- prefill: one bucketed admission per prompt-length bucket, tokens/sec
  through the prefill program;
- multi-turn: T-turn conversations where each turn extends the last
  transcript — KV prefix reuse vs cold engine (VERDICT #3's gain,
  quantified).

Match: the reference benchmarks its serving side through SGLang's
reported throughput (blog/AReaL_v0_3.md); this engine is ours, so it gets
its own figure.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.obs.trace import dist_summary  # noqa: E402


def serving_model_setup(model: str = "qwen25_1p5b"):
    """The canonical serving-bench model: Qwen2.5-1.5B shapes, bf16,
    random weights.  Shared with bench.py's quick probe so the headline
    serving numbers and SERVING_BENCH_r{N}.json can never desynchronise.
    `model="tiny"` is the CPU smoke mode: wall-clock is meaningless there,
    but the token-accounting signals (reused/shared fractions) are
    workload arithmetic and carry over exactly."""
    import jax

    from areal_tpu.models import init_params
    from areal_tpu.models.model_config import qwen25_1p5b, tiny_config

    if model == "tiny":
        cfg = tiny_config(vocab_size=512, qkv_bias=True,
                          hf_architecture="Qwen2ForCausalLM",
                          eos_token_id=None)
    else:
        cfg = qwen25_1p5b().replace(
            dtype="bfloat16", param_dtype="bfloat16", remat=False,
            eos_token_id=None,
        )
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def _reset_stats(eng):
    for k in eng.stats:
        eng.stats[k] = 0


def _engine(cfg, params, n_slots, max_seq_len, kv_reuse=True, decode_chunk=8,
            **kw):
    from areal_tpu.gen.engine import GenEngine

    return GenEngine(
        cfg, params=params, n_slots=n_slots, max_seq_len=max_seq_len,
        prompt_bucket=128, decode_chunk=decode_chunk, kv_reuse=kv_reuse,
        **kw,
    )


def bench_decode(cfg, params, slot_counts, max_seq_len=512, gen_tokens=128,
                 prompt_len=64, spec_decode=False, draft_len=0):
    """Steady-state decode tokens/sec with every slot busy."""
    from areal_tpu.gen.engine import GenRequest

    rng = np.random.default_rng(0)
    out = {}
    for n_slots in slot_counts:
        try:
            eng = _engine(cfg, params, n_slots, max_seq_len, kv_reuse=False,
                          spec_decode=spec_decode,
                          spec_draft_len=draft_len or None)
            # warmup: compile prefill + decode
            reqs = [
                GenRequest(rid=f"w{i}",
                           input_ids=rng.integers(0, cfg.vocab_size, prompt_len).tolist(),
                           max_new_tokens=8, temperature=1.0)
                for i in range(n_slots)
            ]
            eng.generate_blocking(reqs)
            _reset_stats(eng)  # warmup compiles must not skew counters
            # measured run: fixed budget per slot, no stop tokens
            reqs = [
                GenRequest(rid=f"m{i}",
                           input_ids=rng.integers(0, cfg.vocab_size, prompt_len).tolist(),
                           max_new_tokens=gen_tokens, temperature=1.0)
                for i in range(n_slots)
            ]
            for r in reqs:
                eng.submit(r)
            eng.step()  # admission (prefill) outside the decode timing
            t0 = time.perf_counter()
            delivered = 0
            while any(not r.stop_reason for r in reqs):
                delivered += eng.step()
            dt = time.perf_counter() - t0
            # per-request latency triple off the GenRequest perf_counter
            # stamps (submit -> first delivered token -> finish); the
            # admission step above sits inside TTFT, as it does for a
            # real client
            ttfts = [r.first_token_ts - r.submit_ts for r in reqs
                     if r.first_token_ts > 0.0]
            e2es = [r.finish_ts - r.submit_ts for r in reqs
                    if r.finish_ts > 0.0]
            itls = [
                (r.finish_ts - r.first_token_ts)
                / max(1, len(r.output_tokens) - 1)
                for r in reqs
                if r.finish_ts > 0.0 and r.first_token_ts > 0.0
                and len(r.output_tokens) > 1
            ]
            out[str(n_slots)] = {
                "tokens_per_sec": round(delivered / dt, 1),
                "wall_s": round(dt, 2),
                "latency": {
                    "ttft_s": dist_summary(ttfts),
                    "e2e_s": dist_summary(e2es),
                    "inter_token_s": dist_summary(itls),
                },
                "decode_calls": eng.stats["decode_calls"],
                # attended span / ceiling (ISSUE 5 window accounting):
                # decode reads this fraction of the configured cache width
                "decode_attended_fraction": round(
                    eng.decode_attended_fraction(), 4
                ),
                # speculative-decode accounting (ISSUE 12): all zero when
                # --spec-decode is off
                "verify_calls": eng.stats["verify_calls"],
                "spec_draft_tokens": eng.stats["spec_drafted"],
                "spec_accepted_tokens": eng.stats["spec_accepted"],
                "spec_acceptance_rate": round(
                    eng.stats["spec_accepted"]
                    / max(1, eng.stats["spec_drafted"]), 4
                ),
            }
            print(f"decode n_slots={n_slots}: {out[str(n_slots)]}",
                  file=sys.stderr, flush=True)
            del eng
        except Exception as e:  # noqa: BLE001 — record and continue the curve
            out[str(n_slots)] = {"error": str(e)[:200]}
            print(f"decode n_slots={n_slots} failed: {str(e)[:120]}",
                  file=sys.stderr, flush=True)
    return out


def bench_prefill(cfg, params, buckets=(128, 512, 1024), rows=8,
                  max_seq_len=2048):
    """Prefill throughput per prompt bucket: one bucketed admission of
    `rows` prompts, tokens/sec through the prefill program."""
    from areal_tpu.gen.engine import GenRequest

    rng = np.random.default_rng(1)
    eng = _engine(cfg, params, rows, max_seq_len, kv_reuse=False)
    out = {}
    for bucket in buckets:
        plen = bucket - 1  # stay inside the bucket
        for warm in (True, False):
            reqs = [
                GenRequest(rid=f"p{bucket}_{warm}_{i}",
                           input_ids=rng.integers(0, cfg.vocab_size, plen).tolist(),
                           max_new_tokens=1, temperature=1.0)
                for i in range(rows)
            ]
            for r in reqs:
                eng.submit(r)
            t0 = time.perf_counter()
            eng.step()
            dt = time.perf_counter() - t0
            while any(not r.stop_reason for r in reqs):
                eng.step()
        out[f"bucket_{bucket}"] = {
            "tokens_per_sec": round(rows * plen / dt, 1),
            "ms": round(dt * 1e3, 1),
        }
        print(f"prefill bucket={bucket}: {out[f'bucket_{bucket}']}",
              file=sys.stderr, flush=True)
    return out


def bench_multi_turn(cfg, params, n_convs=8, turns=4, turn_prompt=64,
                     turn_gen=32, max_seq_len=1024):
    """T-turn conversations: each turn replays the transcript + new user
    tokens.  Reuse engine vs cold engine, wall-clock + prefill-token
    accounting."""
    from areal_tpu.gen.engine import GenRequest

    out = {}
    for mode in ("reuse", "cold"):
        rng = np.random.default_rng(2)  # identical workload both modes
        eng = _engine(cfg, params, n_convs, max_seq_len,
                      kv_reuse=(mode == "reuse"))
        # compile EVERY program the timed loop will hit by replaying ALL
        # `turns` rounds of the real shapes: growing transcripts cross a
        # new pow-2 prefill bucket as late as the final turn, plus the
        # suffix-prefill program reuse mode enters from turn 2, plus
        # decode.  A partial warmup leaks a 30-60 s tunnel-side compile
        # into the timed region and swamps the ~seconds workload
        # (measured: a cold-compile run reported 0.197x where the compiled
        # engines give the real ratio).
        warm_tr = [[1] * turn_prompt for _ in range(n_convs)]
        for _ in range(turns):
            wreqs = [
                GenRequest(rid=f"w{i}", input_ids=list(warm_tr[i]),
                           max_new_tokens=turn_gen, temperature=1.0)
                for i in range(n_convs)
            ]
            for r in wreqs:
                eng.submit(r)
            while any(not r.stop_reason for r in wreqs):
                eng.step()
            for i, r in enumerate(wreqs):
                warm_tr[i] = (
                    warm_tr[i] + r.output_tokens + [2] * turn_prompt
                )
        _reset_stats(eng)  # warmup must not skew the token accounting
        eng.retained_len[:] = 0  # nor seed a reusable prefix
        transcripts = [
            rng.integers(0, cfg.vocab_size, turn_prompt).tolist()
            for _ in range(n_convs)
        ]
        t0 = time.perf_counter()
        for turn in range(turns):
            reqs = [
                GenRequest(rid=f"c{i}", input_ids=list(transcripts[i]),
                           max_new_tokens=turn_gen, temperature=1.0)
                for i in range(n_convs)
            ]
            for r in reqs:
                eng.submit(r)
            while any(not r.stop_reason for r in reqs):
                eng.step()
            for i, r in enumerate(reqs):
                transcripts[i] = (
                    transcripts[i] + r.output_tokens
                    + rng.integers(0, cfg.vocab_size, turn_prompt).tolist()
                )
        dt = time.perf_counter() - t0
        out[mode] = {
            "wall_s": round(dt, 2),
            "prefill_tokens": eng.stats["prefill_tokens"],
            "suffix_tokens": eng.stats["suffix_tokens"],
            "reused_tokens": eng.stats["reused_tokens"],
        }
        print(f"multi_turn {mode}: {out[mode]}", file=sys.stderr, flush=True)
        del eng
    out["speedup"] = round(out["cold"]["wall_s"] / out["reuse"]["wall_s"], 3)
    return out


def bench_group_fanout(cfg, params, group_size=8, n_groups=6, prompt_len=256,
                       gen_tokens=16, max_seq_len=1024):
    """GRPO-shaped admission: `n_groups` groups of `group_size` requests
    over ONE prompt each (distinct prompts across groups).  Share engine
    (group fan-out prefill) vs no-share engine over the identical workload;
    reports wall clock plus the hardware-independent signal —
    `shared_prefill_fraction`: the fraction of grouped prompt tokens that
    were NEVER recomputed (fanned out from the representative's KV).

    A third pass (`share_host`) reruns the share workload with the
    host-DRAM overflow tier enabled and retained prefixes spilling between
    groups; its streams must be bit-identical to the device-only share
    pass — cache placement (device row, page remap, host round trip) is
    invisible to the counter-keyed sampler."""
    from areal_tpu.gen.engine import GenRequest

    out = {"group_size": group_size, "n_groups": n_groups,
           "prompt_len": prompt_len}
    streams = {}  # mode -> [[output_tokens per sibling] per group]
    mode_kw = {
        "share": dict(share_prefix=True),
        "noshare": dict(share_prefix=False),
        "share_host": dict(share_prefix=True, host_offload=True,
                           host_cache_mb=32, host_min_tokens=16),
    }
    for mode in ("share", "noshare", "share_host"):
        rng = np.random.default_rng(5)  # identical workload all modes
        eng = _engine(cfg, params, group_size, max_seq_len,
                      **mode_kw[mode])

        def run_group(prompt, tag):
            reqs = [
                GenRequest(rid=f"{tag}-{i}", input_ids=list(prompt),
                           max_new_tokens=gen_tokens, temperature=1.0,
                           group_id=tag, group_n=group_size)
                for i in range(group_size)
            ]
            eng.submit_batch(reqs)
            while any(not r.stop_reason for r in reqs):
                eng.step()
            return reqs

        # warmup compiles every program the timed loop hits (prefill
        # bucket, fan-out copy, sibling suffix bucket, decode)
        run_group([1] * prompt_len, "warm")
        _reset_stats(eng)
        eng.retained_len[:] = 0  # no cross-group retained carryover
        t0 = time.perf_counter()
        for g in range(n_groups):
            # mode-independent tag: stream keys derive from the rid, so
            # the share/noshare identity check needs identical rids
            done = run_group(
                rng.integers(0, cfg.vocab_size, prompt_len).tolist(),
                f"g{g}",
            )
            streams.setdefault(mode, []).append(
                [r.output_tokens for r in done]
            )
        dt = time.perf_counter() - t0
        st = eng.stats
        total = (st["prefill_tokens"] + st["suffix_tokens"]
                 + st["reused_tokens"] + st["shared_tokens"])
        out[mode] = {
            "wall_s": round(dt, 2),
            "prefill_tokens": st["prefill_tokens"],
            "suffix_tokens": st["suffix_tokens"],
            "shared_tokens": st["shared_tokens"],
            "copy_calls": st["copy_calls"],
            "shared_prefill_fraction": round(
                st["shared_tokens"] / max(total, 1), 4
            ),
        }
        if mode == "share_host":
            out[mode]["prefix_cache_host_swaps"] = st[
                "prefix_cache_host_swaps"
            ]
            out[mode]["prefix_cache_evictions"] = st[
                "prefix_cache_evictions"
            ]
        print(f"group_fanout {mode}: {out[mode]}", file=sys.stderr,
              flush=True)
        del eng
    out["shared_prefill_fraction"] = out["share"]["shared_prefill_fraction"]
    # the host tier must be invisible to the counter-keyed sampler: the
    # share workload rerun under spill pressure emits the exact streams
    out["streams_bit_identical"] = streams["share"] == streams["share_host"]
    out["speedup"] = round(
        out["noshare"]["wall_s"] / max(out["share"]["wall_s"], 1e-9), 3
    )
    return out


def bench_decode_ceiling_ab(cfg, params, n_slots=16, ceilings=(4096, 16384),
                            prompt_len=64, gen_tokens=128, tiers=1,
                            window=True):
    """ISSUE 5 acceptance A/B: the SAME decode workload under different
    `max_seq_len` ceilings.  Before the bucketed key window, decode
    attention read the full ceiling width every step, so tokens/s degraded
    as the ceiling grew even though the workload never used the headroom;
    with tiered/windowed decode the large-ceiling number should land
    within ~10% of the small-ceiling one.  Reports per-ceiling tokens/s,
    `decode_attended_fraction`, and the large/small throughput ratio."""
    from areal_tpu.gen.engine import GenRequest

    out = {"n_slots": n_slots, "prompt_len": prompt_len,
           "gen_tokens": gen_tokens, "decode_window": window,
           "decode_tiers": tiers}
    per = {}
    for ceiling in ceilings:
        rng = np.random.default_rng(7)  # identical workload per ceiling
        try:
            eng = _engine(cfg, params, n_slots, ceiling, kv_reuse=False,
                          decode_window=window, decode_tiers=tiers)
            warm = [
                GenRequest(rid=f"w{i}",
                           input_ids=rng.integers(0, cfg.vocab_size,
                                                  prompt_len).tolist(),
                           max_new_tokens=8, temperature=1.0)
                for i in range(n_slots)
            ]
            eng.generate_blocking(warm)
            _reset_stats(eng)
            reqs = [
                GenRequest(rid=f"m{i}",
                           input_ids=rng.integers(0, cfg.vocab_size,
                                                  prompt_len).tolist(),
                           max_new_tokens=gen_tokens, temperature=1.0)
                for i in range(n_slots)
            ]
            for r in reqs:
                eng.submit(r)
            eng.step()  # admission (prefill) outside the decode timing
            t0 = time.perf_counter()
            delivered = 0
            while any(not r.stop_reason for r in reqs):
                delivered += eng.step()
            dt = time.perf_counter() - t0
            per[str(ceiling)] = {
                "tokens_per_sec": round(delivered / dt, 1),
                "wall_s": round(dt, 2),
                "decode_attended_fraction": round(
                    eng.decode_attended_fraction(), 4
                ),
            }
            print(f"ceiling_ab max_seq_len={ceiling}: {per[str(ceiling)]}",
                  file=sys.stderr, flush=True)
            del eng
        except Exception as e:  # noqa: BLE001 — record and continue the A/B
            per[str(ceiling)] = {"error": str(e)[:200]}
            print(f"ceiling_ab max_seq_len={ceiling} failed: {str(e)[:120]}",
                  file=sys.stderr, flush=True)
    out["by_ceiling"] = per
    lo, hi = str(min(ceilings)), str(max(ceilings))
    if "tokens_per_sec" in per.get(lo, {}) and "tokens_per_sec" in per.get(hi, {}):
        # >= 0.9 is the acceptance bar: the large ceiling costs <= 10%
        out["large_over_small_tok_s"] = round(
            per[hi]["tokens_per_sec"] / max(per[lo]["tokens_per_sec"], 1e-9),
            3,
        )
    return out


def _repetition_params(cfg, params):
    """Repetition-heavy synthetic regime (ISSUE 12): zeroing the attention
    output projection makes greedy next-token a pure function of the
    current token, so every stream settles into a short cycle — the
    deterministic stand-in for math-style restatement / code-identifier
    loops that the prompt-lookup drafter feeds on.  Engine-side cost per
    dispatch is unchanged (serving throughput does not depend on weight
    values), so the spec-on/off A/B stays fair while guaranteeing
    draftable streams."""
    import jax.numpy as jnp

    out = dict(params)
    out["layers"] = dict(params["layers"])
    out["layers"]["attn"] = dict(params["layers"]["attn"])
    out["layers"]["attn"]["wo"] = jnp.zeros_like(
        params["layers"]["attn"]["wo"]
    )
    return out


def bench_spec_decode_ab(cfg, params, n_slots=8, prompt_len=64,
                         gen_tokens=128, max_seq_len=512, draft_len=31):
    """ISSUE 12 acceptance A/B: the SAME repetition-heavy greedy workload
    with speculative decoding off vs on.  Spec-off pays one sequential
    model call per token; spec-on verifies D+1 positions in one batched
    dispatch, so accepted drafts collapse dispatches.  Reports per-arm
    tokens/s, draft/accept counters, the bit-identical-stream check (the
    correctness contract rides along with the perf number), and the
    on/off throughput ratio — acceptance bar: >= 1.4x on the CPU rig,
    target >= 2x on real chips (ROADMAP 3b).

    Prompts are the model's OWN prior greedy output (an untimed setup
    rollout from one seed token per slot) — the continuation-of-own-output
    shape that self-speculation targets.  Random tiled prompts would hide
    the win behind each stream's cycle-entry transient: until a cycle has
    repeated once inside visible history the drafter has nothing to look
    up, and in a mixed batch the already-drafting slots drag the still-
    transient ones through verify dispatches at one token each."""
    from areal_tpu.gen.engine import GenRequest

    rep_params = _repetition_params(cfg, params)
    out = {"n_slots": n_slots, "prompt_len": prompt_len,
           "gen_tokens": gen_tokens, "draft_len": draft_len}
    rng = np.random.default_rng(9)
    seeds = rng.integers(0, cfg.vocab_size, n_slots).tolist()
    seed_eng = _engine(cfg, rep_params, n_slots, max_seq_len, kv_reuse=False)
    seed_reqs = [
        GenRequest(rid=f"s{i}", input_ids=[int(s)],
                   max_new_tokens=prompt_len - 1, temperature=0.0)
        for i, s in enumerate(seeds)
    ]
    seed_eng.generate_blocking(seed_reqs)
    prompts = [[int(s)] + list(r.output_tokens)
               for s, r in zip(seeds, seed_reqs)]
    del seed_eng
    streams = {}
    for mode in ("off", "on"):
        kw = (dict(spec_decode=True, spec_draft_len=draft_len or None)
              if mode == "on" else {})
        eng = _engine(cfg, rep_params, n_slots, max_seq_len, kv_reuse=False,
                      **kw)
        # full-length warmup: the timed loop crosses the same key-window
        # buckets, so every decode/verify program compiles here
        warm = [
            GenRequest(rid=f"w{i}", input_ids=list(p),
                       max_new_tokens=gen_tokens, temperature=0.0)
            for i, p in enumerate(prompts)
        ]
        eng.generate_blocking(warm)
        _reset_stats(eng)
        eng.retained_len[:] = 0
        reqs = [
            GenRequest(rid=f"m{i}", input_ids=list(p),
                       max_new_tokens=gen_tokens, temperature=0.0)
            for i, p in enumerate(prompts)
        ]
        for r in reqs:
            eng.submit(r)
        eng.step()  # admission (prefill) outside the decode timing
        t0 = time.perf_counter()
        delivered = 0
        while any(not r.stop_reason for r in reqs):
            delivered += eng.step()
        dt = time.perf_counter() - t0
        streams[mode] = [tuple(r.output_tokens) for r in reqs]
        drafted = eng.stats["spec_drafted"]
        accepted = eng.stats["spec_accepted"]
        out[mode] = {
            "tokens_per_sec": round(delivered / dt, 1),
            "wall_s": round(dt, 2),
            "decode_calls": eng.stats["decode_calls"],
            "verify_calls": eng.stats["verify_calls"],
            "spec_draft_tokens": drafted,
            "spec_accepted_tokens": accepted,
            "spec_acceptance_rate": round(accepted / max(1, drafted), 4),
        }
        print(f"spec_ab {mode}: {out[mode]}", file=sys.stderr, flush=True)
        del eng
    out["streams_bit_identical"] = streams["on"] == streams["off"]
    out["spec_over_plain_tok_s"] = round(
        out["on"]["tokens_per_sec"] / max(out["off"]["tokens_per_sec"], 1e-9),
        3,
    )
    return out


def bench_ragged_ab(cfg, params, n_slots=8, gen_tokens=96, max_seq_len=512,
                    draft_len=31):
    """ISSUE 19 acceptance A/B: the SAME workload through the dense tiered
    decode path and the collapsed ragged-kernel path, on two regimes:

      - mixed:      mixed-length random prompts, greedy, spec off — the
                    ragged-span case (per-slot paged gather vs the dense
                    tier ceiling), one grid-wide dispatch per step vs one
                    per active tier.
      - repetition: repetition-heavy continuation-of-own-output prompts
                    with speculative decoding on — verification rides the
                    SAME kernel (T = D+1 query positions), so the per-tier
                    verify fan-out collapses too.

    The correctness contract rides along with the perf number: token AND
    logprob streams must be bit-identical across arms, and the acceptance
    bar is a strict decode+verify dispatch-count reduction at equal
    streams.  On the CPU rig the kernel runs in Pallas interpret mode —
    dispatch counts, attended-page accounting, and bit-identity all
    transfer to real chips, wall-clock ratios do NOT (interpret-mode
    per-dispatch overhead dominates; see docs/perf.md Round 13)."""
    from areal_tpu.gen.engine import GenRequest

    out = {"n_slots": n_slots, "gen_tokens": gen_tokens,
           "interpret_caveat": (
               "CPU run: kernel in Pallas interpret mode; dispatch counts "
               "and bit-identity transfer to chips, wall-clock does not")}
    rng = np.random.default_rng(17)

    mixed_prompts = [
        rng.integers(0, cfg.vocab_size, int(n)).tolist()
        for n in rng.integers(16, 257, n_slots)
    ]
    rep_params = _repetition_params(cfg, params)
    seeds = rng.integers(0, cfg.vocab_size, n_slots).tolist()
    seed_eng = _engine(cfg, rep_params, n_slots, max_seq_len, kv_reuse=False)
    seed_reqs = [
        GenRequest(rid=f"s{i}", input_ids=[int(s)], max_new_tokens=63,
                   temperature=0.0)
        for i, s in enumerate(seeds)
    ]
    seed_eng.generate_blocking(seed_reqs)
    rep_prompts = [[int(s)] + list(r.output_tokens)
                   for s, r in zip(seeds, seed_reqs)]
    del seed_eng

    regimes = {
        "mixed": dict(params=params, prompts=mixed_prompts, kw={}),
        "repetition": dict(
            params=rep_params, prompts=rep_prompts,
            kw=dict(spec_decode=True, spec_draft_len=draft_len or None)),
    }
    for name, regime in regimes.items():
        streams, res = {}, {}
        for mode in ("dense", "ragged"):
            eng = _engine(cfg, regime["params"], n_slots, max_seq_len,
                          kv_reuse=False, decode_tiers=2,
                          ragged_attn=(mode == "ragged"), **regime["kw"])
            warm = [
                GenRequest(rid=f"w{i}", input_ids=list(p),
                           max_new_tokens=gen_tokens, temperature=0.0)
                for i, p in enumerate(regime["prompts"])
            ]
            eng.generate_blocking(warm)
            _reset_stats(eng)
            eng.retained_len[:] = 0
            reqs = [
                GenRequest(rid=f"m{i}", input_ids=list(p),
                           max_new_tokens=gen_tokens, temperature=0.0)
                for i, p in enumerate(regime["prompts"])
            ]
            for r in reqs:
                eng.submit(r)
            eng.step()  # admission (prefill) outside the decode timing
            t0 = time.perf_counter()
            delivered = 0
            while any(not r.stop_reason for r in reqs):
                delivered += eng.step()
            dt = time.perf_counter() - t0
            streams[mode] = [(tuple(r.output_tokens),
                              tuple(r.output_logprobs)) for r in reqs]
            res[mode] = {
                "tokens_per_sec": round(delivered / dt, 1),
                "wall_s": round(dt, 2),
                "decode_calls": eng.stats["decode_calls"],
                "verify_calls": eng.stats["verify_calls"],
                "ragged_dispatches": eng.stats["ragged_dispatches"],
                "ragged_attended_pages": eng.stats["ragged_attended_pages"],
            }
            print(f"ragged_ab {name}/{mode}: {res[mode]}", file=sys.stderr,
                  flush=True)
            del eng
        res["streams_bit_identical"] = streams["dense"] == streams["ragged"]
        dn, rg = res["dense"], res["ragged"]
        res["dispatches_dense"] = dn["decode_calls"] + dn["verify_calls"]
        res["dispatches_ragged"] = rg["decode_calls"] + rg["verify_calls"]
        res["dispatch_reduction"] = round(
            1 - res["dispatches_ragged"] / max(1, res["dispatches_dense"]), 4)
        res["ragged_over_dense_tok_s"] = round(
            rg["tokens_per_sec"] / max(dn["tokens_per_sec"], 1e-9), 3)
        out[name] = res
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--slots", default="8,32,64,128,256")
    p.add_argument("--skip-decode", action="store_true")
    p.add_argument("--skip-prefill", action="store_true")
    p.add_argument("--skip-multi-turn", action="store_true")
    p.add_argument("--skip-group", action="store_true")
    p.add_argument("--skip-ceiling-ab", action="store_true")
    # tiered-decode ceiling A/B knobs (ISSUE 5 acceptance: large ceiling
    # within 10% of small on the same workload)
    p.add_argument("--ab-slots", type=int, default=16)
    p.add_argument("--ab-ceilings", default="4096,16384")
    p.add_argument("--ab-prompt", type=int, default=64)
    p.add_argument("--ab-gen", type=int, default=128)
    p.add_argument("--ab-tiers", type=int, default=1)
    p.add_argument("--no-decode-window", action="store_true",
                   help="A/B with the window disabled (reproduces the "
                        "pre-ISSUE-5 ceiling-bound decode)")
    # speculative decode knobs (ISSUE 12)
    p.add_argument("--spec-decode", action="store_true",
                   help="run the decode curve with self-speculative "
                        "decoding (prompt-lookup drafts) enabled")
    p.add_argument("--draft-len", type=int, default=31,
                   help="pin the draft length D (0 = adaptive ladder); the "
                        "A/B wants D comfortably above the decode chunk so "
                        "one verify dispatch commits more than one chunk")
    p.add_argument("--ab-spec", action="store_true",
                   help="spec-on/off A/B on the repetition-heavy workload "
                        "(ISSUE 12 acceptance: >= 1.4x decode tok/s on CPU)")
    p.add_argument("--spec-slots", type=int, default=8)
    p.add_argument("--spec-gen", type=int, default=128)
    # ragged paged-decode kernel A/B (ISSUE 19 acceptance)
    p.add_argument("--ab-ragged", action="store_true",
                   help="ragged-vs-dense decode A/B on the mixed-length "
                        "and repetition workloads (ISSUE 19 acceptance: "
                        "bit-identical streams, strictly fewer "
                        "decode+verify dispatches; CPU numbers run the "
                        "kernel in Pallas interpret mode)")
    p.add_argument("--ragged-slots", type=int, default=8)
    p.add_argument("--ragged-gen", type=int, default=96)
    # group fan-out regime knobs (GRPO-shaped grouped admission)
    p.add_argument("--group-size", type=int, default=8)
    p.add_argument("--group-prompt", type=int, default=256)
    p.add_argument("--n-groups", type=int, default=6)
    # multi-turn regime knobs — the published figures are reproduced with:
    #   decode-dominated floor: --turn-prompt 64  --turns 3 --mt-max-seq-len 1024
    #   prefill-dominated:      --turn-prompt 512 --turns 4 --mt-max-seq-len 4096
    # (SERVING_BENCH_r04.json multi_turn carries both)
    p.add_argument("--turn-prompt", type=int, default=512)
    p.add_argument("--turns", type=int, default=4)
    p.add_argument("--turn-gen", type=int, default=32)
    p.add_argument("--mt-max-seq-len", type=int, default=4096)
    p.add_argument("--model", default="qwen25_1p5b",
                   choices=["qwen25_1p5b", "tiny"],
                   help="tiny = CPU smoke mode (token accounting only)")
    p.add_argument("--telemetry-dir", default="",
                   help="enable unified telemetry (utils/telemetry.py) and "
                        "dump events.jsonl + the gen registry snapshot here")
    args = p.parse_args()

    from areal_tpu.utils import telemetry

    if args.telemetry_dir:
        os.makedirs(args.telemetry_dir, exist_ok=True)
        telemetry.set_enabled(True)

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # the baked TPU plugin forces jax_platforms at interpreter boot;
        # re-apply the env choice so CPU smoke runs stay off the chip
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    cfg, params = serving_model_setup(args.model)
    result = {"model": args.model, "device_kind": jax.devices()[0].device_kind}
    if not args.skip_decode:
        result["decode"] = bench_decode(
            cfg, params, [int(s) for s in args.slots.split(",")],
            spec_decode=args.spec_decode, draft_len=args.draft_len,
        )
    if not args.skip_prefill:
        result["prefill"] = bench_prefill(cfg, params)
    if not args.skip_multi_turn:
        result["multi_turn"] = bench_multi_turn(
            cfg, params, turns=args.turns, turn_prompt=args.turn_prompt,
            turn_gen=args.turn_gen, max_seq_len=args.mt_max_seq_len,
        )
    if not args.skip_group and args.group_size > 1:
        result["grouped"] = bench_group_fanout(
            cfg, params, group_size=args.group_size,
            n_groups=args.n_groups, prompt_len=args.group_prompt,
        )
    if args.ab_spec:
        result["spec_ab"] = bench_spec_decode_ab(
            cfg, params, n_slots=args.spec_slots,
            gen_tokens=args.spec_gen, draft_len=args.draft_len,
        )
    if args.ab_ragged:
        result["ragged_ab"] = bench_ragged_ab(
            cfg, params, n_slots=args.ragged_slots,
            gen_tokens=args.ragged_gen, draft_len=args.draft_len,
        )
    if not args.skip_ceiling_ab:
        result["decode_ceiling_ab"] = bench_decode_ceiling_ab(
            cfg, params, n_slots=args.ab_slots,
            ceilings=tuple(int(c) for c in args.ab_ceilings.split(",")),
            prompt_len=args.ab_prompt, gen_tokens=args.ab_gen,
            tiers=args.ab_tiers, window=not args.no_decode_window,
        )
    if args.telemetry_dir:
        events_path = os.path.join(args.telemetry_dir, "events.jsonl")
        snap_path = os.path.join(args.telemetry_dir, "metrics.json")
        n_events = telemetry.EVENTS.dump_jsonl(events_path)
        with open(snap_path, "w") as f:
            json.dump({"gen": telemetry.GEN.snapshot()}, f, indent=2,
                      default=str)
        result["telemetry"] = {
            "dir": args.telemetry_dir,
            "events_jsonl": events_path,
            "metrics_snapshot": snap_path,
            "n_events": n_events,
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
