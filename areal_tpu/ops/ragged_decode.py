"""Ragged paged-decode attention: one Pallas program per slot grid.

Role counterpart of vLLM's PagedAttention / SGLang's ragged decode kernels
(PAPERS.md; SNIPPETS [1] shows the `pallas_call` + `shard_map` idiom this
module follows).  The dense decode path (`models/transformer.py
forward_decode`) pays three XLA ops per layer — a scatter append, a
row gather, and a [B, K] bucketed matmul over the tier's FULL K bucket —
for every slot, long or short.  This kernel fuses all three into one
program over the slot grid and makes the KV *read* ragged: each slot DMAs
only the `ceil((length + T) / page)` pages its occupied span covers out of
its page-table row, so HBM traffic tracks per-slot occupancy instead of
the cohort ceiling, and the per-tier dispatch fan-out collapses to a
single program (`gen/engine.py step`).

Generalised query tile: `T = 1` is plain decode; `T = D + 1` scores the
pending token plus D speculative drafts in the same kernel — verification
rides the decode read for free, which is what lets the engine collapse
decode + verify into one dispatch per step.

Exactness discipline (docs/perf.md Round 13): the kernel is BIT-IDENTICAL
to the dense bucketed path, not merely close.  A classic online-softmax
accumulation (rescale by exp(m_old - m_new) per visiting page) cannot be —
its division/rescale order differs from `jax.nn.softmax` — so the kernel
instead gathers the occupied pages into a zero-filled VMEM scratch of the
static K-bucket width and then applies the EXACT op sequence of
`ops/attention.py naive_attention` (einsum -> f32 -> scale -> softcap ->
mask -> softmax -> einsum).  Masked tail columns carry exact-zero softmax
mass (exp(MASK_VALUE - max) underflows to 0.0) and the zero-filled pages
contribute exact zeros to the output contraction, so the page-windowed
result equals the full-bucket result bit-for-bit — the same width-
invariance the dense windowed path already relies on.  The bandwidth win
survives: reads drop from K to the occupied span; only the compute shape
stays at K.

The K/V append write is fused in: new keys/values are DMA'd into the
slot's page-table row at its write positions (index M = scatter-drop,
mirroring the dense path's idle-slot/overflow clamp) and overlaid into
the scratch before the compute, reproducing the dense write-then-read
order exactly.

`INTERPRET` (or any non-TPU backend) runs the SAME kernel through the
Pallas interpreter, so CPU tier-1 tests and benches exercise the real
program, not a shadow implementation.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from areal_tpu.ops.attention import MASK_VALUE, _shard_map

# Tests may force interpret mode explicitly; any non-TPU backend always
# interprets (the kernel is the only decode path when ragged_attn is on,
# so CPU runs must execute it rather than fail to lower).
INTERPRET = False

# VMEM budget for the per-slot K/V scratch (two [K, Hkv, hd] buffers).
# Half the ~16 MB/core so the q/out blocks and the surrounding layer's
# weight tiles keep headroom; engines whose worst-case bucket would
# overflow this fall back to the dense path at init (gen/engine.py).
RAGGED_VMEM_BYTES = 8 << 20


def _interpret_mode(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return INTERPRET or jax.default_backend() != "tpu"


def ragged_supported(
    max_key_window: int,
    num_kv_heads: int,
    head_dim: int,
    kv_itemsize: int,
    tp: int = 1,
) -> bool:
    """Static gate for enabling the ragged path on an engine: the worst-
    case (max bucket) K/V scratch for one slot must fit the VMEM budget.
    Evaluated once at engine init so the dispatch-site flag is
    engine-lifetime config (areal-lint C6 value lattice)."""
    hkv = max(1, num_kv_heads // max(1, tp))
    scratch = 2 * max_key_window * hkv * head_dim * kv_itemsize
    return scratch <= RAGGED_VMEM_BYTES


def _kernel(
    # scalar prefetch (SMEM)
    rows_ref,  # int32 [B] physical cache row per slot (page table)
    npages_ref,  # int32 [B] full pages the slot's span covers
    tail_ref,  # int32 [B] 1 -> also copy the static tail (K % page != 0)
    widx_ref,  # int32 [B, T] write positions; M = scatter-drop
    # blocked inputs (VMEM)
    q_ref,  # [1, T, Hq, hd] compute dtype
    kn_ref,  # [1, T, Hkv, hd] kv dtype (pre-cast: write-then-read order)
    vn_ref,  # [1, T, Hkv, hd]
    mask_ref,  # uint8 [1, T, K] attended-position mask
    ck_hbm,  # [S, M, Hkv, hd] ANY — full cache, read via DMA
    cv_hbm,
    # outputs
    out_ref,  # [1, T, Hq, hd]
    ck_out,  # aliased with ck_hbm (in-place append)
    cv_out,
    # scratch
    ks_ref,  # VMEM [K, Hkv, hd] kv dtype
    vs_ref,
    sem,
    *,
    T: int,
    K: int,
    M: int,
    page: int,
    group: int,
    hd: int,
    logit_softcap: Optional[float],
):
    i = pl.program_id(0)
    row = rows_ref[i]
    npg = npages_ref[i]
    n_full = K // page
    tail = K - n_full * page

    # zero-fill, then gather ONLY the slot's occupied pages over it: the
    # untouched tail pages contribute exact zeros downstream, which is
    # what makes the page-windowed softmax bit-equal to the dense bucket
    ks_ref[...] = jnp.zeros_like(ks_ref)
    vs_ref[...] = jnp.zeros_like(vs_ref)

    def copy_page(p, _):
        for hbm, scr in ((ck_out, ks_ref), (cv_out, vs_ref)):
            cp = pltpu.make_async_copy(
                hbm.at[row, pl.ds(p * page, page)],
                scr.at[pl.ds(p * page, page)],
                sem,
            )
            cp.start()
            cp.wait()
        return 0

    jax.lax.fori_loop(0, npg, copy_page, 0)
    if tail:
        # K not page-aligned (key_window == max_seq_len off the pow2
        # ladder): the remainder is a STATIC slice, copied when the span
        # reaches past the last full page
        @pl.when(tail_ref[i] > 0)
        def _():
            for hbm, scr in ((ck_out, ks_ref), (cv_out, vs_ref)):
                cp = pltpu.make_async_copy(
                    hbm.at[row, pl.ds(n_full * page, tail)],
                    scr.at[pl.ds(n_full * page, tail)],
                    sem,
                )
                cp.start()
                cp.wait()

    # fused append: the new K/V lands in the page-table row (HBM) AND is
    # overlaid into the scratch — the dense path's write-then-read order.
    # widx == M is the dense scatter-drop sentinel (idle slot / padding
    # position of a short draft): neither write happens.
    for t in range(T):
        wi = widx_ref[i, t]

        @pl.when(wi < M)
        def _():
            ks_ref[pl.ds(wi, 1)] = kn_ref[0, pl.ds(t, 1)]
            vs_ref[pl.ds(wi, 1)] = vn_ref[0, pl.ds(t, 1)]
            for hbm, new in ((ck_out, kn_ref), (cv_out, vn_ref)):
                cp = pltpu.make_async_copy(
                    new.at[0, pl.ds(t, 1)],
                    hbm.at[row, pl.ds(wi, 1)],
                    sem,
                )
                cp.start()
                cp.wait()

    # EXACT naive_attention op order (ops/attention.py) — any deviation
    # here breaks the bit-identity contract the parity tests pin
    dtype = q_ref.dtype
    qs = q_ref[0].reshape(T, ks_ref.shape[1], group, hd)
    ks = ks_ref[...].astype(dtype)
    vs = vs_ref[...].astype(dtype)
    scores = jnp.einsum("tkgh,skh->kgts", qs, ks).astype(jnp.float32)
    scores *= 1.0 / np.sqrt(hd)
    if logit_softcap:
        # barrier-pinned to match naive_attention exactly — see the
        # twin comment there (the simplifier otherwise merges the
        # scale/softcap constants differently per compilation context)
        scores = jax.lax.optimization_barrier(scores)
        scores = jnp.tanh(scores / logit_softcap) * logit_softcap
        scores = jax.lax.optimization_barrier(scores)
    m = mask_ref[0][None, None] != 0  # [1, 1, T, K]
    scores = jnp.where(m, scores, MASK_VALUE)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("kgts,skh->tkgh", probs.astype(vs.dtype), vs)
    out_ref[0] = out.reshape(T, group * ks_ref.shape[1], hd)


def _ragged_call(
    q, k_new, v_new, ck, cv, rows, npages, tail, widx, mask,
    *, K: int, page: int, logit_softcap: Optional[float], interpret: bool,
):
    B, T, Hq, hd = q.shape
    Hkv = ck.shape[2]
    M = ck.shape[1]
    kernel = functools.partial(
        _kernel,
        T=T, K=K, M=M, page=page, group=Hq // Hkv, hd=hd,
        logit_softcap=logit_softcap,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, T, Hq, hd), lambda i, *_: (i, 0, 0, 0)),
            pl.BlockSpec((1, T, Hkv, hd), lambda i, *_: (i, 0, 0, 0)),
            pl.BlockSpec((1, T, Hkv, hd), lambda i, *_: (i, 0, 0, 0)),
            pl.BlockSpec((1, T, K), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, T, Hq, hd), lambda i, *_: (i, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((K, Hkv, hd), ck.dtype),
            pltpu.VMEM((K, Hkv, hd), cv.dtype),
            pltpu.SemaphoreType.DMA,
        ],
    )
    fn = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, T, Hq, hd), q.dtype),
            jax.ShapeDtypeStruct(ck.shape, ck.dtype),
            jax.ShapeDtypeStruct(cv.shape, cv.dtype),
        ],
        # operand indices INCLUDE the scalar-prefetch args: q=4 ... ck=8,
        # cv=9; the cache updates in place (the dense path's donated-scan
        # analogue)
        input_output_aliases={8: 1, 9: 2},
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ) if not interpret else None,
    )
    return fn(rows, npages, tail, widx, q, k_new, v_new, mask, ck, cv)


def ragged_paged_attention(
    q: jax.Array,  # [B, T, Hq, hd] compute dtype (rope already applied)
    k_new: jax.Array,  # [B, T, Hkv, hd] kv dtype (caller casts — the
    v_new: jax.Array,  # dense path rounds through the cache dtype too)
    ck: jax.Array,  # [S_total, M, Hkv, hd] cache keys (one layer)
    cv: jax.Array,
    rows: jax.Array,  # int32 [B] physical row per slot (page table)
    lengths: jax.Array,  # int32 [B] cache fill per slot
    widx: jax.Array,  # int32 [B, T] write positions; M = drop
    mask: jax.Array,  # bool [B, T, K] attended cache positions
    *,
    key_window: int,  # STATIC bucketed compute width (K)
    page_size: int,  # STATIC page granularity (the prompt-bucket quantum)
    logit_softcap: Optional[float] = None,
    mesh: Optional[Mesh] = None,  # tp>1: shard kv heads via shard_map
    interpret: Optional[bool] = None,
):
    """Fused ragged decode/verify attention for one layer of a slot grid.

    Returns `(attn_out [B, T, Hq, hd], ck, cv)` with the new K/V appended
    into the cache — bit-identical to the dense sequence
    ``set -> take -> naive_attention`` over the same `key_window`, while
    reading only each slot's occupied pages.  `T = 1` is decode; `T > 1`
    is speculative verification (same program family, wider query tile).
    """
    B, T = q.shape[:2]
    M = ck.shape[1]
    K = min(key_window, M)
    page = min(page_size, K)
    n_full = K // page
    # span covers every attended/written position: cache cols [0, len + T)
    span = jnp.minimum(lengths + T, K)
    npages = jnp.minimum((span + page - 1) // page, n_full).astype(jnp.int32)
    tail = (span > n_full * page).astype(jnp.int32)
    mask_u8 = mask.astype(jnp.uint8)
    interp = _interpret_mode(interpret)
    call = functools.partial(
        _ragged_call, K=K, page=page, logit_softcap=logit_softcap,
        interpret=interp,
    )
    if mesh is None or mesh.shape.get("tp", 1) <= 1:
        return call(
            q, k_new, v_new, ck, cv, rows, npages, tail, widx, mask_u8
        )
    # tp>1 serving path (SNIPPETS [1] pattern): kv heads ride the mesh's
    # tp axis exactly as the engine's cache sharding lays them out; q
    # heads are kv-major so the same split keeps each query group with
    # its kv head.  Per-shard compute is the identical op sequence, so
    # bit-identity holds shard-locally and the concat restores the dense
    # layout.
    kvs = P(None, None, "tp", None)
    return _shard_map(
        call,
        mesh=mesh,
        in_specs=(
            kvs,  # q [B, T, Hq, hd] — kv-major head split
            kvs,  # k_new
            kvs,  # v_new
            kvs,  # ck [S, M, Hkv, hd]
            kvs,  # cv
            P(None),  # rows
            P(None),  # npages
            P(None),  # tail
            P(None, None),  # widx
            P(None, None, None),  # mask
        ),
        out_specs=(kvs, kvs, kvs),
        check_vma=False,
    )(q, k_new, v_new, ck, cv, rows, npages, tail, widx, mask_u8)
