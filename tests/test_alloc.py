"""Allocation DSL tests (parity with reference test_allocation_mode.py)."""

import pytest

from areal_tpu.api.alloc import (
    AllocationMode,
    AllocationType,
    InvalidAllocationModeError,
    ParallelStrategy,
)


def test_gen_only():
    m = AllocationMode.from_str("jax:d4t2")
    assert m.type_ == AllocationType.LLM_SERVER_ONLY
    assert m.gen_backend == "jax"
    assert m.gen.dp_size == 4 and m.gen.tp_size == 2
    assert m.gen_world_size == 8
    assert m.gen_instance_size == 2


def test_gen_backend_aliases():
    for b in ("sglang", "vllm"):
        m = AllocationMode.from_str(f"{b}:d2t4")
        assert m.type_ == AllocationType.LLM_SERVER_ONLY
        assert m.gen_backend == b
        assert m.gen_world_size == 8


def test_disaggregated():
    m = AllocationMode.from_str("jax:d4t2+jax:d2f4")
    assert m.type_ == AllocationType.DECOUPLED_TRAIN
    assert m.gen_world_size == 8
    assert m.train.fsdp_size == 4
    assert m.train_world_size == 8
    assert m.world_size == 16


def test_colocated():
    m = AllocationMode.from_str("jax:d2t4|jax:d2t2s2")
    assert m.type_ == AllocationType.COLOCATE
    assert m.train.sp_size == 2
    assert m.world_size == 8


def test_train_only_sft():
    m = AllocationMode.from_str("d2f2t2")
    assert m.type_ == AllocationType.COLOCATE
    assert m.gen is None
    assert m.train_world_size == 8
    assert m.train_backend == "jax"


def test_train_backend_alias():
    m = AllocationMode.from_str("jax:d4+fsdp:d8")
    assert m.train_backend == "fsdp"
    assert m.train.dp_size == 8
    m = AllocationMode.from_str("sglang:d4+megatron:d2t2p2")
    assert m.train.pp_size == 2


def test_eval_expr():
    m = AllocationMode.from_str("jax:d4t2+eval")
    assert m.type_ == AllocationType.DECOUPLED_EVAL
    assert m.gen_world_size == 8


def test_hybrid_moe():
    m = AllocationMode.from_str("jax:d4+jax:(attn:d2c2|ffn:d2e2)")
    assert m.train_hybrid is not None
    assert m.train_hybrid.attn.cp_size == 2
    assert m.train_hybrid.ffn.ep_size == 2
    assert m.train_world_size == 4


def test_hybrid_world_size_mismatch():
    with pytest.raises(InvalidAllocationModeError):
        AllocationMode.from_str("jax:d4+jax:(attn:d2c2|ffn:d8e2)")


def test_context_and_sequence_conflict():
    with pytest.raises(InvalidAllocationModeError):
        ParallelStrategy(sequence_parallel_size=2, context_parallel_size=2)


def test_gen_dims_restricted():
    with pytest.raises(InvalidAllocationModeError):
        AllocationMode.from_str("jax:d2e4+jax:d2")


def test_bad_exprs():
    for expr in ["", "foo:d2", "jax:d2+", "d2+d4+d8", "jax:d0", "jax:dd2"]:
        with pytest.raises((InvalidAllocationModeError, ValueError)):
            AllocationMode.from_str(expr)


def test_mesh_shape():
    s = ParallelStrategy(
        data_parallel_size=2,
        fsdp_parallel_size=2,
        tensor_parallel_size=2,
        sequence_parallel_size=2,
    )
    assert s.mesh_shape() == {"dp": 2, "fsdp": 2, "sp": 2, "tp": 2}
    assert s.world_size == 16


def test_roundtrip_str():
    s = ParallelStrategy(data_parallel_size=4, tensor_parallel_size=2)
    assert str(s) == "d4t2"


def test_hybrid_ffn_expert_heavy():
    # ep larger than the ffn section's dense product must parse (MoE folding)
    m = AllocationMode.from_str("jax:d4+jax:(attn:d2c4|ffn:d2e4)")
    assert m.train_hybrid.ffn.ep_size == 4


def test_plain_expert_fold_divisibility():
    m = AllocationMode.from_str("d4t2e2")
    assert m.train.ep_size == 2
    with pytest.raises(InvalidAllocationModeError):
        AllocationMode.from_str("d3e2")  # 2 does not divide 3


def test_gen_backend_rejected_as_train():
    with pytest.raises(InvalidAllocationModeError):
        AllocationMode.from_str("jax:d4+vllm:d2")
