"""Loss functions and token-level numerics, jax-native.

Capability counterpart of the reference's `areal/utils/functional.py`
(gather_logprobs :28, ppo_actor_loss_fn :171 with the decoupled objective,
dual clip) and `realhf/impl/model/utils/ppo_functional.py` (actor/critic
losses, reward shaping).  All reductions are masked *sums* plus explicit
weights so callers can normalise globally across micro-batches and dp ranks
(the reference's loss_weight_fn protocol, fsdp_engine.py:499-606); under a
single jit over the mesh a `jnp.sum` is already a global sum, no psum needed.

Softmax/log-softmax run in fp32 regardless of activation dtype (MXU-friendly
bf16 matmuls, fp32 numerics).
"""
# areal-lint: hot-path

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def masked_mean(x: jax.Array, mask: Optional[jax.Array], eps: float = 1e-8) -> jax.Array:
    if mask is None:
        return jnp.mean(x)
    mask = mask.astype(x.dtype)
    return jnp.sum(x * mask) / (jnp.sum(mask) + eps)


def masked_normalize(
    x: jax.Array,
    mask: Optional[jax.Array],
    unbiased: bool = False,
    eps: float = 1e-5,
) -> jax.Array:
    """Whiten x over masked entries (reference: ppo_functional masked_normalize)."""
    if mask is None:
        mask = jnp.ones_like(x)
    mask = mask.astype(x.dtype)
    n = jnp.sum(mask)
    mean = jnp.sum(x * mask) / jnp.maximum(n, 1.0)
    var = jnp.sum(jnp.square(x - mean) * mask) / jnp.maximum(
        n - (1.0 if unbiased else 0.0), 1.0
    )
    return (x - mean) * jax.lax.rsqrt(var + eps) * mask


def gather_logprobs(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """log p(labels) from logits [..., V]; fp32 log-softmax.

    (reference: areal/utils/functional.py:28-47 gather_logprobs)
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return picked - logz


def gather_logprobs_entropy(
    logits: jax.Array, labels: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """(logprobs, entropy) in one pass (reference: functional.py:85-116)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    logp_all = logits - logz[..., None]
    entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return picked - logz, entropy


def _chunk_len(n: int, target: int) -> int:
    c = min(n, target)
    while n % c:
        c -= 1
    return c


def _clamped_entropy(logits: jax.Array, entropy_clamp: float) -> jax.Array:
    """Entropy of the policy renormalised over the top (1-entropy_clamp)
    fraction of the vocabulary — the bottom tail is masked out before the
    softmax (reference: recipe/AEnt/functional.py clamped_softmax_entropy,
    which removes the k = V*clamp smallest logits).  Token-space clamping
    keeps the entropy bonus from rewarding mass on junk tokens."""
    V = logits.shape[-1]
    keep = max(1, V - int(V * entropy_clamp))
    kth = jax.lax.top_k(logits, keep)[0][..., -1:]
    mask = logits >= kth
    neg_inf = jnp.finfo(logits.dtype).min
    clamped = jnp.where(mask, logits, neg_inf)
    logz = jax.nn.logsumexp(clamped, axis=-1)
    p = jax.nn.softmax(clamped, axis=-1)
    return logz - jnp.sum(jnp.where(mask, p * logits, 0.0), axis=-1)


def lm_logprobs_entropy(
    out,  # LMOutput (deferred head) or materialised logits [..., V]
    labels: jax.Array,  # int [...]
    temperature: float = 1.0,
    chunk: int = 1024,
    with_entropy: bool = True,
    entropy_clamp: float = 0.0,
    entropy_grad: bool = True,
    impl: Optional[str] = None,  # fused | chunked; None -> env or "fused"
    vocab_chunk: Optional[int] = None,  # fused-head chunk width; None ->
    # AREAL_LM_HEAD_CHUNK env or 8192 (TrainEngineConfig.lm_head_chunk is
    # the plumbed spelling — loss partials pass it through here)
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(logprobs, entropy, argmax-correct) of `labels`, fp32 numerics.

    With an `LMOutput` the default "fused" impl runs the vocab-chunked
    online-softmax head with a hand-written VJP (ops/fused_xent.py): never
    holds [tokens, V] fp32 logits, accumulates dx in a [tokens, D] carry,
    writes each dW vocab slice once, and (with entropy_grad=False — the
    GRPO stats-only case) skips the entropy backward term entirely.  This
    is the TPU-side counterpart of the reference's vocab-parallel
    cross-entropy memory discipline (realhf .../tensor_parallel/
    modules.py:1180 vocab_parallel_cross_entropy).  "chunked" keeps the
    legacy rematerialised token-chunk scan (also used for entropy_clamp,
    which needs a per-token top-k over the full vocab row).
    """
    from areal_tpu.models.transformer import LMOutput

    inv_t = float(1.0 / temperature)
    if not isinstance(out, LMOutput):
        logits = out.astype(jnp.float32) * inv_t
        logp, ent = gather_logprobs_entropy(logits, labels)
        if entropy_clamp > 0:
            ent = _clamped_entropy(logits, entropy_clamp)
        corr = (jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32)
        return logp, ent, corr

    shape = labels.shape
    if impl is None:
        # AREAL_LM_HEAD_IMPL=chunked is the A/B + fallback lever
        import os

        impl = os.environ.get("AREAL_LM_HEAD_IMPL", "fused")
    if (
        impl == "fused"
        and entropy_clamp == 0
        and getattr(out, "logit_softcap", None) is None
    ):
        import os as _os

        from areal_tpu.ops.fused_xent import fused_logprobs_entropy

        D = out.hidden.shape[-1]
        lp, ent, corr = fused_logprobs_entropy(
            out.hidden.reshape(-1, D),
            out.head,
            labels.reshape(-1),
            temperature=temperature,
            vocab_chunk=int(
                vocab_chunk
                or _os.environ.get("AREAL_LM_HEAD_CHUNK", 8192)
            ),
            with_entropy=with_entropy,
            entropy_grad=entropy_grad,
        )
        return lp.reshape(shape), ent.reshape(shape), corr.reshape(shape)
    D = out.hidden.shape[-1]
    h = out.hidden.reshape(-1, D)
    lab = labels.reshape(-1)
    N = h.shape[0]
    c = _chunk_len(N, chunk)
    hs = h.reshape(N // c, c, D)
    ls = lab.reshape(N // c, c)
    head = out.head

    cap = getattr(out, "logit_softcap", None)

    @jax.checkpoint
    def one_chunk(carry, xs):
        hc, lc = xs
        logits = (hc @ head).astype(jnp.float32)
        if cap:
            # gemma2 final-logit tanh cap is part of the model's output
            # distribution, applied before temperature
            logits = jnp.tanh(logits / cap) * cap
        logits = logits * inv_t
        logz = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        if with_entropy:
            if entropy_clamp > 0:
                ent = _clamped_entropy(logits, entropy_clamp)
            else:
                p = jax.nn.softmax(logits, axis=-1)
                ent = logz - jnp.sum(p * logits, axis=-1)
            corr = (jnp.argmax(logits, axis=-1) == lc).astype(jnp.float32)
        else:
            ent = jnp.zeros_like(logz)
            corr = jnp.zeros_like(logz)
        return carry, (picked - logz, ent, corr)

    _, (lp, ent, corr) = jax.lax.scan(one_chunk, (), (hs, ls))
    return lp.reshape(shape), ent.reshape(shape), corr.reshape(shape)


def kl_estimate(
    logp: jax.Array, ref_logp: jax.Array, kind: str = "k1", clip: float = 20.0
) -> jax.Array:
    """Schulman k1/k2/k3 estimators of KL(pi || ref) per token
    (reference: areal/utils/data.py KLEstimator :1306)."""
    diff = jnp.clip(logp - ref_logp, -clip, clip)
    if kind == "k1":
        return diff
    if kind == "k2":
        return 0.5 * jnp.square(diff)
    if kind == "k3":
        return jnp.exp(-diff) - 1.0 + diff
    raise ValueError(f"unknown KL estimator {kind}")


# ---------------------------------------------------------------------------
# PPO / GRPO
# ---------------------------------------------------------------------------


def ppo_actor_loss_fn(
    logprobs: jax.Array,
    old_logprobs: jax.Array,
    advantages: jax.Array,
    eps_clip: float,
    loss_mask: jax.Array,
    c_clip: Optional[float] = None,
    proximal_logprobs: Optional[jax.Array] = None,
    behav_imp_weight_cap: Optional[float] = None,
    eps_clip_higher: Optional[float] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Decoupled-PPO actor loss (reference: areal/utils/functional.py:171-235).

    With `proximal_logprobs` (the recomputed policy at train time), the ratio
    is taken against the *proximal* policy and the sample is reweighted by the
    capped behaviour importance weight exp(prox - old) — the decoupled PPO
    objective that makes staleness η≤4 trainable (blog/AReaL_v0_3.md ablation).
    Returns (sum-reduced masked loss, stats dict of masked sums).
    """
    denorm_logprobs = proximal_logprobs if proximal_logprobs is not None else old_logprobs
    loss_mask = loss_mask.astype(jnp.float32)
    ratio = jnp.exp(logprobs - denorm_logprobs)
    clipped_ratio = jnp.clip(
        ratio,
        1.0 - eps_clip,
        1.0 + (eps_clip_higher if eps_clip_higher is not None else eps_clip),
    )
    pg_loss1 = -advantages * ratio
    pg_loss2 = -advantages * clipped_ratio
    clip_mask = pg_loss1 < pg_loss2
    pg_loss = jnp.maximum(pg_loss1, pg_loss2)
    if c_clip is not None:
        # dual clip: bound the loss for very negative advantages
        pg_loss3 = jnp.sign(advantages) * c_clip * advantages
        # mask marks positions where the dual clip actually takes effect
        dual_clip_mask = (advantages < 0) & (pg_loss3 < pg_loss)
        pg_loss = jnp.where(advantages < 0, jnp.minimum(pg_loss, pg_loss3), pg_loss)
    else:
        dual_clip_mask = jnp.zeros_like(clip_mask)
    if proximal_logprobs is not None:
        behav_kl = denorm_logprobs - old_logprobs
        behav_imp_weight = jnp.exp(behav_kl)
        if behav_imp_weight_cap is not None:
            behav_mask = (behav_imp_weight <= behav_imp_weight_cap) & (loss_mask > 0)
        else:
            behav_mask = loss_mask > 0
        behav_imp_weight = jnp.where(behav_mask, behav_imp_weight, 0.0)
        pg_loss = pg_loss * behav_imp_weight
        stat_behav_kl = jnp.sum(behav_kl * behav_mask)
        stat_behav_w = jnp.sum(behav_imp_weight * behav_mask)
    else:
        stat_behav_kl = jnp.zeros(())
        stat_behav_w = jnp.zeros(())
    loss = jnp.sum(pg_loss * loss_mask)
    stats = {
        "importance_weight": jnp.sum(ratio * loss_mask),
        "approx_kl": jnp.sum((logprobs - denorm_logprobs) * loss_mask),
        "clip_ratio": jnp.sum(clip_mask * loss_mask),
        "dual_clip_ratio": jnp.sum(dual_clip_mask * loss_mask),
        "behave_kl": stat_behav_kl,
        "behave_imp_weight": stat_behav_w,
        "n_valid_tokens": jnp.sum(loss_mask),
    }
    return loss, stats


def grpo_loss_fn(
    model_out,  # LMOutput or [T, V] packed logits
    batch: Dict[str, jax.Array],
    eps_clip: float,
    c_clip: Optional[float] = None,
    behav_imp_weight_cap: Optional[float] = None,
    temperature: float = 1.0,
    use_decoupled_loss: bool = True,
    entropy_coef: float = 0.0,
    eps_clip_higher: Optional[float] = None,
    vocab_chunk: Optional[int] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Packed GRPO/PPO policy loss over next-token logits
    (reference: areal/engine/ppo/actor.py:313-391 grpo_loss_fn).

    batch keys (flat [T]): input_ids, loss_mask, logprobs (behaviour),
    advantages, and optionally prox_logp.
    """
    labels = jnp.roll(batch["input_ids"], -1, axis=-1)
    loss_mask = batch["loss_mask"].astype(jnp.float32)
    logprobs, entropy, _ = lm_logprobs_entropy(
        model_out, labels, temperature=temperature,
        # entropy is a logged stat unless an entropy bonus actually trains
        # on it — skipping its backward term saves an elementwise pass over
        # every recomputed logits block
        entropy_grad=bool(entropy_coef),
        vocab_chunk=vocab_chunk,
    )
    old_logp = batch["logprobs"]
    prox = batch.get("prox_logp") if use_decoupled_loss else None
    loss, stats = ppo_actor_loss_fn(
        logprobs=logprobs,
        old_logprobs=old_logp,
        advantages=batch["advantages"],
        eps_clip=eps_clip,
        loss_mask=loss_mask,
        c_clip=c_clip,
        proximal_logprobs=prox,
        behav_imp_weight_cap=behav_imp_weight_cap,
        eps_clip_higher=eps_clip_higher,
    )
    if entropy_coef:
        loss = loss - entropy_coef * jnp.sum(entropy * loss_mask)
    aux = getattr(model_out, "aux_loss", None)
    if aux is not None:
        # MoE load-balance penalty, weighted per valid token so the global
        # loss normalisation leaves it as an average across micro-batches
        loss = loss + aux * jnp.sum(loss_mask)
        stats["moe_aux_loss"] = aux * jnp.sum(loss_mask)
    stats["entropy"] = jnp.sum(entropy * loss_mask)
    stats["new_logp"] = jnp.sum(logprobs * loss_mask)
    stats["old_logp"] = jnp.sum(old_logp * loss_mask)
    return loss, stats


def ppo_critic_loss_fn(
    values: jax.Array,
    old_values: jax.Array,
    returns: jax.Array,
    loss_mask: jax.Array,
    eps_clip_value: Optional[float] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Clipped value loss (reference: realhf .../ppo_functional.py critic_loss_fn)."""
    loss_mask = loss_mask.astype(jnp.float32)
    err = jnp.square(values - returns)
    if eps_clip_value is not None:
        clipped = old_values + jnp.clip(values - old_values, -eps_clip_value, eps_clip_value)
        err_clipped = jnp.square(clipped - returns)
        clip_mask = err_clipped > err
        err = jnp.maximum(err, err_clipped)
    else:
        clip_mask = jnp.zeros_like(err, dtype=bool)
    loss = 0.5 * jnp.sum(err * loss_mask)
    return loss, {
        "value_clip_ratio": jnp.sum(clip_mask * loss_mask),
        "n_valid_tokens": jnp.sum(loss_mask),
    }


# ---------------------------------------------------------------------------
# SFT / RW / DPO
# ---------------------------------------------------------------------------


def sft_loss_fn(
    model_out, batch: Dict[str, jax.Array],
    vocab_chunk: Optional[int] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Token cross-entropy over next-token targets, masked sum
    (reference: areal/engine/sft/lm_engine.py)."""
    labels = jnp.roll(batch["input_ids"], -1, axis=-1)
    mask = batch["loss_mask"].astype(jnp.float32)
    logprobs, _, correct = lm_logprobs_entropy(
        model_out, labels, entropy_grad=False, vocab_chunk=vocab_chunk
    )
    loss = -jnp.sum(logprobs * mask)
    aux = getattr(model_out, "aux_loss", None)
    if aux is not None:
        loss = loss + aux * jnp.sum(mask)
    return loss, {
        "loss_sum": loss,
        "n_valid_tokens": jnp.sum(mask),
        "correct_tokens": jnp.sum(correct * mask),
    }


def pairwise_reward_loss_fn(
    chosen_scores: jax.Array,
    rejected_scores: jax.Array,
    pair_mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Bradley-Terry pairwise loss (reference: areal/engine/rw/rw_engine.py).
    `pair_mask` excludes filler pairs (dp-padding rows)."""
    margin = chosen_scores - rejected_scores
    if pair_mask is None:
        pair_mask = jnp.ones_like(margin)
    pair_mask = pair_mask.astype(jnp.float32)
    loss = -jnp.sum(jax.nn.log_sigmoid(margin) * pair_mask)
    return loss, {
        "acc": jnp.sum((margin > 0) * pair_mask),
        "margin": jnp.sum(margin * pair_mask),
        "n_pairs": jnp.sum(pair_mask),
    }


def dpo_loss_fn(
    policy_chosen_logp: jax.Array,
    policy_rejected_logp: jax.Array,
    ref_chosen_logp: jax.Array,
    ref_rejected_logp: jax.Array,
    beta: float = 0.1,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Direct preference optimization loss over sequence logprobs."""
    pi_ratio = policy_chosen_logp - policy_rejected_logp
    ref_ratio = ref_chosen_logp - ref_rejected_logp
    h = beta * (pi_ratio - ref_ratio)
    loss = -jnp.sum(jax.nn.log_sigmoid(h))
    return loss, {"acc": jnp.sum(h > 0), "n_pairs": jnp.asarray(h.size, jnp.float32)}
