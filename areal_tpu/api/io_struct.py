"""Dataclasses crossing process / engine boundaries.

Capability counterpart of the reference's `areal/api/io_struct.py` (ModelRequest
:21, ModelResponse :47, WeightUpdateMeta :105, ParamSpec :93, SaveLoadMeta :197,
FinetuneSpec :77, StepInfo :215, RolloutStat).  torch-free: sizes are computed
with numpy dtypes and the weight-update channel is TPU-native ("disk" via a
shared filesystem + version handshake, or "transfer" via host RPC push).
"""

import os
import uuid
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Dict, List, Literal, Optional, Tuple

import numpy as np

from areal_tpu.api.config import GenerationHyperparameters

if TYPE_CHECKING:
    from areal_tpu.api.alloc import AllocationMode


@dataclass
class ModelRequest:
    """One generation request travelling client -> inference server."""

    rid: str = field(default_factory=lambda: str(uuid.uuid4()))
    input_ids: List[int] = field(default_factory=list)
    gconfig: GenerationHyperparameters = field(
        default_factory=GenerationHyperparameters
    )
    metadata: Dict[str, Any] = field(default_factory=dict)
    tokenizer: Any = None
    image_data: Optional[List[Any]] = None
    processor: Any = None
    # native VLM serving wire format (gen/server.py): pre-patchified pixels
    # + per-image patch grids, the AutoProcessor's output layout
    pixel_values: Optional[Any] = None  # np [N, patch_dim]
    image_grid_thw: Optional[Any] = None  # np [n_img, 3]
    # group fan-out (gen/engine.py): GRPO siblings over one prompt share a
    # group_id + expected size so the router keeps them on one replica and
    # the engine clusters them for cross-slot KV prefix sharing
    group_id: str = ""
    group_n: int = 0
    # telemetry (utils/telemetry.py): trajectory-lifecycle trace id, carried
    # on the wire and echoed in the response meta; survives the interruption
    # loop's resubmissions because copy() preserves it
    trace_id: str = ""

    def copy(self) -> "ModelRequest":
        return ModelRequest(
            rid=self.rid,
            input_ids=list(self.input_ids),
            gconfig=self.gconfig.new(),
            metadata=dict(self.metadata),
            tokenizer=self.tokenizer,
            image_data=list(self.image_data) if self.image_data is not None else None,
            processor=self.processor,
            pixel_values=self.pixel_values,
            image_grid_thw=self.image_grid_thw,
            group_id=self.group_id,
            group_n=self.group_n,
            trace_id=self.trace_id,
        )


@dataclass
class ModelResponse:
    """Generation result; `output_versions` carries the weight version that
    produced each output token — the raw signal for staleness accounting and
    the decoupled-PPO behavior policy (reference: io_struct.py:47-75)."""

    input_tokens: List[int] = field(default_factory=list)
    output_tokens: List[int] = field(default_factory=list)
    output_logprobs: List[float] = field(default_factory=list)
    output_versions: List[int] = field(default_factory=list)
    stop_reason: Literal["length", "stop", "interrupt", "abort"] = "stop"
    tokenizer: Any = None
    input_images: List[Any] = field(default_factory=list)
    processor: Any = None
    # timing stats
    latency: float = float("inf")
    ttft: float = float("inf")
    itl: List[float] = field(default_factory=list)

    @property
    def input_len(self) -> int:
        return len(self.input_tokens)

    @property
    def output_len(self) -> int:
        return len(self.output_tokens)


@dataclass
class FinetuneSpec:
    total_train_epochs: int
    dataset_size: int
    train_batch_size: int

    def __post_init__(self):
        if self.train_batch_size <= 0:
            raise ValueError(f"train_batch_size={self.train_batch_size} must be > 0")
        if self.dataset_size < self.train_batch_size:
            raise ValueError(
                f"dataset_size={self.dataset_size} < train_batch_size="
                f"{self.train_batch_size}: zero steps per epoch (drop_last)"
            )

    @property
    def total_train_steps(self) -> int:
        return self.total_train_epochs * self.steps_per_epoch

    @property
    def steps_per_epoch(self) -> int:
        return self.dataset_size // self.train_batch_size


@dataclass
class ParamSpec:
    name: str
    shape: Tuple[int, ...]
    dtype: str

    @property
    def size(self) -> int:
        """Parameter bytes."""
        return int(np.dtype(self.dtype).itemsize * int(np.prod(self.shape)))


@dataclass
class WeightUpdateMeta:
    """How fresh trainer weights reach inference servers.

    - "disk": trainer writes a safetensors/tensorstore snapshot under `path`
      and publishes a version timestamp in name_resolve; servers reload from
      the shared filesystem (reference disk path: fsdp_engine.py:403-425).
    - "transfer": trainer pushes host-gathered shards over HTTP chunks
      directly into server HBM (TPU-native replacement of the reference's
      NCCL broadcast group, fsdp_engine.py:298-401).
    """

    type: Literal["disk", "transfer"] = "disk"
    path: Optional[str] = None
    alloc_mode: Optional["AllocationMode"] = None
    chunk_mb: int = 256
    use_lora: bool = False
    # transfer commits only: swap without aborting in-flight generation
    # (GenEngine.swap_weights_live semantics — requests keep decoding, the
    # policy transition is recorded in per-token versions).  Default ON —
    # abort-and-resume measurably sinks async throughput below sync
    # (E2E_GRPO_BENCH_r04 publish_mode_interrupt); False reproduces the
    # reference's abort-only choreography.
    live_commit: bool = True
    # identify the trial for the name_resolve version handshake
    experiment_name: str = ""
    trial_name: str = ""
    # disk updates only: pin the exact version the servers must load.
    # None (the default, normal training) lets each server resolve the
    # newest v{N} snapshot itself; recovery replays set it so rejoining
    # servers are forced to the RECOVERED version even when a newer,
    # never-trained-on snapshot survived the crash on disk.
    version: Optional[int] = None

    @classmethod
    def from_disk(
        cls,
        experiment_name: str,
        trial_name: str,
        file_root: str,
        name: str = "default",
        use_lora: bool = False,
    ) -> "WeightUpdateMeta":
        path = os.path.join(
            file_root,
            "checkpoints",
            experiment_name,
            trial_name,
            name,
            "weight_update",
        )
        return cls(
            type="disk",
            path=path,
            use_lora=use_lora,
            experiment_name=experiment_name,
            trial_name=trial_name,
        )

    @classmethod
    def from_transfer(
        cls,
        experiment_name: str = "",
        trial_name: str = "",
        alloc_mode: Optional["AllocationMode"] = None,
        chunk_mb: int = 256,
        live_commit: bool = True,
    ) -> "WeightUpdateMeta":
        return cls(
            type="transfer",
            alloc_mode=alloc_mode,
            chunk_mb=chunk_mb,
            live_commit=live_commit,
            experiment_name=experiment_name,
            trial_name=trial_name,
        )


@dataclass
class SaveLoadMeta:
    path: str
    weight_format: str = "safetensors"  # safetensors | tensorstore
    with_optim: bool = False
    tokenizer: Any = None
    processor: Any = None
    base_model_path: Optional[str] = None


@dataclass
class RolloutStat:
    submitted: int = 0
    accepted: int = 0
    running: int = 0
    # rollouts that settled without acceptance (should_accept veto, episode
    # failure, or trajectory lost to fleet failure) — tracked explicitly so
    # the ledger invariant submitted == accepted + rejected + running is
    # checkable at every transition
    rejected: int = 0


@dataclass
class StepInfo:
    epoch: int
    epoch_step: int
    global_step: int
    steps_per_epoch: int

    def next(self) -> "StepInfo":
        last = self.epoch_step == self.steps_per_epoch - 1
        return StepInfo(
            epoch=self.epoch + int(last),
            epoch_step=0 if last else self.epoch_step + 1,
            global_step=self.global_step + 1,
            steps_per_epoch=self.steps_per_epoch,
        )


@dataclass
class HttpRequest:
    endpoint: str
    payload: Dict[str, Any]
    method: str = "POST"


@dataclass
class HttpGenerationResult:
    output_tokens: List[int]
    output_logprobs: List[float]
    stop_reason: str
    version: int = -1
    # prompt tokens served from the server's radix/paged prefix cache
    # (warm-started failover resubmits report nonzero here)
    cache_hit_tokens: int = 0


@dataclass
class WeightUpdateRequests:
    requests: List[HttpRequest] = field(default_factory=list)
