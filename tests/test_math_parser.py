"""Math verifier depth tests.

Coverage mirrors the reference pipeline's behaviors
(areal/reward/math_parser.py strip_string :219 / extract_answer :360 /
math_equal :495 and the vendored latex2sympy cases): latex normalisation,
units, word numbers, mixed numbers, percentage forms, tuples/intervals,
matrices, equations, symbolic equivalence — plus the strict-extraction
reward-honesty contract from the round-1 review (weak #6).
"""

import pytest

from areal_tpu.reward.math_parser import (
    extract_answer,
    gsm8k_reward_fn,
    math_equal,
    normalize_answer,
)

# ---------------------------------------------------------------- extraction


@pytest.mark.parametrize(
    "text,expected",
    [
        ("so we get \\boxed{\\frac{3}{4}}", "\\frac{3}{4}"),
        ("nested \\boxed{\\text{f}(x) = {x}^2}!", "\\text{f}(x) = {x}^2"),
        ("…the final answer is $\\sqrt{2}$. I hope it is correct.", "\\sqrt{2}"),
        ("Thus the answer is 42.", "42"),
        ("Thus The Answer Is: 1/2", "1/2"),
        ("reasoning...\n#### 72", "72"),
        ("The answer is $18$ dollars.", "$18$ dollars"),
        ("\\boxed 7 loose form", "7"),
    ],
)
def test_extract(text, expected):
    assert extract_answer(text) == expected


def test_strict_mode_blocks_bare_numbers():
    """A completion with numbers but no explicit answer marker earns nothing
    (reward hacking: emitting digits must not farm reward)."""
    text = "I think maybe 3 or 7 or 9"
    assert extract_answer(text, strict=True) is None
    assert extract_answer(text, strict=False) == "9"
    assert gsm8k_reward_fn("", text, [], [], answer="9") == 0.0
    assert gsm8k_reward_fn("", "the answer is 9", [], [], answer="9") == 1.0


# ------------------------------------------------------------- normalisation


@pytest.mark.parametrize(
    "raw,norm",
    [
        ("\\frac{1}{2}", "((1)/(2))"),
        ("\\frac12", "((1)/(2))"),
        ("\\frac{12}x", "((12)/(x))"),
        ("\\dfrac{a}{b}", "((a)/(b))"),
        ("\\text{m}", "m"),
        ("10\\%", "10"),
        ("\\$5.00", "5"),
        ("90^\\circ", "90"),
        (".5", "0.5"),
        ("2.0", "2"),
        ("1{,}000", "1000") if False else ("1,000,000", "1000000"),
        ("x = 5", "5"),
        ("twelve", "12"),
        ("25 \\text{ miles}", "25"),
        ("3 hours", "3"),
    ],
)
def test_normalize(raw, norm):
    assert normalize_answer(raw) == norm


# ---------------------------------------------------------------- math_equal


@pytest.mark.parametrize(
    "pred,target",
    [
        # numeric + formatting
        ("42", "42.0"),
        ("1,234", "1234"),
        ("0.5", "\\frac{1}{2}"),
        ("3.14159", "3.1416"),
        # percentage forms (reference include_percentage)
        ("50", "0.5"),
        ("0.25", "25"),
        # units / currency / degrees
        ("$18", "18 dollars"),
        ("90^\\circ", "90"),
        ("25 \\text{ miles}", "25"),
        # word numbers, mixed numbers
        ("seven", "7"),
        ("3\\frac{1}{2}", "3.5"),
        # radicals / symbolic
        ("\\sqrt{8}", "2\\sqrt{2}"),
        ("\\frac{\\sqrt{3}}{3}", "\\frac{1}{\\sqrt{3}}"),
        ("x^2-1", "(x-1)(x+1)"),
        ("2x+2", "2(x+1)"),
        ("\\frac{pi}{4}", "pi/4"),
        # tuples / intervals element-wise
        ("(1, 2)", "(1.0, 2.0)"),
        ("(0, \\frac{1}{2})", "(0, 0.5)"),
        ("[1, \\infty)", "[1,oo)"),
        # equations: both sides
        ("y = 2x + 1", "y = 2x + 1.0"),
        # matrices element-wise
        (
            "\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}",
            "\\begin{bmatrix}1.0&2\\\\3&4.0\\end{bmatrix}",
        ),
        # prefix variable strip
        ("k = 12", "12"),
    ],
)
def test_equal(pred, target):
    assert math_equal(pred, target), (pred, target)


@pytest.mark.parametrize(
    "pred,target",
    [
        ("42", "43"),
        ("0.333", "1/3"),  # outside tolerance
        ("(1, 2)", "(2, 1)"),
        ("(1, 2)", "(1, 2, 3)"),
        ("[0, 1)", "(0, 1)"),  # bracket kind matters for intervals
        ("x+1", "x-1"),
        ("\\sqrt{2}", "2"),
        ("", "5"),
        (None, "5"),
        ("nonsense[", "42"),
        (
            "\\begin{pmatrix}1&2\\\\3&4\\end{pmatrix}",
            "\\begin{pmatrix}1&2\\\\3&5\\end{pmatrix}",
        ),
    ],
)
def test_not_equal(pred, target):
    assert not math_equal(pred, target), (pred, target)


def test_aime_style_closed_forms():
    """Eval-harness breadth (VERDICT r2 weak #9): decimal-vs-closed-form,
    binomials, and bare 'Answer:' lines the AIME/AMC sets need."""
    from areal_tpu.reward.math_parser import extract_answer, math_equal

    assert math_equal(r"\frac{1+\sqrt{5}}{2}", "1.6180339887")
    assert math_equal("1.6180339887", r"\frac{1+\sqrt{5}}{2}")
    assert math_equal(r"\binom{10}{3}", "120")
    assert math_equal(r"\dbinom{5}{2}", "10")
    assert math_equal(r"2\sqrt{3}", "3.4641016")
    assert not math_equal(r"2\sqrt{3}", "3.5")
    assert not math_equal(r"\frac{m}{n}", "1.5")  # free symbols stay symbolic
    assert extract_answer("Answer: 042") == "042"
    assert math_equal("042", "42")
