"""VLM end-to-end: VisionRLVR episodes roll out against the native VLM
generation server, the resulting batch carries pixels + mrope positions,
and the VLM GRPO actor trains on it — the full loop the reference runs
with SGLang-multimodal + FSDP-VLM (workflow/vision_rlvr.py +
base_hf_engine VLM branch)."""

import asyncio
import threading

import numpy as np
import pytest
from aiohttp import web

from areal_tpu.api.config import (
    GenerationHyperparameters,
    InferenceEngineConfig,
    MeshConfig,
    MicroBatchSpec,
    NormConfig,
    OptimizerConfig,
    PPOActorConfig,
)
from areal_tpu.api.io_struct import FinetuneSpec
from areal_tpu.core.remote import RemoteInfEngine
from areal_tpu.engine.jax_remote import JaxBackend
from areal_tpu.engine.vlm_engine import JaxVLMPPOActor
from areal_tpu.gen.engine import GenEngine
from areal_tpu.gen.server import GenServer
from areal_tpu.models.model_config import VisionConfig, tiny_config
from areal_tpu.workflow.vision_rlvr import VisionRLVRWorkflow

IMG_TOK = 60

VCFG = VisionConfig(
    patch_size=2,
    temporal_patch_size=1,
    in_channels=3,
    hidden_size=16,
    intermediate_size=32,
    num_layers=1,
    num_heads=2,
    spatial_merge_size=2,
    out_hidden_size=48,
)


def _vlm_cfg():
    return tiny_config(
        vocab_size=64,
        hidden_size=48,
        num_heads=4,
        num_kv_heads=2,
        qkv_bias=True,
        dtype="float32",
        param_dtype="float32",
        hf_architecture="Qwen2VLForConditionalGeneration",
    ).replace(vision=VCFG, image_token_id=IMG_TOK, mrope_section=(2, 3, 3))


class _Tok:
    eos_token_id = None

    def decode(self, tokens):
        return " ".join(str(t) for t in tokens)


@pytest.mark.slow
def test_vision_rollout_to_vlm_training(tmp_path):
    engine = GenEngine(_vlm_cfg(), n_slots=4, max_seq_len=96, seed=0)
    server = GenServer(engine)
    server.start()
    started = threading.Event()
    holder = {}

    def _run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def _serve():
            runner = web.AppRunner(server.app())
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            holder["addr"] = f"127.0.0.1:{runner.addresses[0][1]}"
            started.set()

        loop.run_until_complete(_serve())
        loop.run_forever()

    threading.Thread(target=_run, daemon=True).start()
    assert started.wait(10)

    client = RemoteInfEngine(
        InferenceEngineConfig(
            experiment_name="vlm-e2e", trial_name="t", consumer_batch_size=2
        ),
        JaxBackend(),
    )
    client.initialize(addr=holder["addr"])

    def reward_fn(prompt, completion, prompt_ids, completion_ids, **kw):
        return 1.0 if "7" in completion else 0.0

    group_size = 2
    workflow = VisionRLVRWorkflow(
        reward_fn=reward_fn,
        gconfig=GenerationHyperparameters(
            n_samples=group_size, max_new_tokens=8, temperature=1.0
        ),
        tokenizer=_Tok(),
        image_token_id=IMG_TOK,
        spatial_merge_size=VCFG.spatial_merge_size,
    )

    rng = np.random.default_rng(0)

    def episode(i):
        return {
            "query_id": str(i),
            "input_ids": [5, 6] + [IMG_TOK] * 4 + [7, 8],
            "pixel_values": rng.normal(size=(16, VCFG.patch_dim)).astype(
                np.float32
            ),
            "image_grid_thw": np.array([[1, 4, 4]]),
            "answer": "7",
        }

    try:
        batch = client.rollout_batch([episode(0), episode(1)], workflow=workflow)
        B = batch["input_ids"].shape[0]
        assert B == 2 * group_size
        for key in ("pixel_values", "patch_img_ids", "mrope_positions"):
            assert key in batch, sorted(batch)
        assert batch["pixel_values"].shape[0] == B * 16  # patches per row
        # image ids unique per row across episodes
        ids = batch["patch_img_ids"]
        assert len(set(ids.tolist())) == B
        assert batch["mrope_positions"].shape == (
            B, batch["input_ids"].shape[1], 3,
        )

        # train on the rollout with the VLM GRPO actor
        actor = JaxVLMPPOActor(
            PPOActorConfig(
                experiment_name="vlm-e2e",
                trial_name="t",
                init_from_scratch=True,
                dtype="float32",
                gradient_checkpointing=False,
                mesh=MeshConfig(),
                mb_spec=MicroBatchSpec(n_mbs=1),
                optimizer=OptimizerConfig(
                    lr=5e-3, warmup_steps_proportion=0.0, weight_decay=0.0
                ),
                pack_length_quantum=16,
                group_size=group_size,
                ppo_n_minibatches=1,
                adv_norm=NormConfig(
                    mean_level="group", std_level="group", group_size=group_size
                ),
            ),
            model_config=_vlm_cfg(),
        )
        actor.initialize(ft_spec=FinetuneSpec(1, 64, 8))
        try:
            batch["prox_logp"] = actor.compute_logp(batch)
            actor.compute_advantages(batch)
            stats = actor.ppo_update(batch)
            assert np.isfinite(stats[-1]["loss"])
            assert stats[-1]["n_tokens"] > 0
        finally:
            actor.destroy()
    finally:
        client.destroy()
        server.shutdown.set()
