"""Killable recover trainer: the subprocess half of tests/test_recover_e2e.py.

A miniature but REAL async training loop (tiny JaxLMEngine + RemoteJaxEngine
executor against the parent's FakeGenServer) wearing the full ISSUE-15
recovery harness: config-fingerprinted RecoverHandler, per-step atomic
generation dumps, disk weight publishes, fault points.  The parent SIGKILLs
it mid-run (via `kill_trainer_at_step` or `AREAL_FAULT_POINTS=recover_mid_
dump...`), relaunches it with AREAL_RUN_ID incremented, and asserts step
continuity + ledger invariants + the stitched lifecycle trace from the
artifacts this process leaves behind.

Env contract (all paths under the parent's tmpdir):
  AREAL_FAKE_SERVER_ADDR  host:port of the parent-owned fake gen server
  AREAL_RUN_ID            0 for the first launch, +1 per relaunch
  RECOVER_FILEROOT        RecoverConfig.fileroot (checkpoints + recover/)
  RECOVER_STEPS           total global steps the run should reach
  RECOVER_KILL_AT_STEP    optional: SIGKILL self at the END of this step
  AREAL_FAULT_POINTS      optional: e.g. "recover_mid_dump@2:kill"
  RECOVER_STEPS_LOG       steps.jsonl appended one line per completed step
  RECOVER_EVENTS_PATH     telemetry events JSONL, rewritten every step so
                          it survives the SIGKILL
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=1"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from areal_tpu.api.config import (  # noqa: E402
    GenerationHyperparameters,
    InferenceEngineConfig,
    MeshConfig,
    MicroBatchSpec,
    OptimizerConfig,
    RecoverConfig,
    TrainEngineConfig,
)
from areal_tpu.api.io_struct import (  # noqa: E402
    FinetuneSpec,
    StepInfo,
    WeightUpdateMeta,
)
from areal_tpu.engine.jax_remote import RemoteJaxEngine  # noqa: E402
from areal_tpu.engine.sft import JaxLMEngine  # noqa: E402
from areal_tpu.models.model_config import tiny_config  # noqa: E402
from areal_tpu.utils import telemetry  # noqa: E402
from areal_tpu.utils.dataloader import StatefulDataLoader  # noqa: E402
from areal_tpu.utils.faults import fault_point, kill_trainer_at_step  # noqa: E402
from areal_tpu.utils.recover import (  # noqa: E402
    RecoverHandler,
    check_if_recover,
    config_fingerprint,
)
from areal_tpu.workflow.rlvr import RLVRWorkflow  # noqa: E402

BATCH_SIZE = 4


def _reward(prompt, completion, prompt_ids, completion_ids, **kw):
    return float(len(completion_ids))


def main():
    telemetry.set_enabled(True)
    run_id = int(os.environ.get("AREAL_RUN_ID", 0))
    fileroot = os.environ["RECOVER_FILEROOT"]
    total_steps = int(os.environ["RECOVER_STEPS"])
    kill_at = int(os.environ.get("RECOVER_KILL_AT_STEP", -1))
    steps_log = os.environ["RECOVER_STEPS_LOG"]
    events_path = os.environ["RECOVER_EVENTS_PATH"]

    engine = JaxLMEngine(
        TrainEngineConfig(
            experiment_name="recover-e2e", trial_name="t",
            init_from_scratch=True, dtype="float32",
            gradient_checkpointing=False, mesh=MeshConfig(),
            mb_spec=MicroBatchSpec(), pack_length_quantum=16,
            optimizer=OptimizerConfig(lr=1e-2, warmup_steps_proportion=0.0),
        ),
        model_config=tiny_config(vocab_size=128, qkv_bias=True,
                                 hf_architecture="Qwen2ForCausalLM"),
    )
    engine.initialize(ft_spec=FinetuneSpec(1, 64, BATCH_SIZE))

    client = RemoteJaxEngine(InferenceEngineConfig(
        experiment_name="recover-e2e", trial_name="t",
        consumer_batch_size=BATCH_SIZE,
        max_concurrent_rollouts=BATCH_SIZE * 2,
        max_head_offpolicyness=4,
        request_timeout=30,
    ))
    client.initialize(addr=os.environ["AREAL_FAKE_SERVER_ADDR"])

    meta = WeightUpdateMeta.from_disk("recover-e2e", "t", fileroot)
    dataset = [{"input_ids": [i % 32], "query_id": str(i)} for i in range(64)]
    dataloader = StatefulDataLoader(dataset, batch_size=BATCH_SIZE, seed=0)
    workflow = RLVRWorkflow(
        reward_fn=_reward,
        gconfig=GenerationHyperparameters(max_new_tokens=8),
    )

    rcfg = RecoverConfig(mode="fault", experiment_name="recover-e2e",
                         trial_name="t", fileroot=fileroot)
    recover = RecoverHandler(rcfg, fingerprint=config_fingerprint(
        {"model": "tiny128", "batch_size": BATCH_SIZE, "lr": 1e-2}
    ))
    start_step = 0
    if check_if_recover(rcfg, run_id=run_id):
        info = recover.load(
            engine,
            dataloader=dataloader,
            inference_engine=client,
            weight_update_meta=meta,
        )
        if info is not None:
            start_step = info.recover_start.global_step

    if kill_at >= start_step:
        kill_trainer_at_step(kill_at, start_step)

    try:
        for global_step in range(start_step, total_steps):
            batch = client.prepare_batch(dataloader, workflow=workflow)
            engine.train_lm({
                "input_ids": np.asarray(batch["input_ids"]),
                "attention_mask": np.asarray(batch["attention_mask"]),
                "loss_mask": np.asarray(batch["loss_mask"], np.float32),
            })
            version = global_step + 1
            engine.set_version(version)
            engine.update_weights(meta)
            client.update_weights(meta)
            client.set_version(version)

            step_info = StepInfo(
                epoch=0, epoch_step=global_step, global_step=global_step,
                steps_per_epoch=total_steps,
            )
            recover.dump(engine, step_info, dataloader=dataloader,
                         inference_engine=client)

            stat = client.executor.staleness_manager.get_stats()
            line = {
                "run_id": run_id,
                "global_step": global_step,
                "version": version,
                "ledger": {
                    "submitted": stat.submitted, "accepted": stat.accepted,
                    "rejected": stat.rejected, "running": stat.running,
                },
                "ledger_ok": (
                    stat.submitted
                    == stat.accepted + stat.rejected + stat.running
                    and stat.running >= 0
                ),
            }
            with open(steps_log, "a") as f:
                f.write(json.dumps(line) + "\n")
                f.flush()
                os.fsync(f.fileno())
            # rewrite (not append) the full ring each step: the file must be
            # intact at whatever step the SIGKILL lands
            telemetry.EVENTS.dump_jsonl(events_path)
            print(f"run{run_id} step {global_step} done", flush=True)
            fault_point("train_step")
    finally:
        client.destroy()
    print(f"DONE run{run_id}", flush=True)


if __name__ == "__main__":
    main()
