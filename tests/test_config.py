import yaml

from areal_tpu.api.config import (
    GRPOConfig,
    GenerationHyperparameters,
    SFTConfig,
    load_expr_config,
    save_config,
    to_dict,
)


def test_load_defaults():
    cfg, _ = load_expr_config([], GRPOConfig)
    assert cfg.actor.optimizer.lr == 2e-5
    assert cfg.gconfig.n_samples == 1
    assert cfg.async_training


def test_yaml_plus_overrides(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text(
        yaml.safe_dump(
            {
                "experiment_name": "exp1",
                "trial_name": "t0",
                "actor": {"path": "/models/qwen", "group_size": 8},
                "gconfig": {"max_new_tokens": 128},
            }
        )
    )
    cfg, path = load_expr_config(
        [
            "--config", str(p),
            "actor.optimizer.lr=1e-6",
            "gconfig.temperature=0.7",
            "rollout.max_head_offpolicyness=4",
            "async_training=false",
        ],
        GRPOConfig,
    )
    assert path == str(p)
    assert cfg.actor.path == "/models/qwen"
    assert cfg.actor.group_size == 8
    assert cfg.actor.optimizer.lr == 1e-6
    assert cfg.gconfig.temperature == 0.7
    assert cfg.rollout.max_head_offpolicyness == 4
    assert cfg.async_training is False
    # experiment/trial names propagate into nested configs
    assert cfg.actor.experiment_name == "exp1"
    assert cfg.rollout.trial_name == "t0"
    assert cfg.saver.fileroot == cfg.cluster.fileroot


def test_unknown_key_rejected(tmp_path):
    p = tmp_path / "cfg.yaml"
    p.write_text("bogus_key: 1\n")
    try:
        load_expr_config(["--config", str(p)], SFTConfig)
        raise AssertionError("should have raised")
    except ValueError as e:
        assert "bogus_key" in str(e)


def test_roundtrip_save(tmp_path):
    cfg, _ = load_expr_config(["actor.group_size=4"], GRPOConfig)
    out = tmp_path / "saved.yaml"
    save_config(cfg, str(out))
    cfg2, _ = load_expr_config(["--config", str(out)], GRPOConfig)
    assert to_dict(cfg) == to_dict(cfg2)


def test_gconfig_new():
    g = GenerationHyperparameters(max_new_tokens=10)
    g2 = g.new(temperature=0.1)
    assert g2.max_new_tokens == 10 and g2.temperature == 0.1
    assert g.temperature == 1.0


def test_flag_style_override_rejected():
    try:
        load_expr_config(["--actor.lr=1e-6"], GRPOConfig)
        raise AssertionError("should have raised")
    except ValueError as e:
        assert "actor.lr" in str(e)


def test_ignore_unknown_top_level_only(tmp_path):
    """Launchers parse experiment configs leniently at the TOP level (an
    example-specific section like PPOConfig's `critic` must not fail the
    launch) while nested typos still error loudly."""
    import pytest

    p = tmp_path / "cfg.yaml"
    p.write_text(
        "experiment_name: e\ntrial_name: t\n"
        "critic:\n  value_eps_clip: 0.2\n"   # unknown to GRPOConfig
        "actor:\n  group_size: 4\n"
    )
    cfg, _ = load_expr_config(
        ["--config", str(p)], GRPOConfig, ignore_unknown_top=True
    )
    assert cfg.actor.group_size == 4

    # strict callers (the entry points) still reject the same file
    with pytest.raises(ValueError, match="critic"):
        load_expr_config(["--config", str(p)], GRPOConfig)

    # nested typos fail even in lenient mode
    p2 = tmp_path / "cfg2.yaml"
    p2.write_text("experiment_name: e\ntrial_name: t\nactor:\n  grp_size: 4\n")
    with pytest.raises(ValueError, match="grp_size"):
        load_expr_config(["--config", str(p2)], GRPOConfig,
                         ignore_unknown_top=True)


def test_build_cmd_plumbs_role_host_tier_and_parallel_flags():
    """Regression (ISSUE 18 / C10 config-plumbing): the PR-16/17 knobs
    (role split, host-DRAM tier, expert parallelism) must flow
    GenServerConfig -> build_cmd -> gen/server.py argparse; until this PR
    build_cmd silently dropped all four, so every launcher-started server
    came up colocated with the host tier off."""
    from areal_tpu.api.config import GenServerConfig, MeshConfig

    cfg = GenServerConfig(
        model_path="/m",
        role="decode",
        host_offload=True,
        host_cache_mb=128,
        mesh=MeshConfig(tensor_parallel_size=2, expert_parallel_size=4),
    )
    cmd = GenServerConfig.build_cmd(cfg, host="h", port=1234)
    assert "--role=decode" in cmd
    assert "--host-offload" in cmd
    assert "--host-cache-mb=128" in cmd
    assert "--tp=2" in cmd
    assert "--ep=4" in cmd
    # defaults stay flagless: gen/server.py's argparse defaults are
    # authoritative for the colocated case
    default_cmd = GenServerConfig.build_cmd(
        GenServerConfig(model_path="/m"), host="h", port=0
    )
    assert "--role" not in default_cmd
    assert "--host-offload" not in default_cmd
    assert "--host-cache-mb" not in default_cmd
