from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.models.transformer import (
    forward,
    init_params,
    param_partition_specs,
)

__all__ = [
    "TransformerConfig",
    "forward",
    "init_params",
    "param_partition_specs",
]
