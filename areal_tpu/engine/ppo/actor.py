"""PPO/GRPO actor.

Behavioral counterpart of the reference's `PPOActor`
(areal/engine/ppo/actor.py:25): compute_logp (:52), compute_advantages (:72 —
reward scale/clip/norm, KL-regularized token rewards, GAE, group
normalisation) and ppo_update (:166 — dynamic sampling, minibatch splitting,
stats).  TPU-first differences:

- GAE runs as the reverse `lax.scan` kernel (areal_tpu/ops/gae.py), jitted
  over the whole padded batch — replacing both the reference's CUDA `cugae`
  and its python fallback loop.
- Alignment convention: trajectories arrive token-aligned (arr[t] describes
  token t, the workflow/inference convention); losses consume
  predictor-aligned arrays (arr[t] describes token t+1).
  `compute_advantages` performs that shift ONCE, explicitly — everything it
  writes back (advantages, logprobs, prox_logp, loss_mask) is
  predictor-aligned, matching what `grpo_loss_fn` and `engine.forward`'s
  logprob hook produce.
"""

import functools
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from areal_tpu.api.config import NormConfig, PPOActorConfig
from areal_tpu.engine.jax_train import JaxTrainEngine
from areal_tpu.ops.functional import grpo_loss_fn
from areal_tpu.ops.gae import gae_padded
from areal_tpu.utils import logging, stats
from areal_tpu.utils.data import Normalization, split_padded_tensor_dict_into_mb_list

# jitted once per (shape, gamma, lam): eager execution would pay a device
# round-trip per op, which dominates on tunneled TPU runtimes
_gae_padded_jit = jax.jit(gae_padded, static_argnums=(3, 4))

logger = logging.getLogger("ppo.actor")


def _roll_back(arr: np.ndarray) -> np.ndarray:
    """token-aligned [B, L] -> predictor-aligned (arr[t] <- arr[t+1])."""
    return np.roll(arr, -1, axis=-1)


class PPOActor:
    """Algorithm layer over any TrainEngine (reference: actor.py:25)."""

    # batch keys forwarded into the jitted loss; recipe subclasses extend
    LOSS_KEYS = (
        "input_ids", "attention_mask", "loss_mask", "logprobs",
        "advantages", "prox_logp",
    )
    # sum-reduced loss stats normalised to per-token means after each step
    PER_TOKEN_STAT_KEYS = (
        "importance_weight", "approx_kl", "clip_ratio", "dual_clip_ratio",
        "behave_kl", "behave_imp_weight", "entropy", "new_logp", "old_logp",
        "moe_aux_loss",
    )

    def __init__(self, config: PPOActorConfig, engine):
        self.config = config
        self.engine = engine
        self._pending_stats: List[stats.PendingTrainStats] = []
        def make_norm(norm_cfg):
            if norm_cfg is None:
                return None
            # NormConfig.group_size overrides when set; default to the GRPO
            # group size so the common case needs no duplication
            return Normalization(
                mean_level=norm_cfg.mean_level,
                std_level=norm_cfg.std_level,
                group_size=(
                    norm_cfg.group_size
                    if norm_cfg.group_size > 1
                    else config.group_size
                ),
                eps=norm_cfg.eps,
            )

        self.adv_norm = make_norm(config.adv_norm)
        # explicit NormConfig wins: the recipe variants shape rewards
        # differently (dr.grpo removes the std division entirely, lite_ppo
        # uses group mean + batch std); group_reward_norm is the legacy
        # group/group switch
        self.reward_norm = make_norm(
            config.reward_norm
            or (NormConfig() if config.group_reward_norm else None)
        )

    # ------------------------------------------------------------------

    def compute_logp(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        """Recompute current-policy logprobs (predictor-aligned [B, L]);
        the proximal policy of the decoupled objective."""
        return self.engine.forward(batch, post_hook=self._get_logp_hook())

    def _get_logp_hook(self):
        """The logp post-hook, built once — the jitted forward is keyed on
        the callable's identity, so compute_logp and warm_shapes must hand
        the engine the SAME object."""
        if not hasattr(self, "_logp_hook"):
            temp = self.config.temperature
            vchunk = getattr(self.config, "lm_head_chunk", 0) or None

            def hook(model_out, mb):
                import jax.numpy as jnp

                from areal_tpu.ops.functional import lm_logprobs_entropy

                labels = jnp.roll(mb["input_ids"], -1, axis=-1)
                logp, _, _ = lm_logprobs_entropy(
                    model_out, labels, temperature=temp, with_entropy=False,
                    vocab_chunk=vchunk,
                )
                return logp

            self._logp_hook = hook
        return self._logp_hook

    # ------------------------------------------------------------------

    def compute_advantages(self, batch: Dict[str, np.ndarray]) -> None:
        """In-place: add predictor-aligned advantages/logprobs/loss_mask
        (reference: actor.py:72-165)."""
        cfg = self.config
        mask_tok = batch["loss_mask"].astype(np.float32)  # token-aligned
        B, L = mask_tok.shape

        # ---- sequence-level reward shaping (reference: actor.py:80-118)
        rewards = batch["rewards"].astype(np.float32).copy()
        seq_lens_completion = mask_tok.sum(-1)
        if cfg.mask_no_eos_with_zero and "no_eos" in batch:
            rewards = np.where(batch["no_eos"].astype(bool), 0.0, rewards)
        if cfg.overlong_reward_penalty and cfg.overlong_tokens > 0:
            # DAPO soft length penalty measured against the *configured*
            # generation budget, not the batch's padded width (reference:
            # actor.py:84-89 uses max_new_tokens)
            if cfg.max_new_tokens <= 0:
                raise ValueError(
                    "overlong_reward_penalty requires max_new_tokens to be "
                    "set to the rollout's generation budget"
                )
            overflow = seq_lens_completion - (cfg.max_new_tokens - cfg.overlong_tokens)
            penalty = np.clip(
                overflow / cfg.overlong_tokens, 0.0, 1.0
            ) * cfg.overlong_penalty_factor
            rewards = rewards - penalty
        rewards = (rewards + cfg.reward_bias) * cfg.reward_scaling
        rewards = np.clip(rewards, -cfg.reward_clip, cfg.reward_clip)
        if self.reward_norm is not None:
            rewards = self.reward_norm(rewards[:, None])[:, 0]

        # ---- shift to predictor alignment
        mask = _roll_back(mask_tok)
        mask[:, -1] = 0.0
        prox_logp = batch.get("prox_logp")  # already predictor-aligned
        if prox_logp is not None and not cfg.use_decoupled_loss:
            # plain PPO with a recompute pass: the ratio must be taken
            # against the recomputed policy, so the recomputed logprobs
            # replace the inference engine's (reference: actor.py:103-106)
            old_logp = np.asarray(prox_logp, np.float32) * mask
        else:
            old_logp = _roll_back(batch["logprobs"].astype(np.float32)) * mask

        # ---- token rewards: KL penalty + terminal reward (actor.py:119-135)
        tok_rewards = np.zeros((B, L), np.float32)
        if cfg.kl_ctl > 0 and "ref_logp" in batch:
            ref = _roll_back(batch["ref_logp"].astype(np.float32)) * mask
            from areal_tpu.utils.data import KLEstimator

            kl = KLEstimator(cfg.kl_estimator)(old_logp, ref)
            tok_rewards -= cfg.kl_ctl * kl * mask
        # terminal reward at the last predictor position of each sequence
        idx = np.maximum(mask.shape[1] - 1 - np.argmax(mask[:, ::-1], axis=1), 0)
        has_completion = mask.sum(-1) > 0
        tok_rewards[np.arange(B), idx] += np.where(has_completion, rewards, 0.0)

        # ---- GAE (values default 0: GRPO / reward-to-go)
        # values are NOT rolled: the critic head's output at position t is
        # V(prefix through token t) = the state before emitting token t+1,
        # which is already predictor alignment — rolling would train the
        # critic one step shifted
        values = batch.get("values")
        values = (
            values.astype(np.float32) * mask
            if values is not None
            else np.zeros((B, L), np.float32)
        )
        adv, returns = _gae_padded_jit(
            tok_rewards, values, mask, cfg.discount, cfg.gae_lambda
        )
        adv, returns = jax.device_get((adv, returns))
        if self.adv_norm is not None:
            adv = self.adv_norm(adv, mask)

        batch["advantages"] = adv.astype(np.float32)
        batch["returns"] = returns.astype(np.float32)
        batch["logprobs"] = old_logp.astype(np.float32)
        batch["loss_mask"] = mask.astype(np.float32)
        batch["tot_rewards"] = rewards.astype(np.float32)
        if prox_logp is None and cfg.use_decoupled_loss:
            # without a recompute pass, proximal == behaviour policy
            batch["prox_logp"] = old_logp.astype(np.float32)

    # ------------------------------------------------------------------

    def _dynamic_filter(self, batch: Dict[str, np.ndarray]) -> Optional[np.ndarray]:
        """Drop groups whose rewards are all identical — zero advantage,
        zero gradient (reference: actor.py dynamic sampling)."""
        g = self.config.group_size
        r = batch["rewards"].astype(np.float32)
        B = r.shape[0]
        if g <= 1 or B % g != 0:
            return None
        groups = r.reshape(-1, g)
        keep_group = ~np.all(np.isclose(groups, groups[:, :1]), axis=1)
        keep = np.repeat(keep_group, g)
        if keep.all():
            return None
        if not keep.any():
            logger.warning("dynamic sampling rejected every group; keeping all")
            return None
        return np.nonzero(keep)[0]

    def ppo_update(self, batch: Dict[str, np.ndarray]) -> List[Dict[str, float]]:
        cfg = self.config
        if cfg.dynamic_sampling:
            keep = self._dynamic_filter(batch)
            if keep is not None:
                from areal_tpu.utils.data import select_rows

                batch = select_rows(batch, keep)

        # consumption evidence must be taken HERE, on the post-filter batch:
        # the LOSS_KEYS view below drops `versions`/`trace_keys`, so the
        # engine-level hook inside train_batch never sees them on this path
        if hasattr(self.engine, "_consume_telemetry"):
            batch = self.engine._consume_telemetry(batch)
        train_view = {k: batch[k] for k in self.LOSS_KEYS if k in batch}
        mbs = split_padded_tensor_dict_into_mb_list(
            train_view, n_mbs=cfg.ppo_n_minibatches
        )
        all_stats = []
        for mb in mbs.mbs:
            all_stats.append(self._train_one_mb(mb))
        return all_stats

    def flush_stats(self) -> None:
        """Materialise every deferred stats fetch (async_stats mode); call
        before reading the tracker/logging so commits are complete."""
        for st in self._pending_stats:
            st.materialize()
        self._pending_stats.clear()

    def warm_shapes(self, shapes) -> None:
        """Precompile the PPO step programs for packed-batch shape
        signatures, side-effect-free.

        RL rollout lengths vary step to step, so the packer's
        (rows, row_len) signature varies, and under jit each new signature
        is a fresh XLA compile that otherwise lands INSIDE the training
        loop (a torch-eager reference never sees this class of stall).
        The shape space is already log-bounded (pow-2 row buckets x the
        pack_length_quantum ladder, utils/data.py pack_into_rows); this
        walks it up front through the REAL packer + jit plumbing, so the
        compiled programs are exactly the ones the live loop will request.

        Compilation is AOT (`jit.lower(...).compile()` via the engine's
        precompile_* methods): nothing executes, nothing is donated, no
        state changes — warming is exactly free of side effects.

        shapes: iterable of (n_sequences, seq_len) pairs; each warms the
        signature the packer produces for n full rows of seq_len.
        n_sequences must respect the group-norm group size.
        """
        eng = self.engine
        rng = np.random.default_rng(0)
        # validate against the RESOLVED normalization groups (NormConfig
        # group_size defaults to 1 and is overridden by config.group_size
        # in __init__ — the raw config field is not what group_view asserts)
        g = 1
        for norm in (self.adv_norm, self.reward_norm):
            if norm is not None:
                g = max(g, norm.group_size)
        if not hasattr(self, "_loss_fn"):
            self._loss_fn = self._build_loss_fn()
        for n_seqs, seq_len in shapes:
            if n_seqs % g:
                raise ValueError(
                    f"warm shape n_sequences={n_seqs} must be divisible by "
                    f"the adv-norm group size {g}"
                )
            V = eng.model_config.vocab_size
            prompt = max(1, seq_len // 4)
            loss_mask = np.zeros((n_seqs, seq_len), np.float32)
            loss_mask[:, prompt:] = 1.0
            batch = {
                "input_ids": rng.integers(0, V, (n_seqs, seq_len)).astype(
                    np.int32),
                "attention_mask": np.ones((n_seqs, seq_len), bool),
                "loss_mask": loss_mask,
                "logprobs": rng.normal(-1.0, 0.1, (n_seqs, seq_len)).astype(
                    np.float32),
                "rewards": rng.integers(0, 2, n_seqs).astype(np.float32),
            }
            if self.config.recompute_logprob:
                eng.precompile_forward(batch,
                                       post_hook=self._get_logp_hook())
            # advantages run host/numpy-side (plus a tiny gae program):
            # executing them is cheap, touches no engine state, and yields
            # the exact key-set ppo_update's loss view needs
            batch["prox_logp"] = batch["logprobs"].copy()
            self.compute_advantages(batch)
            train_view = {k: batch[k] for k in self.LOSS_KEYS if k in batch}
            mbs = split_padded_tensor_dict_into_mb_list(
                train_view, n_mbs=self.config.ppo_n_minibatches
            )
            for mb in mbs.mbs:
                eng.precompile_train_batch(mb, self._loss_fn)

    def _build_loss_fn(self):
        """The cached grpo loss partial (built ONCE: the compiled step is
        keyed on the callable's identity)."""
        cfg = self.config
        return functools.partial(
            grpo_loss_fn,
            eps_clip=cfg.eps_clip,
            c_clip=cfg.c_clip,
            behav_imp_weight_cap=cfg.behav_imp_weight_cap,
            temperature=cfg.temperature,
            use_decoupled_loss=cfg.use_decoupled_loss,
            eps_clip_higher=cfg.eps_clip_higher,
            # plumbed fused-head chunk width (0/unset -> env default);
            # baked into the partial so the bench ladder's sweep value
            # reaches the compiled step, not just the config dataclass
            vocab_chunk=getattr(cfg, "lm_head_chunk", 0) or None,
        )

    def _train_one_mb(self, mb: Dict[str, np.ndarray]):
        """One train_batch + stat normalisation + tracker commit — shared
        with VLM/recipe actors so their stats cannot drift from the base.

        With `async_stats` the engine returns a PendingTrainStats; the
        normalisation/commit below runs when the stats materialise, so the
        next step's dispatch is never blocked on this one's scalars."""
        if not hasattr(self, "_loss_fn"):
            self._loss_fn = self._build_loss_fn()
        st = self.engine.train_batch(
            mb,
            self._loss_fn,
            loss_weight_fn=lambda b: float(np.sum(b["loss_mask"])),
        )
        if isinstance(st, stats.PendingTrainStats):
            st.then(self._finalize_mb_stats)
            # registered here (the one chokepoint) so flush_stats always
            # covers every pending fetch, whichever actor path dispatched it
            self._pending_stats.append(st)
            return st
        return self._finalize_mb_stats(st)

    def _finalize_mb_stats(self, st: Dict[str, float]) -> Dict[str, float]:
        n = max(st.pop("n_valid_tokens", 1.0), 1.0)
        for k in self.PER_TOKEN_STAT_KEYS:
            if k in st:
                st[k] = st[k] / n
        st["n_tokens"] = n
        with stats.DEFAULT_TRACKER.scope("ppo_actor"):
            stats.DEFAULT_TRACKER.scalar(**{
                k: v for k, v in st.items() if np.isscalar(v)
            })
        return st


class JaxPPOActor(JaxTrainEngine):
    """JaxTrainEngine + PPOActor algorithm surface, mirroring the
    reference's FSDPPPOActor (actor.py:278)."""

    def __init__(self, config: PPOActorConfig, model_config=None):
        super().__init__(config, model_config)
        self.actor = PPOActor(config, self)

    def compute_logp(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        return self.actor.compute_logp(batch)

    def compute_advantages(self, batch: Dict[str, np.ndarray]) -> None:
        self.actor.compute_advantages(batch)

    def ppo_update(self, batch: Dict[str, np.ndarray]) -> List[Dict[str, float]]:
        return self.actor.ppo_update(batch)

    def warm_shapes(self, shapes) -> None:
        self.actor.warm_shapes(shapes)

    def flush_stats(self) -> None:
        self.actor.flush_stats()
