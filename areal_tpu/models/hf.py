"""HF checkpoint <-> param-pytree conversion.

Capability counterpart of the reference's HF interop: lite loads via
transformers AutoModelForCausalLM (areal/engine/base_hf_engine.py:46) and
saves full state dicts (areal/engine/fsdp_engine.py:228-254); legacy keeps
per-arch name maps (realhf/api/from_hf/{llama,qwen2,qwen3,mistral}.py).

TPU-first: weights stream shard-by-shard from safetensors into numpy buffers
stacked over the layer axis (our scan layout), never materialising a torch
model.  Saving emits HF-format safetensors + config.json so any HF-ecosystem
inference server (and our generation engine) can reload them — this is the
"disk" weight-update path (reference: fsdp_engine.py:403-425).
"""

import json
import os
import re
import shutil
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from areal_tpu.models.model_config import TransformerConfig
from areal_tpu.utils import logging

logger = logging.getLogger("models.hf")

_LAYER_RE = re.compile(r"model\.layers\.(\d+)\.(.+)")

# our (path-in-layer, transpose?) for each HF per-layer suffix
_LAYER_MAP = {
    "self_attn.q_proj.weight": (("attn", "wq"), True),
    "self_attn.k_proj.weight": (("attn", "wk"), True),
    "self_attn.v_proj.weight": (("attn", "wv"), True),
    "self_attn.o_proj.weight": (("attn", "wo"), True),
    "self_attn.q_proj.bias": (("attn", "bq"), False),
    "self_attn.k_proj.bias": (("attn", "bk"), False),
    "self_attn.v_proj.bias": (("attn", "bv"), False),
    "self_attn.q_norm.weight": (("attn", "q_norm"), False),
    "self_attn.k_norm.weight": (("attn", "k_norm"), False),
    "mlp.gate_proj.weight": (("mlp", "w_gate"), True),
    "mlp.up_proj.weight": (("mlp", "w_up"), True),
    "mlp.down_proj.weight": (("mlp", "w_down"), True),
    "input_layernorm.weight": (("input_norm",), False),
    "post_attention_layernorm.weight": (("post_attn_norm",), False),
}


def layer_name_map(cfg: TransformerConfig) -> Dict[str, Tuple[Tuple[str, ...], bool]]:
    """Per-layer HF-name map for a config.  The gemma2 sandwich layout
    renames the norms: its post_attention_layernorm normalises the attention
    OUTPUT (our sandwich_attn_norm) while pre_feedforward_layernorm is the
    pre-FFN norm every other family calls post_attention_layernorm.

    gpt2 is its own dialect: Conv1D weights already store [in, out] (no
    transpose), LayerNorms carry biases, the MLP is non-gated, and the
    fused attn.c_attn qkv is handled separately in state_to_params."""
    if cfg.hf_architecture == "GPT2LMHeadModel":
        return {
            "ln_1.weight": (("input_norm",), False),
            "ln_1.bias": (("input_norm_b",), False),
            "attn.c_proj.weight": (("attn", "wo"), False),
            "attn.c_proj.bias": (("attn", "bo"), False),
            "ln_2.weight": (("post_attn_norm",), False),
            "ln_2.bias": (("post_attn_norm_b",), False),
            "mlp.c_fc.weight": (("mlp", "w_up"), False),
            "mlp.c_fc.bias": (("mlp", "b_up"), False),
            "mlp.c_proj.weight": (("mlp", "w_down"), False),
            "mlp.c_proj.bias": (("mlp", "b_down"), False),
        }
    m = dict(_LAYER_MAP)
    if cfg.sandwich_norms:
        m["post_attention_layernorm.weight"] = (("sandwich_attn_norm",), False)
        m["pre_feedforward_layernorm.weight"] = (("post_attn_norm",), False)
        m["post_feedforward_layernorm.weight"] = (("sandwich_ffn_norm",), False)
    return m

# vision tower (models/vision.py tree) <-> "visual."-prefixed names in the
# REAL Qwen2.5-VL checkpoint convention (RMSNorm norm1/norm2, biased
# qkv/proj + gated mlp, merger.ln_q + merger.mlp.{0,2}); weights store
# [in, out], HF linears [out, in].  patch_embed.proj is a Conv3d
# [D, C, tps, ps, ps] reshaped to the tower's [patch_dim, D] matmul.
_VISION_RE = re.compile(r"visual\.blocks\.(\d+)\.(.+)")
_VISION_LAYER_MAP = {
    "norm1.weight": (("input_norm",), False),
    "attn.qkv.weight": (("wqkv",), True),
    "attn.qkv.bias": (("b_qkv",), False),
    "attn.proj.weight": (("wo",), True),
    "attn.proj.bias": (("b_o",), False),
    "norm2.weight": (("post_attn_norm",), False),
    "mlp.up_proj.weight": (("w_up",), True),
    "mlp.up_proj.bias": (("b_up",), False),
    "mlp.gate_proj.weight": (("w_gate",), True),
    "mlp.gate_proj.bias": (("b_gate",), False),
    "mlp.down_proj.weight": (("w_down",), True),
    "mlp.down_proj.bias": (("b_down",), False),
}
# MoE per-layer names: qwen-MoE (mlp.experts.N.*_proj + mlp.gate router)
# and mixtral (block_sparse_moe.experts.N.w{1,2,3} + block_sparse_moe.gate)
_MOE_EXPERT_RE = re.compile(
    r"(?:mlp|block_sparse_moe)\.experts\.(\d+)\.(gate_proj|up_proj|down_proj|w1|w2|w3)\.weight"
)
_MOE_ROUTER_NAMES = ("mlp.gate.weight", "block_sparse_moe.gate.weight")
_MOE_LEAF = {
    "gate_proj": "w_gate", "up_proj": "w_up", "down_proj": "w_down",
    "w1": "w_gate", "w3": "w_up", "w2": "w_down",
}

# read-only aliases: this repo's pre-r3 checkpoints used short mlp names
_VISION_LAYER_ALIASES = {
    "mlp.up.weight": (("w_up",), True),
    "mlp.gate.weight": (("w_gate",), True),
    "mlp.down.weight": (("w_down",), True),
}
_VISION_TOP_MAP = {  # name -> (key, transpose)
    "visual.merger.ln_q.weight": ("merger_norm", False),
    "visual.merger.mlp.0.weight": ("merger_fc1", True),
    "visual.merger.mlp.0.bias": ("merger_fc1_b", False),
    "visual.merger.mlp.2.weight": ("merger_fc2", True),
    "visual.merger.mlp.2.bias": ("merger_fc2_b", False),
}
_VISION_TOP_ALIASES = {
    "visual.patch_embed.weight": ("patch_embed", False),
    "visual.merger.ln.weight": ("merger_norm", False),
    "visual.merger.fc1.weight": ("merger_fc1", True),
    "visual.merger.fc2.weight": ("merger_fc2", True),
}


def _set_nested(tree: Dict, path: Tuple[str, ...], value):
    for p in path[:-1]:
        tree = tree.setdefault(p, {})
    tree[path[-1]] = value


def _get_nested(tree: Dict, path: Tuple[str, ...]):
    for p in path:
        tree = tree[p]
    return tree


def iter_safetensors(path: str) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield (name, numpy array) over all safetensors shards in a dir."""
    from safetensors import safe_open

    if os.path.isfile(path):
        files = [path]
    else:
        files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith(".safetensors")
        )
    if not files:
        raise FileNotFoundError(f"no .safetensors under {path}")
    for f in files:
        with safe_open(f, framework="np") as sf:
            for name in sf.keys():
                yield name, sf.get_tensor(name)


def state_to_params(
    items: Iterator[Tuple[str, np.ndarray]],
    cfg: TransformerConfig,
    dtype: str = "float32",
) -> Dict[str, Any]:
    """HF-named (name, array) pairs -> scan-stacked param pytree, with
    completeness validation.  Shared by checkpoint loading and the
    streamed weight-update path (gen/server.py /update_weights_chunk)."""
    L = cfg.num_layers
    np_dtype = np.dtype(dtype)
    lmap = layer_name_map(cfg)
    params: Dict[str, Any] = {"layers": {}}
    fill_count: Dict[Tuple[str, ...], int] = {}
    # expected writes per path: L for dense leaves, L*E for expert stacks
    fill_expected: Dict[Tuple[str, ...], int] = {}

    def layer_buf(path_in_layer: Tuple[str, ...], shape):
        try:
            return _get_nested(params["layers"], path_in_layer)
        except KeyError:
            buf = np.zeros((L, *shape), dtype=np_dtype)
            _set_nested(params["layers"], path_in_layer, buf)
            return buf

    Lv = cfg.vision.num_layers if cfg.vision is not None else 0
    vision: Dict[str, Any] = {"layers": {}}
    vision_fill: Dict[Tuple[str, ...], int] = {}

    def vision_layer_buf(path_in_layer: Tuple[str, ...], shape):
        try:
            return _get_nested(vision["layers"], path_in_layer)
        except KeyError:
            buf = np.zeros((Lv, *shape), dtype=np_dtype)
            _set_nested(vision["layers"], path_in_layer, buf)
            return buf

    gpt2 = cfg.hf_architecture == "GPT2LMHeadModel"
    D = cfg.hidden_size
    seen_head = False
    for name, arr in items:
        arr = np.asarray(arr)  # bf16 arrives as ml_dtypes.bfloat16; the cast-on-assignment into the stacked buffers handles it
        if gpt2:
            # gpt2 dialect: transformer.{wte,wpe,h.N.*,ln_f}; Conv1D
            # weights are already [in, out]
            if name.startswith("transformer."):
                name = name[len("transformer."):]
            if name.endswith((".attn.bias", ".attn.masked_bias")):
                continue  # causal-mask buffers, not weights (c_attn.bias
                # is a real weight and does NOT match the leading dot)
            if name == "wte.weight":
                name = "model.embed_tokens.weight"
            elif name == "wpe.weight":
                params["pos_embedding"] = arr.astype(np_dtype)
                continue
            elif name == "ln_f.weight":
                name = "model.norm.weight"
            elif name == "ln_f.bias":
                params["final_norm_b"] = arr.astype(np_dtype)
                continue
            elif name.startswith("h."):
                name = "model.layers." + name[len("h."):]
            gm = _LAYER_RE.match(name)
            if gm and gm.group(2) in ("attn.c_attn.weight", "attn.c_attn.bias"):
                # fused qkv: split [D, 3D] columns (or [3D] bias) into q/k/v
                idx = int(gm.group(1))
                leaves = (
                    ("attn", "wq"), ("attn", "wk"), ("attn", "wv")
                ) if gm.group(2).endswith("weight") else (
                    ("attn", "bq"), ("attn", "bk"), ("attn", "bv")
                )
                for j, path_in_layer in enumerate(leaves):
                    part = arr[..., j * D:(j + 1) * D]
                    buf = layer_buf(path_in_layer, part.shape)
                    buf[idx] = part
                    fill_count[path_in_layer] = (
                        fill_count.get(path_in_layer, 0) + 1
                    )
                continue
        # newer transformers nest the decoder/tower under model.*
        if name.startswith("model.language_model."):
            name = "model." + name[len("model.language_model."):]
        elif name.startswith("model.visual."):
            name = name[len("model."):]
        if name.startswith("visual."):
            if cfg.vision is None:
                logger.warning("skipping vision weight %s (text-only config)", name)
                continue
            vm = _VISION_RE.match(name)
            if vm:
                idx, suffix = int(vm.group(1)), vm.group(2)
                entry = _VISION_LAYER_MAP.get(suffix) or _VISION_LAYER_ALIASES.get(suffix)
                if entry is None:
                    logger.warning("skipping unmapped weight %s", name)
                    continue
                path_in_layer, transpose = entry
                if transpose:
                    arr = arr.T
                buf = vision_layer_buf(path_in_layer, arr.shape)
                buf[idx] = arr  # assignment casts; no intermediate copy
                vision_fill[path_in_layer] = vision_fill.get(path_in_layer, 0) + 1
            elif name == "visual.patch_embed.proj.weight":
                # Conv3d [D, C, tps, ps, ps] -> matmul [patch_dim, D]
                vision["patch_embed"] = (
                    arr.reshape(arr.shape[0], -1).T.astype(np_dtype)
                )
            elif name in _VISION_TOP_MAP or name in _VISION_TOP_ALIASES:
                key, transpose = (
                    _VISION_TOP_MAP.get(name) or _VISION_TOP_ALIASES[name]
                )
                vision[key] = (arr.T if transpose else arr).astype(np_dtype)
            else:
                logger.warning("skipping unmapped weight %s", name)
            continue
        m = _LAYER_RE.match(name)
        if m:
            idx, suffix = int(m.group(1)), m.group(2)
            if suffix in lmap:
                path_in_layer, transpose = lmap[suffix]
                if transpose:
                    arr = arr.T
                buf = layer_buf(path_in_layer, arr.shape)
                buf[idx] = arr  # assignment casts; no intermediate copy
                fill_count[path_in_layer] = fill_count.get(path_in_layer, 0) + 1
                continue
            if cfg.num_experts > 0:
                em = _MOE_EXPERT_RE.fullmatch(suffix)
                if em:
                    e, leaf = int(em.group(1)), _MOE_LEAF[em.group(2)]
                    path_in_layer = ("moe", leaf)
                    buf = layer_buf(
                        path_in_layer, (cfg.num_experts, *arr.T.shape)
                    )
                    buf[idx, e] = arr.T
                    fill_count[path_in_layer] = (
                        fill_count.get(path_in_layer, 0) + 1
                    )
                    fill_expected[path_in_layer] = L * cfg.num_experts
                    continue
                if suffix in _MOE_ROUTER_NAMES:
                    # HF router Linear [E, D] -> ours [D, E]
                    path_in_layer = ("moe", "router")
                    buf = layer_buf(path_in_layer, arr.T.shape)
                    buf[idx] = arr.T
                    fill_count[path_in_layer] = (
                        fill_count.get(path_in_layer, 0) + 1
                    )
                    continue
            logger.warning("skipping unmapped weight %s", name)
        elif name == "model.embed_tokens.weight":
            params["embedding"] = arr.astype(np_dtype)
        elif name == "model.norm.weight":
            params["final_norm"] = arr.astype(np_dtype)
        elif name == "lm_head.weight":
            params["lm_head"] = arr.T.astype(np_dtype)
            seen_head = True
        else:
            logger.warning("skipping unmapped weight %s", name)
    for path_in_layer, n in fill_count.items():
        want = fill_expected.get(path_in_layer, L)
        if n != want:
            raise ValueError(
                f"incomplete weights: {'.'.join(path_in_layer)} filled for "
                f"{n}/{want} slots"
            )
    required = ["embedding", "final_norm"]
    if cfg.pos_emb == "learned":
        required.append("pos_embedding")
    if cfg.norm_type == "layernorm":
        required.append("final_norm_b")
    for req in required:
        if req not in params:
            raise ValueError(f"checkpoint missing {req}")
    if cfg.tie_word_embeddings and seen_head:
        del params["lm_head"]
    if not cfg.tie_word_embeddings and not seen_head:
        raise ValueError("untied config but checkpoint has no lm_head.weight")
    if vision_fill or "patch_embed" in vision:
        problems = [
            f"{'.'.join(p)} filled {n}/{Lv} layers"
            for p, n in vision_fill.items()
            if n != Lv
        ] + [
            f"missing visual {req}"
            for req in ("patch_embed", "merger_norm", "merger_fc1", "merger_fc2")
            if req not in vision
        ]
        if problems:
            # unmappable tower (e.g. Qwen2-VL's LayerNorm/fc1-fc2 blocks vs
            # this tree's RMSNorm/gated layout): degrade to a text-only
            # load — the text weights are still valuable — instead of
            # failing the whole checkpoint.  (Unmapped EXTRA visual leaves
            # alone are not fatal: the tower loads if its own tree filled.)
            logger.warning(
                "visual.* tree unmappable (%s); loading TEXT-ONLY — the "
                "vision tower will be randomly initialised",
                "; ".join(problems),
            )
        else:
            params["vision"] = vision
    return params


def load_hf_params(
    path: str,
    cfg: Optional[TransformerConfig] = None,
    dtype: str = "float32",
) -> Tuple[Dict[str, Any], TransformerConfig]:
    """Load an HF checkpoint dir into the scan-stacked param pytree."""
    if cfg is None:
        cfg = TransformerConfig.from_hf(path)
    return state_to_params(iter_safetensors(path), cfg, dtype), cfg


def _gpt2_state(
    params: Dict[str, Any], cfg: TransformerConfig
) -> Iterator[Tuple[str, np.ndarray]]:
    """gpt2-dialect emission: transformer.* names, re-fused c_attn qkv."""
    pre = "transformer."
    yield pre + "wte.weight", np.asarray(params["embedding"])
    yield pre + "wpe.weight", np.asarray(params["pos_embedding"])
    layers = params["layers"]
    lmap = layer_name_map(cfg)
    for i in range(cfg.num_layers):
        p = f"{pre}h.{i}."
        attn = layers["attn"]
        yield p + "attn.c_attn.weight", np.concatenate(
            [np.asarray(attn[leaf][i]) for leaf in ("wq", "wk", "wv")], axis=1
        )
        yield p + "attn.c_attn.bias", np.concatenate(
            [np.asarray(attn[leaf][i]) for leaf in ("bq", "bk", "bv")]
        )
        for suffix, (path_in_layer, _t) in lmap.items():
            yield p + suffix, np.asarray(_get_nested(layers, path_in_layer)[i])
    yield pre + "ln_f.weight", np.asarray(params["final_norm"])
    yield pre + "ln_f.bias", np.asarray(params["final_norm_b"])


def params_to_hf_state(
    params: Dict[str, Any], cfg: TransformerConfig
) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield HF-named (name, array) pairs from the stacked pytree."""
    if cfg.hf_architecture == "GPT2LMHeadModel":
        yield from _gpt2_state(params, cfg)
        return
    yield "model.embed_tokens.weight", np.asarray(params["embedding"])
    layers = params["layers"]
    mixtral = cfg.hf_architecture == "MixtralForCausalLM"
    moe_prefix = "block_sparse_moe" if mixtral else "mlp"
    moe_names = (
        {"w_gate": "w1", "w_up": "w3", "w_down": "w2"}
        if mixtral
        else {"w_gate": "gate_proj", "w_up": "up_proj", "w_down": "down_proj"}
    )
    lmap = layer_name_map(cfg)
    for i in range(cfg.num_layers):
        prefix = f"model.layers.{i}."
        for suffix, (path_in_layer, transpose) in lmap.items():
            try:
                buf = _get_nested(layers, path_in_layer)
            except KeyError:
                continue
            arr = np.asarray(buf[i])
            if transpose:
                arr = arr.T
            yield prefix + suffix, arr
        if "moe" in layers:
            moe = layers["moe"]
            yield (
                f"{prefix}{moe_prefix}.gate.weight",
                np.asarray(moe["router"][i]).T,
            )
            for leaf, hf_leaf in moe_names.items():
                buf = np.asarray(moe[leaf][i])  # [E, D, F] / [E, F, D]
                for e in range(cfg.num_experts):
                    yield (
                        f"{prefix}{moe_prefix}.experts.{e}.{hf_leaf}.weight",
                        buf[e].T,
                    )
    yield "model.norm.weight", np.asarray(params["final_norm"])
    if "lm_head" in params:
        yield "lm_head.weight", np.asarray(params["lm_head"]).T
    elif not cfg.tie_word_embeddings:
        raise ValueError("untied config but params have no lm_head")
    if "vision" in params and cfg.vision is not None:
        vision = params["vision"]
        vc = cfg.vision
        # [patch_dim, D] matmul -> Conv3d [D, C, tps, ps, ps] (the real
        # Qwen2.5-VL layout, so transformers can load our checkpoints)
        yield (
            "visual.patch_embed.proj.weight",
            np.ascontiguousarray(np.asarray(vision["patch_embed"]).T).reshape(
                vc.hidden_size,
                vc.in_channels,
                vc.temporal_patch_size,
                vc.patch_size,
                vc.patch_size,
            ),
        )
        for name, (key, transpose) in _VISION_TOP_MAP.items():
            if key not in vision:
                continue  # pre-r3 trees carry no merger biases
            arr = np.asarray(vision[key])
            yield name, arr.T if transpose else arr
        for i in range(cfg.vision.num_layers):
            for suffix, (path_in_layer, transpose) in _VISION_LAYER_MAP.items():
                try:
                    buf = _get_nested(vision["layers"], path_in_layer)
                except KeyError:
                    continue  # pre-r3 trees carry no block biases
                arr = np.asarray(buf[i])
                yield (
                    f"visual.blocks.{i}.{suffix}",
                    arr.T if transpose else arr,
                )


def save_hf_checkpoint(
    params: Dict[str, Any],
    cfg: TransformerConfig,
    out_dir: str,
    save_dtype: str = "bfloat16",
    max_shard_bytes: int = 4 * 1024**3,
    tokenizer_src: Optional[str] = None,
) -> None:
    """Write an HF-format checkpoint dir (config.json + sharded safetensors
    + weight index), castable to bf16 for serving."""
    import ml_dtypes
    from safetensors.numpy import save_file

    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(cfg.to_hf_dict(), f, indent=2)

    target = np.dtype(ml_dtypes.bfloat16) if save_dtype == "bfloat16" else np.dtype(
        save_dtype
    )
    shards: List[Dict[str, np.ndarray]] = [{}]
    sizes = [0]
    weight_map: Dict[str, str] = {}
    for name, arr in params_to_hf_state(params, cfg):
        # np.asarray over a jax array may be stride-permuted (XLA layout) and
        # transposes are views; safetensors serializes the raw buffer, so the
        # array must be C-contiguous.
        arr = np.ascontiguousarray(arr.astype(target))
        if sizes[-1] + arr.nbytes > max_shard_bytes and shards[-1]:
            shards.append({})
            sizes.append(0)
        shards[-1][name] = arr
        sizes[-1] += arr.nbytes
    n = len(shards)
    for i, shard in enumerate(shards):
        fname = (
            "model.safetensors"
            if n == 1
            else f"model-{i + 1:05d}-of-{n:05d}.safetensors"
        )
        save_file(shard, os.path.join(out_dir, fname))
        for name in shard:
            weight_map[name] = fname
    if n > 1:
        with open(os.path.join(out_dir, "model.safetensors.index.json"), "w") as f:
            json.dump(
                {"metadata": {"total_size": sum(sizes)}, "weight_map": weight_map},
                f,
            )
    if tokenizer_src and os.path.isdir(tokenizer_src):
        for fname in (
            "tokenizer.json",
            "tokenizer_config.json",
            "vocab.json",
            "merges.txt",
            "special_tokens_map.json",
            "generation_config.json",
        ):
            src = os.path.join(tokenizer_src, fname)
            if os.path.exists(src):
                shutil.copy(src, os.path.join(out_dir, fname))
