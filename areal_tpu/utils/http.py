"""Async HTTP with retry (reference: areal/utils/http.py arequest_with_retry)."""

import asyncio
from typing import Any, Dict, Optional

import aiohttp

from areal_tpu.utils import logging

logger = logging.getLogger("http")

def get_default_connector() -> aiohttp.TCPConnector:
    # A fresh connector per session: sessions are created per-request-context
    # on the runner's event loop, and connectors cannot be shared across loops.
    return aiohttp.TCPConnector(limit=0, ttl_dns_cache=300)


class HttpRequestError(RuntimeError):
    pass


async def arequest_with_retry(
    addr: str,
    endpoint: str,
    payload: Optional[Dict[str, Any]] = None,
    method: str = "POST",
    max_retries: int = 3,
    timeout: float = 3600,
    retry_delay: float = 0.5,
    session: Optional[aiohttp.ClientSession] = None,
    data: Optional[bytes] = None,
    headers: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """JSON request (default) or raw-bytes upload (`data` + `headers`)
    with retry/backoff.  `timeout` applies per request even on a shared
    session (aiohttp per-request override)."""
    url = f"http://{addr}{endpoint}"
    last_exc: Optional[BaseException] = None
    owns_session = session is None
    if owns_session:
        session = aiohttp.ClientSession(connector=get_default_connector())
    req_timeout = aiohttp.ClientTimeout(
        total=timeout, sock_connect=min(30, timeout)
    )
    try:
        for attempt in range(max_retries):
            try:
                kwargs: Dict[str, Any] = {"timeout": req_timeout}
                if data is not None:
                    kwargs["data"] = data
                    kwargs["headers"] = {
                        "Content-Type": "application/octet-stream",
                        **(headers or {}),
                    }
                elif method != "GET":
                    kwargs["json"] = payload
                async with session.request(method, url, **kwargs) as resp:
                    if resp.status == 200:
                        ctype = resp.headers.get("Content-Type", "")
                        if "application/json" in ctype:
                            return await resp.json()
                        return {"text": await resp.text()}
                    body = await resp.text()
                    last_exc = HttpRequestError(
                        f"{method} {url} -> HTTP {resp.status}: {body[:200]}"
                    )
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as e:
                last_exc = e
            if attempt < max_retries - 1:
                await asyncio.sleep(retry_delay * (2**attempt))
        raise HttpRequestError(
            f"request to {url} failed after {max_retries} attempts"
        ) from last_exc
    finally:
        if owns_session:
            await session.close()


async def apost_bytes_with_retry(
    addr: str,
    endpoint: str,
    data: bytes,
    headers: Optional[Dict[str, str]] = None,
    max_retries: int = 3,
    timeout: float = 3600,
    retry_delay: float = 0.5,
    session: Optional[aiohttp.ClientSession] = None,
) -> Dict[str, Any]:
    """POST a raw `application/octet-stream` body (weight-chunk fast path:
    no base64 inflation, no json parse per chunk)."""
    return await arequest_with_retry(
        addr=addr,
        endpoint=endpoint,
        method="POST",
        max_retries=max_retries,
        timeout=timeout,
        retry_delay=retry_delay,
        session=session,
        data=data,
        headers=headers,
    )


def request_with_retry_sync(
    addr: str,
    endpoint: str,
    payload: Optional[Dict[str, Any]] = None,
    method: str = "POST",
    max_retries: int = 3,
    timeout: float = 3600,
) -> Dict[str, Any]:
    """Blocking variant for non-async contexts (launchers, tools)."""
    import requests

    url = f"http://{addr}{endpoint}"
    last_exc: Optional[BaseException] = None
    for attempt in range(max_retries):
        try:
            resp = requests.request(
                method,
                url,
                json=payload if method != "GET" else None,
                timeout=timeout,
            )
            if resp.status_code == 200:
                try:
                    return resp.json()
                except ValueError:
                    return {"text": resp.text}
            last_exc = HttpRequestError(
                f"{method} {url} -> HTTP {resp.status_code}: {resp.text[:200]}"
            )
        except OSError as e:
            last_exc = e
        if attempt < max_retries - 1:
            import time

            time.sleep(0.5 * (2**attempt))
    raise HttpRequestError(
        f"request to {url} failed after {max_retries} attempts"
    ) from last_exc
