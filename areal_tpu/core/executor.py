"""Rollout workflow execution gated by staleness capacity.

Behavioral counterpart of the reference's `WorkflowExecutor`
(areal/core/workflow_executor.py:218): episodes are submitted to the
AsyncTaskRunner only when the StalenessManager grants capacity; finished
trajectories are validated, filtered through `should_accept`, shuffled, and
concatenated into a padded batch.  `prepare_batch` keeps ≥2 consumer batches
in flight for maximum generation/training overlap
(workflow_executor.py:561-598).
"""

import queue
import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set

import numpy as np

from areal_tpu.api.config import InferenceEngineConfig
from areal_tpu.api.workflow import RolloutWorkflow
from areal_tpu.core.runner import AsyncTaskRunner, TaskError, TaskQueueFullError
from areal_tpu.core.staleness import StalenessManager
from areal_tpu.utils import logging, telemetry
from areal_tpu.utils.data import concat_padded_tensors
from areal_tpu.utils.dataloader import StatefulDataLoader, cycle_dataloader

logger = logging.getLogger("executor")


class TrajectoryLostError(RuntimeError):
    """A rollout's generation could not be completed on ANY server (the
    failover budget ran out mid-trajectory).  Unlike an ordinary episode
    exception — a workflow bug, which stays fatal — a lost trajectory is an
    expected fleet-failure outcome: the executor settles its staleness
    accounting (submitted -> rejected), counts it, and the run continues
    with a reported loss fraction instead of crashing."""


def check_trajectory_format(
    traj: Dict[str, Any], expected_keys: Optional[Set[str]] = None
):
    """Validate a workflow's output (reference: workflow_executor.py:27)."""
    if not isinstance(traj, dict):
        raise TypeError(f"trajectory must be a dict, got {type(traj)}")
    if "input_ids" not in traj or "attention_mask" not in traj:
        raise ValueError(
            f"trajectory must contain input_ids and attention_mask, "
            f"got {sorted(traj.keys())}"
        )
    B, L = np.asarray(traj["attention_mask"]).shape
    for k, v in traj.items():
        arr = np.asarray(v)
        if arr.shape[:1] != (B,):
            raise ValueError(
                f"trajectory key {k!r} batch dim {arr.shape} != {B}"
            )
    if expected_keys is not None and set(traj.keys()) != expected_keys:
        raise ValueError(
            f"trajectory keys {sorted(traj.keys())} != expected "
            f"{sorted(expected_keys)}"
        )


@dataclass
class _TaskInput:
    data: Dict[str, Any]
    workflow: RolloutWorkflow
    should_accept: Optional[Callable]


class WorkflowExecutor:
    def __init__(
        self,
        config: InferenceEngineConfig,
        inference_engine,
        staleness_manager: Optional[StalenessManager] = None,
        runner: Optional[AsyncTaskRunner] = None,
    ):
        self.config = config
        self.inference_engine = inference_engine
        qsize = config.queue_size or ((config.max_concurrent_rollouts or 64) * 16)
        self.runner = runner or AsyncTaskRunner(max_queue_size=qsize)
        self.staleness_manager = staleness_manager or StalenessManager(
            max_concurrent_rollouts=config.max_concurrent_rollouts or 64,
            consumer_batch_size=config.consumer_batch_size,
            max_staleness=config.max_head_offpolicyness,
        )
        self._pending_inputs: List[_TaskInput] = []
        self._pending_results: List[Dict[str, Any]] = []
        self._expected_keys: Optional[Set[str]] = None
        self._data_generator = None
        # trajectories abandoned after exhausting failover retries; exposed
        # so benches/e2e report a loss fraction instead of hiding deaths
        self.lost_trajectories = 0
        # optional fleet-wide admission gate (set by RemoteInfEngine when a
        # router is discovered): with N clients sharing one generation fleet,
        # the local StalenessManager alone would overshoot the global
        # staleness budget N-fold (reference gserver_manager.py:334)
        self.fleet_gate = None

    # --- lifecycle ---
    def initialize(self):
        self.runner.start()

    def destroy(self):
        if self.fleet_gate is not None and self.runner._loop is not None:
            import asyncio

            try:
                asyncio.run_coroutine_threadsafe(
                    self.fleet_gate.aclose(), self.runner._loop
                ).result(timeout=5)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self.runner.stop()

    # --- capacity ---
    def get_capacity(self) -> int:
        version = self.inference_engine.get_version()
        return self.staleness_manager.get_capacity(version)

    # --- episode wrapper ---
    def _make_task(self, ti: _TaskInput):
        async def _run():
            alloc_id = None
            if self.fleet_gate is not None:
                qid = str(ti.data.get("query_id", "")) if isinstance(ti.data, dict) else ""
                alloc_id = await self.fleet_gate.allocate(qid)
            # the lease MUST be returned on every exit path (format-check
            # and should_accept errors included) or it sits in the router's
            # _running until the TTL, eating fleet admission budget
            accept = False
            try:
                try:
                    traj = await ti.workflow.arun_episode(
                        self.inference_engine, ti.data
                    )
                except TrajectoryLostError as e:
                    # fleet failure, not a code bug: account the loss
                    # explicitly (the reject below settles submitted ->
                    # rejected so capacity never leaks) and keep running
                    self.lost_trajectories += 1
                    logger.warning(f"trajectory lost to fleet failure: {e}")
                    if telemetry.is_enabled():
                        telemetry.emit(
                            "trajectory_lost",
                            lost_total=self.lost_trajectories,
                        )
                    traj = None
                except BaseException:
                    # the submit-side increment must be balanced even on
                    # failure, or every crashed episode permanently eats one
                    # capacity slot
                    self.staleness_manager.on_rollout_rejected()
                    raise
                if traj is not None and self.config.check_trajectory_format:
                    check_trajectory_format(traj, self._expected_keys)
                    if self._expected_keys is None and "input_ids" in traj:
                        self._expected_keys = set(traj.keys())
                accept = traj is not None and (
                    ti.should_accept is None or ti.should_accept(traj)
                )
            finally:
                if self.fleet_gate is not None:
                    await self.fleet_gate.finish(alloc_id, accepted=accept)
            if telemetry.is_enabled():
                telemetry.emit(
                    "episode",
                    accepted=accept,
                    version=self.inference_engine.get_version(),
                )
            if accept:
                self.staleness_manager.on_rollout_accepted()
                if self.config.enable_rollout_tracing:
                    logger.info(f"accept rollout: {self.staleness_manager.get_stats()}")
                return traj
            self.staleness_manager.on_rollout_rejected()
            if self.config.enable_rollout_tracing:
                logger.info(f"reject rollout: {self.staleness_manager.get_stats()}")
            return None

        return _run

    # --- public surface (mirrors InferenceEngine) ---
    def submit(
        self,
        data: Dict[str, Any],
        workflow: Optional[RolloutWorkflow] = None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> None:
        if workflow is None:
            if workflow_builder is None:
                raise ValueError("need workflow or workflow_builder")
            workflow = workflow_builder()
        self._pending_inputs.append(_TaskInput(data, workflow, should_accept))

    def _commit_one(self):
        ti = self._pending_inputs.pop(0)
        try:
            self.runner.submit(self._make_task(ti))
        except TaskQueueFullError:
            self._pending_inputs.insert(0, ti)
            raise queue.Full("runner input queue full; raise queue_size")
        self.staleness_manager.on_rollout_submitted()

    def _drain_capacity(self):
        capacity = self.get_capacity()
        for _ in range(max(0, capacity)):
            if not self._pending_inputs:
                break
            try:
                self._commit_one()
            except queue.Full:
                break

    def wait(self, count: int, timeout: Optional[float] = None) -> Dict[str, Any]:
        start = time.perf_counter()
        timeout = timeout if timeout is not None else 7 * 24 * 3600.0
        while True:
            self._drain_capacity()
            if len(self._pending_results) >= count:
                break
            remaining = timeout - (time.perf_counter() - start)
            if remaining <= 0:
                raise TimeoutError(
                    f"timed out waiting for {count} rollouts "
                    f"({len(self._pending_results)} ready)"
                )
            try:
                batch = self.runner.wait(
                    count=max(1, count - len(self._pending_results)),
                    timeout=min(0.1, remaining),
                )
            except TimeoutError:
                continue
            # collect good results before surfacing any failure, so accepted
            # trajectories from the same runner batch are not dropped
            first_error: Optional[TaskError] = None
            for item in batch:
                if isinstance(item, TaskError):
                    first_error = first_error or item
                elif item is not None:
                    self._pending_results.append(item)
            if first_error is not None:
                raise RuntimeError("rollout task failed") from first_error.exc
        results = self._pending_results[:count]
        self._pending_results = self._pending_results[count:]
        random.shuffle(results)
        return concat_padded_tensors(results)

    def rollout_batch(
        self,
        data: List[Dict[str, Any]],
        workflow: Optional[RolloutWorkflow] = None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> Dict[str, Any]:
        for item in data:
            self.submit(item, workflow, workflow_builder, should_accept)
        return self.wait(count=len(data))

    def prepare_batch(
        self,
        dataloader: StatefulDataLoader,
        workflow: Optional[RolloutWorkflow] = None,
        workflow_builder: Optional[Callable] = None,
        should_accept: Optional[Callable] = None,
    ) -> Dict[str, Any]:
        """Async-RL batch assembly: keep the rollout pipeline saturated while
        returning as soon as one consumer batch is ready."""
        if self._data_generator is None:
            self._data_generator = cycle_dataloader(dataloader)
        bs = dataloader.batch_size
        while True:
            if (
                self.get_capacity() + bs > 0
                and self.runner.get_input_queue_size() + bs < self.runner.max_queue_size
            ):
                for item in next(self._data_generator):
                    self.submit(item, workflow, workflow_builder, should_accept)
            try:
                return self.wait(bs, timeout=1)
            except TimeoutError:
                continue

    def pause(self):
        self.runner.pause()

    def resume(self):
        self.runner.resume()

    def is_paused(self) -> bool:
        return self.runner.paused.is_set()

    # --- crash recovery (utils/recover.py) ---
    def restore_staleness(self, stat) -> int:
        """Adopt a recovered ledger snapshot.  Trajectories that were in
        flight when the trainer died are settled as rejected by the
        manager and surfaced here as lost — same accounting as a
        failover-budget exhaustion, so loss fractions stay honest across
        restarts.  Returns the number settled."""
        settled = self.staleness_manager.restore(stat)
        if settled:
            self.lost_trajectories += settled
            if telemetry.is_enabled():
                telemetry.emit(
                    "trajectory_lost",
                    lost_total=self.lost_trajectories,
                    reason="trainer_crash",
                    settled=settled,
                )
        return settled
