"""Background asyncio task runner.

Capability counterpart of the reference's `AsyncTaskRunner`
(areal/core/async_task_runner.py:60): a daemon thread owning an asyncio event
loop; the main thread feeds async-task factories through a bounded queue and
collects results from an output queue.  uvloop isn't in this image, so the
stock loop is used (rollout workloads are HTTP-bound; the stock loop is
sufficient and keeps the dependency surface zero).

Lock-discipline audit (areal-lint C1): this class deliberately declares no
`_GUARDED_FIELDS` — cross-thread handoff rides the two `queue.Queue`s and
`threading.Event`s (self-synchronizing), `_n_running` is mutated only on
the loop thread and read cross-thread as a monitoring hint, and
`_exception` is write-once before the loop exits and read only by
`health_check` afterwards.
"""

import asyncio
import queue
import threading
import time
from typing import Any, Awaitable, Callable, List, Optional

from areal_tpu.utils import logging

logger = logging.getLogger("runner")

_POLL_INTERVAL = 0.02


class TaskQueueFullError(RuntimeError):
    pass


class RunnerDeadError(RuntimeError):
    pass


class AsyncTaskRunner:
    """Runs `async def` task factories on a dedicated event-loop thread.

    Results (including raised-exception placeholders) appear on the output
    queue in completion order.  `pause()` stops *new* tasks from starting and
    is also visible to in-flight tasks via `paused` (cooperative back-off
    during weight updates).
    """

    def __init__(self, max_queue_size: int = 4096):
        self.max_queue_size = max_queue_size
        self._input: queue.Queue = queue.Queue(maxsize=max_queue_size)
        self._output: queue.Queue = queue.Queue()
        self.paused = threading.Event()
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._n_running = 0
        self._exception: Optional[BaseException] = None
        self._started = threading.Event()

    # --- lifecycle ---
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._thread_main, daemon=True, name="async-task-runner"
        )
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RunnerDeadError("runner event loop failed to start")

    def stop(self, timeout: float = 10.0):
        if self._thread is None:
            return
        self._shutdown.set()
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            logger.warning("runner thread did not exit cleanly")
        self._thread = None

    def health_check(self):
        if self._exception is not None:
            raise RunnerDeadError(
                f"runner event loop died: {self._exception!r}"
            ) from self._exception
        if self._thread is not None and not self._thread.is_alive():
            raise RunnerDeadError("runner thread is not alive")

    # --- submission / collection (main thread) ---
    def submit(self, task_fn: Callable[[], Awaitable[Any]]):
        self.health_check()
        try:
            self._input.put_nowait(task_fn)
        except queue.Full:
            raise TaskQueueFullError(
                f"input queue full ({self.max_queue_size}); raise queue_size"
            )

    def wait(self, count: int, timeout: Optional[float] = None) -> List[Any]:
        """Collect up to... exactly `count` results; raises TimeoutError with
        nothing consumed beyond what's returned."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = []
        while len(out) < count:
            self.health_check()
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                if out:
                    # push back is impossible for a queue; return what we have
                    # via exception payload is worse — so re-queue results
                    for r in out:
                        self._output.put(r)
                raise TimeoutError(f"collected {len(out)}/{count} results")
            try:
                item = self._output.get(
                    timeout=min(0.05, remaining) if remaining is not None else 0.05
                )
            except queue.Empty:
                continue
            out.append(item)
        return out

    def get_input_queue_size(self) -> int:
        return self._input.qsize()

    def get_num_running(self) -> int:
        return self._n_running

    def pause(self):
        self.paused.set()

    def resume(self):
        self.paused.clear()

    # --- event-loop thread ---
    def _thread_main(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        try:
            self._loop.run_until_complete(self._main())
        except BaseException as e:  # noqa: BLE001 — surfaced via health_check
            self._exception = e
            logger.error(f"runner loop crashed: {e!r}")
        finally:
            try:
                self._loop.close()
            except Exception:
                pass

    async def _main(self):
        self._started.set()
        pending: set = set()

        def _done(task: asyncio.Task):
            self._n_running -= 1
            pending.discard(task)
            if task.cancelled():
                return
            exc = task.exception()
            if exc is not None:
                logger.error(f"rollout task failed: {exc!r}")
                self._output.put(TaskError(exc))
            else:
                self._output.put(task.result())

        while not self._shutdown.is_set():
            launched = False
            while not self.paused.is_set():
                try:
                    fn = self._input.get_nowait()
                except queue.Empty:
                    break
                task = asyncio.ensure_future(fn())
                self._n_running += 1
                task.add_done_callback(_done)
                pending.add(task)
                launched = True
            await asyncio.sleep(0 if launched else _POLL_INTERVAL)
        for task in pending:
            task.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)


class TaskError:
    """Wrapper marking a failed task on the output queue."""

    def __init__(self, exc: BaseException):
        self.exc = exc

    def __repr__(self):
        return f"TaskError({self.exc!r})"
